"""Chaos-campaign scenarios: real serving stacks behind a loopback port.

Each scenario builds one REAL serving topology in-process — the same
stacks bench_serve.py measures and the subsystem tests pin — and exposes
the uniform surface the campaign runner (campaign.py) drives cells
through: a loopback HTTP base URL for the seeded workload, a resource
snapshot for the conservation audit, a quiesce barrier, and (where the
scenario has moving parts) a scripted `storm()` of membership/fleet
events the injected faults perturb.

Scenarios:

- ``local``       single-node legacy engine behind admission + SSE
- ``sched``       DNET_SCHED=1 + ragged-KV engine, same HTTP surface
- ``ring``        two-shard in-process ring (loadgen/ring_harness.py),
                  resume armed — the transport/compute fault surface
- ``ring_wire``   the same ring under DNET_WIRE_PIPELINE=1 (overlapped
                  encode/decode seams live)
- ``member``      three-shard ring + ClusterManager + RingModelManager +
                  RingFailureMonitor (HTTP fan-out served in-process):
                  loss -> epoch-fenced recovery (delta reconfig) ->
                  resume -> rejoin, per cell
- ``member_auto`` the same with decode-grant batching
                  (DNET_API_RING_AUTO_STEPS=8)
- ``fleet``       two single-node replicas behind FleetManager
- ``fleet_sched`` the same over the scheduler engine
- ``fleet_ring``  two in-process RINGS behind FleetManager — the composed
                  acceptance cell (replica dies mid-stream on top of
                  in-ring resume) runs here

No scenario opens a real network socket beyond the loopback HTTP port;
no pytest machinery is involved, so ``make chaos`` runs the identical
stacks CI's tier-1 smoke does.
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import socket
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from dnet_tpu.utils.logger import get_logger

log = get_logger()

# Deep admission queue: campaign cells must queue (and surface chaos as
# the injected fault's OWN failure mode), not shed on burst arrival — a
# shed would alias every cell's outcome to 429.
_BASE_ENV = {
    "DNET_ADMIT_QUEUE_DEPTH": "64",
    "DNET_ADMIT_QUEUE_TIMEOUT_S": "30",
}

# Resume armed with fast retries: the ring scenarios recover from
# injected transport/compute faults within a cell's request budget
# (mirrors tests/subsystems/test_ring_membership.py's _ENV).
_RESUME_ENV = {
    "DNET_RESILIENCE_RESUME": "1",
    "DNET_RESILIENCE_RESUME_DEADLINE_S": "30",
    "DNET_RESILIENCE_MAX_RESUMES": "200",
    "DNET_RESILIENCE_RETRY_BASE_S": "0.001",
    "DNET_RESILIENCE_RETRY_MAX_S": "0.01",
    "DNET_API_RING_AUTO_STEPS": "0",
}


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def _wait(cond, timeout_s: float, what: str) -> None:
    t0 = time.monotonic()
    while not cond():
        if time.monotonic() - t0 > timeout_s:
            raise TimeoutError(f"timed out waiting for {what}")
        await asyncio.sleep(0.02)


class _EnvScope:
    """Set env overrides + fresh settings/obs books for one scenario;
    restore the previous environment on exit (the bench_serve leg idiom)."""

    def __init__(self, env: Dict[str, str]) -> None:
        self.env = dict(env)
        self._saved: Dict[str, Optional[str]] = {}

    def enter(self) -> None:
        from dnet_tpu.config import reset_settings_cache
        from dnet_tpu.obs import reset_obs

        for k, v in self.env.items():
            self._saved[k] = os.environ.get(k)
            os.environ[k] = v
        reset_settings_cache()
        reset_obs()

    def exit(self) -> None:
        from dnet_tpu.config import reset_settings_cache

        for k, old in self._saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        self._saved.clear()
        reset_settings_cache()


@dataclass
class ResourceSnapshot:
    """Post-quiesce books for the conservation audit (invariants.py
    family 2).  Every entry is (observed, expected-at-rest)."""

    pools: Dict[str, Tuple[int, int, int]] = field(default_factory=dict)
    # name -> (used, free, total); at rest used==0 and free==total
    admission: Dict[str, Tuple[int, int]] = field(default_factory=dict)
    # name -> (active, queued); at rest (0, 0)
    lanes: Dict[str, Tuple[int, int]] = field(default_factory=dict)
    # name -> (free, slots); at rest free==slots
    streams: Dict[str, int] = field(default_factory=dict)
    # name -> open per-nonce stream contexts; at rest 0


def _pool_entry(snap: ResourceSnapshot, name: str, engine) -> None:
    pool = getattr(engine, "kv_pool", None)
    if pool is not None:
        snap.pools[name] = (pool.used, pool.free, pool.total)


def _lane_entry(snap: ResourceSnapshot, name: str, compute) -> None:
    lp = getattr(compute, "lane_pool", None)
    if lp is not None:
        snap.lanes[name] = (len(lp._free), lp.slots)


def _stream_entry(snap: ResourceSnapshot, name: str, holder) -> None:
    sm = getattr(holder, "_streams", None)
    if sm is not None:
        snap.streams[name] = len(getattr(sm, "_streams", {}))


class Scenario:
    """One serving stack the campaign drives cells through."""

    name = ""
    parity = "bytes"  # bytes | content — how golden comparison is judged
    #: injection points this scenario meaningfully exercises
    points: Tuple[str, ...] = ()
    #: per-request client budget: the server must answer inside this or
    #: the cell records status 0 (a status-contract violation)
    client_timeout_s = 60.0

    def __init__(self, model_dir: str) -> None:
        self.model_dir = str(model_dir)
        self.base_url = ""
        self._scope: Optional[_EnvScope] = None
        self._session = None

    # -- lifecycle ------------------------------------------------------
    def extra_env(self) -> Dict[str, str]:
        return {}

    async def start(self) -> None:
        self._scope = _EnvScope({**_BASE_ENV, **self.extra_env()})
        self._scope.enter()
        try:
            await self._build()
        except BaseException:
            self._scope.exit()
            raise
        import aiohttp

        self._session = aiohttp.ClientSession(
            base_url=self.base_url,
            timeout=aiohttp.ClientTimeout(total=None),
        )

    async def stop(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None
        try:
            await self._teardown()
        finally:
            if self._scope is not None:
                self._scope.exit()
                self._scope = None

    async def _build(self) -> None:
        raise NotImplementedError

    async def _teardown(self) -> None:
        raise NotImplementedError

    # -- request surface ------------------------------------------------
    @property
    def model(self) -> str:
        return self.model_dir

    async def post_chat(
        self, body: dict, timeout_s: float = 60.0
    ) -> Tuple[int, Dict[str, str], bytes]:
        """One streaming chat request; returns (status, headers, raw SSE
        bytes).  Transport failures surface as status 0 (a violation:
        the server must answer, even under chaos)."""
        async def _go():
            async with self._session.post(
                "/v1/chat/completions", json=body
            ) as resp:
                raw = await resp.read()
                return resp.status, dict(resp.headers), raw

        try:
            return await asyncio.wait_for(_go(), timeout_s)
        except asyncio.TimeoutError:
            return 0, {}, b"client timeout"
        except Exception as exc:
            return 0, {}, f"transport failure: {exc}".encode()

    # -- campaign hooks -------------------------------------------------
    async def storm(self) -> None:
        """Scripted mid-cell event arc (membership/fleet scenarios);
        no-op for static stacks."""
        return None

    async def quiesce(self, timeout_s: float = 10.0) -> None:
        """Barrier: in-flight work drained (admission idle)."""
        for name, inference in self._inferences():
            adm = inference.admission
            # dnetlint: disable=DL024 a handful of admission books; the wait is one shared wall-clock, not N round trips
            await _wait(
                lambda a=adm: a.active == 0 and a.queued == 0,
                timeout_s, f"{name} admission idle",
            )

    async def heal(self, timeout_s: float = 10.0) -> bool:
        """Post-cell repair: True when the stack is ready for the next
        cell; False tells the campaign to rebuild the scenario."""
        return True

    def _inferences(self):
        """[(name, InferenceManager)] — every admission book in play."""
        raise NotImplementedError

    def resources(self) -> ResourceSnapshot:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# local / sched: the single-node stack (bench_serve._run_inprocess)
# ---------------------------------------------------------------------------


class LocalScenario(Scenario):
    name = "local"
    parity = "bytes"
    points = ("admit",)

    batch_slots = 2

    async def _build(self) -> None:
        from dnet_tpu.api.http import ApiHTTPServer
        from dnet_tpu.api.inference import InferenceManager
        from dnet_tpu.api.model_manager import LocalModelManager
        from dnet_tpu.config import get_settings

        api = get_settings().api
        self.inference = InferenceManager(
            adapter=None,
            request_timeout_s=api.request_timeout_s,
            max_concurrent=min(
                api.max_concurrent_requests, self.batch_slots
            ),
        )
        self.manager = LocalModelManager(
            self.inference,
            models_dir=api.models_dir,
            max_seq=64,
            param_dtype="float32",
            batch_slots=self.batch_slots,
        )
        await self.manager.load_model(self.model_dir, max_seq=64)
        self.server = ApiHTTPServer(self.inference, self.manager)
        port = _free_port()
        await self.server.start("127.0.0.1", port)
        self.base_url = f"http://127.0.0.1:{port}"

    async def _teardown(self) -> None:
        await self.server.stop()
        await self.manager.unload_model()

    def _inferences(self):
        return [("api", self.inference)]

    def resources(self) -> ResourceSnapshot:
        snap = ResourceSnapshot()
        adm = self.inference.admission
        snap.admission["api"] = (adm.active, adm.queued)
        _pool_entry(snap, "engine", getattr(self.manager, "engine", None))
        return snap


class SchedScenario(LocalScenario):
    name = "sched"

    def extra_env(self) -> Dict[str, str]:
        return {"DNET_SCHED": "1", "DNET_KV_RAGGED": "1"}


# ---------------------------------------------------------------------------
# ring / ring_wire: the two-shard in-process ring (loadgen/ring_harness.py)
# ---------------------------------------------------------------------------


class RingScenario(Scenario):
    name = "ring"
    parity = "bytes"
    points = (
        "send_activation", "token_cb", "shard_compute", "zombie_frame",
        "wire_encode", "wire_decode", "admit",
    )

    wire_pipeline = False

    def extra_env(self) -> Dict[str, str]:
        env = dict(_RESUME_ENV)
        if self.wire_pipeline:
            env["DNET_WIRE_PIPELINE"] = "1"
        return env

    async def _build(self) -> None:
        import json as _json
        from pathlib import Path

        from dnet_tpu.loadgen.ring_harness import InprocRing

        cfg = _json.loads(
            (Path(self.model_dir) / "config.json").read_text()
        )
        n_layers = int(cfg["num_hidden_layers"])
        half = max(n_layers // 2, 1)
        self.ring = InprocRing(
            self.model_dir,
            layers0=range(0, half),
            layers1=range(half, n_layers),
            max_seq=64,
            auto_steps=0,  # per-step frames: the fault surface is widest
            # a token the chaos ate outright (fenced frame, exhausted
            # callback retries) only reaches the resume machinery when
            # await_token times out — keep that bound tight so recovery
            # lands well inside the cell's client budget
            request_timeout_s=6.0,
        )
        await self.ring.start()
        port = _free_port()
        await self.ring.server.start("127.0.0.1", port)
        self.base_url = f"http://127.0.0.1:{port}"

    async def _teardown(self) -> None:
        await self.ring.server.stop()
        await self.ring.stop()

    @property
    def model(self) -> str:
        return "inproc-ring"

    def _inferences(self):
        return [("api", self.ring.inference)]

    async def heal(self, timeout_s: float = 20.0) -> bool:
        # a request the chaos wedged past every server-side timeout means
        # the stack cannot be trusted for the next cell: report unhealed
        # so the campaign rebuilds instead of letting the stuck admission
        # slot cascade violations forward
        try:
            await self.quiesce(timeout_s)
        except TimeoutError:
            return False
        return True

    def resources(self) -> ResourceSnapshot:
        snap = ResourceSnapshot()
        adm = self.ring.inference.admission
        snap.admission["api"] = (adm.active, adm.queued)
        for rt_name, rt, adapter in (
            ("s0", self.ring.s0, self.ring.a0),
            ("s1", self.ring.s1, self.ring.a1),
        ):
            if rt.compute is not None:
                _pool_entry(snap, rt_name, rt.compute.engine)
                _lane_entry(snap, rt_name, rt.compute)
            _stream_entry(snap, rt_name, adapter)
        _stream_entry(snap, "api", self.ring.api)
        return snap


class RingWireScenario(RingScenario):
    name = "ring_wire"
    wire_pipeline = True


# ---------------------------------------------------------------------------
# member / member_auto: the elastic-membership ring
# (port of tests/subsystems/test_ring_membership.py's harness)
# ---------------------------------------------------------------------------


class _MemberStreamCall:
    """grpc aio stream-stream stand-in: write() delivers into the target
    shard's ingress, the returned ACK queues for the reader."""

    def __init__(self, deliver) -> None:
        self._deliver = deliver
        self.acks: asyncio.Queue = asyncio.Queue()

    async def write(self, frame) -> None:
        ack = await self._deliver(frame)
        if ack is not None:
            await self.acks.put(ack)

    async def read(self):
        return await self.acks.get()

    async def done_writing(self) -> None:
        return None


class _MemberRingClient:
    """RingClient stand-in addressed by grpc addr; frames land on the
    addressed shard's adapter in-process."""

    def __init__(self, addr: str, deliver, reset=None) -> None:
        self.addr = addr
        self._deliver = deliver
        self._reset = reset

    def open_stream(self) -> _MemberStreamCall:
        return _MemberStreamCall(lambda f: self._deliver(self.addr, f))

    async def send_activation(self, frame, timeout=10.0):
        return await self._deliver(self.addr, frame)

    async def health_check(self, timeout=5.0):
        from dnet_tpu.transport.protocol import HealthInfo

        return HealthInfo(ok=True)

    async def reset_cache(self, nonce="", timeout=10.0, epoch=0):
        from dnet_tpu.transport.protocol import Empty

        # the API fans per-nonce resets over every shard client after a
        # request ends; without forwarding them the member shards leak a
        # stream context per request — exactly what conservation audits
        if self._reset is not None:
            await self._reset(self.addr, nonce)
        return Empty()

    async def measure_latency(self, probe, timeout=30.0):
        return probe

    async def close(self):
        return None


class _MemberProbeClient(_MemberRingClient):
    """The failure monitor's probe client: fails while its addr is in
    the scenario's dead set (a FlakyClient without the test import)."""

    def __init__(self, addr: str, dead: set) -> None:
        super().__init__(addr, deliver=None)
        self._dead = dead

    async def health_check(self, timeout=5.0):
        if self.addr in self._dead:
            raise ConnectionError(f"{self.addr} unreachable")
        return await super().health_check(timeout)


class _MemberCallbackClient:
    """ApiCallbackClient stand-in: token payloads land in the sink the
    pump task drains into the API adapter."""

    def __init__(self, addr: str, sink: list) -> None:
        self.addr = addr
        self._sink = sink

    async def send_token(self, payload, timeout=3.0):
        from dnet_tpu.transport.protocol import Empty

        self._sink.append(payload)
        return Empty()

    async def close(self):
        return None


class _MemberShards:
    """Three real shard runtimes + adapters behind the faked HTTP control
    plane the ring manager fans out over."""

    def __init__(self, model_dir: str, sink: list) -> None:
        from dnet_tpu.shard.adapter import RingAdapter
        from dnet_tpu.shard.runtime import ShardRuntime

        self.model_dir = str(model_dir)
        self.sink = sink
        self.loads: Dict[str, int] = {}
        self.updates: Dict[str, int] = {}
        self.shards: Dict[str, tuple] = {}
        for i in range(3):
            inst = f"s{i}"
            rt = ShardRuntime(inst)
            adapter = RingAdapter(
                rt,
                ring_client_factory=self.ring_factory,
                callback_client_factory=lambda addr: _MemberCallbackClient(
                    addr, self.sink
                ),
            )
            self.shards[inst] = (rt, adapter)
        self.by_grpc = {f"h{i}:{10 * (i + 1)}": f"s{i}" for i in range(3)}
        self.by_http = {f"h{i}:{i + 1}": f"s{i}" for i in range(3)}

    def ring_factory(self, addr: str) -> _MemberRingClient:
        return _MemberRingClient(addr, self.ingress_ack, self.reset)

    async def reset(self, addr: str, nonce: str) -> None:
        rt, adapter = self.shards[self.by_grpc[addr]]
        await adapter.reset_cache(nonce)

    async def ingress_ack(self, addr: str, frame):
        from dnet_tpu.transport.protocol import StreamAck

        rt, adapter = self.shards[self.by_grpc[addr]]
        ok, msg = await adapter.ingress_frame(frame)
        return StreamAck(
            nonce=frame.nonce, seq=frame.seq, ok=ok, message=msg
        )

    def devices(self) -> list:
        from dnet_tpu.core.types import DeviceInfo

        return [
            DeviceInfo(
                instance=f"s{i}", host=f"h{i}", http_port=i + 1,
                grpc_port=10 * (i + 1), flops_bf16=1e14, hbm_bw=8e11,
                host_to_hbm_bw=1e10, hbm_bytes=16 << 30,
            )
            for i in range(3)
        ]

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        for rt, adapter in self.shards.values():
            rt.start(loop)
            await adapter.start()  # dnetlint: disable=DL024 three in-process adapters at build time; startup order is part of the harness contract

    async def stop(self) -> None:
        for rt, adapter in self.shards.values():
            await adapter.shutdown()  # dnetlint: disable=DL024 teardown must be ordered (adapter before runtime) per shard
            rt.stop()
        for rt, _adapter in self.shards.values():
            if rt.compute is not None:
                rt.compute.engine.close()
                rt.compute = None

    async def handle_post(self, url: str, body: dict):
        """(status, body) for one ring-manager fan-out POST — the
        in-process twin of shard/http.py's control routes, chaos points
        included."""
        from dnet_tpu.resilience import chaos

        hostport, _, path = url.removeprefix("http://").partition("/")
        inst = self.by_http[hostport]
        rt, adapter = self.shards[inst]
        nxt = body.get("next_node") or {}
        next_addr = f"{nxt['host']}:{nxt['grpc_port']}" if nxt else ""
        if path == "load_model":
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(
                None,
                lambda: rt.load_model_core(
                    self.model_dir, body["layers"],
                    max_seq=body["max_seq_len"],
                    param_dtype=body["param_dtype"],
                    epoch=body["epoch"],
                ),
            )
            adapter.configure_topology(next_addr)
            self.loads[inst] = self.loads.get(inst, 0) + 1
            return 200, {"status": "ok"}
        if path == "update_topology":
            # same chaos point the real Shard.update_topology traverses:
            # an injected fault is this shard unreachable for the delta —
            # non-200 sends the manager down the full-load fallback
            try:
                await chaos.inject_async("update_topology")
            except chaos.ChaosError as exc:
                return 503, {"status": "error", "message": str(exc)}
            if rt.compute is None or sorted(rt.compute.layers) != sorted(
                body["layers"]
            ):
                return 409, {"status": "error", "message": "cannot prove"}
            await adapter.reset_topology()
            rt.drain_ingress()
            rt.compute.reset("")
            rt.set_epoch(body["epoch"])
            adapter.configure_topology(next_addr)
            self.updates[inst] = self.updates.get(inst, 0) + 1
            return 200, {"status": "ok", "epoch": rt.epoch}
        if path == "unload_model":
            return 200, {"status": "ok"}
        return 404, {"status": "error", "message": f"unexpected {url}"}


class _MemberHttpx:
    """Stands in for the httpx module inside api.ring_manager."""

    class HTTPError(Exception):
        pass

    class _Resp:
        def __init__(self, status_code: int, body: dict) -> None:
            import json as _json

            self.status_code = status_code
            self._body = body
            self.text = _json.dumps(body)

        def json(self):
            return self._body

    def __init__(self, cluster: _MemberShards) -> None:
        outer = self

        class AsyncClient:
            def __init__(self, timeout=None) -> None:
                pass

            async def __aenter__(self):
                return self

            async def __aexit__(self, *exc):
                return False

            async def post(self, url, json=None):
                status, body = await cluster.handle_post(url, json)
                return outer._Resp(status, body)

        self.AsyncClient = AsyncClient


def _member_solve(model_id: str, n_layers: int):
    """Deterministic mini-solver: contiguous layer runs over whichever
    shards are alive, front-loaded so s0's range is STABLE across 3<->2
    shard shapes (s0 always delta-reconfigs, the tail shard full-loads)."""

    def solve(devices, profile=None, **kw):
        from dnet_tpu.api.ring_manager import build_manual_topology

        insts = sorted({d.instance for d in devices})
        if not insts:
            raise ValueError("no devices to solve over")
        n = len(insts)
        base, extra = divmod(n_layers, n)
        sizes = [base + (1 if i < extra else 0) for i in range(n)]
        assignments, at = [], 0
        for inst, size in zip(insts, sizes):
            assignments.append(
                {"instance": inst, "layers": list(range(at, at + size))}
            )
            at += size
        return build_manual_topology(model_id, n_layers, assignments, devices)

    return solve


class MemberScenario(Scenario):
    name = "member"
    parity = "content"
    # storms re-solve topology and reload shard engines mid-cell; a
    # request that lands inside a recovery window legitimately waits for
    # it, so the member budget is wider than the static stacks'
    client_timeout_s = 120.0
    points = (
        "health_check", "rejoin", "update_topology", "shard_compute",
        "token_cb", "admit",
    )

    auto_steps = 0
    n_layers = 4

    def extra_env(self) -> Dict[str, str]:
        env = dict(_RESUME_ENV)
        env["DNET_API_RING_AUTO_STEPS"] = str(self.auto_steps)
        return env

    async def _build(self) -> None:
        from dnet_tpu.api.cluster import ClusterManager
        from dnet_tpu.api.failure import RingFailureMonitor
        from dnet_tpu.api.http import ApiHTTPServer
        from dnet_tpu.api.inference import InferenceManager
        from dnet_tpu.api import ring_manager as rm_mod
        from dnet_tpu.api.ring_manager import RingModelManager
        from dnet_tpu.parallel import solver as solver_mod

        self._dead: set = set()
        self.sink: list = []
        self.shards = _MemberShards(self.model_dir, self.sink)
        # seam swaps (restored in _teardown): the manager's HTTP fan-out
        # and the re-solver
        self._real_httpx = rm_mod.httpx
        rm_mod.httpx = _MemberHttpx(self.shards)
        self._real_solve = solver_mod.solve_topology
        solver_mod.solve_topology = _member_solve(
            self.model_dir, self.n_layers
        )
        await self.shards.start()
        self.cluster = ClusterManager(discovery=None)

        async def profiled():
            return self.shards.devices()

        self.cluster.profile_cluster = profiled
        self.inference = InferenceManager(
            adapter=None, request_timeout_s=30.0, max_concurrent=8
        )
        self.manager = RingModelManager(
            self.inference,
            self.cluster,
            api_callback_addr="api:1",
            max_seq=64,
            param_dtype="float32",
            ring_client_factory=self.shards.ring_factory,
        )
        topo = solver_mod.solve_topology(self.shards.devices(), None)
        self.cluster.install_topology(topo)
        await self.manager.load_model(self.model_dir)
        self._stop_pump = asyncio.Event()
        self._pump_task = asyncio.ensure_future(self._pump())
        self.monitor = RingFailureMonitor(
            self.cluster,
            self.inference,
            model_manager=self.manager,
            interval_s=0.02,
            fail_threshold=2,
            timeout_s=0.5,
            auto_recover=True,
            ring_client_factory=lambda addr: _MemberProbeClient(
                addr, self._dead
            ),
            rejoin=True,
            rejoin_stable_s=0.1,
        )
        self.inference.failure_monitor = self.monitor
        self.monitor.start()
        self.server = ApiHTTPServer(
            self.inference, self.manager, cluster_manager=self.cluster
        )
        port = _free_port()
        await self.server.start("127.0.0.1", port)
        self.base_url = f"http://127.0.0.1:{port}"

    async def _pump(self) -> None:
        seen = 0
        while not self._stop_pump.is_set():
            while seen < len(self.sink):
                payload = self.sink[seen]
                seen += 1
                if self.inference.adapter is not None:
                    self.inference.adapter.resolve_token(payload.to_result())
            await asyncio.sleep(0.005)

    async def _teardown(self) -> None:
        from dnet_tpu.api import ring_manager as rm_mod
        from dnet_tpu.parallel import solver as solver_mod

        with contextlib.suppress(Exception):
            await self.monitor.stop()
        self._stop_pump.set()
        with contextlib.suppress(asyncio.CancelledError):
            self._pump_task.cancel()
            await asyncio.gather(self._pump_task, return_exceptions=True)
        with contextlib.suppress(Exception):
            await self.server.stop()
        if self.inference.adapter is not None:
            with contextlib.suppress(Exception):
                await self.inference.adapter.shutdown()
        await self.shards.stop()
        rm_mod.httpx = self._real_httpx
        solver_mod.solve_topology = self._real_solve

    @property
    def model(self) -> str:
        return self.model_dir

    async def storm(self) -> None:
        """One loss -> recover -> rejoin arc: s2 drops off the ring, the
        monitor re-solves without it (delta reconfig for the stable-range
        shards), then s2 probes green and rejoins at the next epoch.
        Under chaos, any leg of the arc may stall — that is tolerated
        here (degradation is allowed; 5xx and leaks are not) and repaired
        by heal() after the cell's faults clear."""
        e0 = self.cluster.epoch
        self._dead.add("h2:30")
        with contextlib.suppress(TimeoutError):
            await _wait(
                lambda: self.cluster.epoch > e0, 8.0, "loss re-solve"
            )
        e1 = self.cluster.epoch
        self._dead.discard("h2:30")
        with contextlib.suppress(TimeoutError):
            await _wait(
                lambda: self.cluster.epoch > e1, 8.0, "rejoin re-solve"
            )

    async def heal(self, timeout_s: float = 15.0) -> bool:
        self._dead.clear()
        try:
            await _wait(
                lambda: not self.monitor.degraded, timeout_s,
                "monitor green",
            )
            await self.quiesce(timeout_s)
        except TimeoutError:
            return False
        return True

    def _inferences(self):
        return [("api", self.inference)]

    def resources(self) -> ResourceSnapshot:
        snap = ResourceSnapshot()
        adm = self.inference.admission
        snap.admission["api"] = (adm.active, adm.queued)
        for inst, (rt, adapter) in self.shards.shards.items():
            if rt.compute is not None:
                _pool_entry(snap, inst, rt.compute.engine)
                _lane_entry(snap, inst, rt.compute)
            _stream_entry(snap, inst, adapter)
        if self.inference.adapter is not None:
            _stream_entry(snap, "api", self.inference.adapter)
        return snap


class MemberAutoScenario(MemberScenario):
    name = "member_auto"
    auto_steps = 8


# ---------------------------------------------------------------------------
# fleet / fleet_sched: replicated single-node stacks behind FleetManager
# ---------------------------------------------------------------------------


class FleetScenario(Scenario):
    name = "fleet"
    parity = "content"
    points = ("fleet_dispatch", "admit")

    sched = False
    n_replicas = 2
    batch_slots = 2

    def extra_env(self) -> Dict[str, str]:
        env = {"DNET_FLEET": str(self.n_replicas)}
        if self.sched:
            env.update({"DNET_SCHED": "1", "DNET_KV_RAGGED": "1"})
        return env

    async def _build(self) -> None:
        from dnet_tpu.api.http import ApiHTTPServer
        from dnet_tpu.api.inference import InferenceManager
        from dnet_tpu.api.model_manager import LocalModelManager
        from dnet_tpu.config import get_settings
        from dnet_tpu.fleet import FleetManager

        api = get_settings().api
        self.replicas = []
        for _ in range(self.n_replicas):
            inference = InferenceManager(
                adapter=None,
                request_timeout_s=api.request_timeout_s,
                max_concurrent=min(
                    api.max_concurrent_requests, self.batch_slots
                ),
            )
            manager = LocalModelManager(
                inference,
                models_dir=api.models_dir,
                max_seq=64,
                param_dtype="float32",
                batch_slots=self.batch_slots,
            )
            # dnetlint: disable=DL024 two engine loads share one jit cache: the second is cheap only AFTER the first finishes
            await manager.load_model(self.model_dir, max_seq=64)
            self.replicas.append((inference, manager))
        self.fleet = FleetManager()
        for i, (inference, _mgr) in enumerate(self.replicas):
            self.fleet.add_replica(f"r{i}", inference)
        self.server = ApiHTTPServer(
            self.replicas[0][0], self.replicas[0][1], fleet=self.fleet
        )
        port = _free_port()
        await self.server.start("127.0.0.1", port)
        self.base_url = f"http://127.0.0.1:{port}"

    async def _teardown(self) -> None:
        await self.server.stop()
        for _inf, mgr in self.replicas:
            await mgr.unload_model()  # dnetlint: disable=DL024 serial teardown keeps device memory accounting exact

    def _inferences(self):
        return [
            (f"r{i}", inf) for i, (inf, _m) in enumerate(self.replicas)
        ]

    def resources(self) -> ResourceSnapshot:
        snap = ResourceSnapshot()
        for i, (inference, manager) in enumerate(self.replicas):
            adm = inference.admission
            snap.admission[f"r{i}"] = (adm.active, adm.queued)
            _pool_entry(snap, f"r{i}", getattr(manager, "engine", None))
        return snap


class FleetSchedScenario(FleetScenario):
    name = "fleet_sched"
    sched = True


# ---------------------------------------------------------------------------
# fleet_ring: two in-process rings behind the fleet front door — the
# composed acceptance cell (failover mid-stream on top of in-ring resume)
# ---------------------------------------------------------------------------


class FleetRingScenario(Scenario):
    name = "fleet_ring"
    parity = "content"
    points = ("fleet_dispatch", "send_activation", "shard_compute")

    n_replicas = 2

    def extra_env(self) -> Dict[str, str]:
        env = dict(_RESUME_ENV)
        env["DNET_FLEET"] = str(self.n_replicas)
        return env

    async def _build(self) -> None:
        import json as _json
        from pathlib import Path

        from dnet_tpu.api.http import ApiHTTPServer
        from dnet_tpu.fleet import FleetManager
        from dnet_tpu.loadgen.ring_harness import InprocRing

        cfg = _json.loads(
            (Path(self.model_dir) / "config.json").read_text()
        )
        n_layers = int(cfg["num_hidden_layers"])
        half = max(n_layers // 2, 1)
        self.rings = []
        for _ in range(self.n_replicas):
            ring = InprocRing(
                self.model_dir,
                layers0=range(0, half),
                layers1=range(half, n_layers),
                max_seq=64,
                auto_steps=0,
                request_timeout_s=6.0,  # see RingScenario
            )
            # dnetlint: disable=DL024 two engine loads share one jit cache: the second is cheap only AFTER the first finishes
            await ring.start()
            self.rings.append(ring)
        self.fleet = FleetManager()
        for i, ring in enumerate(self.rings):
            self.fleet.add_replica(f"r{i}", ring.inference)
        self.server = ApiHTTPServer(
            self.rings[0].inference, self.rings[0].manager, fleet=self.fleet
        )
        port = _free_port()
        await self.server.start("127.0.0.1", port)
        self.base_url = f"http://127.0.0.1:{port}"

    async def _teardown(self) -> None:
        await self.server.stop()
        for ring in self.rings:
            await ring.stop()  # dnetlint: disable=DL024 serial teardown keeps device memory accounting exact

    @property
    def model(self) -> str:
        return "inproc-ring"

    async def kill_serving_replica(self, delay_s: float = 0.25) -> str:
        """The composed cell's fleet event: after `delay_s`, mark whichever
        replica holds the in-flight stream dead — its stream must splice
        onto the survivor."""
        await asyncio.sleep(delay_s)
        victim = "r0"
        for i, ring in enumerate(self.rings):
            if ring.inference.admission.active > 0:
                victim = f"r{i}"
                break
        self.fleet.fail_replica(victim)
        return victim

    def _inferences(self):
        return [
            (f"r{i}", ring.inference) for i, ring in enumerate(self.rings)
        ]

    def resources(self) -> ResourceSnapshot:
        snap = ResourceSnapshot()
        for i, ring in enumerate(self.rings):
            adm = ring.inference.admission
            snap.admission[f"r{i}"] = (adm.active, adm.queued)
            for rt_name, rt, adapter in (
                (f"r{i}.s0", ring.s0, ring.a0),
                (f"r{i}.s1", ring.s1, ring.a1),
            ):
                if rt.compute is not None:
                    _pool_entry(snap, rt_name, rt.compute.engine)
                    _lane_entry(snap, rt_name, rt.compute)
                _stream_entry(snap, rt_name, adapter)
            _stream_entry(snap, f"r{i}.api", ring.api)
        return snap


#: name -> scenario class; the campaign matrix and the CLI resolve here
SCENARIOS: Dict[str, type] = {
    cls.name: cls
    for cls in (
        LocalScenario, SchedScenario, RingScenario, RingWireScenario,
        MemberScenario, MemberAutoScenario, FleetScenario,
        FleetSchedScenario, FleetRingScenario,
    )
}


def build_scenario(name: str, model_dir: str) -> Scenario:
    try:
        cls = SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; one of {', '.join(SCENARIOS)}"
        ) from None
    return cls(model_dir)
