"""Chaos campaigns: exhaustive fault-sweep verification.

The `dnet_tpu.resilience.chaos` module injects the faults; this package
proves the system absorbs them.  `campaign` enumerates the deterministic
(point x kind x scenario) matrix and drives each cell with a seeded
workload; `invariants` audits every cell against the five system-wide
families (status contract, resource conservation, metrics conservation,
epoch coherence, SSE integrity); `scenarios` hosts the in-process
serving stacks the cells run on.
"""

from dnet_tpu.chaos.campaign import (
    COMPOSED_CELL_ID,
    POINT_SCENARIOS,
    SMOKE_CELLS,
    Cell,
    build_matrix,
    run_campaign,
    select_cells,
    write_record,
)
from dnet_tpu.chaos.invariants import (
    ALLOWED_STATUSES,
    FAMILIES,
    CellEvidence,
    Violation,
    audit_cell,
)
from dnet_tpu.chaos.scenarios import SCENARIOS, Scenario, build_scenario

__all__ = [
    "ALLOWED_STATUSES",
    "COMPOSED_CELL_ID",
    "FAMILIES",
    "POINT_SCENARIOS",
    "SCENARIOS",
    "SMOKE_CELLS",
    "Cell",
    "CellEvidence",
    "Scenario",
    "Violation",
    "audit_cell",
    "build_matrix",
    "build_scenario",
    "run_campaign",
    "select_cells",
    "write_record",
]
