"""The system-wide invariant auditor behind every chaos-campaign cell.

Five families, each a pure function over what the cell observed — no
family consults another's evidence, so a violation names exactly the
contract that broke:

1. ``status``    — injected transient faults may surface ONLY as
                   200/429/499/503/504.  A 500 (or a connection that
                   never answered, status 0) is a defect, full stop.
2. ``resources`` — after quiesce the books balance: every BlockPool at
                   ``used==0 ∧ free==total``, admission idle with an
                   empty queue, every lane free, every per-nonce stream
                   context closed, no new zombie threads.
3. ``metrics``   — the registry-level check_metrics_names passes hold
                   over the post-cell exposition (names, label contracts,
                   chaos point/kind coverage).
4. ``epoch``     — stale frames/tokens are COUNTED
                   (``dnet_stale_epoch_rejected_total``), never served:
                   a cell that injected zombie frames must show the
                   rejection counter move.
5. ``sse``       — every 200 stream is well-formed (one role chunk, one
                   stream id, exactly one finish_reason, terminal
                   ``[DONE]``), and a greedy faulted cell with resume
                   enabled matches its fault-free golden run modulo
                   rid/created.

The negative-control tests (tests/subsystems/test_chaos_campaign.py)
plant one defect per family — a leaked block, an unclosed stream, a
forced 500, a parity break — and assert each fires exactly where
planted; clean runs must report zero.  Same discipline as dnetlint:
an auditor is only trusted once it has caught a planted bug.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from dnet_tpu.chaos.scenarios import ResourceSnapshot

#: the status-code contract: every acceptable way a faulted request may
#: end.  500 is NEVER here; 0 (transport never answered) is not either.
ALLOWED_STATUSES = frozenset({200, 429, 499, 503, 504})

FAMILY_STATUS = "status"
FAMILY_RESOURCES = "resources"
FAMILY_METRICS = "metrics"
FAMILY_EPOCH = "epoch"
FAMILY_SSE = "sse"

FAMILIES = (
    FAMILY_STATUS, FAMILY_RESOURCES, FAMILY_METRICS, FAMILY_EPOCH,
    FAMILY_SSE,
)


@dataclass(frozen=True)
class Violation:
    family: str
    cell_id: str
    detail: str

    def as_dict(self) -> dict:
        return {
            "family": self.family, "cell": self.cell_id,
            "detail": self.detail,
        }


# ---------------------------------------------------------------------------
# family 1: status-code contract
# ---------------------------------------------------------------------------


def audit_statuses(cell_id: str, statuses: List[int]) -> List[Violation]:
    out = []
    for i, status in enumerate(statuses):
        if status not in ALLOWED_STATUSES:
            out.append(Violation(
                FAMILY_STATUS, cell_id,
                f"request {i} answered {status} "
                f"(allowed: {sorted(ALLOWED_STATUSES)})",
            ))
    return out


# ---------------------------------------------------------------------------
# family 2: resource conservation
# ---------------------------------------------------------------------------


def audit_resources(
    cell_id: str, snap: ResourceSnapshot, zombie_delta: float = 0.0
) -> List[Violation]:
    out = []
    for name, (used, free, total) in snap.pools.items():
        if used != 0 or free != total:
            out.append(Violation(
                FAMILY_RESOURCES, cell_id,
                f"block pool {name}: used={used} free={free}/{total} "
                f"after quiesce (want used=0, free=total)",
            ))
    for name, (active, queued) in snap.admission.items():
        if active != 0 or queued != 0:
            out.append(Violation(
                FAMILY_RESOURCES, cell_id,
                f"admission {name}: active={active} queued={queued} "
                f"after quiesce (want 0/0)",
            ))
    for name, (free, slots) in snap.lanes.items():
        if free != slots:
            out.append(Violation(
                FAMILY_RESOURCES, cell_id,
                f"lanes {name}: {free}/{slots} free after quiesce "
                f"(a lane leaked)",
            ))
    for name, open_streams in snap.streams.items():
        if open_streams != 0:
            out.append(Violation(
                FAMILY_RESOURCES, cell_id,
                f"stream manager {name}: {open_streams} per-nonce "
                f"stream context(s) still open after quiesce",
            ))
    if zombie_delta > 0:
        out.append(Violation(
            FAMILY_RESOURCES, cell_id,
            f"{int(zombie_delta)} zombie worker thread(s) leaked "
            f"during the cell (dnet_san_zombie_threads_total moved)",
        ))
    return out


# ---------------------------------------------------------------------------
# family 3: metrics conservation (registry-level lint passes)
# ---------------------------------------------------------------------------

#: the check_metrics_names passes that read the LIVE registry (the
#: source-scan and federation passes are file-level and run once per
#: campaign, not per cell)
_REGISTRY_PASS_NAMES = (
    "check_registry",
    "check_chaos_points",
    "check_chaos_kinds",
    "check_admission_labels",
    "check_membership_labels",
    "check_attribution_labels",
    "check_san_labels",
    "check_sched_labels",
    "check_wire_labels",
    "check_tp_labels",
    "check_request_segment_labels",
    "check_event_labels",
    "check_fleet_labels",
)


def audit_metrics(cell_id: str) -> List[Violation]:
    from dnet_tpu.analysis import metrics_checks as mc

    errors: list = []
    for pass_name in _REGISTRY_PASS_NAMES:
        getattr(mc, pass_name)(errors)
    return [Violation(FAMILY_METRICS, cell_id, e) for e in errors]


# ---------------------------------------------------------------------------
# family 4: epoch coherence
# ---------------------------------------------------------------------------


def audit_epoch(
    cell_id: str,
    point: str,
    injected: int,
    stale_delta: float,
    kind: str = "",
) -> List[Violation]:
    """Stale state must be counted, never served.  For a cell that
    injected zombie frames, every ERROR-flavored injection marks a frame
    stale — the rejection counter must have moved.  A ``delay`` at the
    same point only slows a current-epoch frame down; it is legitimately
    served, so the must-be-fenced rule does not apply.  A negative delta
    (counter reset mid-cell) is always a violation."""
    out = []
    if stale_delta < 0:
        out.append(Violation(
            FAMILY_EPOCH, cell_id,
            f"dnet_stale_epoch_rejected_total went BACKWARD by "
            f"{-stale_delta:g} during the cell",
        ))
    if (
        point == "zombie_frame" and kind != "delay"
        and injected > 0 and stale_delta <= 0
    ):
        out.append(Violation(
            FAMILY_EPOCH, cell_id,
            f"{injected} zombie frame(s) injected but "
            f"dnet_stale_epoch_rejected_total never moved — a stale "
            f"frame was admitted instead of fenced",
        ))
    return out


# ---------------------------------------------------------------------------
# family 5: SSE integrity + golden parity
# ---------------------------------------------------------------------------


def normalize_sse(raw: bytes) -> bytes:
    """Scrub the per-run request id and mint time so byte parity means
    'same tokens in the same frames', not 'same wall clock'."""
    text = raw.decode("utf-8", errors="replace")
    text = re.sub(r'"id": ?"[^"]*"', '"id": "chatcmpl-X"', text)
    text = re.sub(r'"created": ?\d+', '"created": 0', text)
    return text.encode()


def parse_sse(raw: bytes) -> Tuple[List[dict], bool]:
    """(chunks, saw_done) from one raw SSE body; malformed data lines
    raise ValueError (the caller reports the family-5 violation)."""
    chunks: List[dict] = []
    saw_done = False
    for line in raw.decode("utf-8", errors="replace").splitlines():
        line = line.strip()
        if not line.startswith("data:"):
            continue
        payload = line[len("data:"):].strip()
        if payload == "[DONE]":
            saw_done = True
            continue
        if saw_done:
            raise ValueError("data after [DONE]")
        chunks.append(json.loads(payload))
    return chunks, saw_done


def stream_content(raw: bytes) -> Tuple[str, str]:
    """(concatenated content, finish_reason) of one 200 stream."""
    chunks, _ = parse_sse(raw)
    content, finish = [], ""
    for chunk in chunks:
        for choice in chunk.get("choices") or ():
            delta = choice.get("delta") or {}
            if delta.get("content"):
                content.append(delta["content"])
            if choice.get("finish_reason"):
                finish = choice["finish_reason"]
    return "".join(content), finish


def check_stream(cell_id: str, idx: int, raw: bytes) -> List[Violation]:
    """Well-formedness of one 200 SSE body: single stream id, exactly one
    role chunk, exactly one finish_reason, terminal [DONE]."""
    out = []

    def v(detail: str) -> None:
        out.append(Violation(
            FAMILY_SSE, cell_id, f"request {idx}: {detail}"
        ))

    try:
        chunks, saw_done = parse_sse(raw)
    except (ValueError, json.JSONDecodeError) as exc:
        v(f"malformed SSE body: {exc}")
        return out
    if not chunks:
        v("200 stream carried zero chunks")
        return out
    if not saw_done:
        v("stream did not terminate with [DONE]")
    ids = {c.get("id") for c in chunks if c.get("id")}
    if len(ids) > 1:
        v(f"{len(ids)} distinct stream ids in one stream: {sorted(ids)}")
    roles = sum(
        1
        for c in chunks
        for choice in (c.get("choices") or ())
        if (choice.get("delta") or {}).get("role")
    )
    if roles != 1:
        v(f"{roles} role chunk(s) (want exactly 1)")
    finishes = sum(
        1
        for c in chunks
        for choice in (c.get("choices") or ())
        if choice.get("finish_reason")
    )
    if finishes != 1:
        v(f"{finishes} finish_reason chunk(s) (want exactly 1)")
    return out


def audit_sse(
    cell_id: str,
    results: List[Tuple[int, bytes]],
    golden: Optional[List[Tuple[int, bytes]]],
    parity: str,
) -> List[Violation]:
    """Family 5 over one cell: every 200 stream well-formed; when a
    golden run exists, every request that answered 200 in BOTH runs must
    match it — byte-identical (modulo rid/created) in ``bytes`` mode,
    same assembled content + finish_reason in ``content`` mode (fleet
    failover may re-frame chunks across the splice; the TEXT the client
    assembled must still be exact)."""
    out = []
    for idx, (status, raw) in enumerate(results):
        if status == 200:
            out.extend(check_stream(cell_id, idx, raw))
    if golden is None or parity == "none":
        return out
    for idx, (status, raw) in enumerate(results):
        if idx >= len(golden):
            break
        g_status, g_raw = golden[idx]
        if status != 200 or g_status != 200:
            continue
        if parity == "bytes":
            if normalize_sse(raw) != normalize_sse(g_raw):
                out.append(Violation(
                    FAMILY_SSE, cell_id,
                    f"request {idx}: stream bytes diverge from the "
                    f"fault-free golden run (greedy + resume must be "
                    f"byte-identical modulo rid/created)",
                ))
        else:
            try:
                got = stream_content(raw)
                want = stream_content(g_raw)
            except (ValueError, json.JSONDecodeError):
                continue  # well-formedness above already flagged it
            if got != want:
                out.append(Violation(
                    FAMILY_SSE, cell_id,
                    f"request {idx}: assembled content/finish diverges "
                    f"from golden ({got[1]!r}, {len(got[0])} chars vs "
                    f"{want[1]!r}, {len(want[0])} chars)",
                ))
    return out


# ---------------------------------------------------------------------------
# the composite per-cell audit
# ---------------------------------------------------------------------------


@dataclass
class CellEvidence:
    """Everything one campaign cell observed, handed to the auditor."""

    cell_id: str
    point: str
    kind: str = ""
    results: List[Tuple[int, bytes]] = field(default_factory=list)
    golden: Optional[List[Tuple[int, bytes]]] = None
    parity: str = "bytes"
    snapshot: Optional[ResourceSnapshot] = None
    injected: int = 0
    stale_delta: float = 0.0
    zombie_delta: float = 0.0
    check_metrics: bool = True


def audit_cell(ev: CellEvidence) -> List[Violation]:
    out: List[Violation] = []
    out.extend(
        audit_statuses(ev.cell_id, [status for status, _ in ev.results])
    )
    if ev.snapshot is not None:
        out.extend(audit_resources(ev.cell_id, ev.snapshot, ev.zombie_delta))
    if ev.check_metrics:
        out.extend(audit_metrics(ev.cell_id))
    out.extend(
        audit_epoch(ev.cell_id, ev.point, ev.injected, ev.stale_delta, ev.kind)
    )
    out.extend(audit_sse(ev.cell_id, ev.results, ev.golden, ev.parity))
    return out
