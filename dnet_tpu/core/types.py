"""Core DTOs shared across API and shard roles.

Covers the reference's core/types/messages.py (ActivationMessage, TokenResult,
StopCondition) and core/types/topology.py (LayerAssignment, TopologyInfo) with
a TPU-flavored device model: devices are keyed by (host, slice, chip) so the
solver can distinguish ICI-adjacent chips from DCN-separated hosts — the
analog of the reference's Thunderbolt-vs-LAN distinction
(src/dnet/core/types/topology.py:14-49).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np


def now_ms() -> float:
    return time.time() * 1000.0


@dataclass
class DecodingParams:
    """Per-request sampling knobs carried alongside every token injection.

    Reference: src/dnet/core/decoding/config.py:4-14.
    """

    temperature: float = 0.0
    top_p: float = 1.0
    top_k: int = 0
    min_p: float = 0.0
    repetition_penalty: float = 1.0
    # top-p/min-p/top-k may never filter below this many candidates
    # (reference: core/decoding/config.py:4-14)
    min_tokens_to_keep: int = 1
    logprobs: bool = False
    top_logprobs: int = 0
    seed: Optional[int] = None
    # OpenAI logit_bias {token_id: additive bias in [-100, 100]}: the
    # reference carries the field but never applies it
    # (src/dnet/api/models.py:70 "NOTE: unused"); here it reaches sampling
    logit_bias: Optional[Dict[int, float]] = None
    # EOS ids for SHARD-side stop checks (ring self-continuation halts on
    # them without waiting for the API); sampling itself ignores this
    stop_token_ids: tuple = ()


@dataclass
class ActivationMessage:
    """In-memory activation envelope hopping shard-to-shard.

    dtype == "tokens" marks an int32 token-id payload entering layer 0
    (embedding happens on the shard); anything else is a hidden-state tensor.
    Reference: src/dnet/core/types/messages.py:50-101.
    """

    nonce: str
    layer_id: int  # last layer already applied; -1 = raw tokens
    seq: int  # per-nonce frame sequence number
    dtype: str
    shape: tuple
    data: Any = None  # np.ndarray | jax.Array | bytes
    pos: int = 0  # absolute position of first token in this frame
    callback_url: str = ""
    decoding: DecodingParams = field(default_factory=DecodingParams)
    is_final: bool = False
    token_id: Optional[int] = None
    logprob: Optional[float] = None
    top_logprobs: Optional[list] = None
    error: str = ""
    # ring self-continuation (decode grants): how many more tokens the tail
    # shard may feed back into the ring without an API round trip, and —
    # on a final message — the (token, pos, remaining_steps, next_seq)
    # continuation the adapter should inject at the head
    auto_steps: int = 0
    cont: Optional[tuple] = None
    # ring speculation: drafts ride a widened verify block head -> tail;
    # committed tokens ride the continuation tail -> head (hist commit);
    # extra_finals [(seq, token_id), ...] are the block's additional
    # accepted tokens, delivered as separate API callbacks by the adapter
    drafts: list = field(default_factory=list)
    committed: list = field(default_factory=list)
    extra_finals: Optional[list] = None
    # batched lanes (r5): a COALESCED decode frame serving several nonces in
    # one ring pass.  `lanes` rides every hop — one {"nonce","seq","pos",
    # "decoding"} entry per member, payload rows stacked in the same order;
    # the tail's final message answers with `lane_finals` (one TokenResult-
    # shaped dict per member) which the adapter fans out as per-nonce
    # SendToken callbacks.
    lanes: list = field(default_factory=list)
    lane_finals: Optional[list] = None
    # ring prefix caching (r5): the API (which alone sees token ids) keys
    # every store/hit.  A prompt frame with `prefix_store` asks each shard
    # to snapshot its post-prefill KV under that key; one with `prefix_hit`
    # seeds the session from the shard's snapshot (the frame then carries
    # only the SUFFIX tokens at pos = the snapshot length).
    prefix_store: str = ""
    prefix_hit: str = ""
    # end-to-end request deadline (epoch seconds, 0 = none): stamped by the
    # API's admission layer, rides every hop so ShardRuntime can drop an
    # expired frame at dequeue instead of burning compute on work nobody is
    # waiting for (dnet_tpu/admission/)
    deadline: float = 0.0
    # topology epoch the frame entered under (dnet_tpu/membership/):
    # carried across hops and stamped into the final token callback so the
    # epoch fence holds end to end.  0 = unfenced.
    epoch: int = 0
    # wire pipeline rx half (transport/wire_pipeline.py): the ingress path
    # launches H2D upload + on-device dequant for a QUEUED frame and
    # stashes the resulting device array here, so the compute thread finds
    # the payload already decoded (overlapped with the previous step's
    # compute).  Process-local only — never serialized onto the wire.
    device_data: Any = None
    # profiling timestamps (perf_counter seconds), reference messages.py:28-32
    t_recv: float = 0.0
    t_enq: float = 0.0
    t_tx_enq: float = 0.0

    @property
    def is_tokens(self) -> bool:
        return self.dtype == "tokens"

    def tokens(self) -> np.ndarray:
        if not self.is_tokens:
            raise ValueError("not a token message")
        if isinstance(self.data, (bytes, memoryview)):
            return np.frombuffer(self.data, dtype=np.int32).reshape(self.shape)
        return np.asarray(self.data, dtype=np.int32).reshape(self.shape)


@dataclass
class TokenResult:
    """Sampled token returned from the end shard to the API node."""

    nonce: str
    token_id: int
    logprob: Optional[float] = None
    top_logprobs: Optional[List[tuple]] = None  # [(token_id, logprob), ...]
    step: int = 0
    error: str = ""
    # topology epoch the emitting shard held (dnet_tpu/membership/);
    # 0 = unfenced.  The API drops results minted under a dead epoch.
    epoch: int = 0


@dataclass
class StopCondition:
    max_tokens: int = 256
    stop_token_ids: tuple = ()
    stop_sequences: tuple = ()


@dataclass
class DeviceInfo:
    """A participating device as seen by discovery + the solver."""

    instance: str  # unique shard instance name
    host: str  # reachable IP/hostname
    http_port: int
    grpc_port: int
    is_manager: bool = False
    # TPU placement: chips in the same (host, slice_id) share ICI.
    slice_id: int = 0
    chip_count: int = 1
    chip_kind: str = ""
    hbm_bytes: int = 0
    host_ram_bytes: int = 0
    flops_bf16: float = 0.0  # achieved matmul FLOP/s from microbench
    hbm_bw: float = 0.0  # bytes/s
    host_to_hbm_bw: float = 0.0  # bytes/s (device_put rate)
    t_comm: float = 0.0  # median seconds to next device for solver payloads
    # intra-host interconnect bandwidth (bytes/s per ICI link): what a
    # tensor-parallel all-reduce inside this node's mesh slice pays per
    # hop.  0 = unknown — the solver then neither merges this device into
    # a mesh slice nor charges TP collective cost (today's behavior).
    ici_bw: float = 0.0

    def ici_adjacent(self, other: "DeviceInfo") -> bool:
        """ICI adjacency = same host and same slice (the reference's
        Thunderbolt-link analog, src/dnet/api/cluster.py:52)."""
        return self.host == other.host and self.slice_id == other.slice_id


@dataclass
class LayerAssignment:
    """One device's share of the ring.

    layers: flattened absolute layer ids over all k rounds (contiguous per
    round).  window_size / residency_size drive the weight-streaming policy.
    Reference: src/dnet/core/types/topology.py:14-28.
    """

    instance: str
    layers: List[int]
    rounds: List[List[int]] = field(default_factory=list)
    next_instance: str = ""
    window_size: int = 0
    residency_size: int = 0
    # host-local mesh under this ring node (parallel/shard_mesh.py): the
    # window runs tensor/sequence-parallel over the shard's local chips.
    # 0 = the shard's own DNET_SHARD_MESH_* default; 1 = single chip.
    mesh_tp: int = 0
    mesh_sp: int = 0
    # NamedSharding tensor parallelism (parallel/tp.py): set by the
    # solver's mesh-slice placement for pure-TP shards (no sp, resident
    # weights); rides the load body into shard/compute.py.  0 = unset
    # (the shard's DNET_TP default decides), 1 = pinned single-chip.
    tp_degree: int = 0

    @property
    def min_layer(self) -> int:
        return min(self.layers) if self.layers else -1


@dataclass
class TopologyInfo:
    """Solver output: the full ring plan shared API <-> shards.

    Reference: src/dnet/core/types/topology.py:30-49.
    """

    model: str
    num_layers: int
    kv_bits: int
    devices: List[DeviceInfo]
    assignments: List[LayerAssignment]
    solution: dict = field(default_factory=dict)  # solver diagnostics (k, w, n, obj)
    # membership epoch minted when the API installed this topology
    # (dnet_tpu/membership/epoch.py); 0 = never installed (manual tests)
    epoch: int = 0

    def assignment_for(self, instance: str) -> Optional[LayerAssignment]:
        for a in self.assignments:
            if a.instance == instance:
                return a
        return None

    def head_instance(self) -> str:
        """Owner of layer 0 (first hop target for token injection)."""
        for a in self.assignments:
            if 0 in a.layers:
                return a.instance
        raise ValueError("no assignment owns layer 0")

    def tail_instance(self) -> str:
        last = self.num_layers - 1
        for a in self.assignments:
            if last in a.layers:
                return a.instance
        raise ValueError("no assignment owns the last layer")
