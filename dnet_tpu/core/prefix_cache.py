"""Prefix caching: reuse KV for repeated prompt prefixes.

The reference re-feeds every prompt from scratch ("a KV cache is not
checkpointed; a full prompt re-feed happens per request", SURVEY.md §5
checkpoint/resume).  Matching is EXACT-prefix over full stored prompts, so
the win is multi-turn chat: every follow-up request resends the grown
history verbatim, hits the previous turn's snapshot, and prefills only the
new turn — O(new-suffix) instead of O(history), directly cutting TTFT.
(Two different conversations sharing only a system preamble do NOT match;
prefix checkpoints at message boundaries are a possible extension.)

Design:
- A tiny LRU of full-prompt KV snapshots, keyed by the prompt's token ids.
- Lookup returns the LONGEST cached entry that is a strict proper prefix of
  (or equal to, minus at least one token of) the new prompt, so the engine
  always has >= 1 token left to prefill (the forward pass must produce the
  last position's logits).
- Snapshots are defensive COPIES both ways: engine step functions donate
  their KV argument, so handing out (or keeping) a shared buffer would be
  invalidated by the next decode step.
- Memory: each snapshot is a full KV allocation; capacity is small and
  opt-in (DNET_API_PREFIX_CACHE).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Sequence, Tuple

import jax

from dnet_tpu.analysis.runtime import ownership as dsan
from dnet_tpu.obs import metric

# one labeled family set covers both halves of ring prefix caching: the
# API-side prompt index + LocalEngine PrefixCache (cache="prefix") and the
# shard-side SnapshotStore (cache="snapshot")
_HITS = metric("dnet_kv_cache_hits_total")
_MISSES = metric("dnet_kv_cache_misses_total")
_EVICTIONS = metric("dnet_kv_cache_evictions_total")
_STORES = metric("dnet_kv_cache_stores_total")


def _copy_tree(tree):
    return jax.tree.map(lambda a: a.copy(), tree)


class PrefixIndex:
    """Longest-strict-proper-prefix matcher + LRU over token-id tuples —
    the ONE owner of the matching invariants (>= 1 token must remain to
    prefill, so the forward pass can produce the last position's logits;
    move-to-end on hit; evict-oldest at capacity; tiny prompts skipped).
    PrefixCache stores KV snapshots in it; the ring API adapter
    (api/ring.py) stores snapshot KEYS — both sides of ring prefix
    caching thus share one matching implementation."""

    def __init__(
        self,
        capacity: int,
        min_tokens: int = 16,
        kind: str = "prefix",
        on_evict=None,
    ) -> None:
        self.capacity = capacity
        self.min_tokens = min_tokens
        self.kind = kind  # `cache` label on the hit/miss/store/evict counters
        # called with each evicted VALUE after the lock drops (the paged
        # prefix cache releases its block references here)
        self.on_evict = on_evict
        # every _entries touch happens under _lock (declared in
        # analysis/runtime/domains.py, enforced under DNET_SAN=1)
        self._lock = dsan.san_lock("PrefixIndex._lock")
        self._entries: "OrderedDict[Tuple[int, ...], object]" = (
            dsan.guard_ordered_dict(
                OrderedDict(),
                dsan.maybe_lock_domain(self._lock),
                "PrefixIndex._entries",
            )
        )

    def _match(self, ids: Tuple[int, ...], max_len: int):
        """Longest entry of length <= max_len that prefixes `ids` (caller
        holds the lock)."""
        best = None
        for key in self._entries:
            if len(key) < (best and len(best) or 1):
                continue
            if len(key) <= max_len and ids[: len(key)] == key:
                if best is None or len(key) > len(best):
                    best = key
        return best

    def lookup(self, prompt_ids: Sequence[int]) -> Optional[Tuple[int, object]]:
        """Longest entry covering at most len(prompt)-1 tokens; bumps LRU.
        Returns (n_tokens, value) or None.  Counts the hit/miss here — the
        one matcher — so no wrapper can forget to."""
        ids = tuple(prompt_ids)
        with self._lock:
            # proper prefix with at least one token left to prefill
            best = self._match(ids, len(ids) - 1)
            if best is None:
                _MISSES.labels(cache=self.kind).inc()
                return None
            self._entries.move_to_end(best)
            _HITS.labels(cache=self.kind).inc()
            return len(best), self._entries[best]

    def match_quiet(
        self, prompt_ids: Sequence[int], allow_equal: bool = True
    ) -> Optional[Tuple[int, object]]:
        """Longest-prefix match WITHOUT touching the hit/miss counters or
        the LRU order — the store-side dedup probe (a snapshot store that
        aliases its parent's blocks is not a request-path hit)."""
        ids = tuple(prompt_ids)
        with self._lock:
            best = self._match(ids, len(ids) if allow_equal else len(ids) - 1)
            if best is None:
                return None
            return len(best), self._entries[best]

    def get_exact(self, prompt_ids: Sequence[int]):
        """Exact-match value (LRU-bumped) or None."""
        ids = tuple(prompt_ids)
        with self._lock:
            if ids not in self._entries:
                return None
            self._entries.move_to_end(ids)
            return self._entries[ids]

    def put(self, prompt_ids: Sequence[int], value) -> bool:
        """Insert if absent and long enough; True iff newly stored."""
        ids = tuple(prompt_ids)
        if len(ids) < self.min_tokens:
            return False
        evicted = []
        with self._lock:
            if ids in self._entries:
                self._entries.move_to_end(ids)
                return False
            self._entries[ids] = value
            _STORES.labels(cache=self.kind).inc()
            while len(self._entries) > self.capacity:
                evicted.append(self._entries.popitem(last=False)[1])
                _EVICTIONS.labels(cache=self.kind).inc()
        if self.on_evict is not None:
            for v in evicted:
                self.on_evict(v)
        return True

    def clear(self) -> None:
        with self._lock:
            dropped = list(self._entries.values())
            self._entries.clear()
        if self.on_evict is not None:
            for v in dropped:
                self.on_evict(v)


class PrefixCache:
    def __init__(self, capacity: int, min_tokens: int = 16) -> None:
        # prompt ids -> kv snapshot (repetition counts are zero at prefill
        # end — they track generated tokens only — so KV is the whole state)
        self._index = PrefixIndex(capacity, min_tokens)
        self.stats = {"hits": 0, "misses": 0, "stores": 0}

    @property
    def min_tokens(self) -> int:
        return self._index.min_tokens

    @min_tokens.setter
    def min_tokens(self, v: int) -> None:  # tests tune it for tiny prompts
        self._index.min_tokens = v

    def lookup(self, prompt_ids: Sequence[int]) -> Optional[Tuple[int, dict]]:
        """Longest cached prefix covering at most len(prompt)-1 tokens.
        Returns (n_tokens, kv copy) or None."""
        hit = self._index.lookup(prompt_ids)  # PrefixIndex counts hit/miss
        if hit is None:
            self.stats["misses"] += 1
            return None
        self.stats["hits"] += 1
        n, kv = hit
        return n, _copy_tree(kv)

    def store(self, prompt_ids: Sequence[int], kv: dict) -> None:
        if len(prompt_ids) < self.min_tokens:
            return
        if self._index.get_exact(prompt_ids) is not None:
            return
        if self._index.put(prompt_ids, _copy_tree(kv)):  # counts the store
            self.stats["stores"] += 1

    def clear(self) -> None:
        self._index.clear()


class SnapshotStore:
    """String-keyed KV snapshot LRU — the SHARD half of ring prefix caching.

    The API node owns prefix MATCHING (it alone sees token ids; mid shards
    see only hidden states) and drives every store/hit by key through the
    activation frames; each shard keeps its own window's KV snapshot under
    that key.  Same defensive-copy rules as PrefixCache: engine step
    functions donate KV, so snapshots copy in AND out."""

    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, Tuple[int, dict]]" = OrderedDict()
        self.stats = {"hits": 0, "misses": 0, "stores": 0}

    def get(self, key: str) -> Optional[Tuple[int, dict]]:
        with self._lock:
            hit = self._entries.get(key)
            if hit is None:
                self.stats["misses"] += 1
                _MISSES.labels(cache="snapshot").inc()
                return None
            self._entries.move_to_end(key)
            self.stats["hits"] += 1
            _HITS.labels(cache="snapshot").inc()
            n, kv = hit
        return n, _copy_tree(kv)

    def put(self, key: str, pos: int, kv: dict) -> None:
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return
            self._entries[key] = (pos, _copy_tree(kv))
            self.stats["stores"] += 1
            _STORES.labels(cache="snapshot").inc()
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                _EVICTIONS.labels(cache="snapshot").inc()

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
