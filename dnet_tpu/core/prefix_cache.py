"""Prefix caching: reuse KV for repeated prompt prefixes.

The reference re-feeds every prompt from scratch ("a KV cache is not
checkpointed; a full prompt re-feed happens per request", SURVEY.md §5
checkpoint/resume).  Matching is EXACT-prefix over full stored prompts, so
the win is multi-turn chat: every follow-up request resends the grown
history verbatim, hits the previous turn's snapshot, and prefills only the
new turn — O(new-suffix) instead of O(history), directly cutting TTFT.
(Two different conversations sharing only a system preamble do NOT match;
prefix checkpoints at message boundaries are a possible extension.)

Design:
- A tiny LRU of full-prompt KV snapshots, keyed by the prompt's token ids.
- Lookup returns the LONGEST cached entry that is a strict proper prefix of
  (or equal to, minus at least one token of) the new prompt, so the engine
  always has >= 1 token left to prefill (the forward pass must produce the
  last position's logits).
- Snapshots are defensive COPIES both ways: engine step functions donate
  their KV argument, so handing out (or keeping) a shared buffer would be
  invalidated by the next decode step.
- Memory: each snapshot is a full KV allocation; capacity is small and
  opt-in (DNET_API_PREFIX_CACHE).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Sequence, Tuple

import jax


def _copy_tree(tree):
    return jax.tree.map(lambda a: a.copy(), tree)


class PrefixCache:
    def __init__(self, capacity: int, min_tokens: int = 16) -> None:
        self.capacity = capacity
        self.min_tokens = min_tokens  # tiny prompts aren't worth a snapshot
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple[int, ...], dict]" = OrderedDict()
        # prompt ids -> kv snapshot (repetition counts are zero at prefill
        # end — they track generated tokens only — so KV is the whole state)
        self.stats = {"hits": 0, "misses": 0, "stores": 0}

    def lookup(self, prompt_ids: Sequence[int]) -> Optional[Tuple[int, dict]]:
        """Longest cached prefix covering at most len(prompt)-1 tokens.
        Returns (n_tokens, kv copy) or None."""
        ids = tuple(prompt_ids)
        with self._lock:
            best = None
            for key in self._entries:
                if len(key) < (best and len(best) or 1):
                    continue
                # proper prefix with at least one token left to prefill
                if len(key) <= len(ids) - 1 and ids[: len(key)] == key:
                    if best is None or len(key) > len(best):
                        best = key
            if best is None:
                self.stats["misses"] += 1
                return None
            kv = self._entries[best]
            self._entries.move_to_end(best)
            self.stats["hits"] += 1
        return len(best), _copy_tree(kv)

    def store(self, prompt_ids: Sequence[int], kv: dict) -> None:
        ids = tuple(prompt_ids)
        if len(ids) < self.min_tokens:
            return
        with self._lock:
            if ids in self._entries:
                self._entries.move_to_end(ids)
                return
            self._entries[ids] = _copy_tree(kv)
            self.stats["stores"] += 1
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
