"""Weight streaming: host-DRAM layer store + windowed HBM residency.

The TPU translation of the reference's no-memory-ceiling subsystem
(SURVEY.md §2.1): Apple-UMA disk<->GPU swapping becomes host-DRAM<->HBM
`jax.device_put` streaming.

- HostLayerStore  ≙ utils/model.py + utils/repack.py: lazy mmap-backed
  per-layer host params (model-mapped, pre-transposed), with an optional
  on-disk repack cache keyed by model + layer-set hash (repack.py:175-217)
  so restarts skip the transpose work.
- WeightCache     ≙ core/memory/weight_cache.py: bounded HBM residency
  (max_resident layers), thread-safe load-once via per-layer Futures
  (weight_cache.py:69-196), ref-counted pin/release, LRU eviction of
  unpinned layers (235-259), async prefetch on a thread pool overlapping
  compute (offload.py:395-421).
- plan_policy     ≙ shard/policies/__init__.py:20-65 thresholds.
"""

from __future__ import annotations

import hashlib
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from dnet_tpu.utils.logger import get_logger

log = get_logger()


# ---- policy planning -------------------------------------------------------


@dataclass(frozen=True)
class PolicyPlan:
    name: str  # "fit" | "offload" | "sliding_fit"
    window_size: int
    residency: int  # max layers resident in HBM

    @property
    def streams_weights(self) -> bool:
        return self.name != "fit"


def plan_policy(
    local_count: int, window_size: int = 0, residency_size: int = 0
) -> PolicyPlan:
    """Reference thresholds (policies/__init__.py:20-65):
    residency < window        -> sliding_fit (evict inside the window)
    window >= local layers    -> fit (everything resident)
    else                      -> offload (window-at-a-time streaming)
    """
    w = window_size or local_count
    n = residency_size or local_count
    if w >= local_count and n >= local_count:
        return PolicyPlan("fit", local_count, local_count)
    if n < w:
        return PolicyPlan("sliding_fit", w, max(n, 1))
    return PolicyPlan("offload", w, min(max(n, w), local_count))


# ---- host store ------------------------------------------------------------


class HostLayerStore:
    """Model-mapped per-layer host params, lazily materialized.

    Repack cache: mapped (renamed + transposed + dtype-cast) layers are
    written once as .npz under
      <cache_dir>/<model-tag>/<sha1(layers)[:10]>/layer_<i>.npz
    and mmap-loaded on later runs (reference repack.py:98-217).
    """

    def __init__(
        self,
        ckpt,
        model,
        param_dtype: str = "bfloat16",
        repack_dir: Optional[str | Path] = None,
        weight_quant_bits: int = 0,
        weight_quant_group: int = 0,
    ) -> None:
        self.ckpt = ckpt
        self.model = model
        self.param_dtype = np.dtype(
            __import__("ml_dtypes").bfloat16 if param_dtype == "bfloat16" else param_dtype
        )
        self.weight_quant_bits = weight_quant_bits
        self.weight_quant_group = weight_quant_group
        self._cache: Dict[int, Dict[str, np.ndarray]] = {}
        self._lock = threading.Lock()
        self.repack_path: Optional[Path] = None
        if repack_dir is not None:
            tag = Path(ckpt.dir).name
            key = hashlib.sha1(
                f"v3:{param_dtype}:wq{weight_quant_bits}g{weight_quant_group}:"
                f"{','.join(map(str, model.layers))}".encode()
            ).hexdigest()[:10]
            self.repack_path = Path(repack_dir).expanduser() / tag / key
            self.repack_path.mkdir(parents=True, exist_ok=True)

    def _cast(self, tree: Dict[str, object]) -> Dict[str, object]:
        out: Dict[str, object] = {}
        for k, v in tree.items():
            if isinstance(v, dict):  # quantized leaf: q stays int, s is typed
                out[k] = self._cast(v)
            elif np.issubdtype(v.dtype, np.floating) and v.dtype != self.param_dtype:
                out[k] = v.astype(self.param_dtype)
            else:
                out[k] = v
        return out

    def layer_host(self, layer: int):
        """ONE layer's host params shaped as a single-layer window pytree
        (model.wrap_offload_layer), ready for device placement."""
        with self._lock:
            if layer in self._cache:
                return self._cache[layer]
        params = self.model.wrap_offload_layer(self._load_layer_flat(layer))
        with self._lock:
            self._cache[layer] = params
        return params

    def _load_layer_flat(self, layer: int) -> Dict[str, np.ndarray]:
        if self.repack_path is not None:
            f = self.repack_path / f"layer_{layer}.npz"
            if f.is_file():
                z = np.load(f)
                return _unflatten({k: _bf16_view(z[k]) for k in z.files})
        t0 = time.perf_counter()
        mapped = self.model.map_layer(self.ckpt.load_layer_raw(layer))
        if self.weight_quant_bits:
            # quantize the RAW checkpoint values (before any lossy cast) so
            # fit and offload policies serve bit-identical quantized weights
            from dnet_tpu.ops.quant import quantize_tree

            mapped = quantize_tree(
                mapped,
                self.model.quant_keys,
                scale_dtype=self.param_dtype,
                bits=self.weight_quant_bits,
                group_size=self.weight_quant_group,
            )
        mapped = self._cast(mapped)
        log.info(
            "[PROFILE] host-load layer %d in %.1fms", layer, (time.perf_counter() - t0) * 1e3
        )
        if self.repack_path is not None:
            f = self.repack_path / f"layer_{layer}.npz"
            tmp = f.with_suffix(".tmp.npz")
            # bf16 is not npz-native; save raw bytes views.  Quantized leaf
            # dicts flatten to "name::q" / "name::s" entries.
            flat = _flatten(mapped)
            np.savez(tmp, **{k: v.view(np.uint16) if v.dtype == np.dtype("bfloat16") else v for k, v in flat.items()})
            tmp.rename(f)
        return mapped

    def prefetch_disk(self, layers: Sequence[int]) -> None:
        """Kick native page-cache readahead for layers about to materialize
        (disk->DRAM half of the prefetch; host->HBM is WeightCache's).
        Repacked layers read from .npz instead — skip those spans."""
        ckpt = self.ckpt
        if ckpt is None or not hasattr(ckpt, "prefetch_layer"):
            return
        for layer in layers:
            with self._lock:
                if layer in self._cache:
                    continue
            if (
                self.repack_path is not None
                and (self.repack_path / f"layer_{layer}.npz").is_file()
            ):
                continue
            ckpt.prefetch_layer(layer)

    def drop_host(self, layer: int) -> None:
        with self._lock:
            self._cache.pop(layer, None)
        # evicted spans can leave the page cache too (re-faultable); repacked
        # layers never touched the safetensors map, nothing to release
        ckpt = self.ckpt
        if (
            ckpt is not None
            and hasattr(ckpt, "release_layer")
            and not (
                self.repack_path is not None
                and (self.repack_path / f"layer_{layer}.npz").is_file()
            )
        ):
            ckpt.release_layer(layer)


# ---- HBM weight cache -------------------------------------------------------


class WeightCache:
    """Bounded HBM residency with load-once futures + LRU eviction."""

    def __init__(
        self,
        store: HostLayerStore,
        max_resident: int,
        prefetch_workers: int = 2,
        device=None,
        put_fn=None,
    ) -> None:
        self.store = store
        self.max_resident = max_resident
        self.device = device
        # custom host->device placement (host pytree -> device pytree):
        # mesh-backed shards stream each layer as tp/sp-SHARDED device_puts
        # (parallel/shard_mesh.py) instead of whole-layer single-chip copies
        self.put_fn = put_fn
        self._lock = threading.Lock()
        self._futures: Dict[int, Future] = {}  # layer -> Future[device params]
        self._resident: Dict[int, dict] = {}  # layer -> device params
        self._refs: Dict[int, int] = {}
        self._last_used: Dict[int, float] = {}
        self._pool = ThreadPoolExecutor(
            max_workers=prefetch_workers, thread_name_prefix="prefetch"
        )
        self.stats = {"loads": 0, "hits": 0, "evictions": 0}

    # -- internal ------------------------------------------------------------
    def _load_to_device(self, layer: int) -> dict:
        host = self.store.layer_host(layer)
        t0 = time.perf_counter()
        if self.put_fn is not None:
            dev = self.put_fn(jax.tree.map(_bf16_view, host))
        else:
            dev = jax.tree.map(
                lambda v: jax.device_put(_bf16_view(v), self.device), host
            )
        jax.block_until_ready(dev)  # dnetlint: disable=DL005 load-time weight-upload fence, not on the decode path
        log.info(
            "[PROFILE] HBM-load layer %d in %.1fms", layer, (time.perf_counter() - t0) * 1e3
        )
        return dev

    def _ensure_future(self, layer: int) -> Future:
        """Caller must hold the lock. Dedups concurrent loads via one Future
        per layer (reference weight_cache.py:89-104)."""
        fut = self._futures.get(layer)
        if fut is None:
            fut = self._pool.submit(self._load_to_device, layer)
            self._futures[layer] = fut
            self.stats["loads"] += 1
        return fut

    def _evict_to_budget(self, incoming: int = 1) -> None:
        """Caller must hold the lock. Evict LRU unpinned layers until the
        incoming load fits the residency budget."""
        while len(self._resident) + incoming > self.max_resident:
            candidates = [
                (self._last_used.get(l, 0.0), l)
                for l in self._resident
                if self._refs.get(l, 0) == 0
            ]
            if not candidates:
                return  # everything pinned; caller may exceed budget briefly
            _, victim = min(candidates)
            del self._resident[victim]
            self._refs.pop(victim, None)
            self._last_used.pop(victim, None)
            self.stats["evictions"] += 1

    # -- public --------------------------------------------------------------
    def prefetch(self, layers: Sequence[int]) -> None:
        """Schedule async host->HBM loads (no waiting)."""
        # start disk->page-cache readahead for the whole window first: the
        # executor materializes layers one at a time, the native worker
        # pulls the later ones off disk concurrently
        if hasattr(self.store, "prefetch_disk"):
            self.store.prefetch_disk(layers)
        with self._lock:
            for layer in layers:
                if layer not in self._resident:
                    self._ensure_future(layer)

    def get(self, layer: int, pin: bool = True) -> dict:
        """Blocking: returns device params, loading if needed; pins by ref."""
        with self._lock:
            if layer in self._resident:
                self.stats["hits"] += 1
                if pin:
                    self._refs[layer] = self._refs.get(layer, 0) + 1
                self._last_used[layer] = time.monotonic()
                return self._resident[layer]
            fut = self._ensure_future(layer)
        try:
            dev = fut.result()  # outside the lock: others can proceed
        except Exception:
            # drop the failed future so a retry can load fresh (a cached
            # failure would poison the layer forever)
            with self._lock:
                if self._futures.get(layer) is fut:
                    self._futures.pop(layer, None)
            raise
        with self._lock:
            if layer not in self._resident:
                self._evict_to_budget(incoming=1)
                self._resident[layer] = dev
            self._futures.pop(layer, None)
            if pin:
                self._refs[layer] = self._refs.get(layer, 0) + 1
            self._last_used[layer] = time.monotonic()
            return self._resident[layer]

    def release(self, layers: Sequence[int]) -> None:
        with self._lock:
            for layer in layers:
                if self._refs.get(layer, 0) > 0:
                    self._refs[layer] -= 1

    def evict(self, layers: Sequence[int]) -> None:
        """Proactive eviction of unpinned layers (reference 261-290)."""
        with self._lock:
            for layer in layers:
                if self._refs.get(layer, 0) == 0:
                    self._resident.pop(layer, None)
                    self._last_used.pop(layer, None)

    def resident_layers(self) -> List[int]:
        with self._lock:
            return sorted(self._resident)

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
        with self._lock:
            self._resident.clear()
            self._futures.clear()
            self._refs.clear()


def _bf16_view(v: np.ndarray) -> np.ndarray:
    """npz repack stores bf16 as uint16; view back when shapes match."""
    if v.dtype == np.uint16:
        import ml_dtypes

        return v.view(ml_dtypes.bfloat16)
    return v


def _flatten(tree: Dict[str, object]) -> Dict[str, np.ndarray]:
    """One-level nesting ({"wq": {"q": ..., "s": ...}}) -> "wq::q" keys."""
    flat: Dict[str, np.ndarray] = {}
    for k, v in tree.items():
        if isinstance(v, dict):
            for k2, v2 in v.items():
                flat[f"{k}::{k2}"] = v2
        else:
            flat[k] = v
    return flat


def _unflatten(flat: Dict[str, np.ndarray]) -> Dict[str, object]:
    out: Dict[str, object] = {}
    for k, v in flat.items():
        if "::" in k:
            k1, _, k2 = k.partition("::")
            out.setdefault(k1, {})[k2] = v
        else:
            out[k] = v
    return out
