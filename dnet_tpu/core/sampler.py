"""On-device token sampling: temperature / top-k / top-p / min-p + logprobs.

One jitted function serves every request: all decoding knobs are traced
scalars (not static args), so changing temperature or top_p never recompiles
— the fix for the reference's "end-shard sampling under jit" hard part
(SURVEY.md §7).  Greedy vs stochastic is a `jnp.where` select, top-k with a
*traced* k uses a rank threshold over a single descending sort shared by all
filters.  Functionality mirrors the reference's mlx_lm-based Sampler
(src/dnet/core/decoding/sampler.py:14-65).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from dnet_tpu.core.types import DecodingParams

MAX_TOP_LOGPROBS = 20  # static upper bound (OpenAI API max); request slices host-side
# static per-request logit_bias capacity — the full OpenAI API cap (300
# keys), so no valid client request is rejected; the scatter cost scales
# with this width but stays trivial next to a vocab-sized logits row
MAX_LOGIT_BIAS = 300


def encode_logit_bias(bias) -> tuple:
    """dict {token_id: bias} -> fixed-width (ids [MAX], vals [MAX]) numpy
    arrays, id -1 padding (scattered with mode=drop).  None = no bias."""
    import numpy as np

    ids = np.full((MAX_LOGIT_BIAS,), -1, dtype=np.int32)
    vals = np.zeros((MAX_LOGIT_BIAS,), dtype=np.float32)
    if bias:
        if len(bias) > MAX_LOGIT_BIAS:
            raise ValueError(
                f"logit_bias supports at most {MAX_LOGIT_BIAS} entries; "
                f"got {len(bias)}"
            )
        for i, (t, b) in enumerate(sorted(bias.items())):
            ids[i] = int(t)
            vals[i] = float(b)
    return ids, vals


class SampleParams(NamedTuple):
    """Traced sampling knobs (all jnp scalars inside jit)."""

    temperature: jnp.ndarray
    top_p: jnp.ndarray
    top_k: jnp.ndarray  # int32; 0 disables
    min_p: jnp.ndarray
    repetition_penalty: jnp.ndarray  # 1.0 disables
    # filters may never shrink the candidate set below this many tokens
    # (reference: min_tokens_to_keep, core/decoding/config.py:4-14, passed
    # through make_sampler); 1 = only the argmax is guaranteed
    min_tokens_to_keep: jnp.ndarray  # int32
    # OpenAI logit_bias: fixed-width (ids, additive values); -1 ids drop.
    # The reference carries the field in its DecodingConfig but never
    # applies it (src/dnet/api/models.py:70 "NOTE: unused") — here it bites.
    bias_ids: jnp.ndarray  # [MAX_LOGIT_BIAS] int32
    bias_vals: jnp.ndarray  # [MAX_LOGIT_BIAS] f32

    @classmethod
    def from_decoding(cls, d: DecodingParams) -> "SampleParams":
        ids, vals = encode_logit_bias(getattr(d, "logit_bias", None))
        return cls(
            temperature=jnp.float32(d.temperature),
            top_p=jnp.float32(d.top_p),
            top_k=jnp.int32(d.top_k),
            min_p=jnp.float32(d.min_p),
            repetition_penalty=jnp.float32(d.repetition_penalty),
            min_tokens_to_keep=jnp.int32(d.min_tokens_to_keep),
            bias_ids=jnp.asarray(ids),
            bias_vals=jnp.asarray(vals),
        )


class SamplePlan(NamedTuple):
    """STATIC sampling shape, derived host-side from DecodingParams.

    The traced-knob design (SampleParams) means one program serves every
    request — but it also means every decode step pays for machinery most
    requests never use: three full-vocab sorts for the top-k/p filters and a
    log_softmax + top_k(20) for logprobs cost ~4ms/step at V=128k on v5e,
    comparable to a whole 1B-model forward.  The plan collapses the unused
    machinery at trace time; the handful of plan combinations bound the
    number of compiled variants, and knobs *within* a plan stay traced (a
    temperature change still never recompiles).
    """

    greedy: bool  # temperature <= 0: token = argmax, no sampling machinery
    filters: bool  # any of top_p < 1 / top_k > 0 / min_p > 0 active
    logprobs: bool  # request wants logprob + top-logprob outputs
    penalty: bool  # repetition_penalty != 1
    bias: bool = False  # logit_bias present: scatter-add before everything

    @classmethod
    def from_decoding(cls, d: DecodingParams) -> "SamplePlan":
        return cls(
            greedy=d.temperature <= 0.0,
            filters=(d.top_p < 1.0) or (d.top_k > 0) or (d.min_p > 0.0),
            logprobs=bool(d.logprobs),
            penalty=d.repetition_penalty != 1.0,
            bias=bool(getattr(d, "logit_bias", None)),
        )


# the everything-on plan: default for callers that keep all knobs traced
# (bias included: its ids default to -1 = dropped, so unbiased requests
# through FULL_PLAN still sample identically)
FULL_PLAN = SamplePlan(
    greedy=False, filters=True, logprobs=True, penalty=True, bias=True
)


class SampleResult(NamedTuple):
    token: jnp.ndarray  # [B] int32
    logprob: jnp.ndarray  # [B] f32, log-softmax of raw logits at token
    top_tokens: jnp.ndarray  # [B, MAX_TOP_LOGPROBS] int32
    top_logprobs: jnp.ndarray  # [B, MAX_TOP_LOGPROBS] f32


def pack_chunk_results(results: SampleResult, with_logprobs: bool) -> jnp.ndarray:
    """Pack a scanned SampleResult ([K, B, ...] leaves) into ONE f32 array
    for a single device->host transfer per decode chunk (token ids are exact
    in f32 for V < 2**24).  Shared by LocalEngine's decode_chunk and the
    mesh ring chunk program (parallel/ring.py)."""
    if with_logprobs:
        return jnp.concatenate(
            [
                results.token[..., None].astype(jnp.float32),
                results.logprob[..., None],
                results.top_tokens.astype(jnp.float32),
                results.top_logprobs,
            ],
            axis=-1,
        )
    return results.token[..., None].astype(jnp.float32)


def sample(
    logits: jnp.ndarray,
    params: SampleParams,
    key: jax.Array,
    token_counts: Optional[jnp.ndarray] = None,
    plan: Optional[SamplePlan] = None,
) -> SampleResult:
    """logits [B, V] -> sampled tokens with logprobs.

    Filter semantics (matching mlx_lm's make_sampler composition used by the
    reference): repetition penalty over seen tokens, scale by temperature,
    keep top-k, keep smallest prefix with cumulative prob >= top_p, drop
    tokens below min_p * p_max, sample.  temperature == 0 -> greedy argmax.

    `plan` statically skips machinery a request doesn't use (see SamplePlan);
    the default FULL_PLAN preserves the everything-traced behavior.  Fields
    a plan disables come back as zeros (shapes are stable across plans).
    """
    if plan is None:
        plan = FULL_PLAN
    if plan.bias:
        # additive logit_bias before every other knob: greedy argmax,
        # filters, and reported logprobs all see the biased distribution
        # (OpenAI semantics).  Padded (-1) AND out-of-vocab ids scatter a
        # zero — jax would otherwise wrap/clip them onto real vocab rows
        # and silently force/ban an unrelated token.
        V = logits.shape[-1]
        in_vocab = (params.bias_ids >= 0) & (params.bias_ids < V)
        vals = jnp.where(in_vocab, params.bias_vals, 0.0)
        ids = jnp.clip(params.bias_ids, 0, V - 1)
        logits = logits.astype(jnp.float32).at[:, ids].add(vals)
    if plan.penalty and token_counts is not None:
        logits = apply_repetition_penalty(
            logits, token_counts, params.repetition_penalty
        )
    B, V = logits.shape

    if plan.greedy:
        token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        temp = jnp.maximum(params.temperature, 1e-6)
        scaled = logits.astype(jnp.float32) / temp
        if plan.filters:
            # One descending sort powers top-k, top-p and min-p.
            sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]  # [B, V] desc
            ranks = jnp.argsort(jnp.argsort(scaled, axis=-1)[:, ::-1], axis=-1)

            # top-k: keep ranks < k (k==0 -> keep all)
            k = jnp.where(params.top_k > 0, params.top_k, V)
            keep_topk = ranks < k

            # top-p over the sorted distribution: keep the smallest prefix
            # with cumsum >= top_p (always keep rank 0).
            sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
            cumprobs = jnp.cumsum(sorted_probs, axis=-1)
            prefix_keep_sorted = (cumprobs - sorted_probs) < params.top_p
            keep_topp = jnp.take_along_axis(prefix_keep_sorted, ranks, axis=-1)

            # min-p: probability >= min_p * max prob
            probs = jax.nn.softmax(scaled, axis=-1)
            pmax = jnp.max(probs, axis=-1, keepdims=True)
            keep_minp = probs >= params.min_p * pmax

            keep = keep_topk & keep_topp & keep_minp
            # never mask below min_tokens_to_keep candidates (>= 1: the
            # argmax always survives)
            keep = keep | (ranks < jnp.maximum(params.min_tokens_to_keep, 1))
            masked = jnp.where(keep, scaled, -jnp.inf)
        else:
            masked = scaled

        gumbel = jax.random.gumbel(key, masked.shape, dtype=jnp.float32)
        stochastic = jnp.argmax(masked + gumbel, axis=-1)
        greedy = jnp.argmax(logits, axis=-1)
        token = jnp.where(params.temperature <= 0.0, greedy, stochastic).astype(jnp.int32)

    if plan.logprobs:
        raw_logprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        logprob = jnp.take_along_axis(raw_logprobs, token[:, None], axis=-1)[:, 0]
        n_top = min(MAX_TOP_LOGPROBS, V)
        top_lp, top_ids = jax.lax.top_k(raw_logprobs, n_top)
        if n_top < MAX_TOP_LOGPROBS:  # tiny-vocab tests: pad to the static width
            pad = MAX_TOP_LOGPROBS - n_top
            top_lp = jnp.pad(top_lp, ((0, 0), (0, pad)), constant_values=-jnp.inf)
            top_ids = jnp.pad(top_ids, ((0, 0), (0, pad)))
        top_ids = top_ids.astype(jnp.int32)
    else:
        logprob = jnp.zeros((B,), jnp.float32)
        top_ids = jnp.zeros((B, MAX_TOP_LOGPROBS), jnp.int32)
        top_lp = jnp.zeros((B, MAX_TOP_LOGPROBS), jnp.float32)
    return SampleResult(token, logprob, top_ids, top_lp)


@partial(jax.jit, static_argnames=())
def sample_jit(logits: jnp.ndarray, params: SampleParams, key: jax.Array) -> SampleResult:
    return sample(logits, params, key)


def apply_repetition_penalty(
    logits: jnp.ndarray, token_counts: jnp.ndarray, penalty: jnp.ndarray
) -> jnp.ndarray:
    """CTRL-style repetition penalty from a per-vocab count buffer.

    token_counts: [B, V] int32 counts of generated/context tokens.
    penalty 1.0 = disabled.
    """
    seen = token_counts > 0
    lf = logits.astype(jnp.float32)
    penalized = jnp.where(lf > 0, lf / penalty, lf * penalty)
    return jnp.where(seen, penalized, lf).astype(logits.dtype)
