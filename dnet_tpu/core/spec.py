"""Prompt-lookup speculative decoding (greedy-exact, device-side drafting.

A serving optimization beyond the reference (its roadmap lists only
throughput/long-context items, README.md:51-53): decode normally reads every
weight once per token; here each verify step reads the weights once for
L+1 positions (1 committed token + L drafts), so accepted drafts multiply
tokens-per-weight-read — decode stays HBM-bound, the extra positions ride
along nearly free on the MXU.

Drafting is n-gram prompt-lookup (no draft model): the last `n` committed
tokens are matched against the session's own token history (prompt +
generated so far, device-resident); the tokens that followed the most
recent earlier occurrence become the draft.  Verification is one forward
over [tok, d_1..d_L]: position i's greedy argmax must equal d_{i+1} for the
draft to extend the accepted prefix.  Greedy equivalence is exact — every
emitted token is an argmax of the same logits plain decode would compute.

KV rewind safety: accepted count is known only after the forward, so all
L+1 positions write KV; rejected rows are simply left stale.  With a
max_seq slot-addressed cache and causal masking against the rewound `pos`,
stale rows are never attended and are overwritten when decode reaches their
slot.  Rotating (ring-buffer SWA) caches break this invariant — wrap-around
writes evict live rows — so engines only enable speculation on
non-rotating cache layouts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def ngram_draft(
    hist: jnp.ndarray,  # [B, S] committed token ids (prompt + generated)
    pos: jnp.ndarray,  # scalar int32: tokens committed so far (hist[:, :pos] valid)
    lookahead: int,
    ngram: int = 2,
) -> jnp.ndarray:
    """Draft `lookahead` tokens per lane by matching the trailing `ngram`.

    Finds the most recent j < pos-ngram with
    hist[:, j:j+ngram] == hist[:, pos-ngram:pos] and proposes
    hist[:, j+ngram : j+ngram+lookahead].  No match (or a too-short history)
    degrades to repeating the last committed token — wrong drafts cost
    nothing beyond the verify positions that were already being computed.
    Static shapes throughout: windows are compared over the full buffer and
    invalidated by masks, so the op jits once per (S, lookahead, ngram).
    """
    B, S = hist.shape
    key = jax.lax.dynamic_slice_in_dim(hist, pos - ngram, ngram, axis=1)  # [B, n]
    idx = jnp.arange(S)
    # windows[:, j] == hist[:, j:j+ngram] compared against the key
    match = jnp.ones((B, S), dtype=bool)
    for k in range(ngram):
        shifted = jnp.roll(hist, -k, axis=1)  # hist[:, j+k] at column j
        match &= shifted == key[:, k : k + 1]
    # a candidate j must be a complete window strictly before the key itself
    valid = (idx[None, :] + ngram) <= (pos - ngram)
    match &= valid
    score = jnp.where(match, idx[None, :] + 1, 0)  # latest match wins
    j = jnp.argmax(score, axis=1)  # [B]
    found = jnp.take_along_axis(score, j[:, None], axis=1)[:, 0] > 0
    start = jnp.where(found, j + ngram, 0)

    def take(h, s):  # [S], scalar -> [lookahead]
        return jax.lax.dynamic_slice_in_dim(h, s, lookahead, axis=0)

    cont = jax.vmap(take)(hist, start)  # [B, lookahead]
    last = jax.lax.dynamic_slice_in_dim(hist, pos - 1, 1, axis=1)  # [B, 1]
    fallback = jnp.broadcast_to(last, (B, lookahead))
    # continuation windows that run past `pos` read committed-or-stale ids;
    # they are still legal token ids and merely risk rejection
    return jnp.where(found[:, None], cont, fallback)


def ngram_draft_np(hist, pos: int, lookahead: int, ngram: int = 2):
    """Host-side single-lane prompt-lookup draft (numpy), used by the gRPC
    ring's HEAD shard where the history lives host-side: same semantics as
    `ngram_draft` — most recent earlier occurrence of the trailing `ngram`,
    propose what followed; no match degrades to repeating the last token."""
    import numpy as np

    hist = np.asarray(hist)
    if pos < ngram + 1:
        return np.full(lookahead, int(hist[max(pos - 1, 0)]), dtype=np.int64)
    key = hist[pos - ngram : pos]
    best = -1
    # candidate windows must END at or before the key starts (j + ngram <=
    # pos - ngram), matching the device version's validity mask exactly
    for j in range(pos - 2 * ngram, -1, -1):  # latest match wins
        if np.array_equal(hist[j : j + ngram], key):
            best = j
            break
    if best < 0:
        return np.full(lookahead, int(hist[pos - 1]), dtype=np.int64)
    start = best + ngram
    cont = hist[start : start + lookahead]
    if len(cont) < lookahead:
        cont = np.concatenate(
            [cont, np.full(lookahead - len(cont), int(hist[pos - 1]))]
        )
    return cont.astype(np.int64)


def accept_drafts(preds: jnp.ndarray, drafts: jnp.ndarray):
    """Greedy acceptance: how far do the model's own argmaxes agree?

    preds  [B, L+1]: argmax at each verified position (position 0 is the
                     committed token's next-token prediction).
    drafts [B, L]:   the proposed continuation.
    Returns (n_accept [B], out_tokens [B, L+1]): n_accept = a means
    positions 0..a of `preds` are emitted (a+1 tokens: the a accepted
    drafts each confirmed by preds[:i]==drafts[:i], plus the first
    disagreeing/bonus prediction).  out_tokens[:, i] is -1 beyond a.
    """
    B, L1 = preds.shape
    L = L1 - 1
    agree = preds[:, :L] == drafts  # [B, L]
    n_accept = jnp.argmin(
        jnp.concatenate([agree, jnp.zeros((B, 1), bool)], axis=1).astype(jnp.int32),
        axis=1,
    )  # first False index == count of leading Trues (works for all-True via sentinel)
    emit = jnp.arange(L1)[None, :] <= n_accept[:, None]
    out = jnp.where(emit, preds, -1)
    return n_accept, out


def commit_history(
    hist: jnp.ndarray, pos: jnp.ndarray, tokens: jnp.ndarray, n_valid: jnp.ndarray
) -> jnp.ndarray:
    """Write `tokens[:, :n_valid]` at hist[:, pos:] (static-width write of
    the full token block; columns past n_valid carry stale/-1 values that
    the NEXT write overwrites because pos only advances by n_valid).
    Clamps at the buffer end like the KV cache's slot writes."""
    B, W = tokens.shape
    safe = jnp.where(tokens < 0, 0, tokens)

    def put(h, t):
        return jax.lax.dynamic_update_slice_in_dim(h, t, pos, axis=0)

    return jax.vmap(put)(hist, safe)


def make_spec_step(model, window_pass, L: int):
    """Shared speculative verify-block body (LocalEngine and the mesh-shard
    engine differ ONLY in how the window pass executes): commit the fed
    token, draft L tokens by prompt-lookup, verify in one (L+1)-wide
    forward through `window_pass(window_params, x, kv, pos, t_real)`, and
    return accept_drafts' sentinel-packed output.  One owner of the
    commit/draft/verify contract — engines jit the returned fn with their
    own donation choices."""

    def spec_step_fn(window_params, edge_params, tok, hist, kv, pos):
        hist = commit_history(hist, pos, tok, jnp.int32(1))
        drafts = ngram_draft(hist, pos + 1, L)  # [B, L]
        hist = commit_history(hist, pos + 1, drafts, jnp.int32(L))
        block = jnp.concatenate([tok, drafts], axis=1)  # [B, L+1]
        x = model.embed(edge_params, block)
        x, kv = window_pass(window_params, x, kv, pos, L + 1)
        x = model.normalize(edge_params, x)
        logits = model.lm_project(edge_params, x)  # [B, L+1, V]
        preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # n_accept is recoverable host-side from out's -1 sentinel (preds
        # are argmaxes, always >= 0), so only `out` crosses device->host
        _, out = accept_drafts(preds, drafts)
        return out, hist, kv

    return spec_step_fn
