"""Continuous batching: N session slots share one batched decode program.

The reference serves one in-flight sequence per nonce and leaves batching
absent (SURVEY.md §2.8 "Speculative / batching schedulers: absent");
`max_concurrent_requests` merely interleaves requests through one
single-sequence engine.  On TPU, batch-1 decode is weight-bound — the MXU
reads every weight to produce ONE token — so lanes 2..N of a batched matmul
are nearly free.  This engine turns concurrency into throughput:

- A fixed pool of `slots` KV-cache rows ([L, slots, S, ...]) serves all
  active requests; a request owns one slot from prefill to EOS.
- The decode step is `jax.vmap` of the SAME single-example forward+sample
  the LocalEngine uses (per-slot pos / sampling params / RNG key / active
  flag), jitted once — adding or finishing requests never recompiles.
- Inactive lanes compute garbage that is discarded: their `active=False`
  flag gates the KV write (kv_commit) and the repetition-count update, so
  slot state cannot be corrupted.  This trades a constant slot's worth of
  (weight-bound, ~free) FLOPs for a completely static program shape.
- Prefill runs per-request on the LocalEngine's B=1 bucket programs, then
  the session's KV row is inserted into the batched cache.

Per-slot sampling params are traced vectors, so mixed temperatures /
top-p's batch together (same property as core/sampler.py's traced scalars).
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dnet_tpu.core.engine import LocalEngine
from dnet_tpu.core.sampler import (
    MAX_LOGIT_BIAS,
    MAX_TOP_LOGPROBS,
    SampleParams,
    SampleResult,
    encode_logit_bias,
    sample,
)
from dnet_tpu.core.types import DecodingParams
from dnet_tpu.kv import (
    BlockPool,
    BlockStore,
    KVPoolExhausted,
    PagedKVConfig,
    PagedPrefixCache,
    PageTable,
    paged_enabled,
    ragged_enabled,
)
from dnet_tpu.kv.store import _bucket_pow2
from dnet_tpu.obs import get_recorder, metric, obs_enabled
from dnet_tpu.obs.jit import instrument_jit
from dnet_tpu.obs.phases import (
    PHASE_COMPUTE,
    PHASE_KV_GATHER,
    PHASE_KV_SCATTER,
    PHASE_SAMPLE,
)
from dnet_tpu.utils.logger import get_logger

log = get_logger()

_PHASE_MS = metric("dnet_step_phase_ms")
_DECODE_STEP_MS = metric("dnet_decode_step_ms")


class BatchedEngine:
    """LocalEngine-compatible surface plus `decode_batch` for the scheduler."""

    token_result = staticmethod(LocalEngine.token_result)

    def __init__(self, model_dir: str | Path, slots: int = 8, **engine_kwargs):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        paged, prefix_size, engine_kwargs = self._split_paged_kwargs(engine_kwargs)
        self.eng = LocalEngine(model_dir, **engine_kwargs)
        self._init_state(slots, paged=paged, prefix_size=prefix_size)

    @classmethod
    def from_params(
        cls, config, window_params, edge_params, *, slots: int = 8, **kw
    ) -> "BatchedEngine":
        """Build around already-materialised params (the zero-egress bench
        path, mirroring LocalEngine.from_params)."""
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self = cls.__new__(cls)
        paged, prefix_size, kw = cls._split_paged_kwargs(kw)
        self.eng = LocalEngine.from_params(config, window_params, edge_params, **kw)
        self._init_state(slots, paged=paged, prefix_size=prefix_size)
        return self

    @staticmethod
    def _split_paged_kwargs(kw: Dict[str, Any]):
        """Resolve the paged-KV flag and claim the prefix-cache capacity.

        Under DNET_KV_PAGED=1 the BATCHED engine owns the pool, the
        per-slot page tables, and prefix sharing — the inner B=1 engine is
        pure prefill staging and must not run its own ledger or snapshot
        cache (double admission / double memory)."""
        kw = dict(kw)
        paged = kw.pop("kv_paged", None)
        paged = paged_enabled() if paged is None else bool(paged)
        prefix_size = int(kw.pop("prefix_cache_size", 0) or 0)
        # ALWAYS pin the inner engine dense: left to read DNET_KV_PAGED
        # itself it would build a phantom ledger that spuriously rejects
        # staging prefills and publishes gauges for a pool nobody serves
        kw["kv_paged"] = False
        if not paged and prefix_size:
            kw["prefix_cache_size"] = prefix_size
        return paged, prefix_size, kw

    def _init_state(
        self, slots: int, paged: bool = False, prefix_size: int = 0
    ) -> None:
        # typed load-time refusals: the HTTP layer maps these to 422
        # (operator/config error) instead of the generic 500 the old
        # NotImplementedError fell through to.  Function-level import —
        # the api layer depends on core, not the other way around, so the
        # exception type is fetched only at this (load-time) raise site.
        from dnet_tpu.api.inference import EngineCapabilityError

        if self.eng.plan.streams_weights:
            raise EngineCapabilityError(
                "continuous batching needs resident weights (fit policy); "
                "weight streaming serves single-sequence"
            )
        if not self.eng.model.supports_kv_commit:
            # fail at load, not mid-stream on the first batched step
            raise EngineCapabilityError(
                f"continuous batching not supported for "
                f"{self.eng.config.model_type} (no gated KV writes yet)"
            )
        self.slots = slots
        self.max_seq = self.eng.max_seq
        self.config = self.eng.config
        self.model = self.eng.model
        # per-LANE speculative decoding (VERDICT r3 next #5): spec_lookahead
        # flows through engine_kwargs into the inner LocalEngine, whose B=1
        # prefill paths maintain the per-session history buffers we adopt
        self.spec_lookahead = self.eng.spec_lookahead
        if self.spec_lookahead > 0 and not self.eng.model.kv_rewindable(self.max_seq):
            log.warning(
                "speculative decoding needs a rewind-safe cache layout; "
                "%s uses rotating SWA buffers — disabled for this model",
                self.eng.config.model_type,
            )
            self.spec_lookahead = 0
        m = self.eng.model
        # paged KV (kv/): per-slot page tables over a shared block pool
        # replace the dense [L, slots, S] residency; the dense view exists
        # only transiently per step (gather -> step -> block scatter)
        self.kv_pool: Optional[BlockPool] = None
        self.kv_store: Optional[BlockStore] = None
        self.paged_prefix: Optional[PagedPrefixCache] = None
        self._kv_cfg: Optional[PagedKVConfig] = None
        self._tables: List[Optional[PageTable]] = [None] * slots
        self._adopt: Dict[str, Tuple[int, List[int], int]] = {}
        if paged:
            try:
                cfg = PagedKVConfig.from_settings(
                    self.max_seq, slots=slots + prefix_size
                )
                store = BlockStore(
                    m, len(m.layers), cfg, self.eng.kv_dtype,
                    quant_bits=self.eng.kv_quant_bits,
                    session_tokens=self.max_seq,
                )
            except (ValueError, NotImplementedError) as exc:
                log.warning(
                    "paged KV disabled for batched engine (%s); "
                    "serving dense slots", exc,
                )
                paged = False
                if prefix_size > 0:
                    # the kwargs split claimed the prefix capacity for the
                    # (now unavailable) paged cache: give the inner engine
                    # its dense snapshot cache back
                    self.eng.prefix_cache = self.eng._build_prefix_cache(
                        prefix_size
                    )
            else:
                self._kv_cfg = cfg
                self.kv_pool = BlockPool(cfg)
                self.kv_store = store
                if prefix_size > 0:
                    self.paged_prefix = PagedPrefixCache(
                        self.kv_pool, store, prefix_size,
                        row_tokens=self.max_seq,
                    )
                if self.spec_lookahead > 0:
                    log.warning(
                        "per-lane speculation disabled under paged KV "
                        "(verify blocks bypass the block scatter path)"
                    )
                    self.spec_lookahead = 0
                log.info(
                    "paged KV on: %d blocks x %d tokens serving %d slots",
                    cfg.pool_blocks, cfg.block_tokens, slots,
                )
        # ragged paged attention (DNET_KV_RAGGED=1): decode attends the
        # pool in place through the page tables; the dense gather/scatter
        # round trip — and its kv_gather/kv_scatter phases — stop existing.
        # Dense-gather stays the fallback for everything the kernel
        # refuses (quantized caches, non-llama attention stacks), on top
        # of the session layouts BlockStore itself already refused.
        self.kv_ragged = False
        if paged and ragged_enabled():
            from dnet_tpu.ops.paged_attention import ragged_refusal

            why = ragged_refusal(m, self.eng.kv_quant_bits)
            if why is not None:
                log.warning(
                    "ragged paged attention disabled (%s); serving "
                    "dense-gather decode", why,
                )
            else:
                self.kv_ragged = True
                log.info(
                    "ragged paged attention on: decode attends the block "
                    "pool in place"
                )
        self.kv = (
            None
            if paged
            else m.init_kv(
                len(m.layers), slots, self.max_seq, self.eng.kv_dtype,
                quant_bits=self.eng.kv_quant_bits,
            )
        )
        V = self.config.vocab_size
        self.counts = jnp.zeros((slots, V), dtype=jnp.int32)
        self.keys = jax.random.split(
            jax.random.key(int.from_bytes(__import__("os").urandom(4), "little")),
            slots,
        )
        self.pos = np.zeros(slots, dtype=np.int64)  # host-side per-slot length
        self.last_used = np.zeros(slots, dtype=np.float64)
        self.slot_of: Dict[str, int] = {}  # nonce -> slot
        self._free: List[int] = list(range(slots))
        # fused-chunk results not yet handed to the driver (nonce -> FIFO);
        # dropped with the session like the pipelined engine's buffers
        self._buffer: Dict[str, List[SampleResult]] = {}
        # per-nonce [blocks, emitted] acceptance stats (adaptive spec gate)
        self._spec_stats: Dict[str, List[int]] = {}
        self.hist = (
            jnp.zeros((slots, self.max_seq), dtype=jnp.int32)
            if self.spec_lookahead > 0
            else None
        )
        self._build()

    # ---- program ------------------------------------------------------
    def _build(self) -> None:
        model = self.eng.model

        def one(wp, ep, token, kv, pos, active, sp, key, counts):
            """Single-example decode+sample; vmapped over the slot axis.
            kv leaves arrive batch-axis-stripped [L, S, ...]: re-add B=1."""
            kv = jax.tree.map(lambda a: a[:, None], kv)
            x = model.embed(ep, token[None, :])  # [1, 1, D]
            x, kv = model.apply_window(wp, x, kv, pos, kv_commit=active)
            x = model.normalize(ep, x[:, -1:])
            logits = model.lm_project(ep, x)[:, 0]  # [1, V]
            new_key, step_key = jax.random.split(key)
            res = sample(logits, sp, step_key, token_counts=counts[None])
            counts = counts.at[res.token[0]].add(jnp.where(active, 1, 0))
            kv = jax.tree.map(lambda a: a[:, 0], kv)
            # inactive lanes must not advance their RNG stream either, or a
            # seeded request's tokens would depend on unrelated traffic
            key = jax.random.wrap_key_data(
                jnp.where(
                    active, jax.random.key_data(new_key), jax.random.key_data(key)
                )
            )
            return res, kv, counts, key

        # paged mode has no persistent dense cache; the pool tree has the
        # same leaf STRUCTURE, which is all the axis spec needs
        kv_axes = jax.tree.map(
            lambda _: 1, self.kv if self.kv is not None else self.kv_store.kv
        )
        sp_axes = SampleParams(0, 0, 0, 0, 0, 0, 0, 0)
        self._vmapped = jax.vmap(
            one,
            in_axes=(None, None, 0, kv_axes, 0, 0, sp_axes, 0, 0),
            out_axes=(0, kv_axes, 0, 0),
        )
        self._step = instrument_jit(
            jax.jit(self._vmapped, donate_argnums=(3, 8)), "batched_step"
        )
        # fused R-step chunks (budget-driven): sampled tokens re-enter their
        # lanes on device, one dispatch + one packed read per R tokens
        self._chunks: Dict[int, Any] = {}
        if self.kv_ragged:
            self._build_ragged()

        L = self.spec_lookahead
        if L > 0:
            from dnet_tpu.core.spec import accept_drafts, ngram_draft

            def one_spec(wp, ep, token, hist, kv, pos, active):
                """One per-lane verify block (vmapped): commit the fed
                token, draft L by prompt-lookup against THIS lane's history,
                verify in one (L+1)-wide forward, emit the agreeing prefix.
                Lanes accept independently — the host advances each slot by
                its own emitted count (uneven progress is the point)."""
                hist0 = hist
                hist = jax.lax.dynamic_update_slice_in_dim(hist, token, pos, axis=0)
                drafts = ngram_draft(hist[None], pos + 1, L)[0]  # [L]
                hist = jax.lax.dynamic_update_slice_in_dim(
                    hist, drafts, pos + 1, axis=0
                )
                # non-speculating lanes ride along with garbage inputs; their
                # history must stay untouched (the hist twin of kv_commit)
                hist = jnp.where(active, hist, hist0)
                block = jnp.concatenate([token, drafts])[None, :]  # [1, L+1]
                kv = jax.tree.map(lambda a: a[:, None], kv)
                x = model.embed(ep, block)
                x, kv = model.apply_window(
                    wp, x, kv, pos, kv_commit=active, t_real=L + 1
                )
                x = model.normalize(ep, x)
                logits = model.lm_project(ep, x)[0]  # [L+1, V]
                preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                _, out = accept_drafts(preds[None], drafts[None])
                kv = jax.tree.map(lambda a: a[:, 0], kv)
                return out[0], hist, kv

            self._spec_vmapped = jax.vmap(
                one_spec,
                in_axes=(None, None, 0, 0, kv_axes, 0, 0),
                out_axes=(0, 0, kv_axes),
            )
            self._spec_step = instrument_jit(
                jax.jit(self._spec_vmapped, donate_argnums=(3, 4)),
                "batched_spec",
            )

    def _build_ragged(self) -> None:
        """The ragged decode programs (ops/paged_attention.py): one step
        that reads the block pool IN PLACE — page tables and per-slot
        positions ride along as the kernel's scalar-prefetched block index
        map — plus fused R-step chunks that carry the (donated) pool and
        block-append each step's new K/V rows in-program.  The per-slot
        forward is the SAME math as the vmapped dense program: the model's
        norm/rope/MLP stack runs unchanged (apply_window's attend_fn hook
        swaps only the cache write + attention read), and sampling vmaps
        the identical per-lane tail, so greedy streams are parity-testable
        against the gather path byte for byte."""
        from dnet_tpu.ops.paged_attention import paged_attend, paged_attend_impl

        model = self.eng.model
        impl = paged_attend_impl()
        sp_axes = SampleParams(0, 0, 0, 0, 0, 0, 0, 0)

        def one_sample(logits, active, sp, key, counts):
            """Per-lane sampling tail, identical to the vmapped `one()`:
            inactive lanes advance neither counts nor their RNG stream."""
            new_key, step_key = jax.random.split(key)
            res = sample(logits[None], sp, step_key, token_counts=counts[None])
            counts = counts.at[res.token[0]].add(jnp.where(active, 1, 0))
            key = jax.random.wrap_key_data(
                jnp.where(
                    active, jax.random.key_data(new_key), jax.random.key_data(key)
                )
            )
            return res, counts, key

        vsample = jax.vmap(one_sample, in_axes=(0, 0, sp_axes, 0, 0))

        def ragged_step(wp, ep, token, pool, tables, pos, active, sp, keys,
                        counts):
            """One batched decode step against the pool (READ-ONLY here):
            returns the sampled results plus the stacked per-layer new K/V
            rows for the kv_append program.  tables [slots, nb] int32
            (bucketed), pos [slots] int32 live pool rows per slot."""

            def attend_fn(q, k_new, v_new, kvs):
                attn = paged_attend(
                    q, kvs["k"], kvs["v"], tables, pos,
                    k_new[:, 0], v_new[:, 0], impl=impl,
                )
                return attn, {"k": k_new[:, 0], "v": v_new[:, 0]}

            x = model.embed(ep, token)  # [slots, 1, D]
            x, rows = model.apply_window(
                wp, x, pool, pos[:, None], attend_fn=attend_fn
            )
            x = model.normalize(ep, x[:, -1:])
            logits = model.lm_project(ep, x)[:, 0]  # [slots, V]
            res, counts, keys = vsample(logits, active, sp, keys, counts)
            return res, rows, counts, keys

        self._ragged_step_fn = ragged_step
        self._ragged_step = instrument_jit(
            jax.jit(ragged_step, donate_argnums=(9,)), "paged_attend"
        )
        self._ragged_chunks: Dict[int, Any] = {}

    def _ragged_chunk_fn(self, R: int):
        """Fused R-step ragged chunk: the pool rides the scan carry
        (donated — XLA appends in place), each step attends it through the
        kernel and block-appends its new rows before the next step reads
        them.  Same one-dispatch-per-R-tokens contract as _chunk_fn, with
        the gather/scatter round trip deleted."""
        fn = self._ragged_chunks.get(R)
        if fn is None:
            step = self._ragged_step_fn
            bt = self._kv_cfg.block_tokens

            def chunk(wp, ep, token, pool, tables, pos, active, sp, keys,
                      counts):
                def body(carry, _):
                    token, pool, pos, keys, counts = carry
                    res, rows, counts, keys = step(
                        wp, ep, token, pool, tables, pos, active, sp, keys,
                        counts,
                    )
                    nb = tables.shape[1]
                    bidx = jnp.clip(pos // bt, 0, nb - 1)
                    phys = jnp.take_along_axis(tables, bidx[:, None], axis=1)[:, 0]
                    # frozen lanes write PAST the block axis (mode="drop"
                    # discards out-of-range, but a negative index would
                    # wrap to block N-1 and clobber a live block)
                    phys = jnp.where(active, phys, self._kv_cfg.pool_blocks)
                    off = pos % bt
                    pool = jax.tree.map(
                        lambda p, r: p.at[:, phys, off].set(
                            r.astype(p.dtype), mode="drop"
                        ),
                        pool, rows,
                    )
                    token = jnp.where(active[:, None], res.token, token)
                    pos = pos + active.astype(pos.dtype)
                    return (token, pool, pos, keys, counts), res

                (token, pool, pos, keys, counts), stacked = jax.lax.scan(
                    body, (token, pool, pos, keys, counts), None, length=R
                )
                return stacked, pool, counts, keys

            fn = instrument_jit(
                jax.jit(chunk, donate_argnums=(3, 9)), "paged_attend"
            )
            self._ragged_chunks[R] = fn
        return fn

    # chunk widths tried largest-first (bounded compiled-program set, same
    # discipline as LocalEngine.DECODE_CHUNK_BUCKETS)
    CHUNK_BUCKETS = (16, 8, 4, 2)

    def _chunk_fn(self, R: int):
        fn = self._chunks.get(R)
        if fn is None:
            vstep = self._vmapped

            def chunk(wp, ep, token, kv, pos, active, sp, keys, counts):
                def body(carry, _):
                    token, kv, pos, keys, counts = carry
                    res, kv, counts, keys = vstep(
                        wp, ep, token, kv, pos, active, sp, keys, counts
                    )
                    # active lanes chain their sampled token on device;
                    # frozen lanes keep feeding their stale input (inert:
                    # their KV/counts/keys writes are gated off)
                    token = jnp.where(active[:, None], res.token, token)
                    pos = pos + active.astype(pos.dtype)
                    return (token, kv, pos, keys, counts), res

                (_, kv, _, keys, counts), stacked = jax.lax.scan(
                    body, (token, kv, pos, keys, counts), None, length=R
                )
                return stacked, kv, counts, keys

            fn = instrument_jit(
                jax.jit(chunk, donate_argnums=(3, 8)), "batched_chunk"
            )
            self._chunks[R] = fn
        return fn

    # ---- slot lifecycle ----------------------------------------------
    def alloc_slot(self, nonce: str) -> int:
        if nonce in self.slot_of:
            return self.slot_of[nonce]
        if not self._free:
            raise RuntimeError(f"no free batch slots (capacity {self.slots})")
        slot = self._free.pop(0)
        self.slot_of[nonce] = slot
        self.pos[slot] = 0
        self.last_used[slot] = time.time()
        return slot

    def free_slot(self, nonce: str) -> None:
        self._buffer.pop(nonce, None)
        self._spec_stats.pop(nonce, None)
        stash = self._adopt.pop(nonce, None)
        if stash is not None and self.kv_pool is not None:
            # adopted-but-never-committed prefix references (cancel race)
            self.kv_pool.free_blocks(stash[1])
        slot = self.slot_of.pop(nonce, None)
        if slot is not None:
            if self.kv_pool is not None:
                # block-table release: the whole point of paging — a
                # finished request's blocks return to the free list (or
                # drop a refcount on shared prefix blocks)
                tbl, self._tables[slot] = self._tables[slot], None
                self.kv_pool.release_table(tbl)
            self.counts = self.counts.at[slot].set(0)
            if self.hist is not None:
                self.hist = self.hist.at[slot].set(0)
            self.pos[slot] = 0
            self._free.append(slot)

    def end_session(self, nonce: str) -> None:
        self.free_slot(nonce)
        self.eng.end_session(nonce)

    def reset(self) -> None:
        for nonce in list(self.slot_of):
            self.free_slot(nonce)
        self.eng.reset()

    def sweep_sessions(self, ttl_s: float = 600.0) -> int:
        now = time.time()
        dead = [
            n for n, s in self.slot_of.items()
            if now - self.last_used[s] > ttl_s
        ]
        for n in dead:
            self.free_slot(n)
        return len(dead) + self.eng.sweep_sessions()

    def close(self) -> None:
        self.reset()
        self.eng.close()

    @property
    def sessions(self):  # adapter compatibility (membership checks)
        return self.slot_of

    # ---- inference ----------------------------------------------------
    def seed_from_prefix(self, nonce, full_ids, seed=None) -> int:
        """Paged mode: a PrefixIndex hit resolves to SHARED refcounted
        blocks — the full blocks alias straight into this request's future
        page table (no copy); only the staging dense row for the inner
        B=1 prefill is gathered.  Dense mode defers to the inner engine's
        snapshot cache."""
        if self.kv_pool is None:
            return self.eng.seed_from_prefix(nonce, full_ids, seed)
        if self.paged_prefix is None or nonce in self.eng.sessions:
            return 0
        full = list(full_ids)
        hit = self.paged_prefix.lookup_blocks(full)
        if hit is None:
            return 0
        n, blocks, n_full = hit
        kv_row = self.kv_store.gather_row(blocks, self.max_seq)
        self.eng._restore_session(nonce, full, n, kv_row, seed)
        self._adopt[nonce] = (n, blocks, n_full)
        get_recorder().span(nonce, "prefix_cache_hit", 0.0, tokens=n)
        return n

    def store_prefix(self, nonce, full_ids) -> None:
        if self.kv_pool is None:
            return self.eng.store_prefix(nonce, full_ids)
        if self.paged_prefix is None:
            return
        full = list(full_ids)
        slot = self.slot_of.get(nonce)
        if (
            slot is not None
            and self._tables[slot] is not None
            and int(self.pos[slot]) == len(full)
        ):
            # adopted slot: snapshot by ALIASING the live table (zero copy)
            self.paged_prefix.store_blocks(
                full, len(full), self._tables[slot].blocks
            )
            return
        sess = self.eng.sessions.get(nonce)
        if sess is not None and sess.kv is not None and sess.pos == len(full):
            # still staging on the inner engine (chunked prefill): commit
            # tail blocks, dedup the parent prefix block-level
            self.paged_prefix.store(full, sess.kv)

    def reserve_slot(self, nonce) -> None:
        """Claim a batch slot BEFORE chunked prefill burns any compute
        (same fail-fast invariant as prefill_and_sample)."""
        self.alloc_slot(nonce)

    def prefill_chunk(self, nonce, ids, seed=None):
        """One prompt chunk on the B=1 bucket program (continuation when the
        session already exists); returns last-position logits.  The adapter
        interleaves these with batched decode steps so a long prompt never
        stalls active lanes for its whole prefill.  allow_store=False keeps
        partial-prompt snapshots out of the prefix cache (store_prefix
        snapshots the full prompt at the end)."""
        if self.kv_pool is not None:
            # admission per chunk: the slot-commit at adopt time is the
            # authoritative (all-or-nothing) alloc; this pre-check stops a
            # doomed long prompt from burning its remaining chunks
            sess = self.eng.sessions.get(nonce)
            pos = 0 if sess is None else int(sess.pos)
            # only the FULL aliased blocks survive into the commit's table;
            # a shared partial tail is COW-copied from fresh blocks, so it
            # must not be counted as already-held capacity
            n_full = self._adopt.get(nonce, (0, [], 0))[2]
            need = self._kv_cfg.blocks_for(min(pos + len(ids), self.max_seq))
            self.kv_pool.require(max(need - n_full, 0))
        return self.eng.prefill(nonce, list(ids), seed, allow_store=False)

    def abandon_prefill(self, nonce) -> None:
        """Drop a half-prefilled request (cancelled mid-chunks)."""
        self.free_slot(nonce)
        self.eng.end_session(nonce)

    def adopt_prefilled(self, nonce, logits, decoding: DecodingParams) -> SampleResult:
        """Sample the first token from a fully-chunk-prefilled session and
        move its KV/sampling state into this request's batch slot."""
        sess = self.eng.sessions[nonce]
        res = self.eng._sample_with_counts(sess, logits, decoding)
        self._move_to_slot(nonce, sess)
        return res

    def _commit_paged_slot(self, nonce: str, slot: int, sess) -> None:
        """Turn a staged B=1 prefill into this slot's page table: aliased
        prefix blocks stay in place, everything from the first non-shared
        block commits out of the staged dense row (which already merged
        shared-partial content with the new tokens — the COW copy)."""
        cfg = self._kv_cfg
        n = int(sess.pos)
        nb = cfg.blocks_for(n)
        stash = self._adopt.pop(nonce, None)
        n_sh, blocks, n_full = stash if stash is not None else (0, [], 0)
        try:
            own = self.kv_pool.alloc(nb - n_full)
        except KVPoolExhausted:
            if stash is not None:
                self._adopt[nonce] = stash  # abandon_prefill releases it
            raise
        self.kv_store.commit_row(sess.kv, list(range(n_full, nb)), own)
        if stash is not None:
            if n_sh % cfg.block_tokens:
                # the request diverged mid-block: the shared tail block was
                # copied (via the staged row) instead of mutated in place
                self.kv_pool.count_cow()
            self.kv_pool.free_blocks(blocks[n_full:])  # transient refs
        # a re-prefilled nonce keeps its slot: drop the superseded table
        self.kv_pool.release_table(self._tables[slot])
        self._tables[slot] = PageTable(
            blocks=list(blocks[:n_full]) + own, shared_upto=n_full
        )

    def _paged_extend(self, order, errors, active, R: int) -> int:
        """Extend every stepping lane's page table to cover R more tokens.
        If the pool cannot cover the full chunk width, the WHOLE dispatch
        shrinks to single steps (keeping one program) and only lanes that
        cannot get even one block fail — alone, with the typed
        backpressure message."""
        while True:
            appended: Dict[int, List[int]] = {}
            for nonce, slot in list(order.items()):
                try:
                    appended[slot] = self.kv_pool.ensure(
                        self._tables[slot], int(self.pos[slot]) + R
                    )
                except KVPoolExhausted as exc:
                    if R > 1:
                        break  # shrink the chunk and re-try every lane
                    errors[nonce] = str(exc)
                    active[slot] = False
                    del order[nonce]
            else:
                return R
            # roll the failed wide pass back before retrying at R=1: a
            # lane's unused hoard (blocks past its next single step) must
            # not starve the lanes that come after it in the retry
            for slot, fresh in appended.items():
                tbl = self._tables[slot]
                keep = max(
                    len(tbl.blocks) - len(fresh),
                    self._kv_cfg.blocks_for(int(self.pos[slot]) + 1),
                )
                if keep < len(tbl.blocks):
                    self.kv_pool.free_blocks(tbl.blocks[keep:])
                    del tbl.blocks[keep:]
            R = 1

    def _table_ids(self, order: Optional[Dict[str, int]] = None) -> np.ndarray:
        """[slots, nb] physical block ids (0-padded past each table; padded
        rows sit beyond every live pos, where the causal mask zeroes them
        exactly).

        With `order` (the dispatch's active nonce -> slot map), nb is the
        pow2 BUCKET of the widest active table instead of max_seq/bt: the
        dense fallback stops gathering dead blocks every step, the ragged
        kernel walks fewer (elided) grid steps, and the compiled-program
        set stays bounded — the same discipline as _bucket_pow2 scatter
        widths.  Only R==1 dispatches pass `order` (warm_chunks pre-warms
        the step at every bucket width); fused R-step chunks keep the
        single full-width program — they amortize the gather over R
        tokens already, and a per-width chunk set would multiply the
        compiled programs by the width count.  Frozen lanes' longer
        tables truncate harmlessly (their compute is garbage, their
        blocks are never written)."""
        nb = self.max_seq // self._kv_cfg.block_tokens
        if order:
            widest = max(
                (
                    len(self._tables[s].blocks)
                    for s in order.values()
                    if self._tables[s] is not None
                ),
                default=1,
            )
            nb = min(_bucket_pow2(max(widest, 1)), nb)
        ids = np.zeros((self.slots, nb), dtype=np.int32)
        for slot, tbl in enumerate(self._tables):
            if tbl is not None and tbl.blocks:
                n = min(len(tbl.blocks), nb)
                ids[slot, :n] = tbl.blocks[:n]
        return ids

    def _move_to_slot(self, nonce: str, sess) -> None:
        slot = self.alloc_slot(nonce)
        if self.kv_pool is not None:
            self._commit_paged_slot(nonce, slot, sess)
        else:
            self.kv = jax.tree.map(
                lambda big, one: big.at[:, slot : slot + 1].set(one.astype(big.dtype)),
                self.kv,
                sess.kv,
            )
        self.counts = self.counts.at[slot].set(sess.counts[0])
        self.keys = self.keys.at[slot].set(sess.key)
        if self.hist is not None and sess.hist is not None:
            # the inner LocalEngine's prefill paths committed the prompt to
            # the session history; adopt it for this lane's prompt-lookup
            self.hist = self.hist.at[slot].set(sess.hist[0])
        self.pos[slot] = sess.pos
        self.last_used[slot] = time.time()
        self.eng.end_session(nonce)  # B=1 cache row no longer needed

    def prefill_and_sample(
        self, nonce: str, prompt_ids: Sequence[int], decoding: DecodingParams
    ) -> SampleResult:
        """Prefill on the B=1 bucket program, then move the session's KV row
        and sampling state into this request's batch slot."""
        self.alloc_slot(nonce)  # fail on a full pool BEFORE burning prefill
        if self.kv_pool is None:
            res = self.eng.prefill_and_sample(nonce, prompt_ids, decoding)
            self._move_to_slot(nonce, self.eng.sessions[nonce])
            return res
        full = list(prompt_ids)
        try:
            n = self.seed_from_prefix(nonce, full, decoding.seed)
            # admission: the POOL must cover the non-shared remainder
            # before any prefill compute burns (same fail-fast invariant
            # as the slot claim above) — a shortfall surfaces as the typed
            # backpressure error, never a mid-prefill crash.  Only FULL
            # aliased blocks count as held: the commit COW-copies a shared
            # partial tail from a fresh block.
            n_full = self._adopt.get(nonce, (0, [], 0))[2]
            need = self._kv_cfg.blocks_for(min(len(full), self.max_seq))
            self.kv_pool.require(max(need - n_full, 0))
            logits = self.eng.prefill(
                nonce, full[n:], decoding.seed, allow_store=False
            )
            res = self.eng._sample_with_counts(
                self.eng.sessions[nonce], logits, decoding
            )
            self._move_to_slot(nonce, self.eng.sessions[nonce])
        except Exception:
            self.abandon_prefill(nonce)
            raise
        self.store_prefix(nonce, full)
        return res

    def decode_batch(
        self,
        requests: Dict[str, Tuple[int, DecodingParams]],
        budgets: Optional[Dict[str, Optional[int]]] = None,
    ) -> Tuple[Dict[str, SampleResult], Dict[str, str]]:
        """One batched decode step for every (nonce -> last token) request.
        Slots not in `requests` stay frozen (active=False gates their KV
        write and counts).  Returns (results, per-nonce errors): a request
        whose slot vanished (client disconnect race) or hit max_seq fails
        ALONE — it must never poison the rest of the batch.

        `budgets` (nonce -> remaining tokens the driver will accept) widen
        the dispatch into a fused R-step chunk: active lanes chain their
        sampled tokens on device and the extra results buffer engine-side,
        resolving later decode_batch calls instantly — the host pays one
        dispatch + one packed read per R tokens per lane (the same contract
        as LocalEngine.decode_chunk / the pipelined engine's rotations).
        The active set is FIXED across a chunk, so the stream is
        bit-identical to R serial steps with the same request set."""
        errors: Dict[str, str] = {}
        if not requests:
            return {}, errors
        # buffered tokens from an earlier fused chunk resolve first
        out_buf: Dict[str, SampleResult] = {}
        now = time.time()
        for nonce in list(requests):
            buf = self._buffer.get(nonce)
            if buf:
                out_buf[nonce] = buf.pop(0)
                slot = self.slot_of.get(nonce)
                if slot is not None:
                    self.last_used[slot] = now
        requests = {n: r for n, r in requests.items() if n not in out_buf}
        if not requests:
            return out_buf, errors

        # per-lane speculation: greedy lanes with budget to spare verify a
        # drafted block instead of stepping once; they advance by their OWN
        # acceptance count (buffered), while the remaining lanes take the
        # plain batched step below — the two programs touch disjoint lanes
        spec_out: Dict[str, SampleResult] = {}
        if self.spec_lookahead > 0 and budgets:
            spec_reqs = {}
            for nonce, (tok, dec) in requests.items():
                slot = self.slot_of.get(nonce)
                budget = budgets.get(nonce) or 1
                if (
                    slot is not None
                    and dec.temperature == 0.0
                    and not dec.logprobs
                    and dec.repetition_penalty == 1.0
                    and not dec.logit_bias  # verify argmaxes are unbiased
                    and budget > 1
                    and self.pos[slot] + self.spec_lookahead + 1 <= self.max_seq
                    and self._spec_worthwhile(nonce)
                ):
                    spec_reqs[nonce] = (tok, slot, budget)
            if spec_reqs:
                spec_out = self._decode_spec_lanes(spec_reqs)
                requests = {
                    n: r for n, r in requests.items() if n not in spec_reqs
                }
        out_buf = {**out_buf, **spec_out}
        if not requests:
            return out_buf, errors
        token = np.zeros((self.slots, 1), dtype=np.int32)
        active = np.zeros(self.slots, dtype=bool)
        pos = np.zeros(self.slots, dtype=np.int32)
        temp = np.zeros(self.slots, dtype=np.float32)
        top_p = np.ones(self.slots, dtype=np.float32)
        top_k = np.zeros(self.slots, dtype=np.int32)
        min_p = np.zeros(self.slots, dtype=np.float32)
        rep = np.ones(self.slots, dtype=np.float32)
        mtk = np.ones(self.slots, dtype=np.int32)
        b_ids = np.full((self.slots, MAX_LOGIT_BIAS), -1, dtype=np.int32)
        b_vals = np.zeros((self.slots, MAX_LOGIT_BIAS), dtype=np.float32)
        order: Dict[str, int] = {}
        for nonce, (tok, dec) in requests.items():
            slot = self.slot_of.get(nonce)
            if slot is None:
                errors[nonce] = f"request {nonce!r} has no batch slot (cancelled?)"
                continue
            if self.pos[slot] >= self.max_seq:
                errors[nonce] = (
                    f"sequence length {self.pos[slot]} reached max_seq {self.max_seq}"
                )
                continue
            token[slot, 0] = tok
            active[slot] = True
            pos[slot] = self.pos[slot]
            temp[slot] = dec.temperature
            top_p[slot] = dec.top_p
            top_k[slot] = dec.top_k
            min_p[slot] = dec.min_p
            rep[slot] = dec.repetition_penalty
            mtk[slot] = dec.min_tokens_to_keep
            b_ids[slot], b_vals[slot] = encode_logit_bias(dec.logit_bias)
            order[nonce] = slot
        if not order:
            return out_buf, errors

        sp = SampleParams(
            temperature=jnp.asarray(temp),
            top_p=jnp.asarray(top_p),
            top_k=jnp.asarray(top_k),
            min_p=jnp.asarray(min_p),
            repetition_penalty=jnp.asarray(rep),
            min_tokens_to_keep=jnp.asarray(mtk),
            bias_ids=jnp.asarray(b_ids),
            bias_vals=jnp.asarray(b_vals),
        )
        # fused-chunk width: bounded by the smallest remaining budget and
        # by every active lane's sequence capacity
        R = 1
        if budgets:
            cap = min((budgets.get(n) or 1) for n in order)
            cap = min(cap, *(int(self.max_seq - self.pos[s]) for s in order.values()))
            R = next((r for r in self.CHUNK_BUCKETS if r <= cap), 1)
        # performance attribution (obs/phases.py): when obs is enabled the
        # phase boundaries are FENCED (block_until_ready) so kv_gather /
        # compute / kv_scatter / sample carry honest device time instead of
        # async-dispatch noise — the device-sync gating contract from
        # dnet_tpu.obs.  The parent dnet_decode_step_ms observation always
        # records (the step ends in a synchronous host readback anyway).
        attribute = obs_enabled()
        t_parent = time.perf_counter()
        if self.kv_pool is not None:
            # block-table extension is admission: a lane the pool cannot
            # cover fails ALONE with the typed backpressure message
            R = self._paged_extend(order, errors, active, R)
            if not order:
                return out_buf, errors
        paged = self.kv_pool is not None
        if paged and self.kv_ragged:
            # ragged paged attention: the pool is attended IN PLACE through
            # the page tables and the new rows block-append — the gather/
            # scatter round trip (and its two phases) does not exist here
            src = self._dispatch_ragged(order, active, R, token, pos, sp,
                                        attribute)
        else:
            if paged:
                t0 = time.perf_counter()
                kv_in = self.kv_store.gather(
                    self._table_ids(order if R == 1 else None)
                )
                if attribute:
                    jax.block_until_ready(kv_in)
                    self._observe_phase(PHASE_KV_GATHER, t0, order, R)
            else:
                kv_in = self.kv
            args = (
                self.eng.window_params,
                self.eng.edge_params,
                jnp.asarray(token),
                kv_in,
                jnp.asarray(pos),
                jnp.asarray(active),
                sp,
                self.keys,
                self.counts,
            )
            t0 = time.perf_counter()
            if R > 1:
                stacked, kv_out, self.counts, self.keys = self._chunk_fn(R)(*args)
                src = stacked
            else:
                res, kv_out, self.counts, self.keys = self._step(*args)
                src = res
            if attribute:
                jax.block_until_ready((src, kv_out))
                self._observe_phase(PHASE_COMPUTE, t0, order, R)
            if paged:
                # persist ONLY the blocks this step wrote (block-append
                # write); the contiguous view kv_out is scratch and dies here
                bt = self._kv_cfg.block_tokens
                triples = []
                for _nonce, slot in order.items():
                    p0 = int(self.pos[slot])
                    tbl = self._tables[slot]
                    triples.extend(
                        (slot, b, tbl.blocks[b])
                        for b in range(p0 // bt, (p0 + R - 1) // bt + 1)
                    )
                t0 = time.perf_counter()
                self.kv_store.scatter(kv_out, triples)
                if attribute:
                    jax.block_until_ready(self.kv_store.kv)
                    self._observe_phase(PHASE_KV_SCATTER, t0, order, R)
            else:
                self.kv = kv_out
        now = time.time()
        out: Dict[str, SampleResult] = dict(out_buf)
        # ONE packed device->host read per field per dispatch (the
        # pipelined engine's drain pattern), then host-side slicing —
        # per-element device gathers would reintroduce the dispatch
        # overhead the fused chunk exists to remove
        t0 = time.perf_counter()
        toks = np.asarray(src.token)
        lps = np.asarray(src.logprob)
        tts = np.asarray(src.top_tokens)
        tlps = np.asarray(src.top_logprobs)
        if attribute:
            self._observe_phase(PHASE_SAMPLE, t0, order, R)
        for nonce, slot in order.items():
            self.pos[slot] += R
            self.last_used[slot] = now
            if R > 1:
                rows = [
                    SampleResult(toks[k, slot], lps[k, slot],
                                 tts[k, slot], tlps[k, slot])
                    for k in range(R)
                ]
                out[nonce] = rows[0]
                self._buffer.setdefault(nonce, []).extend(rows[1:])
            else:
                out[nonce] = SampleResult(
                    token=toks[slot], logprob=lps[slot],
                    top_tokens=tts[slot], top_logprobs=tlps[slot],
                )
        # per-token share, observed tokens-served times: the family's
        # count stays == tokens across the local / chunked / speculative /
        # batched paths (LocalEngine's amortization convention), and the
        # sum stays == dispatch wall so the phase sums still account for it
        n_tok = R * len(order)
        per_tok_ms = (time.perf_counter() - t_parent) * 1000.0 / n_tok
        _DECODE_STEP_MS.observe_n(per_tok_ms, n_tok)
        return out, errors

    def _dispatch_ragged(
        self,
        order: Dict[str, int],
        active: np.ndarray,
        R: int,
        token: np.ndarray,
        pos: np.ndarray,
        sp: SampleParams,
        attribute: bool,
    ):
        """One ragged decode dispatch (R == 1: the read-only paged_attend
        program + the jitted kv_append block-append; R > 1: the fused
        chunk carrying the donated pool).  Everything here is the compute
        phase — kv_gather/kv_scatter stop existing on this path."""
        tables = jnp.asarray(self._table_ids(order if R == 1 else None))
        args = (
            self.eng.window_params,
            self.eng.edge_params,
            jnp.asarray(token),
            self.kv_store.kv,
            tables,
            jnp.asarray(pos, dtype=jnp.int32),
            jnp.asarray(active),
            sp,
            self.keys,
            self.counts,
        )
        t0 = time.perf_counter()
        if R > 1:
            stacked, pool, self.counts, self.keys = self._ragged_chunk_fn(R)(*args)
            self.kv_store.kv = pool
            src = stacked
        else:
            res, rows, self.counts, self.keys = self._ragged_step(*args)
            bt = self._kv_cfg.block_tokens
            # inactive-lane sentinel: past the block axis, never negative
            # (see BlockStore append)
            phys = np.full(self.slots, self._kv_cfg.pool_blocks, dtype=np.int32)
            off = np.zeros(self.slots, dtype=np.int32)
            for _nonce, slot in order.items():
                p0 = int(self.pos[slot])
                phys[slot] = self._tables[slot].blocks[p0 // bt]
                off[slot] = p0 % bt
            self.kv_store.append_rows(rows, phys, off)
            src = res
        if attribute:
            jax.block_until_ready((src, self.kv_store.kv))
            self._observe_phase(PHASE_COMPUTE, t0, order, R)
        return src

    def _observe_phase(
        self, phase: str, t0: float, order: Dict[str, int], R: int
    ) -> None:
        """One histogram observation per dispatch, plus a recorder span on
        every participating request's timeline (the recorder applies its
        own trace sampling)."""
        dur_ms = (time.perf_counter() - t0) * 1000.0
        _PHASE_MS.labels(phase=phase).observe(dur_ms)
        rec = get_recorder()
        for nonce in order:
            rec.span(nonce, phase, dur_ms, batch=len(order), chunk=R)

    # adaptive spec gate, same thresholds/semantics as LocalEngine's
    SPEC_WARMUP_BLOCKS = LocalEngine.SPEC_WARMUP_BLOCKS
    SPEC_MIN_TOKENS_PER_BLOCK = LocalEngine.SPEC_MIN_TOKENS_PER_BLOCK

    def _spec_worthwhile(self, nonce: str) -> bool:
        st = self._spec_stats.get(nonce)
        if st is None or st[0] < self.SPEC_WARMUP_BLOCKS:
            return True
        return st[1] / st[0] >= self.SPEC_MIN_TOKENS_PER_BLOCK

    def _decode_spec_lanes(
        self, spec_reqs: Dict[str, Tuple[int, int, int]]
    ) -> Dict[str, SampleResult]:
        """One vmapped verify block over the speculating lanes.  Each lane
        emits 1..L+1 tokens (its own acceptance); the first returns now and
        the rest buffer, so lanes genuinely advance unevenly."""
        token = np.zeros((self.slots, 1), dtype=np.int32)
        active = np.zeros(self.slots, dtype=bool)
        pos = np.zeros(self.slots, dtype=np.int32)
        for nonce, (tok, slot, _budget) in spec_reqs.items():
            token[slot, 0] = tok
            active[slot] = True
            pos[slot] = self.pos[slot]
        t_blk = time.perf_counter()
        out_block, self.hist, self.kv = self._spec_step(
            self.eng.window_params, self.eng.edge_params, jnp.asarray(token),
            self.hist, self.kv, jnp.asarray(pos), jnp.asarray(active),
        )
        out_h = np.asarray(out_block)  # [slots, L+1]; -1 past acceptance
        blk_ms = (time.perf_counter() - t_blk) * 1000.0
        now = time.time()
        zero_lp = np.zeros((1,), np.float32)
        zero_tt = np.zeros((1, MAX_TOP_LOGPROBS), np.int32)
        zero_tlp = np.zeros((1, MAX_TOP_LOGPROBS), np.float32)
        res: Dict[str, SampleResult] = {}
        total_emitted = 0
        for nonce, (_tok, slot, budget) in spec_reqs.items():
            emitted = min(int((out_h[slot] >= 0).sum()), budget)
            total_emitted += emitted
            rows = [
                SampleResult(
                    np.ascontiguousarray(out_h[slot, i : i + 1]).astype(np.int32),
                    zero_lp, zero_tt, zero_tlp,
                )
                for i in range(emitted)
            ]
            self.pos[slot] += emitted
            self.last_used[slot] = now
            st = self._spec_stats.setdefault(nonce, [0, 0])
            st[0] += 1
            st[1] += emitted
            res[nonce] = rows[0]
            if rows[1:]:
                self._buffer.setdefault(nonce, []).extend(rows[1:])
        # the verify block amortizes one dispatch over every accepted
        # token: per-token share, observed tokens-served times (the same
        # convention as the plain batched dispatch and LocalEngine's spec
        # path, keeping the family's count == tokens on every path)
        per_tok_ms = blk_ms / max(total_emitted, 1)
        _DECODE_STEP_MS.observe_n(per_tok_ms, total_emitted)
        return res

    def warm_chunks(self) -> None:
        """Compile the batched step and the fused-chunk widths up front with
        a throwaway session, so the FIRST budgeted request doesn't stall
        every concurrent lane on a multi-second scan compile (the batch loop
        runs all lanes on one compute executor)."""
        t0 = time.time()
        dec = DecodingParams(temperature=0.0)
        self.prefill_and_sample("__warm__", [0], dec)
        slot = self.slot_of["__warm__"]
        if self.spec_lookahead > 0:
            # the greedy warm request IS spec-eligible: the first budgeted
            # round below compiles the verify block; disable the gate stats
            # afterwards so warmup acceptance doesn't bias real requests
            self.decode_batch({"__warm__": (0, dec)}, budgets={"__warm__": 8})
            self._buffer.pop("__warm__", None)
            self._spec_stats.pop("__warm__", None)
        # sampled decoding is spec-ineligible, so these rounds compile the
        # PLAIN step/chunk programs even on spec-enabled engines
        dec_plain = DecodingParams(temperature=1.0) if self.spec_lookahead else dec
        for r in (1,) + tuple(self.CHUNK_BUCKETS):
            if self.pos[slot] + r < self.max_seq:
                self.decode_batch(
                    {"__warm__": (0, dec_plain)},
                    budgets={"__warm__": r} if r > 1 else None,
                )
                self._buffer.pop("__warm__", None)
        self.end_session("__warm__")
        widths = 1 + len(self.CHUNK_BUCKETS)
        if self.kv_pool is not None:
            # R==1 dispatches gather at the pow2 bucket of the widest
            # ACTIVE table (_table_ids): compile the step at every bucket
            # width now, with a throwaway session grown into each bucket,
            # so the first long-context request doesn't stall the whole
            # batch loop on a mid-flight width compile
            from dnet_tpu.kv import KVPoolExhausted

            bt = self._kv_cfg.block_tokens
            nb_full = self.max_seq // bt
            # bucket ladder: pow2 widths, plus the clamped full width when
            # nb_full itself is not a power of two (dispatches clamp to it,
            # so it is a real compiled width too)
            half = 1
            while half < nb_full:
                w = min(half * 2, nb_full)
                # smallest prompt whose table lands in bucket w: one token
                # past `half` full blocks (half+1 blocks round up past half)
                n_tok = half * bt + 1
                if n_tok + 1 >= self.max_seq:
                    break
                try:
                    self.prefill_and_sample("__warm__", [0] * n_tok, dec_plain)
                except KVPoolExhausted:
                    # a pool this tight can never serve a table this wide,
                    # so the width can never be dispatched either
                    self.end_session("__warm__")
                    break
                self.decode_batch({"__warm__": (0, dec_plain)})
                self._buffer.pop("__warm__", None)
                self.end_session("__warm__")
                widths += 1
                half = w
        log.info(
            "[PROFILE] warmed batched chunk programs (%d widths) in %.1fs",
            widths, time.time() - t0,
        )

    def generate(
        self,
        prompt_ids: Sequence[int],
        decoding: Optional[DecodingParams] = None,
        max_tokens: int = 256,
        eos_token_ids: Optional[set] = None,
        nonce: str = "batched",
    ):
        """Single-sequence convenience loop over the batched program (tests /
        parity with LocalEngine.generate)."""
        decoding = decoding or DecodingParams()
        eos = eos_token_ids or set()
        self.end_session(nonce)
        res = self.prefill_and_sample(nonce, prompt_ids, decoding)
        token = int(res.token[0])
        yield self.token_result(nonce, res, step=0, decoding=decoding)
        if token in eos:
            self.end_session(nonce)
            return
        for step in range(1, max_tokens):
            if self.pos[self.slot_of[nonce]] >= self.max_seq:
                break
            res_map, errs = self.decode_batch({nonce: (token, decoding)})
            if errs:
                raise RuntimeError(errs[nonce])
            res_row = res_map[nonce]
            token = int(res_row.token[0])
            yield self.token_result(nonce, res_row, step=step, decoding=decoding)
            if token in eos:
                break
        self.end_session(nonce)
