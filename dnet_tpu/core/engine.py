"""Single-process inference engine: the minimum end-to-end slice.

Runs a model (or a shard's layer range) on the local JAX device(s):
prefill + token-by-token decode with preallocated KV, bucketed prompt
padding (static shapes -> no per-request recompiles), donated cache buffers
(XLA-level reuse standing in for the reference's memory pools,
src/dnet/core/memory/memory_pool.py), and per-nonce KV sessions with TTL
expiry (reference: src/dnet/shard/runtime.py:374-396).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dnet_tpu.core.kvcache import init_cache
from dnet_tpu.core.sampler import (
    MAX_TOP_LOGPROBS,
    SamplePlan,
    SampleParams,
    SampleResult,
    pack_chunk_results,
    sample,
)
from dnet_tpu.core.types import DecodingParams, TokenResult
from dnet_tpu.kv import (
    BlockPool,
    BlockStore,
    KVPoolExhausted,
    PagedKVConfig,
    PagedPrefixCache,
    PageTable,
    paged_enabled,
)
from dnet_tpu.models import ModelConfig, get_ring_model_cls
from dnet_tpu.obs import get_recorder, metric
from dnet_tpu.obs.jit import instrument_jit
from dnet_tpu.utils.checkpoint import Checkpoint
from dnet_tpu.utils.logger import get_logger

log = get_logger()

_DECODE_STEP_MS = metric("dnet_decode_step_ms")
_PREFILL_MS = metric("dnet_prefill_ms")
_LAYER_MS = metric("dnet_layer_compute_ms")
_SESS_EVICTED = metric("dnet_kv_sessions_evicted_total")


def bucket_length(n: int, min_bucket: int = 16) -> int:
    b = min_bucket
    while b < n:
        b *= 2
    return b


@dataclass
class Session:
    """Per-nonce decode state."""

    nonce: str = ""  # owning request id (flight-recorder span key)
    kv: dict = None  # stacked [L, ...] cache (fit policy)
    kv_list: list = None  # per-layer [1, ...] caches (offload policies)
    pos: int = 0
    key: jax.Array = None
    counts: jax.Array = None  # [B, V] int32 seen-token counts (repetition penalty)
    last_used: float = field(default_factory=time.time)
    # chunk pipelining: last sampled token ON DEVICE (chains the next chunk
    # without a host round trip) + dispatched-but-unread chunk queue
    last_token: jax.Array = None  # [B, 1] int32
    pending: "deque" = field(default_factory=lambda: deque())
    # speculative decoding: device-resident committed-token history
    # (prompt + generated), indexed by position — hist[i] is the token FED
    # at position i (whose KV landed in slot i).  None unless the engine
    # was built with spec_lookahead > 0.
    hist: jax.Array = None  # [B, max_seq] int32
    # paged KV (DNET_KV_PAGED=1): this session's block ledger in the
    # engine's BlockPool — admission/extension debit free blocks as pos
    # grows instead of pinning max_seq rows up front (kv/paged.py)
    pages: object = None
    # draft-MODEL speculation: the small model's own KV cache (None unless
    # the engine was built with draft_dir)
    dkv: dict = None
    # acceptance accounting: blocks run / tokens emitted, feeding the
    # adaptive spec-vs-chunk gate (spec_worthwhile)
    spec_blocks: int = 0
    spec_emitted: int = 0


class LocalEngine:
    """One process, one device (or data-parallel later): full hot path.

    layers=None means the full model (single-shard serving); a sub-range
    makes this engine a shard's compute core.
    """

    # class default so engine subclasses with their own __init__ (MeshEngine)
    # are spec-ineligible unless they opt in
    spec_lookahead = 0

    def __init__(
        self,
        model_dir: str | Path,
        layers: Optional[Sequence[int]] = None,
        batch: int = 1,
        max_seq: int = 2048,
        param_dtype: str = "bfloat16",
        kv_dtype: Optional[str] = None,
        kv_ttl_s: float = 600.0,
        shard_mode: bool = False,
        window_size: int = 0,
        residency_size: int = 0,
        repack_dir: Optional[str] = None,
        kv_quant_bits: int = 0,
        weight_quant_bits: int = 0,
        weight_quant_group: int = 0,
        prefix_cache_size: int = 0,
        spec_lookahead: int = 0,
        draft_dir: Optional[str | Path] = None,
        kv_paged: Optional[bool] = None,
    ):
        self.ckpt = Checkpoint(model_dir)
        self.config = ModelConfig.from_hf(self.ckpt.config)
        model_cls = get_ring_model_cls(self.config.model_type)
        all_layers = list(range(self.config.num_hidden_layers))
        self.model = model_cls(self.config, layers if layers is not None else all_layers)
        self.batch = batch
        self.max_seq = max_seq
        self.param_dtype = jnp.dtype(param_dtype)
        self.kv_dtype = kv_dtype or param_dtype
        self.kv_quant_bits = kv_quant_bits
        self.weight_quant_bits = weight_quant_bits
        self.weight_quant_group = weight_quant_group
        if weight_quant_bits not in (0, 4, 8):
            raise NotImplementedError(
                "weight quantization supports 4 (packed int4) or 8 (int8) bits"
            )
        self.kv_ttl_s = kv_ttl_s
        # shard_mode: load only the edge weights this layer range needs
        # (reference: edge tensors loaded iff shard holds layer 0 / the last
        # layer, src/dnet/shard/runtime.py:262-286)
        self.shard_mode = shard_mode
        self.spec_lookahead = int(spec_lookahead)
        self.sessions: Dict[str, Session] = {}

        from dnet_tpu.core.weights import plan_policy

        self.plan = plan_policy(
            len(self.model.layers), window_size, residency_size
        )
        self._repack_dir = repack_dir
        self.weight_cache = None
        self._windows: list[list[int]] = []
        # paged KV (kv/paged.py): the pool is this engine's admission
        # ledger — sessions debit blocks as their pos grows instead of
        # pinning max_seq rows, and exhaustion raises KVPoolExhausted (a
        # queueable backpressure signal) before any compute burns
        self.kv_pool = None
        self._kv_paged_cfg = None
        want_paged = paged_enabled() if kv_paged is None else bool(kv_paged)
        if want_paged:
            if shard_mode:
                log.warning(
                    "paged KV not supported for shard engines (the ring "
                    "runtime owns shard admission); serving the dense path"
                )
            else:
                self._init_paged(slots=8 + prefix_cache_size)
        self.prefix_cache = None
        if prefix_cache_size > 0:
            if self.plan.streams_weights or shard_mode:
                log.warning(
                    "prefix cache requested but unsupported for %s engines; "
                    "disabled",
                    "weight-streaming" if self.plan.streams_weights else "shard",
                )
            else:
                self.prefix_cache = self._build_prefix_cache(prefix_cache_size)

        # observability sync knobs (reference core/observability.py:31-107:
        # forced mx.eval sync points; here block_until_ready fences): without
        # a fence, XLA async dispatch makes per-stage wall times unattributable
        from dnet_tpu.config import get_settings

        obs = get_settings().obs
        self._sync_per_layer = obs.sync_per_layer
        self._sync_every_n = obs.sync_stride()  # 0 = never, N >= 1 = every N

        # draft-MODEL speculation (r5, beyond both the reference and the
        # prompt-lookup drafts): a second, much smaller checkpoint drafts
        # spec_lookahead tokens autoregressively; the target verifies the
        # block in ONE forward.  Greedy-exactness is independent of draft
        # quality (only acceptance varies), so any same-vocab model works.
        self.draft = None
        if draft_dir is not None:
            if spec_lookahead <= 0:
                raise ValueError(
                    "draft_dir needs spec_lookahead > 0 (the draft model "
                    "exists only to draft verify blocks)"
                )
            self._load_draft(draft_dir)

        self._load_params()
        self._build_fns()

    def _load_draft(self, draft_dir: str | Path) -> None:
        ckpt = Checkpoint(draft_dir)
        cfg = ModelConfig.from_hf(ckpt.config)
        if cfg.vocab_size != self.config.vocab_size:
            raise ValueError(
                f"draft model vocab {cfg.vocab_size} != target vocab "
                f"{self.config.vocab_size}; speculation needs a shared "
                f"token space"
            )
        model_cls = get_ring_model_cls(cfg.model_type)
        dmodel = model_cls(cfg, list(range(cfg.num_hidden_layers)))
        if not dmodel.kv_rewindable(self.max_seq):
            raise ValueError(
                f"draft model {cfg.model_type} uses rotating SWA caches, "
                f"which cannot rewind after partial acceptance"
            )
        per_layer = [dmodel.map_layer(ckpt.load_layer_raw(a)) for a in dmodel.layers]
        window = self._cast(dmodel.stack_layers(per_layer))
        edge = self._cast(dmodel.map_edge(ckpt.load_edge_raw()))
        from types import SimpleNamespace

        self.draft = SimpleNamespace(
            model=dmodel, config=cfg, window=window, edge=edge
        )
        log.info(
            "draft model loaded: %s (%d layers) drafting for %s",
            cfg.model_type, cfg.num_hidden_layers, self.config.model_type,
        )

    @classmethod
    def from_params(
        cls,
        config: ModelConfig,
        window_params,
        edge_params,
        *,
        batch: int = 1,
        max_seq: int = 2048,
        param_dtype: str = "bfloat16",
        kv_dtype: Optional[str] = None,
        kv_quant_bits: int = 0,
        kv_ttl_s: float = 600.0,
        spec_lookahead: int = 0,
        kv_paged: Optional[bool] = None,
    ) -> "LocalEngine":
        """Build an engine around already-materialised parameters (no
        checkpoint on disk) — the zero-egress bench path: the serving hot
        loop is identical, only weight provenance differs."""
        from dnet_tpu.core.weights import plan_policy

        self = cls.__new__(cls)
        self.ckpt = None
        self.config = config
        model_cls = get_ring_model_cls(config.model_type)
        self.model = model_cls(config, list(range(config.num_hidden_layers)))
        self.batch = batch
        self.max_seq = max_seq
        self.param_dtype = jnp.dtype(param_dtype)
        self.kv_dtype = kv_dtype or param_dtype
        self.kv_quant_bits = kv_quant_bits
        self.weight_quant_bits = 0
        self.weight_quant_group = 0
        self.kv_ttl_s = kv_ttl_s
        self.shard_mode = False
        self.spec_lookahead = int(spec_lookahead)
        self.sessions = {}
        self.plan = plan_policy(len(self.model.layers), 0, 0)
        self._repack_dir = None
        self.weight_cache = None
        self._windows = []
        self.prefix_cache = None
        self.draft = None
        self.kv_pool = None
        self._kv_paged_cfg = None
        if paged_enabled() if kv_paged is None else bool(kv_paged):
            self._init_paged(slots=8)
        self.window_params = jax.tree.map(jnp.asarray, window_params)
        self.edge_params = jax.tree.map(jnp.asarray, edge_params)
        self._sync_per_layer = False
        self._sync_every_n = 0
        self._build_fns()
        return self

    # ---- paged KV ------------------------------------------------------
    def _init_paged(self, slots: int) -> None:
        """Build this engine's BlockPool admission ledger (DNET_KV_PAGED=1).
        `slots` only feeds the auto pool size when DNET_KV_POOL_BLOCKS=0
        — how many max_seq sequences' worth of blocks to provision."""
        try:
            cfg = PagedKVConfig.from_settings(self.max_seq, slots=max(slots, 1))
        except ValueError as exc:
            log.warning("paged KV disabled (%s); serving the dense path", exc)
            return
        self._kv_paged_cfg = cfg
        self.kv_pool = BlockPool(cfg)
        log.info(
            "paged KV on: %d blocks x %d tokens (%s sequences' worth)",
            cfg.pool_blocks, cfg.block_tokens,
            cfg.pool_blocks * cfg.block_tokens // self.max_seq,
        )

    def _build_prefix_cache(self, capacity: int):
        """Dense PrefixCache, or the block-sharing PagedPrefixCache when
        the paged pool is on (same lookup/store surface; snapshots dedup
        shared prefixes into refcounted block runs instead of deep copies)."""
        from dnet_tpu.core.prefix_cache import PrefixCache

        if self.kv_pool is None:
            return PrefixCache(capacity)
        if self.batch != 1:
            log.warning(
                "paged prefix sharing needs batch=1 sessions; "
                "using dense snapshots"
            )
            return PrefixCache(capacity)
        try:
            store = BlockStore(
                self.model, len(self.model.layers), self._kv_paged_cfg,
                self.kv_dtype, quant_bits=self.kv_quant_bits,
                session_tokens=self.max_seq,
            )
        except NotImplementedError as exc:
            log.warning(
                "paged prefix sharing unavailable (%s); using dense "
                "snapshots", exc,
            )
            return PrefixCache(capacity)
        return PagedPrefixCache(
            self.kv_pool, store, capacity, row_tokens=self.max_seq
        )

    def _paged_ensure(self, sess: "Session", n_tokens: int) -> None:
        """Admit/extend a session to cover n_tokens: debit the pool for any
        blocks its ledger is missing.  Raises KVPoolExhausted (typed
        backpressure) BEFORE any compute — never a shape error mid-step."""
        if self.kv_pool is None:
            return
        if sess.pages is None:
            sess.pages = PageTable()
        self.kv_pool.ensure(sess.pages, min(n_tokens, self.max_seq))

    def _paged_release(self, sess: Optional["Session"]) -> None:
        if self.kv_pool is not None and sess is not None:
            self.kv_pool.release_table(sess.pages)

    # ---- loading ------------------------------------------------------
    def _cast(self, tree):
        def cast_leaf(a: np.ndarray):
            arr = jnp.asarray(a)
            if jnp.issubdtype(arr.dtype, jnp.floating):
                arr = arr.astype(self.param_dtype)
            return arr

        return jax.tree.map(cast_leaf, tree)

    def _load_params(self) -> None:
        t0 = time.perf_counter()
        m = self.model
        if self.weight_quant_bits and not m.supports_weight_quant:
            raise NotImplementedError(
                f"weight quantization not supported for {self.config.model_type}"
            )
        if self.plan.streams_weights:
            # offload / sliding_fit: layers stream host<->HBM via WeightCache;
            # quantized layers shrink the host->HBM transfer (the streaming
            # bottleneck) by the same 2x/4x as the resident case
            from dnet_tpu.core.weights import HostLayerStore, WeightCache

            store = HostLayerStore(
                self.ckpt,
                m,
                param_dtype=str(self.param_dtype),
                repack_dir=self._repack_dir,
                weight_quant_bits=self.weight_quant_bits,
                weight_quant_group=self.weight_quant_group,
            )
            self.weight_cache = WeightCache(store, max_resident=self.plan.residency)
            w = self.plan.window_size
            self._windows = [
                m.layers[i : i + w] for i in range(0, len(m.layers), w)
            ]
            self.window_params = None
            self.weight_cache.prefetch(self._windows[0])
        else:
            per_layer = [m.map_layer(self.ckpt.load_layer_raw(a)) for a in m.layers]
            stacked = m.stack_layers(per_layer)
            if self.weight_quant_bits:
                stacked = m.quantize_params(
                    stacked, self.weight_quant_bits, scale_dtype=self.param_dtype,
                    group_size=self.weight_quant_group,
                )
            self.window_params = self._cast(stacked)
        edge_raw = m.map_edge(self.ckpt.load_edge_raw())
        if self.shard_mode:
            tied = self.config.tie_word_embeddings
            if not (m.is_first or (m.is_last and tied)):
                edge_raw.pop("embed", None)
            if not m.is_last:
                edge_raw.pop("final_norm", None)
                edge_raw.pop("lm_head", None)
        # tied embeddings: lm_project reads edge["embed"] (reference handles
        # ties in load_weights, src/dnet/core/models/base.py:111-195)
        if self.weight_quant_bits:
            edge_raw = m.quantize_edge(
                edge_raw, self.weight_quant_bits, scale_dtype=self.param_dtype,
                group_size=self.weight_quant_group,
            )
        self.edge_params = self._cast(edge_raw)
        log.info(
            "[PROFILE] loaded %d layers (%s) in %.2fs",
            len(m.layers),
            self.config.model_type,
            time.perf_counter() - t0,
        )

    # ---- jitted step functions ---------------------------------------
    def _build_fns(self) -> None:
        model = self.model

        def full_logits(window_params, edge_params, tokens, kv, pos, last_idx):
            x = model.embed(edge_params, tokens)
            x, kv = model.apply_window(window_params, x, kv, pos, t_real=last_idx + 1)
            x_last = jax.lax.dynamic_slice_in_dim(x, last_idx, 1, axis=1)
            x_last = model.normalize(edge_params, x_last)
            logits = model.lm_project(edge_params, x_last)
            return logits[:, 0], kv

        # donate kv (arg 3): each step reuses the cache buffers in place
        # (instrumented: dnet_jit_compiles_total{fn=} separates warmup
        # compiles from steady state in load reports)
        self._forward = instrument_jit(
            jax.jit(full_logits, donate_argnums=(3,)), "local_prefill"
        )

        def decode_and_sample(window_params, edge_params, token, kv, pos, sp, key, counts,
                              plan=None):
            logits, kv = full_logits(window_params, edge_params, token, kv, pos, 0)
            res = sample(logits, sp, key, token_counts=counts, plan=plan)
            counts = counts.at[jnp.arange(counts.shape[0]), res.token].add(1)
            return res, kv, counts

        self._decode = instrument_jit(
            jax.jit(decode_and_sample, static_argnums=(8,),
                    donate_argnums=(3, 7)),
            "local_decode",
        )

        def decode_chunk_fn(window_params, edge_params, token, kv, pos, sp, key, counts,
                            n_steps, plan=None):
            """n_steps decode iterations fused into ONE XLA program: the
            sampled token feeds back on-device, so the host pays one dispatch
            + one device->host read per CHUNK instead of per token.  Key
            evolution matches the per-step path exactly (split-before-sample),
            so chunked and unchunked decode produce identical streams for a
            given seed.

            Returns the per-step results PACKED into one f32 array (one
            device->host transfer per chunk — four separate array reads cost
            4 round trips, which dominates chunk latency on a remote-attached
            device), plus the last sampled token ON DEVICE so the next chunk
            can chain without a host round trip."""

            def body(carry, _):
                tok, kv, pos, key, counts = carry
                key, step_key = jax.random.split(key)
                logits, kv = full_logits(window_params, edge_params, tok, kv, pos, 0)
                res = sample(logits, sp, step_key, token_counts=counts, plan=plan)
                counts = counts.at[jnp.arange(counts.shape[0]), res.token].add(1)
                return (res.token[:, None], kv, pos + 1, key, counts), res

            (last_tok, kv, _, key, counts), results = jax.lax.scan(
                body, (token, kv, pos, key, counts), None, length=n_steps
            )
            packed = pack_chunk_results(results, plan is None or plan.logprobs)
            return packed, last_tok, kv, key, counts

        self._decode_chunk = instrument_jit(
            jax.jit(decode_chunk_fn, static_argnums=(8, 9),
                    donate_argnums=(3, 7)),
            "local_decode_chunk",
        )

        def hidden_step(window_params, x, kv, pos, t_real, kinds=None):
            return model.apply_window(
                window_params, x, kv, pos, layer_kinds=kinds, t_real=t_real
            )

        # mid-shard path (no embed/head): used by the ring runtime and the
        # offload per-layer loop (kinds slices the mixed-attention array)
        self._hidden = jax.jit(hidden_step, donate_argnums=(2,))

        def hidden_round(window_params, x, kv, pos, t_real, lo, hi, kinds=None):
            """One ring ROUND: apply the [lo, hi) slice of this engine's
            stacked layers (static bounds -> one compiled program per round;
            XLA slices in place, no host-side weight copies)."""
            wp = jax.tree.map(lambda a: a[lo:hi], window_params)
            kv_r = jax.tree.map(lambda a: a[lo:hi], kv)
            x, kv_r = model.apply_window(
                wp, x, kv_r, pos, layer_kinds=kinds, t_real=t_real
            )
            kv = jax.tree.map(lambda f, s: f.at[lo:hi].set(s), kv, kv_r)
            return x, kv

        self._hidden_round = jax.jit(
            hidden_round, static_argnums=(5, 6), donate_argnums=(2,)
        )

        def embed_window(window_params, edge_params, tokens, kv, pos, t_real):
            """First-shard path: embed + this shard's window, hidden out."""
            x = model.embed(edge_params, tokens)
            return model.apply_window(window_params, x, kv, pos, t_real=t_real)

        self._embed_window = jax.jit(embed_window, donate_argnums=(3,))

        def hidden_tail(window_params, edge_params, x, kv, pos, last_idx, sp, key, counts):
            """Last-shard path: window + normalize + head + sample."""
            x, kv = model.apply_window(window_params, x, kv, pos, t_real=last_idx + 1)
            x_last = jax.lax.dynamic_slice_in_dim(x, last_idx, 1, axis=1)
            x_last = model.normalize(edge_params, x_last)
            logits = model.lm_project(edge_params, x_last)[:, 0]
            res = sample(logits, sp, key, token_counts=counts)
            counts = counts.at[jnp.arange(counts.shape[0]), res.token].add(1)
            return res, kv, counts

        self._hidden_tail = jax.jit(hidden_tail, donate_argnums=(3, 8))

        L = self.spec_lookahead
        if L > 0:
            # one speculative verify step: draft L tokens from history, run
            # ONE forward over [tok, d_1..d_L], greedily accept the agreeing
            # prefix.  KV for all L+1 positions is written; the host-side
            # caller rewinds pos to the accepted count (core/spec.py)
            from dnet_tpu.core.spec import make_spec_step

            def window_pass(wp, x, kv, pos, t_real):
                return model.apply_window(wp, x, kv, pos, t_real=t_real)

            self._spec_step = jax.jit(
                make_spec_step(model, window_pass, L), donate_argnums=(3, 4)
            )

        if L > 0 and self.draft is not None:
            # draft-MODEL verify block: L sequential small-model steps draft
            # the block on-device (the draft's own KV rides the session),
            # then the target verifies in one (L+1)-wide forward.  Rewind
            # discipline matches the ngram path: all drafted positions
            # write both caches; stale rows are never attended (causal
            # masks at the rewound pos) and are overwritten on reuse.
            from dnet_tpu.core.spec import accept_drafts

            dmodel = self.draft.model

            def draft_forward(dwp, dep, tok, dkv, p):
                x = dmodel.embed(dep, tok)
                x, dkv = dmodel.apply_window(dwp, x, dkv, p, t_real=1)
                x = dmodel.normalize(dep, x)
                return dmodel.lm_project(dep, x)[:, 0], dkv

            def spec_step_draft(wp, ep, dwp, dep, tok, kv, dkv, pos):
                def body(carry, _):
                    t, dkv, p = carry
                    logits, dkv = draft_forward(dwp, dep, t, dkv, p)
                    nt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    return (nt[:, None], dkv, p + 1), nt

                (_, dkv, _), drafts = jax.lax.scan(
                    body, (tok, dkv, pos), None, length=L
                )
                drafts = jnp.moveaxis(drafts, 0, 1)  # [B, L]
                block = jnp.concatenate([tok, drafts], axis=1)  # [B, L+1]
                x = model.embed(ep, block)
                x, kv = model.apply_window(wp, x, kv, pos, t_real=L + 1)
                x = model.normalize(ep, x)
                logits = model.lm_project(ep, x)
                preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                _, out = accept_drafts(preds, drafts)
                return out, kv, dkv

            self._spec_step_draft = jax.jit(
                spec_step_draft, donate_argnums=(5, 6)
            )

            def draft_prefill(dwp, dep, tokens, dkv, pos, t_real):
                x = dmodel.embed(dep, tokens)
                _, dkv = dmodel.apply_window(dwp, x, dkv, pos, t_real=t_real)
                return dkv

            self._draft_prefill = jax.jit(draft_prefill, donate_argnums=(3,))

    # ---- offload execution --------------------------------------------
    def run_layers(self, sess: "Session", x: jnp.ndarray, pos: int, t_real=None) -> jnp.ndarray:
        """Apply this engine's layers to x under the active policy.

        Fit: one fused scan over the resident stack.  Offload/sliding_fit:
        window-at-a-time — wait on the current window's prefetch, compute
        per-layer (one compiled program reused for every layer), prefetch
        the next window during compute, release+evict behind us, and wrap
        the prefetch to window 0 for the next token
        (reference offload.py:183-421)."""
        t_real = jnp.int32(x.shape[1] if t_real is None else t_real)
        if not self.plan.streams_weights:
            x, sess.kv = self._hidden(
                self.window_params, x, sess.kv, jnp.int32(pos), t_real
            )
            return x
        return self._stream_windows(sess, x, pos, t_real, self._windows, None)

    def _stream_windows(
        self, sess, x, pos, t_real, windows, prefetch_after
    ) -> jnp.ndarray:
        """Window-at-a-time weight-streaming loop; `prefetch_after` (a layer
        list) overrides the wrap-to-first prefetch — multi-round rings
        prefetch the NEXT round's window while other devices compute."""
        sliding = self.plan.name == "sliding_fit"
        for wi, window in enumerate(windows):
            if wi + 1 < len(windows):
                nxt = windows[wi + 1]
            else:
                nxt = prefetch_after if prefetch_after is not None else windows[0]
            if len(windows) > 1 or prefetch_after is not None:
                self.weight_cache.prefetch(nxt)
            for layer in window:
                p = self.weight_cache.get(layer)
                li = self.model.abs_to_local[layer]
                kinds = (
                    None
                    if self.model.layer_kinds is None
                    else self.model.layer_kinds[li : li + 1]
                )
                t0 = time.perf_counter() if self._sync_per_layer else 0.0
                x, sess.kv_list[li] = self._hidden(
                    p, x, sess.kv_list[li], jnp.int32(pos), t_real, kinds
                )
                if self._sync_per_layer:
                    x.block_until_ready()
                    dt_ms = (time.perf_counter() - t0) * 1000
                    _LAYER_MS.observe(dt_ms)
                    get_recorder().span(
                        sess.nonce, "layer_compute", dt_ms, layer=layer
                    )
                    log.info("[PROFILE] layer %d: %.2fms", layer, dt_ms)
                # unpin immediately so the residency budget can evict behind
                # us; sliding_fit (residency < window) delta-swaps eagerly
                self.weight_cache.release([layer])
                if sliding:
                    self.weight_cache.evict([layer])
            if (len(windows) > 1 or prefetch_after is not None) and not sliding:
                self.weight_cache.evict(window)  # make room for what's coming
        return x

    def apply_round(
        self,
        sess: "Session",
        x: jnp.ndarray,
        pos: int,
        run: Sequence[int],
        t_real=None,
        prefetch_next: Optional[Sequence[int]] = None,
    ) -> jnp.ndarray:
        """Apply ONE contiguous round (`run`) of this engine's layers — the
        k-round ring schedule (reference api/utils.py:62-131): a device's
        layers are dealt in k contiguous chunks and the activation visits it
        k times per token, so streamed weights prefetch while OTHER devices
        compute.  `prefetch_next` seeds the next round's first window."""
        m = self.model
        t_real = jnp.int32(x.shape[1] if t_real is None else t_real)
        if not self.plan.streams_weights:
            if (
                getattr(m, "pair_kinds", None)
                or getattr(m, "ring_phases", 1) > 1
                or getattr(m, "segmented_stack", False)
            ):
                raise NotImplementedError(
                    "multi-round rings need a flat layer stack (gpt_oss "
                    "paired / deepseek + mixed-qwen3_moe segmented layouts "
                    "pending)"
                )
            lo, hi = m.abs_to_local[run[0]], m.abs_to_local[run[-1]] + 1
            kinds = None if m.layer_kinds is None else m.layer_kinds[lo:hi]
            x, sess.kv = self._hidden_round(
                self.window_params, x, sess.kv, jnp.int32(pos), t_real, lo, hi,
                kinds,
            )
            return x
        w = self.plan.window_size or len(run)
        windows = [list(run[i : i + w]) for i in range(0, len(run), w)]
        return self._stream_windows(
            sess, x, pos, t_real, windows, list(prefetch_next or [])[:w] or None
        )

    # ---- sessions -----------------------------------------------------
    def new_session(
        self, nonce: str, seed: Optional[int] = None, kv=None, pos: int = 0
    ) -> Session:
        """kv/pos: seed the session from a prefix-cache snapshot instead of
        allocating + zero-filling a fresh cache it would immediately drop."""
        if seed is None:
            # fresh entropy per unseeded request — two users must not share a stream
            seed = int.from_bytes(__import__("os").urandom(4), "little")
        kv_list = None
        if kv is None:
            if self.plan.streams_weights:
                kv_list = [
                    init_cache(
                        self.model.kv_config(
                            1, self.batch, self.max_seq, self.kv_dtype,
                            quant_bits=self.kv_quant_bits,
                        )
                    )
                    for _ in self.model.layers
                ]
            else:
                kv = self.model.init_kv(
                    len(self.model.layers), self.batch, self.max_seq,
                    self.kv_dtype, quant_bits=self.kv_quant_bits,
                )
        sess = Session(
            nonce=nonce,
            kv=kv,
            kv_list=kv_list,
            pos=pos,
            key=jax.random.key(seed),
            counts=jnp.zeros((self.batch, self.config.vocab_size), dtype=jnp.int32),
            hist=(
                jnp.zeros((self.batch, self.max_seq), dtype=jnp.int32)
                if self.spec_lookahead > 0
                else None
            ),
            dkv=(
                self.draft.model.init_kv(
                    self.draft.config.num_hidden_layers, self.batch,
                    self.max_seq, self.kv_dtype,
                )
                if self.draft is not None
                else None
            ),
        )
        self.sessions[nonce] = sess
        return sess

    def end_session(self, nonce: str) -> None:
        self._paged_release(self.sessions.pop(nonce, None))

    def sweep_sessions(self) -> int:
        now = time.time()
        dead = [n for n, s in self.sessions.items() if now - s.last_used > self.kv_ttl_s]
        for n in dead:
            # the TTL sweep is the paged pool's garbage collector too: an
            # abandoned session's blocks return to the free list
            self._paged_release(self.sessions.pop(n))
        if dead:
            _SESS_EVICTED.inc(len(dead))
        return len(dead)

    def reset(self) -> None:
        for sess in self.sessions.values():
            self._paged_release(sess)
        self.sessions.clear()

    def close(self) -> None:
        self.reset()
        if self.weight_cache is not None:
            self.weight_cache.shutdown()

    # ---- inference ----------------------------------------------------
    def prefill(
        self,
        nonce: str,
        prompt_ids: Sequence[int],
        seed: Optional[int] = None,
        allow_store: bool = True,
    ):
        """Run the prompt; returns logits at the last real position.

        Reusing a live session continues at sess.pos (chunked prefill).
        allow_store=False suppresses the inline prefix-cache snapshot (a
        chunked caller stores the FULL prompt itself at the end).
        """
        full_ids = list(prompt_ids)
        if not full_ids:
            raise ValueError("empty prompt")
        t_pf = time.perf_counter()
        sess = self.sessions.get(nonce)
        fresh = sess is None
        # validate against the FULL prompt before any session mutation: a
        # too-long prompt must not leave a half-restored session behind
        start = 0 if sess is None else sess.pos
        if start + len(full_ids) > self.max_seq:
            raise ValueError(
                f"prompt length {start + len(full_ids)} exceeds max_seq {self.max_seq}"
            )
        if sess is None:
            hit = (
                self.prefix_cache.lookup(full_ids)
                if self.prefix_cache is not None
                else None
            )
            if hit is not None:
                n, kv_copy = hit
                sess = self.new_session(nonce, seed, kv=kv_copy, pos=n)
                get_recorder().span(nonce, "prefix_cache_hit", 0.0, tokens=n)
                prompt_ids = full_ids[n:]  # >= 1 token left by construction
            else:
                sess = self.new_session(nonce, seed)
        else:
            fresh = sess.pos == 0  # explicit chunked continuation
        T = len(prompt_ids)
        if self.kv_pool is not None:
            try:
                # admit BEFORE the forward: exhaustion must cost nothing and
                # must not leave a half-written cache behind
                self._paged_ensure(sess, sess.pos + T)
            except KVPoolExhausted:
                if fresh:
                    self.end_session(nonce)
                raise
        self._commit_prompt_hist(sess, full_ids, prompt_ids)
        # the PADDED width must also fit — dynamic_update_slice would clamp
        # the start index and silently shift the whole KV write otherwise
        Tpad = min(bucket_length(T), self.max_seq - sess.pos)
        tokens = np.zeros((self.batch, Tpad), dtype=np.int32)
        tokens[:, :T] = np.asarray(prompt_ids, dtype=np.int32)
        if self.plan.streams_weights:
            x = self.model.embed(self.edge_params, jnp.asarray(tokens))
            x = self.run_layers(sess, x, sess.pos, t_real=T)
            x_last = jax.lax.dynamic_slice_in_dim(x, T - 1, 1, axis=1)
            x_last = self.model.normalize(self.edge_params, x_last)
            logits = self.model.lm_project(self.edge_params, x_last)[:, 0]
        else:
            logits, sess.kv = self._forward(
                self.window_params, self.edge_params, jnp.asarray(tokens), sess.kv,
                jnp.int32(sess.pos), jnp.int32(T - 1),
            )
        if self.draft is not None:
            if fresh and len(prompt_ids) != len(full_ids):
                # prefix-cache hit seeded only the TARGET's kv; the draft
                # (tiny) simply re-reads the whole prompt from position 0
                self._advance_draft(sess, full_ids, 0)
            else:
                self._advance_draft(sess, prompt_ids, sess.pos)
        # repetition penalty counts GENERATED tokens only (prompt tokens are
        # not seeded): the ring's sampling shard never sees prompt ids, so
        # both serving paths must share this definition to stay equivalent.
        sess.pos += T
        sess.last_used = time.time()
        if (
            self.prefix_cache is not None
            and allow_store
            and fresh
            and sess.pos == len(full_ids)
        ):
            # snapshot the full-prompt KV (copied: step fns donate their kv;
            # the cache itself skips prompts below its min_tokens threshold)
            self.prefix_cache.store(full_ids, sess.kv)
        # dispatch wall time (logits are still async); a synced number needs
        # the DNET_OBS_SYNC_* fences, same as the [PROFILE] lines always did
        dt_ms = (time.perf_counter() - t_pf) * 1000
        _PREFILL_MS.observe(dt_ms)
        get_recorder().span(nonce, "prefill", dt_ms, tokens=T)
        return logits

    def seed_from_prefix(
        self, nonce: str, full_ids: Sequence[int], seed: Optional[int] = None
    ) -> int:
        """Chunk-aware prefix-cache entry: seed a FRESH session from the
        longest cached prefix of the FULL prompt (a chunked prefill would
        otherwise only look up its first chunk).  Returns the cached token
        count (0 = no hit)."""
        if self.prefix_cache is None or nonce in self.sessions:
            return 0
        hit = self.prefix_cache.lookup(list(full_ids))
        if hit is None:
            return 0
        n, kv_copy = hit
        self._restore_session(nonce, full_ids, n, kv_copy, seed)
        return n

    def _restore_session(
        self, nonce: str, full_ids: Sequence[int], n: int, kv, seed
    ) -> "Session":
        """Seed a FRESH session from a restored n-token prefix: the session
        itself, the spec history (the follow-up prefill only writes its own
        remainder — without this, prompt-lookup drafts would match against
        zeros), and the draft model's context (its kv is not cached;
        re-reading the prefix through the tiny model is cheaper than
        caching a second kv family).  Shared by this engine's prefix path
        and the batched engine's paged block adoption."""
        sess = self.new_session(nonce, seed, kv=kv, pos=n)
        if sess.hist is not None:
            ids = jnp.asarray(
                np.broadcast_to(np.asarray(full_ids[:n], dtype=np.int32), (self.batch, n))
            )
            sess.hist = jax.lax.dynamic_update_slice_in_dim(sess.hist, ids, 0, axis=1)
        self._advance_draft(sess, list(full_ids[:n]), 0)
        return sess

    def _advance_draft(self, sess: "Session", ids: Sequence[int], pos0: int) -> None:
        """Run the draft model over `ids` at absolute position pos0 so its
        cache tracks the committed context (draft-model speculation)."""
        if self.draft is None or sess.dkv is None or not ids:
            return
        T = len(ids)
        Tpad = min(bucket_length(T), self.max_seq - pos0)
        tokens = np.zeros((self.batch, Tpad), dtype=np.int32)
        tokens[:, :T] = np.asarray(ids, dtype=np.int32)
        sess.dkv = self._draft_prefill(
            self.draft.window, self.draft.edge, jnp.asarray(tokens),
            sess.dkv, jnp.int32(pos0), jnp.int32(T),
        )

    def store_prefix(self, nonce: str, full_ids: Sequence[int]) -> None:
        """Snapshot a fully-prefilled session's KV under the full prompt
        (chunked-prefill counterpart of the inline store in prefill())."""
        sess = self.sessions.get(nonce)
        if (
            self.prefix_cache is not None
            and sess is not None
            and sess.kv is not None
            and sess.pos == len(full_ids)
        ):
            self.prefix_cache.store(list(full_ids), sess.kv)

    def hidden_states(self, prompt_ids: Sequence[int]) -> np.ndarray:
        """Final-norm'd hidden states for a prompt — the embeddings serving
        primitive (BEYOND the reference, which schemas /v1/embeddings but
        never serves it).  One forward over a throwaway session, no
        sampling; works under every weight policy via run_layers.  Returns
        float32 [T, D] (callers pool)."""
        ids = list(prompt_ids)
        if not ids:
            raise ValueError("empty embeddings input")
        if len(ids) > self.max_seq:
            raise ValueError(
                f"input length {len(ids)} exceeds max_seq {self.max_seq}"
            )
        T = len(ids)
        Tpad = min(bucket_length(T), self.max_seq)
        tokens = np.zeros((self.batch, Tpad), dtype=np.int32)
        tokens[:, :T] = np.asarray(ids, dtype=np.int32)
        nonce = "__embed__"
        self.end_session(nonce)
        sess = self.new_session(nonce, seed=0)
        try:
            x = self.model.embed(self.edge_params, jnp.asarray(tokens))
            x = self.run_layers(sess, x, 0, t_real=T)
            h = self.model.normalize(self.edge_params, x)
            return np.asarray(h[0, :T], dtype=np.float32)
        finally:
            self.end_session(nonce)

    def decode_step(self, nonce: str, token_id: int, decoding: DecodingParams) -> SampleResult:
        sess = self.sessions[nonce]
        if sess.pos >= self.max_seq:
            raise ValueError(
                f"sequence length {sess.pos} reached max_seq {self.max_seq}"
            )
        self._paged_ensure(sess, sess.pos + 1)  # may raise KVPoolExhausted
        t_step = time.perf_counter()
        sess.key, step_key = jax.random.split(sess.key)
        sp = SampleParams.from_decoding(decoding)
        plan = SamplePlan.from_decoding(decoding)
        token = jnp.full((self.batch, 1), token_id, dtype=jnp.int32)
        if self.plan.streams_weights:
            x = self.model.embed(self.edge_params, token)
            x = self.run_layers(sess, x, sess.pos, t_real=1)
            x = self.model.normalize(self.edge_params, x)
            logits = self.model.lm_project(self.edge_params, x)[:, 0]
            res = sample(logits, sp, step_key, token_counts=sess.counts, plan=plan)
            sess.counts = sess.counts.at[:, int(res.token[0])].add(1)
        else:
            res, sess.kv, sess.counts = self._decode(
                self.window_params, self.edge_params, token, sess.kv,
                jnp.int32(sess.pos), sp, step_key, sess.counts, plan,
            )
        if self._sync_every_n and sess.pos % self._sync_every_n == 0:
            t0 = time.perf_counter()
            res.token.block_until_ready()
            drain_ms = (time.perf_counter() - t0) * 1000
            get_recorder().span(nonce, "decode_sync_drain", drain_ms,
                                step=sess.pos)
            log.info(
                "[PROFILE] decode step %d sync: %.2fms drain",
                sess.pos, drain_ms,
            )
        # dispatch wall (synced only when the fence above ran this step)
        _DECODE_STEP_MS.observe((time.perf_counter() - t_step) * 1000)
        sess.pos += 1
        sess.last_used = time.time()
        return res

    # ---- speculative decoding ----------------------------------------
    def _commit_prompt_hist(self, sess, full_ids, prompt_ids) -> None:
        """Commit the prompt to the spec history buffer; on a prefix-cache
        hit write the FULL prompt at 0 (the cached tokens were never fed
        through THIS session).  Shared by LocalEngine and MeshEngine
        prefill (same hist contract, two execution substrates)."""
        if self.spec_lookahead <= 0 or sess.hist is None:
            return
        n_cached = len(full_ids) - len(prompt_ids)
        ids = jnp.asarray(
            np.broadcast_to(
                np.asarray(full_ids, dtype=np.int32), (self.batch, len(full_ids))
            )
        )
        sess.hist = jax.lax.dynamic_update_slice_in_dim(
            sess.hist, ids, sess.pos - n_cached, axis=1
        )

    def spec_eligible(self, decoding: DecodingParams) -> bool:
        """Whether this engine + request pair may take the speculative path.

        Greedy only (spec emits raw argmaxes; sampled streams would need
        rejection sampling), no logprobs (the verify forward discards the
        softmax), no repetition penalty (counts are not threaded through the
        verify block), resident weights only (a streamed verify would re-read
        every window per block, erasing the win), batch 1 (acceptance length
        is per-lane), and a rewind-safe cache layout (rotating SWA ring
        buffers cannot rewind — core/spec.py)."""
        return (
            self.spec_lookahead > 0
            and self.batch == 1
            and not self.plan.streams_weights
            and self.model.kv_rewindable(self.max_seq)
            and decoding.temperature == 0.0
            and not decoding.logprobs
            and decoding.repetition_penalty == 1.0
            and not decoding.logit_bias  # verify argmaxes are unbiased
        )

    # adaptive gate thresholds: a spec block costs one (L+1)-wide forward +
    # one host sync per <=L+1 tokens; a decode chunk costs one forward per
    # token but only one sync per ~32.  Below ~1.5 tokens/block, chunks win.
    SPEC_WARMUP_BLOCKS = 4
    SPEC_MIN_TOKENS_PER_BLOCK = 1.5

    def spec_worthwhile(self, nonce: str) -> bool:
        """Per-session acceptance gate: after a warmup, sessions whose
        drafts rarely accept (non-repetitive output — prompt-lookup has
        nothing to look up) fall back to chunked decode rather than paying
        one dispatch + host sync per ~1 token, the exact gap chunking
        closed.  The callers re-check every block, so speculation stops the
        moment it stops paying; it does not resume within the session."""
        sess = self.sessions.get(nonce)
        if sess is None or sess.spec_blocks < self.SPEC_WARMUP_BLOCKS:
            return True
        return (
            sess.spec_emitted / sess.spec_blocks >= self.SPEC_MIN_TOKENS_PER_BLOCK
        )

    def decode_spec(
        self,
        nonce: str,
        token_id: Optional[int],
        decoding: DecodingParams,
        max_new: int,
    ) -> List[SampleResult]:
        """One speculative verify block: feed `token_id` (None chains from
        the device-resident last emitted token), draft spec_lookahead tokens
        by prompt-lookup, verify in ONE forward, emit the accepted prefix
        plus the first correction — 1..L+1 tokens per weight read.  Emission
        is clamped to `max_new`; sess.pos advances by exactly the emitted
        count (stale KV/history rows are overwritten by the next block)."""
        sess = self.sessions[nonce]
        L = self.spec_lookahead
        if sess.pos >= self.max_seq:
            raise ValueError(
                f"sequence length {sess.pos} reached max_seq {self.max_seq}"
            )
        budget = min(max_new, self.max_seq - sess.pos)
        if self.kv_pool is not None and sess.pos + L + 1 <= self.max_seq:
            try:
                # the verify block writes L+1 positions; a pool that cannot
                # cover them degrades to a plain step (whose own admission
                # raises the definitive backpressure error)
                self._paged_ensure(sess, sess.pos + L + 1)
            except KVPoolExhausted:
                budget = 1
        if budget <= 1 or sess.pos + L + 1 > self.max_seq:
            # no room to speculate: one plain step keeps the stream moving
            tid = (
                token_id
                if token_id is not None
                else int(np.asarray(sess.last_token)[0, 0])
            )
            return [self.decode_step(nonce, tid, decoding)]
        t_blk = time.perf_counter()
        if token_id is None:
            if sess.last_token is None:
                raise RuntimeError("no device-resident token to chain from")
            tok = sess.last_token
        else:
            tok = jnp.full((self.batch, 1), token_id, dtype=jnp.int32)
        if self.draft is not None:
            out, sess.kv, sess.dkv = self._spec_step_draft(
                self.window_params, self.edge_params,
                self.draft.window, self.draft.edge,
                tok, sess.kv, sess.dkv, jnp.int32(sess.pos),
            )
        else:
            out, sess.hist, sess.kv = self._spec_step(
                self.window_params, self.edge_params, tok, sess.hist, sess.kv,
                jnp.int32(sess.pos),
            )
        out_h = np.asarray(out)  # [B, L+1]; blocks until the block finishes
        emitted = min(int((out_h[0] >= 0).sum()), budget)
        # the verify block amortizes one forward over `emitted` tokens:
        # record the per-token share so the histogram's count stays equal
        # to tokens served across the plain / chunked / speculative paths
        per_tok_ms = (time.perf_counter() - t_blk) * 1000 / max(emitted, 1)
        _DECODE_STEP_MS.observe_n(per_tok_ms, emitted)
        sess.pos += emitted
        sess.spec_blocks += 1
        sess.spec_emitted += emitted
        sess.last_used = time.time()
        sess.last_token = jnp.asarray(out_h[:, emitted - 1 : emitted])
        B = out_h.shape[0]
        zero_lp = np.zeros((B,), np.float32)
        zero_tt = np.zeros((B, MAX_TOP_LOGPROBS), np.int32)
        zero_tlp = np.zeros((B, MAX_TOP_LOGPROBS), np.float32)
        return [
            SampleResult(
                np.ascontiguousarray(out_h[:, i]).astype(np.int32),
                zero_lp, zero_tt, zero_tlp,
            )
            for i in range(emitted)
        ]

    # chunk widths tried largest-first: a fixed bucket set keeps the number
    # of compiled scan programs bounded (one per width actually used)
    DECODE_CHUNK_BUCKETS = (32, 16, 8, 4, 2)

    def decode_chunk_dispatch(
        self,
        nonce: str,
        token_id: Optional[int],
        decoding: DecodingParams,
        max_steps: int,
    ) -> int:
        """Dispatch (async) a fused chunk of up to `max_steps` decode steps.

        token_id None chains from the DEVICE-resident last token of the
        previously dispatched chunk — the host never has to read a token to
        keep the device busy, so result transfers overlap the next chunk's
        compute.  Returns the dispatched width (0 = not chunkable; caller
        falls back to decode_step).  Results are read by decode_chunk_read
        in dispatch order.
        """
        sess = self.sessions[nonce]
        if sess.pos >= self.max_seq:
            # full context is not an error HERE: the caller may be
            # speculating past a chunk that exactly filled the sequence —
            # returning 0 routes the next real step to decode_step, which
            # raises the definitive "reached max_seq" for the request
            return 0
        budget = min(max_steps, self.max_seq - sess.pos)
        K = next((b for b in self.DECODE_CHUNK_BUCKETS if b <= budget), 1)
        if K == 1 or self.plan.streams_weights:
            return 0
        if self.kv_pool is not None:
            try:
                self._paged_ensure(sess, sess.pos + K)
            except KVPoolExhausted:
                # graceful degradation: an un-extendable chunk falls back to
                # single steps, whose own admission raises the definitive
                # backpressure error if even one block is unavailable
                return 0
        if token_id is None:
            if sess.last_token is None:
                raise RuntimeError("no device-resident token to chain from")
            token = sess.last_token
        else:
            token = jnp.full((self.batch, 1), token_id, dtype=jnp.int32)
        sp = SampleParams.from_decoding(decoding)
        plan = SamplePlan.from_decoding(decoding)
        packed, sess.last_token, sess.kv, sess.key, sess.counts = self._decode_chunk(
            self.window_params, self.edge_params, token, sess.kv,
            jnp.int32(sess.pos), sp, sess.key, sess.counts, K, plan,
        )
        sess.pending.append((K, packed, plan))
        sess.pos += K
        sess.last_used = time.time()
        return K

    def pending_chunks(self, nonce: str) -> int:
        """Dispatched-but-unread chunk count (0 for unknown sessions)."""
        sess = self.sessions.get(nonce)
        return len(sess.pending) if sess is not None else 0

    def pending_width(self, nonce: str) -> int:
        """Total tokens in flight across dispatched-but-unread chunks."""
        sess = self.sessions.get(nonce)
        return sum(k for k, _, _ in sess.pending) if sess is not None else 0

    def decode_chunk_read(self, nonce: str) -> List[SampleResult]:
        """Read the oldest dispatched chunk: ONE device->host transfer for
        the packed [K, B, W] result block, split host-side."""
        sess = self.sessions[nonce]
        K, packed, plan = sess.pending.popleft()
        t0 = time.perf_counter()
        arr = np.asarray(packed)  # blocks until the chunk's program finishes
        # the blocking read amortizes the chunk: record the per-token share
        # (K observations keep the histogram's count == tokens served)
        per_tok_ms = (time.perf_counter() - t0) * 1000 / K
        _DECODE_STEP_MS.observe_n(per_tok_ms, K)
        toks = arr[..., 0].astype(np.int32)  # [K, B]
        if plan.logprobs:
            M = MAX_TOP_LOGPROBS
            lps = arr[..., 1]
            tt = arr[..., 2 : 2 + M].astype(np.int32)
            tlp = arr[..., 2 + M : 2 + 2 * M]
        else:
            B = arr.shape[1]
            lps = np.zeros((K, B), np.float32)
            tt = np.zeros((K, B, MAX_TOP_LOGPROBS), np.int32)
            tlp = np.zeros((K, B, MAX_TOP_LOGPROBS), np.float32)
        return [SampleResult(toks[i], lps[i], tt[i], tlp[i]) for i in range(K)]

    def decode_chunk(
        self,
        nonce: str,
        token_id: int,
        decoding: DecodingParams,
        max_steps: int,
    ) -> list[SampleResult]:
        """Up to `max_steps` decode steps in one on-device lax.scan
        (dispatch + read in one call; the pipelining adapter calls the two
        halves itself to overlap the read with the next chunk's compute).

        Returns one host-side SampleResult per generated token.  The caller
        owns EOS / stop-sequence checks: tokens past a stop are simply
        discarded with the session, exactly as the reference's driver
        discards its own overshoot (the KV rows they wrote die with the
        session).  Closes the per-token dispatch gap flagged in BASELINE.md
        (49 tok/s dispatched vs 208 fused).
        """
        if self.decode_chunk_dispatch(nonce, token_id, decoding, max_steps) == 0:
            return [self.decode_step(nonce, token_id, decoding)]
        return self.decode_chunk_read(nonce)

    # plans warmed ahead of traffic: greedy, unfiltered-sampled (the
    # OpenAI-default request: temperature 1, top_p 1), and filtered-sampled;
    # logprobs/penalty variants compile on first use
    WARM_DECODINGS = (
        DecodingParams(),  # greedy: temperature 0, no filters
        DecodingParams(temperature=1.0),  # API-default sampled, no filters
        DecodingParams(temperature=0.7, top_p=0.9),  # sampled + filters
        # bias=True is its own plan dimension: warm it so the first
        # logit_bias request doesn't stall mid-stream on the compile
        DecodingParams(logit_bias={0: 0.0}),
    )

    def warm_chunks(self) -> None:
        """Compile the decode-chunk programs (and the single-step decode)
        for the common sampling plans up front, so the first request's ramp
        never stalls mid-stream on a synchronous XLA compile.  SamplePlan is
        a static jit argument, so each warmed DecodingParams shape is its
        own program set."""
        if self.plan.streams_weights:
            return
        nonce = "__warm__"
        t0 = time.perf_counter()
        for dec in self.WARM_DECODINGS:
            self.end_session(nonce)
            try:
                self.prefill_and_sample(nonce, [0], dec)
                for b in self.DECODE_CHUNK_BUCKETS:
                    if self.sessions[nonce].pos + b < self.max_seq:
                        self.decode_chunk(nonce, 0, dec, b)
                self.decode_step(nonce, 0, dec)
            finally:
                self.end_session(nonce)
        if self.spec_lookahead > 0:
            # the verify block is the same compile class as the chunk scans;
            # pay it here, not on the first eligible request's first block
            self.end_session(nonce)
            try:
                self.prefill_and_sample(nonce, [0], DecodingParams(temperature=0.0))
                self.decode_spec(nonce, 0, DecodingParams(temperature=0.0), 2)
            finally:
                self.end_session(nonce)
        log.info(
            "[PROFILE] warmed decode-chunk programs (%d plans) in %.1fs",
            len(self.WARM_DECODINGS),
            time.perf_counter() - t0,
        )

    def generate(
        self,
        prompt_ids: Sequence[int],
        decoding: Optional[DecodingParams] = None,
        max_tokens: int = 256,
        eos_token_ids: Optional[set[int]] = None,
        nonce: str = "local",
    ) -> Iterator[TokenResult]:
        """Greedy/sampled autoregressive generation, yielding per-token results."""
        decoding = decoding or DecodingParams()
        eos = eos_token_ids or set()
        self.end_session(nonce)
        # session is created by prefill (which may seed it from the prefix
        # cache); the seed flows via prefill_and_sample
        res = self.prefill_and_sample(nonce, prompt_ids, decoding)
        sess = self.sessions[nonce]
        token = int(res.token[0])
        yield self.token_result(nonce, res, step=0, decoding=decoding)
        if token in eos:
            self.end_session(nonce)
            return

        use_spec = self.spec_eligible(decoding)
        step = 1
        while step < max_tokens:
            if sess.pos >= self.max_seq:
                break  # cache capacity reached: stop cleanly (finish_reason=length)
            if use_spec and self.spec_worthwhile(nonce):
                results = self.decode_spec(nonce, token, decoding, max_tokens - step)
            else:
                results = [self.decode_step(nonce, token, decoding)]
            stop = False
            for res in results:
                token = int(res.token[0])
                yield self.token_result(nonce, res, step=step, decoding=decoding)
                step += 1
                if token in eos:
                    stop = True
                    break
            if stop:
                break
        self.end_session(nonce)

    def _sample_with_counts(
        self, sess: "Session", logits, decoding: DecodingParams
    ) -> SampleResult:
        """THE place owning the key-split/sample/counts invariants (shared by
        LocalEngine and MeshEngine)."""
        sess.key, step_key = jax.random.split(sess.key)
        res = sample(
            logits, SampleParams.from_decoding(decoding), step_key,
            token_counts=sess.counts, plan=SamplePlan.from_decoding(decoding),
        )
        # per-lane counts, matching the jitted decode/chunk programs exactly —
        # penalty state must not depend on which dispatch path served a step
        sess.counts = sess.counts.at[
            jnp.arange(sess.counts.shape[0]), res.token
        ].add(1)
        return res

    def prefill_and_sample(
        self, nonce: str, prompt_ids: Sequence[int], decoding: DecodingParams
    ) -> SampleResult:
        """Prefill the prompt and sample the first token."""
        logits = self.prefill(nonce, prompt_ids, decoding.seed)
        return self._sample_with_counts(self.sessions[nonce], logits, decoding)

    @staticmethod
    def token_result(nonce: str, res: SampleResult, step: int, decoding: DecodingParams) -> TokenResult:
        top = None
        if decoding.logprobs and decoding.top_logprobs > 0:
            n = min(decoding.top_logprobs, res.top_tokens.shape[-1])
            top = list(
                zip(
                    np.asarray(res.top_tokens[0, :n]).tolist(),
                    np.asarray(res.top_logprobs[0, :n]).tolist(),
                )
            )
        return TokenResult(
            nonce=nonce,
            token_id=int(res.token[0]),
            logprob=float(res.logprob[0]) if decoding.logprobs else None,
            top_logprobs=top,
            step=step,
        )
