"""KV cache: preallocated, jit-friendly, layer-stacked.

Layout: k/v are [L_local, B, S_max, KVH, Hd] so a window of layers scans with
the cache as `lax.scan` xs/ys and a single `dynamic_update_slice` per layer
writes the new tokens.  Static S_max keeps every decode step the same XLA
program (the reference recompiles nothing either — mlx grows caches
imperatively; on TPU preallocation is the idiomatic answer, and S_max is part
of the solver's memory model exactly like the reference's kv_bits,
src/dnet/shard/runtime.py:204-214).

Sliding-window layers use a rotating write (pos % window) — the analog of
mlx-lm's RotatingKVCache used by the reference for GPT-OSS
(src/dnet/utils/model.py:470-555).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


@dataclass(frozen=True)
class KVConfig:
    n_layers: int  # local layers in this cache
    batch: int
    max_seq: int
    n_kv_heads: int
    head_dim: int  # key head dim
    dtype: str = "bfloat16"
    sliding_window: int = 0  # 0 = full cache; >0 = ring buffer of this size
    v_head_dim: int = 0  # 0 = same as head_dim (MLA caches differ: k=nope+rope, v=v_head)
    # 0 = dtype as-is; 8 = int8, 4 = packed int4 (two values/byte along the
    # head dim) — both with per-(pos,head) f32 scales
    quant_bits: int = 0


def resolve_kv_bits(kv_bits: int) -> Tuple[Optional[str], int]:
    """Map the API-level kv_bits knob (reference's DNET_KV_BITS / solver
    kv_bits) to engine args: (kv_dtype override, quant bits)."""
    if kv_bits == 16:
        return "bfloat16", 0
    if kv_bits in (4, 8):
        return None, kv_bits
    if kv_bits != 0:
        # a typo'd value must not silently serve an unquantized cache the
        # solver didn't budget for
        raise NotImplementedError(f"kv_bits={kv_bits} (supported: 0/4/8/16)")
    return None, 0


def init_cache(cfg: KVConfig) -> dict:
    seq = cfg.sliding_window if cfg.sliding_window > 0 else cfg.max_seq
    vd = cfg.v_head_dim or cfg.head_dim
    k_shape = (cfg.n_layers, cfg.batch, seq, cfg.n_kv_heads, cfg.head_dim)
    v_shape = (cfg.n_layers, cfg.batch, seq, cfg.n_kv_heads, vd)
    if cfg.quant_bits == 8:
        scale_shape = (cfg.n_layers, cfg.batch, seq, cfg.n_kv_heads, 1)
        return {
            "k": jnp.zeros(k_shape, dtype=jnp.int8),
            "v": jnp.zeros(v_shape, dtype=jnp.int8),
            "k_scale": jnp.zeros(scale_shape, dtype=jnp.float32),
            "v_scale": jnp.zeros(scale_shape, dtype=jnp.float32),
        }
    if cfg.quant_bits == 4:
        # packed nibbles along the head dim (token-granular writes stay one
        # dynamic_update_slice); uint8 storage distinguishes q4 from the
        # int8 scheme at trace time
        if cfg.head_dim % 2 or vd % 2:
            raise ValueError("int4 KV needs even head dims")
        k4 = (*k_shape[:-1], cfg.head_dim // 2)
        v4 = (*v_shape[:-1], vd // 2)
        scale_shape = (cfg.n_layers, cfg.batch, seq, cfg.n_kv_heads, 1)
        return {
            "k": jnp.zeros(k4, dtype=jnp.uint8),
            "v": jnp.zeros(v4, dtype=jnp.uint8),
            "k_scale": jnp.zeros(scale_shape, dtype=jnp.float32),
            "v_scale": jnp.zeros(scale_shape, dtype=jnp.float32),
        }
    if cfg.quant_bits not in (0, 16):
        raise NotImplementedError(f"kv quant_bits={cfg.quant_bits} (only 0/4/8/16)")
    dt = jnp.dtype(cfg.dtype)
    return {"k": jnp.zeros(k_shape, dtype=dt), "v": jnp.zeros(v_shape, dtype=dt)}


def cache_nbytes(cfg: KVConfig) -> int:
    seq = cfg.sliding_window if cfg.sliding_window > 0 else cfg.max_seq
    base = cfg.n_layers * cfg.batch * seq * cfg.n_kv_heads
    vd = cfg.v_head_dim or cfg.head_dim
    if cfg.quant_bits == 8:
        return base * (cfg.head_dim + vd) + base * 2 * 4  # int8 + f32 scales
    if cfg.quant_bits == 4:
        return base * (cfg.head_dim + vd) // 2 + base * 2 * 4
    return base * (cfg.head_dim + vd) * jnp.dtype(cfg.dtype).itemsize


# ---- quantized read/write ---------------------------------------------------


def _quantize_q8(x: jnp.ndarray):
    """Per-(..., head) symmetric int8: scale over the last axis."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _quantize_q4(x: jnp.ndarray):
    """Per-(..., head) symmetric int4, offset-binary nibbles packed in pairs
    along the last (head) axis: [..., Hd] -> uint8 [..., Hd/2]."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(amax / 7.0, 1e-8)
    q = (
        jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -7, 7) + 8
    ).astype(jnp.uint8)
    return q[..., 0::2] | (q[..., 1::2] << 4), scale


def _unpack_q4(p: jnp.ndarray) -> jnp.ndarray:
    """uint8 [..., Hd/2] -> f32 [..., Hd] (inverse of _quantize_q4's pack)."""
    lo = (p & jnp.uint8(0xF)).astype(jnp.float32) - 8.0
    hi = ((p >> 4) & jnp.uint8(0xF)).astype(jnp.float32) - 8.0
    return jnp.stack([lo, hi], axis=-1).reshape(*p.shape[:-1], p.shape[-1] * 2)


def write_kv(kvs: dict, k_new: jnp.ndarray, v_new: jnp.ndarray, pos, kv_commit=None) -> dict:
    """Write new k/v ([B, T, KVH, Hd]) at `pos` into one layer's cache slices,
    quantizing when the cache carries scales.  kv_commit gates O(T)."""
    quant = "k_scale" in kvs
    quantize = _quantize_q4 if (quant and kvs["k"].dtype == jnp.uint8) else _quantize_q8

    def gate(new, cache_arr):
        if kv_commit is None:
            return new
        old = lax.dynamic_slice(cache_arr, (0, pos, 0, 0), new.shape)
        return jnp.where(kv_commit, new, old)

    out = dict(kvs)
    if quant:
        kq, ks = quantize(k_new)
        vq, vs = quantize(v_new)
        for name, val in (("k", kq), ("k_scale", ks), ("v", vq), ("v_scale", vs)):
            val = gate(val.astype(kvs[name].dtype), kvs[name])
            out[name] = lax.dynamic_update_slice(kvs[name], val, (0, pos, 0, 0))
    else:
        for name, val in (("k", k_new), ("v", v_new)):
            val = gate(val.astype(kvs[name].dtype), kvs[name])
            out[name] = lax.dynamic_update_slice(kvs[name], val, (0, pos, 0, 0))
    return out


def write_kv_rotating(
    kvs: dict,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    pos,
    kv_commit=None,
    t_real=None,
) -> dict:
    """Ring-buffer write: token at absolute position p lands in slot p % W
    (W = the cache's row count).  Arbitrary chunk length T in ONE vectorized
    gather+where — each slot receives its MOST RECENT in-chunk token (for
    T > W the early tokens are dead on arrival, exactly the sliding-window
    semantics).  kv_commit gates the whole write."""
    quant = "k_scale" in kvs
    W = kvs["k"].shape[1]
    T = k_new.shape[1]
    s = jnp.arange(W)
    j0 = jnp.mod(s - pos, W)
    # most recent chunk index j < T with (pos + j) % W == s, or negative
    t_eff = T if t_real is None else t_real
    j = j0 + W * ((t_eff - 1 - j0) // W)
    valid = (j >= 0) & (j < t_eff)
    if kv_commit is not None:
        valid = valid & kv_commit
    jc = jnp.clip(j, 0, T - 1)
    sel = valid[None, :, None, None]
    if quant:
        quantize = _quantize_q4 if kvs["k"].dtype == jnp.uint8 else _quantize_q8
        kq, ks = quantize(k_new)
        vq, vs = quantize(v_new)
        items = [("k", kq), ("k_scale", ks), ("v", vq), ("v_scale", vs)]
    else:
        items = [("k", k_new), ("v", v_new)]
    out = dict(kvs)
    for name, val in items:
        c = kvs[name]
        taken = jnp.take(val.astype(c.dtype), jc, axis=1)
        out[name] = jnp.where(sel, taken, c)
    return out


def write_kv_sp(
    kvs: dict,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    pos,
    axis_name: str,
    kv_commit=None,
) -> dict:
    """Sequence-parallel write: this rank owns KV slots
    [rank*S_local, (rank+1)*S_local).  Each of the T incoming tokens lands on
    exactly one rank; out-of-range ranks re-write the old value (no-op).
    Token-at-a-time keeps a prefill chunk that straddles a shard boundary
    correct — a single clamped slice write could not split across ranks."""
    from jax import lax as _lax

    quant = "k_scale" in kvs
    S_local = kvs["k"].shape[1]
    offset = _lax.axis_index(axis_name) * S_local
    T = k_new.shape[1]
    if quant:
        quantize = _quantize_q4 if kvs["k"].dtype == jnp.uint8 else _quantize_q8
        kq, ks = quantize(k_new)
        vq, vs = quantize(v_new)
        items = [("k", kq), ("k_scale", ks), ("v", vq), ("v_scale", vs)]
    else:
        items = [("k", k_new), ("v", v_new)]

    out = dict(kvs)
    if T == 1:  # decode: one gated single-slot write per cache array
        slot = pos
        local = jnp.clip(slot - offset, 0, S_local - 1)
        in_range = (slot >= offset) & (slot < offset + S_local)
        commit = in_range if kv_commit is None else (in_range & kv_commit)
        for name, val in items:
            c = kvs[name]
            v_i = val.astype(c.dtype)
            old = _lax.dynamic_slice(c, (0, local, 0, 0), v_i.shape)
            sel = jnp.where(commit, v_i, old)
            out[name] = _lax.dynamic_update_slice(c, sel, (0, local, 0, 0))
        return out

    # prefill: each local slot receives at most one of the T tokens, so the
    # whole write is one gather + where (no serialized per-token loop)
    j = offset + jnp.arange(S_local) - pos  # incoming-token index per slot
    valid = (j >= 0) & (j < T)
    if kv_commit is not None:
        valid = valid & kv_commit
    jc = jnp.clip(j, 0, T - 1)
    sel = valid[None, :, None, None]
    for name, val in items:
        c = kvs[name]
        taken = jnp.take(val.astype(c.dtype), jc, axis=1)
        out[name] = jnp.where(sel, taken, c)
    return out


def read_kv(kvs: dict) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Full-cache k/v for attention, dequantizing if needed.

    Quantized path stays f32 (attend computes its softmax/matmuls in f32
    anyway — a round-trip through bf16 would only add a cast and lose bits);
    the plain path returns the cache's own dtype.
    """
    if "k_scale" in kvs:
        if kvs["k"].dtype == jnp.uint8:  # packed int4
            k = _unpack_q4(kvs["k"]) * kvs["k_scale"]
            v = _unpack_q4(kvs["v"]) * kvs["v_scale"]
        else:
            k = kvs["k"].astype(jnp.float32) * kvs["k_scale"]
            v = kvs["v"].astype(jnp.float32) * kvs["v_scale"]
        return k, v
    return kvs["k"], kvs["v"]


def update_layer(
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    pos: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Write k_new/v_new ([B, T, KVH, Hd]) at sequence offset `pos`.

    Single-layer slices ([B, S, KVH, Hd]).  `pos` may be traced.
    """
    start = (0, pos, 0, 0)
    k_cache = lax.dynamic_update_slice(k_cache, k_new.astype(k_cache.dtype), start)
    v_cache = lax.dynamic_update_slice(v_cache, v_new.astype(v_cache.dtype), start)
    return k_cache, v_cache


def update_layer_rotating(
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    pos: jnp.ndarray,
    window: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Ring-buffer write for sliding-window layers (one token at a time in
    decode; prefill handles arbitrary T by scattering each token)."""
    T = k_new.shape[1]

    def write_one(i, caches):
        kc, vc = caches
        slot = (pos + i) % window
        k_i = lax.dynamic_slice_in_dim(k_new, i, 1, axis=1)
        v_i = lax.dynamic_slice_in_dim(v_new, i, 1, axis=1)
        kc = lax.dynamic_update_slice(kc, k_i.astype(kc.dtype), (0, slot, 0, 0))
        vc = lax.dynamic_update_slice(vc, v_i.astype(vc.dtype), (0, slot, 0, 0))
        return kc, vc

    return lax.fori_loop(0, T, write_one, (k_cache, v_cache))


def batched_gather_cache(cache: dict, indices: jnp.ndarray) -> dict:
    """Select batch rows (for future batched scheduling)."""
    return jax.tree.map(lambda a: a[:, indices], cache)
