"""KV cache: preallocated, jit-friendly, layer-stacked.

Layout: k/v are [L_local, B, S_max, KVH, Hd] so a window of layers scans with
the cache as `lax.scan` xs/ys and a single `dynamic_update_slice` per layer
writes the new tokens.  Static S_max keeps every decode step the same XLA
program (the reference recompiles nothing either — mlx grows caches
imperatively; on TPU preallocation is the idiomatic answer, and S_max is part
of the solver's memory model exactly like the reference's kv_bits,
src/dnet/shard/runtime.py:204-214).

Sliding-window layers use a rotating write (pos % window) — the analog of
mlx-lm's RotatingKVCache used by the reference for GPT-OSS
(src/dnet/utils/model.py:470-555).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


@dataclass(frozen=True)
class KVConfig:
    n_layers: int  # local layers in this cache
    batch: int
    max_seq: int
    n_kv_heads: int
    head_dim: int
    dtype: str = "bfloat16"
    sliding_window: int = 0  # 0 = full cache; >0 = ring buffer of this size


def init_cache(cfg: KVConfig) -> dict:
    seq = cfg.sliding_window if cfg.sliding_window > 0 else cfg.max_seq
    shape = (cfg.n_layers, cfg.batch, seq, cfg.n_kv_heads, cfg.head_dim)
    dt = jnp.dtype(cfg.dtype)
    return {
        "k": jnp.zeros(shape, dtype=dt),
        "v": jnp.zeros(shape, dtype=dt),
    }


def cache_nbytes(cfg: KVConfig) -> int:
    seq = cfg.sliding_window if cfg.sliding_window > 0 else cfg.max_seq
    n = cfg.n_layers * cfg.batch * seq * cfg.n_kv_heads * cfg.head_dim
    return 2 * n * jnp.dtype(cfg.dtype).itemsize


def update_layer(
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    pos: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Write k_new/v_new ([B, T, KVH, Hd]) at sequence offset `pos`.

    Single-layer slices ([B, S, KVH, Hd]).  `pos` may be traced.
    """
    start = (0, pos, 0, 0)
    k_cache = lax.dynamic_update_slice(k_cache, k_new.astype(k_cache.dtype), start)
    v_cache = lax.dynamic_update_slice(v_cache, v_new.astype(v_cache.dtype), start)
    return k_cache, v_cache


def update_layer_rotating(
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    pos: jnp.ndarray,
    window: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Ring-buffer write for sliding-window layers (one token at a time in
    decode; prefill handles arbitrary T by scattering each token)."""
    T = k_new.shape[1]

    def write_one(i, caches):
        kc, vc = caches
        slot = (pos + i) % window
        k_i = lax.dynamic_slice_in_dim(k_new, i, 1, axis=1)
        v_i = lax.dynamic_slice_in_dim(v_new, i, 1, axis=1)
        kc = lax.dynamic_update_slice(kc, k_i.astype(kc.dtype), (0, slot, 0, 0))
        vc = lax.dynamic_update_slice(vc, v_i.astype(vc.dtype), (0, slot, 0, 0))
        return kc, vc

    return lax.fori_loop(0, T, write_one, (k_cache, v_cache))


def batched_gather_cache(cache: dict, indices: jnp.ndarray) -> dict:
    """Select batch rows (for future batched scheduling)."""
    return jax.tree.map(lambda a: a[:, indices], cache)
