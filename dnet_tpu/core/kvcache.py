"""KV cache: preallocated, jit-friendly, layer-stacked.

Layout: k/v are [L_local, B, S_max, KVH, Hd] so a window of layers scans with
the cache as `lax.scan` xs/ys and a single `dynamic_update_slice` per layer
writes the new tokens.  Static S_max keeps every decode step the same XLA
program (the reference recompiles nothing either — mlx grows caches
imperatively; on TPU preallocation is the idiomatic answer, and S_max is part
of the solver's memory model exactly like the reference's kv_bits,
src/dnet/shard/runtime.py:204-214).

Sliding-window layers use a rotating write (pos % window) — the analog of
mlx-lm's RotatingKVCache used by the reference for GPT-OSS
(src/dnet/utils/model.py:470-555).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


@dataclass(frozen=True)
class KVConfig:
    n_layers: int  # local layers in this cache
    batch: int
    max_seq: int
    n_kv_heads: int
    head_dim: int  # key head dim
    dtype: str = "bfloat16"
    sliding_window: int = 0  # 0 = full cache; >0 = ring buffer of this size
    v_head_dim: int = 0  # 0 = same as head_dim (MLA caches differ: k=nope+rope, v=v_head)
    quant_bits: int = 0  # 0 = dtype as-is; 8 = int8 + per-(pos,head) scales


def init_cache(cfg: KVConfig) -> dict:
    seq = cfg.sliding_window if cfg.sliding_window > 0 else cfg.max_seq
    vd = cfg.v_head_dim or cfg.head_dim
    k_shape = (cfg.n_layers, cfg.batch, seq, cfg.n_kv_heads, cfg.head_dim)
    v_shape = (cfg.n_layers, cfg.batch, seq, cfg.n_kv_heads, vd)
    if cfg.quant_bits == 8:
        scale_shape = (cfg.n_layers, cfg.batch, seq, cfg.n_kv_heads, 1)
        return {
            "k": jnp.zeros(k_shape, dtype=jnp.int8),
            "v": jnp.zeros(v_shape, dtype=jnp.int8),
            "k_scale": jnp.zeros(scale_shape, dtype=jnp.float32),
            "v_scale": jnp.zeros(scale_shape, dtype=jnp.float32),
        }
    if cfg.quant_bits not in (0, 16):
        raise NotImplementedError(f"kv quant_bits={cfg.quant_bits} (only 0/8/16)")
    dt = jnp.dtype(cfg.dtype)
    return {"k": jnp.zeros(k_shape, dtype=dt), "v": jnp.zeros(v_shape, dtype=dt)}


def cache_nbytes(cfg: KVConfig) -> int:
    seq = cfg.sliding_window if cfg.sliding_window > 0 else cfg.max_seq
    base = cfg.n_layers * cfg.batch * seq * cfg.n_kv_heads
    vd = cfg.v_head_dim or cfg.head_dim
    if cfg.quant_bits == 8:
        return base * (cfg.head_dim + vd) + base * 2 * 4  # int8 + f32 scales
    return base * (cfg.head_dim + vd) * jnp.dtype(cfg.dtype).itemsize


# ---- quantized read/write ---------------------------------------------------


def _quantize_q8(x: jnp.ndarray):
    """Per-(..., head) symmetric int8: scale over the last axis."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127).astype(jnp.int8)
    return q, scale


def write_kv(kvs: dict, k_new: jnp.ndarray, v_new: jnp.ndarray, pos, kv_commit=None) -> dict:
    """Write new k/v ([B, T, KVH, Hd]) at `pos` into one layer's cache slices,
    quantizing when the cache carries scales.  kv_commit gates O(T)."""
    quant = "k_scale" in kvs

    def gate(new, cache_arr):
        if kv_commit is None:
            return new
        old = lax.dynamic_slice(cache_arr, (0, pos, 0, 0), new.shape)
        return jnp.where(kv_commit, new, old)

    out = dict(kvs)
    if quant:
        kq, ks = _quantize_q8(k_new)
        vq, vs = _quantize_q8(v_new)
        for name, val in (("k", kq), ("k_scale", ks), ("v", vq), ("v_scale", vs)):
            val = gate(val.astype(kvs[name].dtype), kvs[name])
            out[name] = lax.dynamic_update_slice(kvs[name], val, (0, pos, 0, 0))
    else:
        for name, val in (("k", k_new), ("v", v_new)):
            val = gate(val.astype(kvs[name].dtype), kvs[name])
            out[name] = lax.dynamic_update_slice(kvs[name], val, (0, pos, 0, 0))
    return out


def read_kv(kvs: dict) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Full-cache k/v for attention, dequantizing if needed.

    Quantized path stays f32 (attend computes its softmax/matmuls in f32
    anyway — a round-trip through bf16 would only add a cast and lose bits);
    the plain path returns the cache's own dtype.
    """
    if "k_scale" in kvs:
        k = kvs["k"].astype(jnp.float32) * kvs["k_scale"]
        v = kvs["v"].astype(jnp.float32) * kvs["v_scale"]
        return k, v
    return kvs["k"], kvs["v"]


def update_layer(
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    pos: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Write k_new/v_new ([B, T, KVH, Hd]) at sequence offset `pos`.

    Single-layer slices ([B, S, KVH, Hd]).  `pos` may be traced.
    """
    start = (0, pos, 0, 0)
    k_cache = lax.dynamic_update_slice(k_cache, k_new.astype(k_cache.dtype), start)
    v_cache = lax.dynamic_update_slice(v_cache, v_new.astype(v_cache.dtype), start)
    return k_cache, v_cache


def update_layer_rotating(
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    pos: jnp.ndarray,
    window: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Ring-buffer write for sliding-window layers (one token at a time in
    decode; prefill handles arbitrary T by scattering each token)."""
    T = k_new.shape[1]

    def write_one(i, caches):
        kc, vc = caches
        slot = (pos + i) % window
        k_i = lax.dynamic_slice_in_dim(k_new, i, 1, axis=1)
        v_i = lax.dynamic_slice_in_dim(v_new, i, 1, axis=1)
        kc = lax.dynamic_update_slice(kc, k_i.astype(kc.dtype), (0, slot, 0, 0))
        vc = lax.dynamic_update_slice(vc, v_i.astype(vc.dtype), (0, slot, 0, 0))
        return kc, vc

    return lax.fori_loop(0, T, write_one, (k_cache, v_cache))


def batched_gather_cache(cache: dict, indices: jnp.ndarray) -> dict:
    """Select batch rows (for future batched scheduling)."""
    return jax.tree.map(lambda a: a[:, indices], cache)
