"""The resumable-request state machine behind DNET_RESILIENCE_RESUME.

`InferenceManager._run` drives every decode step through a
`ResumableDecode`: the controller owns the wire nonce, the step mapping,
and the request checkpoint (prompt ids + every token generated so far —
the detokenizer / stop-sequence holdback / logprob buffers live on in the
driver's own generator frame and need no restore).  When a step fails
because a shard died, the controller — inside the configured budget —

1. waits for the failure monitor to report the ring healthy again
   (``DNET_RESILIENCE_RESUME_DEADLINE_S`` per attempt; auto-recovery
   re-solves the topology underneath while we wait),
2. resets the dead nonce's per-shard state (best effort — the ring that
   just died may not ACK),
3. replays a prefill of ``prompt + generated`` under a FRESH wire nonce,
   routed through `send_tokens(step=0)` so the prefix/snapshot cache path
   applies — when the prefix survives on reloaded shards the replay is a
   cache hit, and a shard-side snapshot miss falls back through the
   transparent prefix-refill path — and
4. hands the replay's sampled token back to the driver as the failed
   step's result: the client stream continues with the same rid, correct
   finish_reason, and usage that counts each token exactly once.

The resume cap is ``DNET_RESILIENCE_MAX_RESUMES`` per request; with resume
disabled `try_resume` returns None immediately and behavior is identical
to the fast-fail path.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from dnet_tpu.obs import get_recorder, metric
from dnet_tpu.obs.events import log_event
from dnet_tpu.utils.logger import get_logger

log = get_logger()

_RESUMED = metric("dnet_request_resumed_total")
_REPLAY_TOKENS = metric("dnet_resume_replay_tokens_total")


@dataclass
class RequestCheckpoint:
    """Everything a replay prefill needs: the prompt and the accepted
    tokens, plus resume bookkeeping."""

    rid: str
    prompt_ids: List[int]
    generated_ids: List[int] = field(default_factory=list)
    resumes: int = 0
    segment: int = 0   # resume generation; names the wire nonce
    step_base: int = 0  # driver step that maps to the current nonce's step 0

    def record(self, token_id: int) -> None:
        self.generated_ids.append(int(token_id))

    def replay_ids(self) -> List[int]:
        return list(self.prompt_ids) + list(self.generated_ids)

    def next_nonce(self) -> str:
        self.segment += 1
        return f"{self.rid}#r{self.segment}"


class ResumableDecode:
    """Per-request send/await facade with transparent resume.

    `get_adapter` is a callable, not a reference: auto-recovery replaces
    `InferenceManager.adapter` with one wired to the re-solved topology,
    and the replay must go to the NEW adapter.
    """

    POLL_S = 0.1  # recovery-wait poll cadence

    def __init__(
        self,
        get_adapter: Callable[[], object],
        rid: str,
        prompt_ids: List[int],
        *,
        monitor=None,
        timeout_s: float = 300.0,
        settings=None,
    ) -> None:
        if settings is None:
            from dnet_tpu.config import get_settings

            settings = get_settings().resilience
        self.enabled = bool(settings.resume)
        self.deadline_s = float(settings.resume_deadline_s)
        self.max_resumes = max(int(settings.max_resumes), 0)
        self._get_adapter = get_adapter
        self.monitor = monitor
        self.timeout_s = timeout_s
        self.ckpt = RequestCheckpoint(rid=rid, prompt_ids=list(prompt_ids))
        self.nonce = rid

    @property
    def adapter(self):
        return self._get_adapter()

    # ---- the driver's per-step surface -----------------------------------
    async def send(self, send_ids, decoding, step: int, budget=None) -> None:
        await self.adapter.send_tokens(
            self.nonce, list(send_ids), decoding,
            step - self.ckpt.step_base, budget=budget,
        )

    async def await_token(self, step: int):
        return await self.adapter.await_token(
            self.nonce, step - self.ckpt.step_base, self.timeout_s
        )

    def record(self, token_id: int) -> None:
        self.ckpt.record(token_id)

    # ---- resume ----------------------------------------------------------
    async def try_resume(self, exc, decoding, step: int, budget=None):
        """Attempt to produce step's token by replaying on a recovered
        ring.  Returns the TokenResult, or None when resume is disabled /
        exhausted / the ring never recovered (caller re-raises `exc`)."""
        if not self.enabled:
            return None
        while self.ckpt.resumes < self.max_resumes:
            self.ckpt.resumes += 1
            log.warning(
                "request %s: decode step %d failed (%s); resume attempt "
                "%d/%d", self.ckpt.rid, step, exc, self.ckpt.resumes,
                self.max_resumes,
            )
            if not await self._wait_recovered():
                log.error(
                    "request %s: ring still degraded after %.1fs; giving up",
                    self.ckpt.rid, self.deadline_s,
                )
                return None
            # best-effort reset of the dead segment: the shards that died
            # may be gone, and the replay uses a fresh nonce regardless
            try:
                await self.adapter.reset_cache(self.nonce)
            except Exception as reset_exc:
                log.warning(
                    "reset of dead nonce %s failed (ignored): %s",
                    self.nonce, reset_exc,
                )
            self.nonce = self.ckpt.next_nonce()
            self.ckpt.step_base = step
            ids = self.ckpt.replay_ids()
            try:
                await self.adapter.send_tokens(
                    self.nonce, ids, decoding, 0, budget=budget
                )
                result = await self.adapter.await_token(
                    self.nonce, 0, self.timeout_s
                )
            except asyncio.CancelledError:
                raise
            except Exception as replay_exc:
                log.warning(
                    "request %s: resume replay failed: %s",
                    self.ckpt.rid, replay_exc,
                )
                continue
            if result.error:
                log.warning(
                    "request %s: resume replay errored: %s",
                    self.ckpt.rid, result.error,
                )
                continue
            _RESUMED.inc()
            _REPLAY_TOKENS.inc(len(ids))
            log_event(
                "resumed", rid=self.ckpt.rid, step=step,
                replay_tokens=len(ids), nonce=self.nonce,
            )
            get_recorder().span(
                self.ckpt.rid, "request_resumed", 0.0, step=step,
                replay_tokens=len(ids), force=True,
            )
            log.info(
                "request %s resumed at step %d (replayed %d tokens as %s)",
                self.ckpt.rid, step, len(ids), self.nonce,
            )
            return result
        return None

    async def _wait_recovered(self) -> bool:
        """Block (bounded) until the failure monitor stops reporting the
        ring degraded.  No monitor => nothing to wait on."""
        if self.monitor is None:
            return True
        deadline = time.monotonic() + self.deadline_s
        while self.monitor.degraded:
            if time.monotonic() >= deadline:
                return False
            await asyncio.sleep(self.POLL_S)
        return True

    async def cleanup(self) -> None:
        """Drop the current nonce's per-shard state, swallowing transport
        errors: the cleanup path runs in the driver's `finally`, where a
        raise would mask the original error and crash the SSE generator."""
        try:
            await self.adapter.reset_cache(self.nonce)
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            log.warning(
                "reset_cache for %s failed on cleanup (ignored): %s",
                self.nonce, exc,
            )
