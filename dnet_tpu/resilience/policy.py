"""Retry/backoff policy for the control and token planes.

Exponential backoff with FULL jitter (delay ~ U[0, base * mult^attempt],
capped), the spread AWS's backoff analysis recommends for thundering-herd
avoidance — after a shard restart every in-flight RPC retries at once, and
correlated retry waves are exactly what a recovering shard cannot absorb.

Classification: an error is retried only when it looks transient —
gRPC ``UNAVAILABLE`` / ``DEADLINE_EXCEEDED`` (duck-typed via ``.code()``
so fakes classify identically), connection/timeout errors, and injected
`ChaosError`s (a ConnectionError subclass, no import needed).  Everything
else (bad argument, compute error, cancellation) surfaces immediately.

Application map:

- `RingClient` unary RPCs retry here inside the transport client
  (grpc_transport.py); ``health_check`` is pinned to ONE attempt — the
  failure monitor counts consecutive failures, and transport-level retries
  would silently stretch its detection window.
- The `ApiCallbackClient.send_token` path retries at its only call site,
  the shard adapter's ``_cb_send`` (shard/adapter.py), so injected fakes
  and the chaos ``token_cb`` point sit inside the retried callable.
- `StreamManager.send` re-opens broken bidi streams under the
  ``send_activation`` policy and re-sends the in-flight frame with its
  original seq (transport/stream_manager.py); the shard dedups on
  ``(nonce, seq, layer_id)``.
"""

from __future__ import annotations

import asyncio
import random
import threading
from dataclasses import dataclass
from typing import Awaitable, Callable, Optional

from dnet_tpu.obs import metric
from dnet_tpu.utils.logger import get_logger

log = get_logger()

_RETRIES = metric("dnet_rpc_retries_total")

#: gRPC status names considered transient.
RETRYABLE_GRPC_CODES = frozenset({"UNAVAILABLE", "DEADLINE_EXCEEDED"})


@dataclass(frozen=True)
class RetryPolicy:
    max_attempts: int = 3       # total attempts, including the first
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: str = "full"        # "full" | "none"

    def delay_s(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry number `attempt` (0-based).  With full
        jitter the delay is uniform over [0, capped exponential]."""
        raw = min(
            self.base_delay_s * (self.multiplier ** attempt), self.max_delay_s
        )
        if self.jitter == "full":
            return rng.uniform(0.0, raw)
        return raw


def policy_for(method: str) -> RetryPolicy:
    """The effective policy for an RPC class.  DNET_RESILIENCE_RETRY_*
    set the base policy for EVERY class; the two class adjustments that
    carry semantics are applied on top:

    - ``health_check`` is pinned to one attempt regardless of settings —
      the monitor's fail_threshold x interval IS the probe retry budget,
      and transport retries would silently stretch detection;
    - ``send_token`` gets one extra attempt — a lost token callback
      strands the whole request until its timeout, so the token path is
      worth one more try than bulk data-plane traffic;
    - ``load_model`` (the failure monitor's recovery reload) backs off on
      the scale of the operation — a whole-(delta-)cluster reload retried
      at unary-RPC cadence would hammer shards still tearing down the
      failed attempt, so its base delay is 20x the unary base.
    """
    from dnet_tpu.config import get_settings

    s = get_settings().resilience
    attempts = max(int(s.retry_attempts), 1)
    base = float(s.retry_base_s)
    max_delay = float(s.retry_max_s)
    if method == "health_check":
        attempts = 1
    elif method == "send_token":
        attempts += 1
    elif method == "load_model":
        base *= 20.0
        max_delay = max(max_delay, base)
    return RetryPolicy(
        max_attempts=attempts,
        base_delay_s=base,
        max_delay_s=max_delay,
    )


_rng: Optional[random.Random] = None
_rng_lock = threading.Lock()


def jitter_rng() -> random.Random:
    """The process jitter RNG; DNET_RESILIENCE_RETRY_JITTER_SEED != 0 makes
    backoff schedules reproducible."""
    global _rng
    if _rng is None:
        with _rng_lock:
            if _rng is None:
                from dnet_tpu.config import get_settings

                seed = get_settings().resilience.retry_jitter_seed
                _rng = random.Random(seed) if seed else random.Random()
    return _rng


def reset_jitter_rng() -> None:
    """Drop the cached RNG so the next use re-reads the seed (tests)."""
    global _rng
    with _rng_lock:
        _rng = None


def is_retryable(exc: BaseException) -> bool:
    """Transient-failure classification (see module docstring)."""
    code = getattr(exc, "code", None)
    if callable(code):
        try:
            name = getattr(code(), "name", None)
        except Exception:
            name = None
        if name is not None:
            return name in RETRYABLE_GRPC_CODES
    if isinstance(exc, (ConnectionError, TimeoutError, OSError)):
        return True
    return isinstance(exc, asyncio.TimeoutError)


async def call_with_retry(
    fn: Callable[[], Awaitable],
    *,
    method: str,
    policy: Optional[RetryPolicy] = None,
    rng: Optional[random.Random] = None,
    sleep: Callable[[float], Awaitable] = asyncio.sleep,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    retryable: Optional[Callable[[BaseException], bool]] = None,
):
    """Run `fn` under the method's retry policy.  Non-retryable errors and
    the final attempt's error propagate unchanged.  `retryable` overrides
    the transient-failure classifier for calls whose failures don't look
    like transport errors but ARE worth retrying (a recovery reload failing
    through an HTTP 500 is a cluster-state problem, not a logic bug)."""
    policy = policy or policy_for(method)
    rng = rng or jitter_rng()
    classify = retryable or is_retryable
    attempt = 0
    while True:
        try:
            return await fn()
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            if attempt + 1 >= policy.max_attempts or not classify(exc):
                raise
            _RETRIES.labels(method=method).inc()
            if on_retry is not None:
                on_retry(attempt, exc)
            log.warning(
                "%s failed (%s); retry %d/%d",
                method, exc, attempt + 1, policy.max_attempts - 1,
            )
            await sleep(policy.delay_s(attempt, rng))
            attempt += 1
