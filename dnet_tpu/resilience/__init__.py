"""Request survival: retry/backoff policy, transparent decode resume, and
the deterministic chaos harness that tests all of it.

- `policy`     — exponential-backoff + full-jitter retries for unary RPCs
  and stream re-open (gRPC UNAVAILABLE/DEADLINE_EXCEEDED classification).
- `checkpoint` — the resumable-request state machine `InferenceManager`
  drives behind ``DNET_RESILIENCE_RESUME=1``.
- `chaos`      — seeded fault injection (``DNET_CHAOS``) at named points in
  transport send, token callback, health check, and shard compute.

Import submodules directly (``from dnet_tpu.resilience import chaos``).
This ``__init__`` stays import-free on purpose: the metrics registry's
core registration imports ``chaos`` for the injection-point names, and an
eager ``policy``/``checkpoint`` import here would re-enter the registry
lock through their module-level `metric()` handles.
"""

__all__ = ["chaos", "checkpoint", "policy"]
