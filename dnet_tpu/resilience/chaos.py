"""Deterministic, seeded fault injection for the recovery paths.

Every retry/resume path this subsystem ships is exercised by reproducible
tests rather than by killing processes and hoping: named injection points
are wired into the transport send (``send_activation``), the shard->API
token callback (``token_cb``), the failure monitor's probe
(``health_check``), the shard compute thread (``shard_compute``), the
admission controller (``admit`` — a delay here reproduces overload
deterministically), and a
spec string — ``DNET_CHAOS="shard_compute:error_at:5,
send_activation:error:0.1,token_cb:delay:50ms"`` — schedules faults at
them.  The schedule is a pure function of the seed and each point's call
counter (one seeded RNG per point, counters advance only at that point's
call sites), so two runs of the same workload inject the identical fault
sequence; there is no wall-clock or cross-point coupling.

Spec grammar (comma-separated, one spec per point; later wins):

- ``point:error:P``    — raise `ChaosError` with probability P per call
- ``point:error_at:N`` — raise on exactly the Nth call (1-based;
  ``N+M+...`` lists several)
- ``point:delay:D``    — sleep D per call (``50ms``, ``0.5s``, or seconds)
- ``point:partition:S+W`` — a seeded outage window: calls S..S+W-1
  (1-based) all raise, then the point heals and every later call passes.
  Partitioning BOTH directions of a hop (``send_activation`` forward and
  ``token_cb`` return) over the same window reproduces a network
  partition of that link deterministically — recovery, delta
  reconfiguration and resume all run against the healed ring.

`ChaosError` subclasses `ConnectionError` so the retry policy's
classification (resilience/policy.py) treats an injected fault exactly like
a real transport failure.  Injections count into
``dnet_chaos_injected_total{point=}``.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from dnet_tpu.utils.logger import get_logger

log = get_logger()

# The declared injection-point names.  The metrics lint
# (scripts/check_metrics_names.py) asserts every name here has a
# pre-touched dnet_chaos_injected_total{point=} series, so a new point
# cannot ship without its observability.
INJECTION_POINTS: Tuple[str, ...] = (
    "send_activation",  # StreamManager.send, before the stream write
    "token_cb",         # shard -> API token callback (RingAdapter._cb_send)
    "health_check",     # RingFailureMonitor's per-shard probe
    "shard_compute",    # ShardRuntime compute thread, before process()
    "admit",            # AdmissionController.acquire, before any check —
                        # a delay here backs the bounded queue up exactly
                        # like a slow burst (deterministic overload tests)
    "zombie_frame",     # shard ingress epoch fence (RingAdapter): an
                        # injected error marks the frame STALE, simulating
                        # a zombie sender without racing a real partition
    "rejoin",           # failure monitor's rejoin attempt: an injected
                        # error aborts the attempt (the shard re-earns its
                        # stability window), exercising rejoin retry
    "wire_encode",      # hop-codec encode (PendingWirePayload.finalize /
                        # the synchronous encode seam): a delay here wedges
                        # the tx stage deterministically — the encode ring
                        # fills and compute blocks on backpressure
    "wire_decode",      # hop-codec decode: an error fails the frame's
                        # decode exactly like a corrupt payload would.
                        # Fires ASYNC at ingress before predecode (a delay
                        # parks that frame's admission, not the loop) and
                        # sync on the compute thread's fallback decode
    "fleet_dispatch",   # FleetManager's per-candidate dispatch (both the
                        # streaming _acquire walk and the non-streaming
                        # generate walk): an injected error fails that
                        # candidate exactly like a dead replica — the walk
                        # falls through to the next; all faulted => the
                        # fleet sheds (429), never a 500
    "update_topology",  # shard delta-reconfig entry (Shard.update_topology
                        # and the in-process membership harness): an error
                        # fails the delta exactly like an unreachable
                        # shard — the API's retry/full-load fallback runs
)

KINDS: Tuple[str, ...] = ("error", "error_at", "delay", "partition")
_KINDS = KINDS  # back-compat alias


class ChaosError(ConnectionError):
    """An injected fault.  ConnectionError base => retryable by the policy
    classifier, same as a real broken channel."""


def _parse_duration(raw: str) -> float:
    raw = raw.strip().lower()
    if raw.endswith("ms"):
        return float(raw[:-2]) / 1000.0
    if raw.endswith("s"):
        return float(raw[:-1])
    return float(raw)


@dataclass
class _PointSpec:
    kind: str
    prob: float = 0.0
    delay_s: float = 0.0
    at: Tuple[int, ...] = ()
    # partition window: calls part_start..part_start+part_width-1 raise
    part_start: int = 0
    part_width: int = 0


@dataclass
class ChaosInjector:
    """Parsed spec + per-point counters/RNGs.  Thread-safe: shard_compute
    fires from the compute thread while transport points fire on the event
    loop."""

    spec: str
    seed: int = 0
    points: Dict[str, _PointSpec] = field(default_factory=dict)

    def __post_init__(self) -> None:
        self.points = self._parse(self.spec)
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {p: 0 for p in self.points}
        self._rngs: Dict[str, random.Random] = {
            p: random.Random(f"{self.seed}:{p}") for p in self.points
        }

    @staticmethod
    def _parse(spec: str) -> Dict[str, _PointSpec]:
        out: Dict[str, _PointSpec] = {}
        for part in (p.strip() for p in spec.split(",") if p.strip()):
            fields = part.split(":")
            if len(fields) != 3:
                raise ValueError(
                    f"chaos spec {part!r} must be point:kind:param"
                )
            point, kind, param = (f.strip() for f in fields)
            if point not in INJECTION_POINTS:
                raise ValueError(
                    f"unknown chaos point {point!r}; declared points: "
                    f"{', '.join(INJECTION_POINTS)}"
                )
            if kind == "error":
                out[point] = _PointSpec(kind, prob=float(param))
            elif kind == "error_at":
                out[point] = _PointSpec(
                    kind, at=tuple(int(n) for n in param.split("+"))
                )
            elif kind == "delay":
                out[point] = _PointSpec(kind, delay_s=_parse_duration(param))
            elif kind == "partition":
                try:
                    start_s, width_s = param.split("+", 1)
                    start, width = int(start_s), int(width_s)
                except ValueError:
                    raise ValueError(
                        f"chaos partition param {param!r} must be S+W "
                        "(1-based start call + window width)"
                    ) from None
                if start < 1 or width < 1:
                    raise ValueError(
                        f"chaos partition window {param!r} must have "
                        "S >= 1 and W >= 1"
                    )
                out[point] = _PointSpec(
                    kind, part_start=start, part_width=width
                )
            else:
                raise ValueError(
                    f"unknown chaos kind {kind!r}; one of {', '.join(KINDS)}"
                )
        return out

    def decide(self, point: str) -> Tuple[str, float]:
        """Advance the point's counter and return ("none"|"error"|"delay",
        delay_s).  Deterministic given (seed, call index)."""
        sp = self.points.get(point)
        if sp is None:
            return ("none", 0.0)
        with self._lock:
            self._counters[point] += 1
            n = self._counters[point]
            # draw ALWAYS (even for error_at/delay) so the schedule depends
            # only on the call index, never on which spec kind is active
            draw = self._rngs[point].random()
        if sp.kind == "error" and draw < sp.prob:
            return ("error", 0.0)
        if sp.kind == "error_at" and n in sp.at:
            return ("error", 0.0)
        if sp.kind == "delay":
            return ("delay", sp.delay_s)
        if (
            sp.kind == "partition"
            and sp.part_start <= n < sp.part_start + sp.part_width
        ):
            # inside the outage window every call fails; past it the
            # point has healed and never fires again
            return ("error", 0.0)
        return ("none", 0.0)

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)


_active: Optional[ChaosInjector] = None
_env_loaded = False
_install_lock = threading.Lock()


def _record(point: str) -> None:
    from dnet_tpu.obs import metric  # lazy: avoid import-time registry work

    metric("dnet_chaos_injected_total").labels(point=point).inc()


def get_chaos() -> Optional[ChaosInjector]:
    """The active injector: whatever install_chaos() set, else DNET_CHAOS
    from settings (read once; tests use install_chaos/clear_chaos)."""
    global _active, _env_loaded
    if _active is not None:
        return _active
    if _env_loaded:
        return None
    with _install_lock:
        if _active is None and not _env_loaded:
            from dnet_tpu.config import get_settings

            s = get_settings().chaos
            if s.chaos:
                _active = ChaosInjector(s.chaos, seed=s.chaos_seed)
                log.warning(
                    "CHAOS ACTIVE: %s (seed=%d)", s.chaos, s.chaos_seed
                )
            _env_loaded = True
    return _active


def validate_startup(role: str = "server") -> Optional[ChaosInjector]:
    """Server-start gate: parse DNET_CHAOS NOW and fail fast on a
    malformed spec (unknown point/kind) with the declared vocabulary in
    the error, instead of silently deferring the ValueError to the first
    injection mid-request.  When chaos IS armed, pre-touch every declared
    point's counter series (so armed-but-never-fired points are visible
    in the exposition) and log one prominent warning naming the armed
    points — an injected fault must never masquerade as a real incident.
    """
    try:
        c = get_chaos()
    except ValueError as exc:
        raise SystemExit(
            f"malformed DNET_CHAOS: {exc}\n"
            f"  declared points: {', '.join(INJECTION_POINTS)}\n"
            f"  declared kinds:  {', '.join(KINDS)}"
        ) from exc
    if c is None:
        return None
    from dnet_tpu.obs import metric  # lazy: avoid import-time registry work

    for point in INJECTION_POINTS:
        metric("dnet_chaos_injected_total").labels(point=point)
    log.warning(
        "=" * 64 + "\n"
        "CHAOS ARMED on this %s: spec=%r seed=%d points=%s\n"
        "Faults below are INJECTED — check /health `chaos` before paging.\n"
        + "=" * 64,
        role, c.spec, c.seed,
        ",".join(f"{p}:{sp.kind}" for p, sp in sorted(c.points.items())),
    )
    return c


def armed_summary() -> Optional[Dict[str, object]]:
    """The /health `chaos` section: active spec/seed and point->kind map,
    or None when no chaos is armed (the section is omitted entirely)."""
    try:
        c = get_chaos()
    except ValueError:
        # malformed env spec outside the server path (validate_startup
        # would have exited); surface that it is armed-but-broken
        return {"spec": "<malformed>", "seed": 0, "points": {}}
    if c is None:
        return None
    return {
        "spec": c.spec,
        "seed": c.seed,
        "points": {p: sp.kind for p, sp in sorted(c.points.items())},
    }


def install_chaos(spec: str, seed: int = 0) -> ChaosInjector:
    """Install an injector programmatically (tests); counters start at 0."""
    global _active
    with _install_lock:
        _active = ChaosInjector(spec, seed=seed)
    return _active


def clear_chaos() -> None:
    global _active, _env_loaded
    with _install_lock:
        _active = None
        _env_loaded = True  # do not fall back to the env spec mid-test


def inject(point: str) -> None:
    """Synchronous injection site (compute thread): may sleep or raise."""
    c = get_chaos()
    if c is None:
        return
    act, delay_s = c.decide(point)
    if act == "delay":
        _record(point)
        time.sleep(delay_s)
    elif act == "error":
        _record(point)
        raise ChaosError(f"chaos injected at {point}")


async def inject_async(point: str) -> None:
    """Event-loop injection site: may await or raise."""
    import asyncio

    c = get_chaos()
    if c is None:
        return
    act, delay_s = c.decide(point)
    if act == "delay":
        _record(point)
        await asyncio.sleep(delay_s)
    elif act == "error":
        _record(point)
        raise ChaosError(f"chaos injected at {point}")
