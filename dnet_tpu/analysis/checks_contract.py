"""Contract-drift checks: conventions PRs 1-7 established, machine-checked.

DL006 — ``DNET_*`` environment reads outside ``config.py``: the settings
layer owns precedence (defaults < .env < process env < CLI) and the
settings cache; a stray ``os.environ.get("DNET_...")`` silently skips
.env files, bypasses type casting, and drifts from ``.env.example``.
``config.env_flag()`` is the sanctioned escape hatch for flags that must
observe post-cache env flips; the module allowlist below covers the
documented pre-import bootstraps.

DL007 — silent exception swallows: ``except Exception: pass`` on a
serving path turns real failures (half-closed streams, leaked channels)
into nothing.  The contract: every broad catch either logs (debug is
fine) or counts.

DL008 — typed-error and wire-header drift: (a) every ``InferenceError``
subclass must appear in the HTTP status mapping (api/http.py) — an
unmapped class falls through to 500 and breaks the 429/504 retry
contract; (b) every ``ActivationFrame`` construction must stamp
``epoch=`` and ``deadline=`` and every ``TokenPayload`` must stamp
``epoch=`` — an unstamped frame is invisible to the zombie fence and the
deadline dropper (membership PR 6, admission PR 5).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set

from dnet_tpu.analysis.core import (
    Check,
    Finding,
    Project,
    SourceFile,
    dotted,
    is_serving_path,
)

#: rel-path -> why raw DNET_* reads are sanctioned there
DL006_ALLOWLIST: Dict[str, str] = {
    "dnet_tpu/config.py": "the settings layer — THE sanctioned env reader",
    "bench.py": (
        "bench driver <-> inner-process coordination (DNET_BENCH_*) runs "
        "before dnet_tpu.config can be imported in the probed interpreter"
    ),
}

_BROAD = {"Exception", "BaseException"}

#: wire classes (transport/protocol.py) -> keywords every constructor
#: outside the protocol module itself must stamp
_FRAME_REQUIRED = {
    "ActivationFrame": ("epoch", "deadline"),
    "TokenPayload": ("epoch",),
}

_ERROR_BASE = "InferenceError"
_STATUS_MAP_SUFFIX = "api/http.py"
_ERROR_HOME_SUFFIX = "api/inference.py"


def _env_read_key(node: ast.AST) -> str:
    """The literal env-var name read by this node, or ''."""
    if isinstance(node, ast.Call):
        d = dotted(node.func)
        if d in ("os.environ.get", "os.getenv") and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                return arg.value
    elif isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        if dotted(node.value) == "os.environ" and isinstance(
            node.slice, ast.Constant
        ) and isinstance(node.slice.value, str):
            return node.slice.value
    elif isinstance(node, ast.Compare) and len(node.ops) == 1 and isinstance(
        node.ops[0], (ast.In, ast.NotIn)
    ):
        if (
            dotted(node.comparators[0]) == "os.environ"
            and isinstance(node.left, ast.Constant)
            and isinstance(node.left.value, str)
        ):
            return node.left.value
    return ""


class EnvReadOutsideConfig(Check):
    code = "DL006"
    name = "env-read-outside-config"
    description = (
        "DNET_* environment reads outside config.py bypass .env layering, "
        "type casting, and the settings cache — use a Settings field or "
        "config.env_flag()"
    )

    def run_file(self, src: SourceFile, project: Project) -> Iterable[Finding]:
        if src.rel in DL006_ALLOWLIST:
            return
        for node in ast.walk(src.tree):
            key = _env_read_key(node)
            if key.startswith("DNET_"):
                yield self.finding(
                    src.rel, node.lineno,
                    f"raw read of {key} outside config.py — route through "
                    f"a Settings field or config.env_flag()",
                    col=node.col_offset,
                )


class SilentExceptionSwallow(Check):
    code = "DL007"
    name = "silent-exception-swallow"
    description = (
        "'except Exception: pass'-style swallow on a serving path without "
        "a counter or log — failures must leave a trace"
    )

    def run_file(self, src: SourceFile, project: Project) -> Iterable[Finding]:
        if not is_serving_path(src.rel):
            return
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._broad(node.type):
                continue
            if all(self._trivial(stmt) for stmt in node.body):
                caught = dotted(node.type) if node.type is not None else "bare"
                yield self.finding(
                    src.rel, node.lineno,
                    f"broad except ({caught}) silently swallows — add a "
                    f"debug log or a counter",
                    col=node.col_offset,
                )

    @staticmethod
    def _broad(type_node) -> bool:
        if type_node is None:
            return True
        if isinstance(type_node, ast.Name):
            return type_node.id in _BROAD
        if isinstance(type_node, ast.Tuple):
            return any(
                isinstance(e, ast.Name) and e.id in _BROAD
                for e in type_node.elts
            )
        return False

    @staticmethod
    def _trivial(stmt: ast.stmt) -> bool:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            return True
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            return True  # docstring / ellipsis
        return False


class ContractDrift(Check):
    code = "DL008"
    name = "error-and-header-contract"
    description = (
        "InferenceError subclasses must map to an HTTP status in "
        "api/http.py; ActivationFrame/TokenPayload constructions must "
        "stamp epoch (and deadline for frames)"
    )

    def run_project(self, project: Project) -> Iterable[Finding]:
        yield from self._typed_errors(project)
        yield from self._frame_headers(project)

    def _typed_errors(self, project: Project) -> Iterable[Finding]:
        home = project.find_suffix(_ERROR_HOME_SUFFIX)
        status_map = project.find_suffix(_STATUS_MAP_SUFFIX)
        if home is None or home.tree is None or status_map is None or (
            status_map.tree is None
        ):
            return
        subclasses: Dict[str, int] = {}
        known: Set[str] = {_ERROR_BASE}
        # two passes so grandchildren (subclass-of-subclass) resolve
        for _ in range(2):
            for node in ast.walk(home.tree):
                if isinstance(node, ast.ClassDef) and any(
                    dotted(b).split(".")[-1] in known for b in node.bases
                ):
                    if node.name not in known:
                        known.add(node.name)
                        subclasses[node.name] = node.lineno
        mapped = {
            n.id for n in ast.walk(status_map.tree) if isinstance(n, ast.Name)
        }
        for name, lineno in sorted(subclasses.items()):
            if name not in mapped:
                yield self.finding(
                    home.rel, lineno,
                    f"typed error {name} has no status mapping in "
                    f"{status_map.rel} — it will fall through to a "
                    f"generic 500",
                )

    def _frame_headers(self, project: Project) -> Iterable[Finding]:
        for src in project.files:
            if src.tree is None or src.rel.endswith("transport/protocol.py"):
                continue
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                cls = d.split(".")[-1]
                required = _FRAME_REQUIRED.get(cls)
                # only direct constructions (Name or module.Name), not
                # classmethods like TokenPayload.from_result
                if required is None or (d != cls and "." in d and not d.endswith(
                    f".{cls}"
                )):
                    continue
                if isinstance(node.func, ast.Attribute) and node.func.attr != cls:
                    continue
                kws = {kw.arg for kw in node.keywords}
                if None in kws:  # **kwargs — assume the dict carries them
                    continue
                missing = [k for k in required if k not in kws]
                if missing:
                    yield self.finding(
                        src.rel, node.lineno,
                        f"{cls}(...) constructed without stamping "
                        f"{'/'.join(missing)} — unfenced against zombie "
                        f"epochs / deadline drops",
                        col=node.col_offset,
                    )
