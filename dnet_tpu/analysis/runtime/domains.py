"""dsan declaration data — the ownership registry and runtime-check catalog.

A LEAF module (stdlib only, imports nothing from dnet_tpu) so that

- instrumented modules (shard/runtime, api/strategies, kv/paged, ...) can
  apply guards by declared name without importing the checker machinery,
- ``dnet_tpu/obs`` can pre-touch the ``dnet_san_*`` label sets at registry
  init without a cycle, and
- the DL009 static check can cross-reference the declarations against the
  code purely from the AST (the declarations below are also parsed as a
  literal by the check's fixture mode).

Everything here is DATA.  The enforcement lives in the sibling modules
(ownership.py / lockorder.py / loop_monitor.py / tasks.py) and in
``dnet_tpu/analysis/checks_dsan.py`` (DL009) / ``metrics_checks.py``
(DL018).
"""

from __future__ import annotations

#: The runtime (dsan) check catalog: (code, name, description).  Shown by
#: ``dnetlint --list-checks``, embedded in the ANALYSIS report's
#: ``runtime`` section, and the label set of dnet_san_findings_total.
RUNTIME_CHECKS = (
    (
        "DS001", "loop-stall",
        "event loop blocked past DNET_SAN_STALL_MS; offending stack "
        "captured via sys._current_frames and attributed to file:line",
    ),
    (
        "DS002", "wrong-thread-access",
        "a structure declared loop-only / thread(<name>) was touched from "
        "a thread outside its ownership domain",
    ),
    (
        "DS003", "lock-not-held",
        "a structure declared guarded-by(<lock>) was touched without the "
        "declared lock held by the current thread",
    ),
    (
        "DS004", "lock-order-cycle",
        "instrumented locks were acquired in cyclic order across threads "
        "(potential deadlock)",
    ),
    (
        "DS005", "task-leak",
        "an asyncio task created during the sanitized window was still "
        "pending (never awaited or cancelled) at the teardown audit",
    ),
    (
        "DS006", "unretrieved-task-exception",
        "an asyncio task finished with an exception nobody retrieved "
        "(the failure would only surface as a GC-time log line, if ever)",
    ),
)

RUNTIME_CHECK_CODES = tuple(c for c, _, _ in RUNTIME_CHECKS)

#: Ownership declarations for the known hot thread/loop boundaries:
#: (module rel-path, class, attribute, kind, arg).
#:
#: kind ``loop``   — only the owning event loop's thread may touch it
#:                   (arg unused; the owning loop is bound at guard time)
#: kind ``thread`` — only threads named ``arg`` (exact, or ``arg_N`` for
#:                   executor pools) may touch the listed operations
#: kind ``lock``   — the instrumented lock attribute named ``arg`` on the
#:                   same instance must be held by the current thread
#:
#: DL009 verifies each declared module/class/attribute (and, for ``lock``
#: kind, the lock attribute) still exists in the code — a refactor cannot
#: silently strand the registry.
OWNERSHIP_DOMAINS = (
    ("dnet_tpu/shard/runtime.py", "ShardRuntime", "recv_q", "thread", "shard-compute"),
    ("dnet_tpu/shard/runtime.py", "ShardRuntime", "out_q", "loop", ""),
    ("dnet_tpu/shard/runtime.py", "ShardRuntime", "epoch", "lock", "_model_lock"),
    ("dnet_tpu/shard/runtime.py", "ShardRuntime", "_pending_errs", "loop", ""),
    ("dnet_tpu/api/strategies.py", "LocalAdapter", "_buffered", "lock", "_buf_lock"),
    ("dnet_tpu/api/strategies.py", "LocalAdapter", "_ramp", "lock", "_buf_lock"),
    ("dnet_tpu/kv/paged.py", "BlockPool", "_free", "lock", "_lock"),
    ("dnet_tpu/kv/paged.py", "BlockPool", "_ref", "lock", "_lock"),
    ("dnet_tpu/core/prefix_cache.py", "PrefixIndex", "_entries", "lock", "_lock"),
    ("dnet_tpu/obs/metrics.py", "MetricsRegistry", "_metrics", "lock", "_lock"),
    ("dnet_tpu/transport/stream_manager.py", "StreamManager", "_streams", "loop", ""),
    # iteration-level scheduler (dnet_tpu/sched/): the queue and the
    # pre-arrival deadline stash are loop-owned — the compute thread only
    # ever sees plain snapshots inside a TickPlan
    ("dnet_tpu/sched/queue.py", "SchedQueue", "_reqs", "loop", ""),
    ("dnet_tpu/sched/engine.py", "SchedulerAdapter", "_deadlines", "loop", ""),
    # overlapped wire pipeline (transport/wire_pipeline.py): the encode
    # ring's in-flight count is touched from the compute thread (acquire)
    # AND the tx executor (release) — guarded-by lock; the tx stage's
    # pending map is egress-worker-only (loop)
    ("dnet_tpu/transport/wire_pipeline.py", "EncodeRing", "_inflight", "lock", "_lock"),
    ("dnet_tpu/transport/wire_pipeline.py", "WireTxStage", "_pending", "loop", ""),
)

#: Modules sanctioned to cross the thread->loop boundary via
#: ``call_soon_threadsafe`` / ``run_coroutine_threadsafe``.  Anywhere else
#: such a bridge is a DL009 finding: ad-hoc bridges are exactly the seams
#: dsan exists to fence, so new ones must be declared here (and annotated)
#: or rewritten through an existing bridge.
BRIDGE_MODULES = (
    "dnet_tpu/shard/runtime.py",
    "dnet_tpu/api/strategies.py",
    "dnet_tpu/analysis/runtime/loop_monitor.py",
    # wire-pipeline tick dispatch: the scheduler's compute-thread tick
    # hands each decode result back to the loop as it is produced
    # (call_soon_threadsafe) instead of barriering on the full tick
    "dnet_tpu/sched/engine.py",
)

#: Label set of dnet_san_zombie_threads_total: worker threads that can
#: fail to join at stop() and get leaked as daemons (DL018 cross-checks
#: these against the exposed series both ways).
ZOMBIE_THREAD_KINDS = ("shard-compute", "tui")
