"""dsan task audit (DS005/DS006): leaked tasks and swallowed exceptions.

A task-factory hook records every task created on the instrumented loop
together with its creation site (the first caller frame outside asyncio
and outside this package).  At teardown :func:`TaskAuditor.audit` walks
the records:

- a task still PENDING is a leak (DS005): nobody awaited or cancelled
  it, so it dies un-run when the loop closes — the runtime twin of the
  static DL003 dropped-coroutine check;
- a task that finished with an exception nobody retrieved (DS006):
  CPython only surfaces these as a "Task exception was never retrieved"
  log line at GC time, often long after the cause — the audit surfaces
  them deterministically at teardown (and retrieves the exception so the
  GC-time spam does not double-report).

Tasks are held by weakref: the auditor must not keep alive what the
program dropped — a task the GC already collected while pending was
ALSO leaked, but CPython's own "Task was destroyed but it is pending!"
warning covers that window.  Records of tasks that finish CLEANLY are
pruned one tick after completion (once any awaiter has had its chance to
retrieve), so a serving-lifetime install stays bounded by the number of
in-flight + failed tasks, not by total tasks ever created.
"""

from __future__ import annotations

import asyncio
import sys
import weakref
from typing import Dict, List, Optional, Tuple

from dnet_tpu.analysis.runtime import sanitizer as _san

_ASYNCIO_DIR = sys.modules["asyncio"].__path__[0]


def _creation_site() -> Tuple[str, int]:
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.startswith(_ASYNCIO_DIR) and not fn.startswith(_san._PKG_DIR):
            return _san._relpath(fn), f.f_lineno
        f = f.f_back
    return "<unknown>", 0


class TaskAuditor:
    """Task-factory hook + teardown audit for ONE loop."""

    def __init__(self, loop: asyncio.AbstractEventLoop) -> None:
        self.loop = loop
        self._prev_factory = None
        self._installed = False
        #: id(task) -> (weakref-to-task, name, (path, line)); cleanly
        #: finished tasks are pruned by :meth:`_settle`
        self._records: Dict[int, tuple] = {}
        #: STRONG refs to tasks that finished with an exception: the
        #: program dropped them, so without this pin the GC collects them
        #: (logging "never retrieved" asynchronously) before the audit
        #: can attribute the failure.  Only failures are pinned.
        self._failed: List[asyncio.Task] = []

    def _on_done(self, task: asyncio.Task) -> None:
        # settle one tick later: the awaiter (if any) was queued as a done
        # callback before this one ran, so by the next call_soon round it
        # has retrieved the exception — only genuinely-unretrieved
        # failures get pinned, and clean finishes get pruned
        try:
            self.loop.call_soon(self._settle, task)
        except RuntimeError:  # loop already closing: audit() re-checks
            pass

    def _settle(self, task: asyncio.Task) -> None:
        # _log_traceback is True from exception-set until retrieval; an
        # awaiter that retrieves even later still clears it, and audit()
        # re-checks before reporting
        if getattr(task, "_log_traceback", False):
            self._failed.append(task)
            return
        self._records.pop(id(task), None)

    def _factory(self, loop, coro, **kwargs):
        if self._prev_factory is not None:
            task = self._prev_factory(loop, coro, **kwargs)
        else:
            task = asyncio.Task(coro, loop=loop, **kwargs)
        site = _creation_site()
        name = getattr(coro, "__qualname__", None) or repr(coro)
        self._records[id(task)] = (weakref.ref(task), name, site)
        task.add_done_callback(self._on_done)
        return task

    def install(self) -> "TaskAuditor":
        self._prev_factory = self.loop.get_task_factory()
        self.loop.set_task_factory(self._factory)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            self.loop.set_task_factory(self._prev_factory)
            self._installed = False

    def audit(self) -> int:
        """Record findings for leaks and unretrieved exceptions; returns
        the number of findings recorded."""
        san = _san.get_sanitizer()
        n = 0
        for ref, name, (path, line) in list(self._records.values()):
            task = ref()
            if task is None:
                continue
            if not task.done():
                if getattr(task, "_must_cancel", False):
                    continue  # cancellation requested, loop closed first
                san.record(
                    "DS005",
                    f"task {name} created here is still pending at the "
                    f"teardown audit (never awaited or cancelled): it "
                    f"dies un-run when the loop closes",
                    path, line,
                )
                n += 1
                continue
            if task.cancelled():
                continue
            if getattr(task, "_log_traceback", False):
                exc = task.exception()  # retrieve: silence the GC-time log
                san.record(
                    "DS006",
                    f"task {name} created here finished with an exception "
                    f"nobody retrieved: {type(exc).__name__}: {exc}",
                    path, line,
                )
                n += 1
        return n


def install(loop: asyncio.AbstractEventLoop) -> Optional[TaskAuditor]:
    """Install a task auditor on ``loop`` when dsan is active; returns
    None — a no-op — otherwise."""
    if not _san.san_enabled():
        return None
    return TaskAuditor(loop).install()
