"""dsan serving-lifetime wiring: one handle per server process.

``install(loop)`` arms the loop-stall watchdog and the task auditor over
the whole serving lifetime of an ``serve_async`` entry point (api and
shard servers both call it); ``teardown()`` at shutdown runs the task
and lock-order audits, logs every finding, and persists them where the
next ``dnetlint --json`` run merges them into the ANALYSIS record.  With
``DNET_SAN`` unset ``install`` returns None and the servers skip the
teardown — zero cost, nothing constructed.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from dnet_tpu.analysis.runtime import loop_monitor, tasks as san_tasks
from dnet_tpu.analysis.runtime.lockorder import audit_lock_order
from dnet_tpu.analysis.runtime.sanitizer import (
    default_report_path,
    get_sanitizer,
    san_enabled,
)


class ServingSanitizer:
    """The armed per-server handle: watchdog + task auditor + teardown."""

    def __init__(self, monitor, auditor) -> None:
        self.monitor = monitor
        self.auditor = auditor

    def teardown(self, log) -> int:
        """Stop the detectors, run the teardown audits, log + persist the
        findings; returns how many findings the window recorded."""
        if self.monitor is not None:
            self.monitor.stop()
        if self.auditor is not None:
            self.auditor.uninstall()
            self.auditor.audit()
        audit_lock_order()
        san = get_sanitizer()
        findings = san.findings
        for f in findings:
            log.error("dsan: %s", f.render())
        report = default_report_path()
        san.persist(report)
        log.info(
            "dsan: %d finding(s) persisted to %s (merged into the next "
            "`dnetlint --json` report)", len(findings), report,
        )
        return len(findings)


def install(loop: asyncio.AbstractEventLoop) -> Optional[ServingSanitizer]:
    """Arm the serving-lifetime detectors when dsan is active; returns
    None — a no-op — otherwise."""
    if not san_enabled():
        return None
    return ServingSanitizer(loop_monitor.install(loop), san_tasks.install(loop))
