"""dsan — the runtime concurrency sanitizer (``DNET_SAN=1``).

The static suite (PR 8, ``dnet_tpu/analysis/checks_*``) proves what the
AST can see; dsan proves what only a RUNNING process can: the event loop
actually blocking (loop_monitor, DS001), a thread actually touching a
structure outside its declared ownership domain (ownership, DS002/DS003),
locks actually acquired in cyclic order (lockorder, DS004), and tasks
actually leaked or left holding an unretrieved exception (tasks,
DS005/DS006).  Findings reuse the static :class:`Finding` model and merge
into the same ``ANALYSIS_r<NN>.json`` records via ``scripts/dnetlint.py``.

Wiring:

- tests/subsystems/test_dsan.py runs designated subsystem suites under
  ``DNET_SAN=1`` in tier-1 and fails on any finding;
- ``scripts/dnetlint.py --json`` embeds the ``runtime`` section (catalog
  + persisted findings) and ``--list-checks`` prints the DS catalog;
- static check DL009 cross-checks the ownership declarations
  (:mod:`.domains`) against the code.

With ``DNET_SAN`` unset every entry point here is a no-op: guards return
their arguments unchanged and nothing is installed — zero cost on the
serving path.
"""

from dnet_tpu.analysis.runtime import (
    lockorder,
    loop_monitor,
    ownership,
    serving,
    tasks,
)
from dnet_tpu.analysis.runtime.domains import (
    BRIDGE_MODULES,
    OWNERSHIP_DOMAINS,
    RUNTIME_CHECK_CODES,
    RUNTIME_CHECKS,
    ZOMBIE_THREAD_KINDS,
)
from dnet_tpu.analysis.runtime.lockorder import (
    SanLock,
    audit_lock_order,
    reset_lock_order,
)
from dnet_tpu.analysis.runtime.sanitizer import (
    Sanitizer,
    get_sanitizer,
    reset_sanitizer,
    runtime_section,
    san_enabled,
)

__all__ = [
    "BRIDGE_MODULES",
    "OWNERSHIP_DOMAINS",
    "RUNTIME_CHECKS",
    "RUNTIME_CHECK_CODES",
    "ZOMBIE_THREAD_KINDS",
    "SanLock",
    "Sanitizer",
    "audit_lock_order",
    "get_sanitizer",
    "lockorder",
    "loop_monitor",
    "ownership",
    "reset_lock_order",
    "reset_sanitizer",
    "runtime_section",
    "san_enabled",
    "serving",
    "tasks",
]
