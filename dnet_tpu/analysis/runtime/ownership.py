"""dsan ownership domains: who may touch a shared structure, enforced.

A *domain* names the concurrency contract of one shared structure:

- ``loop_domain(loop)``      — loop-only: touched only from a thread with
  the owning event loop running (asyncio.Queue, future maps, task sets);
- ``thread_domain(name)``    — owned by the named thread (``shard-compute``;
  executor pools match ``name_N``);
- ``lock_domain(san_lock)``  — guarded-by: the instrumented lock must be
  held by the current thread at every access.

The guard wrappers below are applied at CONSTRUCTION time, and only when
dsan is active — with ``DNET_SAN`` unset every factory returns its
argument unchanged, so the serving path carries zero instrumentation
(no proxy, no extra attribute, no check call).  Violations record DS002
(wrong thread) / DS003 (lock not held) into the process sanitizer,
deduped per site, and never raise — a sanitizer must observe the race,
not change the program under test.

Deliberate, audited cross-domain accesses (queue drains at teardown,
where ``queue.Queue``'s own lock makes the cross-thread pop benign) are
wrapped in :func:`allowed` — the runtime twin of the static
``# dnetlint: disable=...`` suppression, and like it, scoped and named.
"""

from __future__ import annotations

import asyncio
import threading
from collections import OrderedDict
from typing import Iterable, Optional

from dnet_tpu.analysis.runtime import sanitizer as _san
from dnet_tpu.analysis.runtime.lockorder import SanLock

_tls = threading.local()


class _Allowance:
    """Context manager: suppress domain checks for the named structures
    on this thread (deliberate cross-domain access, documented at the
    call site)."""

    __slots__ = ("names",)

    def __init__(self, names: Iterable[str]) -> None:
        self.names = set(names)

    def __enter__(self) -> "_Allowance":
        stack = getattr(_tls, "allowed", None)
        if stack is None:
            stack = _tls.allowed = []
        stack.append(self.names)
        return self

    def __exit__(self, *exc) -> None:
        _tls.allowed.pop()


def allowed(*names: str) -> _Allowance:
    return _Allowance(names)


def _is_allowed(name: str) -> bool:
    for entry in getattr(_tls, "allowed", ()):
        if name in entry:
            return True
    return False


class Domain:
    """Base ownership domain; subclasses implement :meth:`violation`."""

    kind = "any"

    def describe(self) -> str:
        return self.kind

    def violation(self) -> Optional[str]:
        """None when the current thread satisfies the domain, else a
        short description of the actual context."""
        return None

    def check(self, name: str, op: str) -> None:
        san = _san.get_sanitizer()
        if not _san.san_enabled() or san.recording() or _is_allowed(name):
            return
        why = self.violation()
        if why is None:
            return
        code = "DS003" if self.kind == "lock" else "DS002"
        path, line = _san.caller_site()
        san.record(
            code,
            f"{name}.{op} from outside its ownership domain "
            f"[{self.describe()}]: {why}",
            path, line,
        )


class LoopDomain(Domain):
    kind = "loop"

    def __init__(self, loop: Optional[asyncio.AbstractEventLoop] = None) -> None:
        self.loop = loop

    def describe(self) -> str:
        return "loop-only"

    def violation(self) -> Optional[str]:
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            return (
                f"thread {threading.current_thread().name!r} has no "
                f"running event loop"
            )
        if self.loop is not None and running is not self.loop:
            return "a different event loop is running in this thread"
        return None


class ThreadDomain(Domain):
    kind = "thread"

    def __init__(self, thread_name: str) -> None:
        self.thread_name = thread_name

    def describe(self) -> str:
        return f'thread("{self.thread_name}")'

    def violation(self) -> Optional[str]:
        name = threading.current_thread().name
        # exact worker name, or an executor-pool member ("compute_0")
        if name == self.thread_name or name.startswith(self.thread_name + "_"):
            return None
        return f"called from thread {name!r}"


class LockDomain(Domain):
    kind = "lock"

    def __init__(self, lock: SanLock) -> None:
        self.lock = lock

    def describe(self) -> str:
        return f"guarded-by({self.lock.name})"

    def violation(self) -> Optional[str]:
        if self.lock.held_by_current_thread():
            return None
        return (
            f"lock {self.lock.name} not held by thread "
            f"{threading.current_thread().name!r}"
        )


def loop_domain(loop: Optional[asyncio.AbstractEventLoop] = None) -> LoopDomain:
    return LoopDomain(loop)


def thread_domain(name: str) -> ThreadDomain:
    return ThreadDomain(name)


def lock_domain(lock: SanLock) -> LockDomain:
    return LockDomain(lock)


# ---- instrumented containers ----------------------------------------------


def _guarded_method(base: type, mname: str):
    orig = getattr(base, mname)

    def method(self, *a, **k):
        self._dsan_domain.check(self._dsan_name, mname)
        return orig(self, *a, **k)

    method.__name__ = mname
    method.__qualname__ = f"Guarded{base.__name__}.{mname}"
    return method


_DICT_OPS = (
    "__getitem__", "__setitem__", "__delitem__", "__contains__",
    "__iter__", "__len__", "get", "pop", "popitem", "setdefault",
    "update", "clear", "keys", "values", "items",
)
_SET_OPS = (
    "add", "discard", "remove", "pop", "clear", "update",
    "__contains__", "__iter__", "__len__",
)
_LIST_OPS = (
    "append", "extend", "insert", "pop", "remove", "clear",
    "__getitem__", "__setitem__", "__delitem__", "__contains__",
    "__iter__", "__len__",
)


class _GuardedContainer:
    """Mixin: slots + construction that seeds initial content under an
    allowance (wrapping an already-populated structure is the declared
    owner's construction step, not a domain access)."""

    __slots__ = ()

    def __init__(self, data, domain: Domain, name: str) -> None:
        self._dsan_domain = domain
        self._dsan_name = name
        with allowed(name):
            super().__init__(data)


class GuardedDict(_GuardedContainer, dict):
    __slots__ = ("_dsan_domain", "_dsan_name")


class GuardedOrderedDict(_GuardedContainer, OrderedDict):
    __slots__ = ("_dsan_domain", "_dsan_name")


class GuardedSet(_GuardedContainer, set):
    __slots__ = ("_dsan_domain", "_dsan_name")


class GuardedList(_GuardedContainer, list):
    __slots__ = ("_dsan_domain", "_dsan_name")


for _op in _DICT_OPS:
    setattr(GuardedDict, _op, _guarded_method(dict, _op))
for _op in _DICT_OPS + ("move_to_end",):
    setattr(GuardedOrderedDict, _op, _guarded_method(OrderedDict, _op))
for _op in _SET_OPS:
    setattr(GuardedSet, _op, _guarded_method(set, _op))
for _op in _LIST_OPS:
    setattr(GuardedList, _op, _guarded_method(list, _op))


class GuardedProxy:
    """Generic method-intercepting proxy for objects whose operations are
    plain attributes (queue.Queue, asyncio.Queue).  Only the methods named
    at wrap time are checked; everything else passes straight through."""

    __slots__ = ("_dsan_obj", "_dsan_domain", "_dsan_name", "_dsan_methods")

    def __init__(self, obj, domain: Domain, name: str, methods) -> None:
        object.__setattr__(self, "_dsan_obj", obj)
        object.__setattr__(self, "_dsan_domain", domain)
        object.__setattr__(self, "_dsan_name", name)
        object.__setattr__(self, "_dsan_methods", frozenset(methods))

    def __getattr__(self, attr):
        val = getattr(self._dsan_obj, attr)
        if attr in self._dsan_methods and callable(val):
            domain, name = self._dsan_domain, self._dsan_name

            def checked(*a, _fn=val, **k):
                domain.check(name, attr)
                return _fn(*a, **k)

            checked.__name__ = attr
            return checked
        return val

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<dsan guard {self._dsan_name} of {self._dsan_obj!r}>"


# ---- construction-time factories (no-ops when dsan is off) ----------------


def san_lock(name: str, lock: Optional[threading.Lock] = None):
    """Wrap (or mint) a lock as a :class:`SanLock` when dsan is active;
    otherwise return the plain lock unchanged."""
    if not _san.san_enabled():
        return lock if lock is not None else threading.Lock()
    return SanLock(name, lock)


def guard_dict(data: dict, domain: Domain, name: str):
    if not _san.san_enabled() or not isinstance(domain, Domain):
        return data
    return GuardedDict(data, domain, name)


def guard_ordered_dict(data, domain: Domain, name: str):
    if not _san.san_enabled() or not isinstance(domain, Domain):
        return data
    return GuardedOrderedDict(data, domain, name)


def guard_set(data: set, domain: Domain, name: str):
    if not _san.san_enabled() or not isinstance(domain, Domain):
        return data
    return GuardedSet(data, domain, name)


def guard_list(data: list, domain: Domain, name: str):
    if not _san.san_enabled() or not isinstance(domain, Domain):
        return data
    return GuardedList(data, domain, name)


def guard_methods(obj, domain: Domain, name: str, methods):
    if not _san.san_enabled() or not isinstance(domain, Domain):
        return obj
    return GuardedProxy(obj, domain, name, methods)


def maybe_lock_domain(lock) -> Optional[LockDomain]:
    """lock_domain over an attribute that is only a SanLock when dsan was
    active at construction — callers pass whatever they hold and get None
    (=> guards become no-ops) for a plain lock."""
    return LockDomain(lock) if isinstance(lock, SanLock) else None


def check_access(name: str, domain: Optional[Domain], op: str = "access") -> None:
    """Explicit check for boundaries a container proxy cannot cover (a
    scalar attribute write like ``ShardRuntime.epoch``)."""
    if domain is not None:
        domain.check(name, op)
