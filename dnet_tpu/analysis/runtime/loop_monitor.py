"""dsan loop-stall watchdog (DS001): detect the event loop blocked.

A heartbeat callback re-schedules itself on the watched loop every
``stall_ms / 4``; a sampling daemon thread watches the heartbeat age.
When the age exceeds ``DNET_SAN_STALL_MS`` the loop thread is wedged in
something synchronous — a C-extension call, a hidden device sync, a
``time.sleep`` — and the watchdog captures that thread's CURRENT stack
via ``sys._current_frames()``, attributes the stall to the innermost
repo frame (file:line), and records a DS001 finding.  One finding per
stall episode: the latch re-arms only after a heartbeat lands again.

The watchdog observes; it never interrupts.  Overhead while enabled is
one timer callback + one sleeping thread; while disabled it is never
constructed at all (:func:`install` returns None).
"""

from __future__ import annotations

import asyncio
import sys
import threading
import time
from typing import List, Optional

from dnet_tpu.analysis.runtime import sanitizer as _san

_MIN_BEAT_S = 0.005


def _attribute(frame):
    """Render the blocked stack innermost-repo-frame first: the finding's
    file:line is the deepest frame inside the repo (the code that made
    the blocking call), with the raw innermost frame appended when it
    lives outside the repo (the primitive actually blocking)."""
    stack: List[str] = []
    repo_site: Optional[tuple] = None
    f = frame
    while f is not None:
        rel = _san._relpath(f.f_code.co_filename)
        if repo_site is None and not rel.startswith("/") and "site-packages" not in rel:
            repo_site = (rel, f.f_lineno, f.f_code.co_name)
        stack.append(f"{rel}:{f.f_lineno} in {f.f_code.co_name}")
        f = f.f_back
    head = " <- ".join(stack[:4])
    return head, repo_site


class LoopStallMonitor:
    """Watchdog for ONE event loop; see module docstring."""

    def __init__(
        self,
        loop: asyncio.AbstractEventLoop,
        stall_ms: float,
        poll_ms: float = 0.0,
    ) -> None:
        self.loop = loop
        self.stall_s = max(stall_ms, 1.0) / 1000.0
        self.poll_s = (
            poll_ms / 1000.0 if poll_ms > 0 else max(self.stall_s / 4, _MIN_BEAT_S)
        )
        self.beat_s = max(self.stall_s / 4, _MIN_BEAT_S)
        self._last_beat = time.monotonic()
        self._loop_ident: Optional[int] = None
        self._alive = False
        self._fired = False
        self._thread: Optional[threading.Thread] = None
        self.stalls = 0  # episodes observed (tests read this)

    # ---- loop side ------------------------------------------------------
    def _beat(self) -> None:
        self._loop_ident = threading.get_ident()
        self._last_beat = time.monotonic()
        if self._alive:
            self.loop.call_later(self.beat_s, self._beat)

    # ---- sampler side ---------------------------------------------------
    def _sample(self) -> None:
        while self._alive:
            time.sleep(self.poll_s)
            lag = time.monotonic() - self._last_beat
            if lag <= self.stall_s:
                self._fired = False
                continue
            if self._fired or self._loop_ident is None:
                continue
            self._fired = True
            self.stalls += 1
            frame = sys._current_frames().get(self._loop_ident)
            if frame is None:
                continue
            head, repo_site = _attribute(frame)
            path, line = ("<loop>", 0)
            where = ""
            if repo_site is not None:
                path, line = repo_site[0], repo_site[1]
                where = f" in {repo_site[2]}()"
            _san.get_sanitizer().record(
                "DS001",
                f"event loop blocked > {self.stall_s * 1000:.0f} ms"
                f"{where}; loop-thread stack: {head}",
                path, line,
            )

    # ---- lifecycle ------------------------------------------------------
    def start(self) -> None:
        self._alive = True
        self._last_beat = time.monotonic()
        self.loop.call_soon_threadsafe(self._beat)
        self._thread = threading.Thread(
            target=self._sample, name="dsan-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._alive = False
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


def install(loop: asyncio.AbstractEventLoop) -> Optional[LoopStallMonitor]:
    """Start a stall monitor for ``loop`` when dsan is active (settings
    supply the thresholds); returns None — a no-op — otherwise."""
    if not _san.san_enabled():
        return None
    from dnet_tpu.config import get_settings

    san = get_settings().san
    mon = LoopStallMonitor(loop, san.san_stall_ms, san.san_poll_ms)
    mon.start()
    return mon
