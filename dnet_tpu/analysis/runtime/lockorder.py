"""dsan lock-order tracking: instrumented locks, an acquisition-order
graph, and cycle detection for potential-deadlock findings (DS004).

Every :class:`SanLock` acquisition while OTHER SanLocks are held adds a
directed edge ``held -> acquired`` (annotated with the acquisition site)
to a process-global graph.  Two threads that take ``A then B`` and ``B
then A`` — even if they never actually collide in a run — produce the
cycle ``A -> B -> A`` at audit time, which is exactly the latent deadlock
a loaded serving process would eventually hit.

Lock identity is the declared NAME (``LocalAdapter._buf_lock``), not the
instance: the discipline under test is class-level ("pool lock before
prefix lock, never the reverse"), and instance-keyed edges would miss an
inversion across two different adapters.  Self-edges (re-acquiring the
same name, e.g. two pool instances) are recorded separately as they are
legal for distinct instances but still worth surfacing in the audit when
the same INSTANCE re-enters (threading.Lock is not reentrant — that is an
immediate hang, caught live, not at audit).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from dnet_tpu.analysis.runtime import sanitizer as _san

_tls = threading.local()


class LockOrderGraph:
    """Directed name->name acquisition edges with first-seen sites."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        #: (held_name, acquired_name) -> (path, line) of first observation
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    def add(self, held: str, acquired: str, site: Tuple[str, int]) -> None:
        if held == acquired:
            return  # distinct instances of one class: legal, not an order
        key = (held, acquired)
        with self._lock:
            self.edges.setdefault(key, site)

    def clear(self) -> None:
        with self._lock:
            self.edges.clear()

    def cycles(self) -> List[List[str]]:
        """Every elementary cycle's node list (deduped by node set),
        deterministic order.  Graphs here are tiny (a dozen named locks),
        so plain DFS is plenty."""
        with self._lock:
            adj: Dict[str, List[str]] = {}
            for a, b in sorted(self.edges):
                adj.setdefault(a, []).append(b)
        seen_sets: set = set()
        out: List[List[str]] = []

        def dfs(start: str, node: str, path: List[str]) -> None:
            for nxt in adj.get(node, ()):
                if nxt == start:
                    key = frozenset(path)
                    if key not in seen_sets:
                        seen_sets.add(key)
                        out.append(path + [start])
                elif nxt not in path and nxt > start:
                    # only walk nodes > start: each cycle is found exactly
                    # once, rooted at its smallest node
                    dfs(start, nxt, path + [nxt])

        for start in sorted(adj):
            dfs(start, start, [start])
        return out


_graph = LockOrderGraph()


def get_graph() -> LockOrderGraph:
    return _graph


def _held_stack() -> list:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


class SanLock:
    """A ``threading.Lock`` wrapper that records ownership (for
    guarded-by domain checks) and acquisition order (for DS004).

    Supports the full ``with`` protocol plus ``acquire``/``release``/
    ``locked`` so it drops into any attribute that held a plain Lock.
    Only constructed when dsan is active — the plain lock stays in place
    otherwise (see :func:`dnet_tpu.analysis.runtime.ownership.san_lock`).
    """

    __slots__ = ("_inner", "name", "_owner")

    def __init__(self, name: str, inner: Optional[threading.Lock] = None) -> None:
        self._inner = inner if inner is not None else threading.Lock()
        self.name = name
        self._owner: Optional[int] = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        if self._owner == me:
            # non-reentrant lock re-entered by its owner: record the
            # finding BEFORE blocking forever (the block itself would
            # otherwise be the only diagnostic)
            path, line = _san.caller_site()
            _san.get_sanitizer().record(
                "DS004",
                f"lock {self.name} re-acquired by its owning thread "
                f"(threading.Lock is not reentrant: this deadlocks)",
                path, line,
            )
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._owner = me
            if _san.san_enabled() and not _san.get_sanitizer().recording():
                site = _san.caller_site()
                stack = _held_stack()
                for held in stack:
                    _graph.add(held.name, self.name, site)
                stack.append(self)
        return got

    def release(self) -> None:
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        self._owner = None
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    @property
    def inner(self) -> threading.Lock:
        """The wrapped plain lock (deinstrumentation restores it)."""
        return self._inner

    def held_by_current_thread(self) -> bool:
        return self._owner == threading.get_ident()

    def __enter__(self) -> "SanLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        state = "locked" if self.locked() else "unlocked"
        return f"<SanLock {self.name} {state}>"


def audit_lock_order() -> int:
    """Run cycle detection over the recorded graph and record one DS004
    finding per distinct cycle.  Returns how many cycles were found."""
    cycles = _graph.cycles()
    san = _san.get_sanitizer()
    with _graph._lock:
        edges = dict(_graph.edges)
    for cyc in cycles:
        legs = " -> ".join(cyc)
        # attribute to the first recorded edge site of the cycle
        path, line = "", 0
        for a, b in zip(cyc, cyc[1:]):
            if (a, b) in edges:
                path, line = edges[(a, b)]
                break
        sites = "; ".join(
            f"{a}->{b} at {edges[(a, b)][0]}:{edges[(a, b)][1]}"
            for a, b in zip(cyc, cyc[1:]) if (a, b) in edges
        )
        san.record(
            "DS004",
            f"lock-order cycle {legs} (potential deadlock; {sites})",
            path or "<lockorder>", line,
        )
    return len(cycles)


def reset_lock_order() -> None:
    _graph.clear()
