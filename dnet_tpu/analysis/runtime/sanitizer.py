"""dsan core: the enabled gate, the process-global finding sink, and the
report plumbing that merges runtime findings into the dnetlint record.

The sanitizer reuses the PR 8 static-analysis :class:`Finding` model
(path, line, col, code, message, severity) with runtime ``DS00x`` codes
(catalog in :mod:`dnet_tpu.analysis.runtime.domains`), so runtime and
static findings sort, render, and serialize identically and land in the
same ``ANALYSIS_r<NN>.json`` records.

Gating contract: every hook in this package is constructed/installed only
when :func:`san_enabled` is true at that moment (``DNET_SAN=1``, read via
``config.env_flag`` so post-cache flips in tests work), and every check
path ALSO early-returns when the flag is off — a wrapper that outlives a
test's enable window goes quiet instead of misfiring.  With ``DNET_SAN``
unset nothing is wrapped at all: guards return their argument unchanged,
``san_lock`` returns the plain lock, and the serving path runs the exact
objects it runs today (asserted by the no-op test in
tests/subsystems/test_dsan.py).
"""

from __future__ import annotations

import json
import os
import threading
from pathlib import Path
from typing import List, Optional, Tuple

from dnet_tpu.analysis.core import SEVERITY_ERROR, Finding
from dnet_tpu.analysis.runtime.domains import RUNTIME_CHECK_CODES, RUNTIME_CHECKS

_REPO_ROOT = Path(__file__).resolve().parents[3]
_PKG_DIR = os.path.dirname(os.path.abspath(__file__))

#: default persist target for :func:`persist_findings` (repo root);
#: ``DNET_SAN_REPORT`` (SanSettings) overrides.
DEFAULT_REPORT_NAME = ".dsan-findings.json"


def san_enabled() -> bool:
    """The one dsan gate: ``DNET_SAN=1`` in the process environment.  Read
    through ``config.env_flag`` (the sanctioned DL006 escape hatch) so a
    test that flips the env after the settings cache warmed still gates."""
    from dnet_tpu.config import env_flag

    return env_flag("DNET_SAN")


def caller_site(skip_prefixes: Tuple[str, ...] = ()) -> Tuple[str, int]:
    """(repo-relative path, line) of the innermost caller frame OUTSIDE
    this package — the instrumentation site a finding attributes to."""
    import sys

    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.startswith(_PKG_DIR) and not any(
            fn.startswith(p) for p in skip_prefixes
        ):
            return _relpath(fn), f.f_lineno
        f = f.f_back
    return "<unknown>", 0


def _relpath(filename: str) -> str:
    try:
        return Path(filename).resolve().relative_to(_REPO_ROOT).as_posix()
    except ValueError:
        return filename


class Sanitizer:
    """Thread-safe finding sink shared by every dsan detector.

    Findings dedupe on (code, path, line, message) — a hot loop that
    violates its domain ten thousand times per second produces ONE
    finding — and each recorded finding increments
    ``dnet_san_findings_total{check=<code>}``.  A thread-local
    re-entrancy latch suppresses checks fired BY the recording itself
    (counting a finding touches the instrumented metrics registry)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._findings: List[Finding] = []
        self._seen: set = set()
        self._tls = threading.local()

    # ---- recording ------------------------------------------------------
    def recording(self) -> bool:
        return getattr(self._tls, "busy", False)

    def record(
        self, code: str, message: str, path: str = "", line: int = 0
    ) -> Optional[Finding]:
        """Record one runtime finding; returns it, or None when deduped."""
        if code not in RUNTIME_CHECK_CODES:
            raise ValueError(f"unknown dsan check code {code!r}")
        if not path:
            path, line = caller_site()
        key = (code, path, line, message)
        with self._lock:
            if key in self._seen:
                return None
            self._seen.add(key)
            finding = Finding(
                path=path, line=line, col=0, code=code,
                message=message, severity=SEVERITY_ERROR,
            )
            self._findings.append(finding)
        self._tls.busy = True
        try:
            from dnet_tpu.obs import metric

            metric("dnet_san_findings_total").labels(check=code).inc()
        except Exception:
            pass  # obs unavailable (bare script): the finding still counts
        finally:
            self._tls.busy = False
        return finding

    # ---- inspection -----------------------------------------------------
    @property
    def findings(self) -> List[Finding]:
        with self._lock:
            return sorted(self._findings)

    def findings_for(self, code: str) -> List[Finding]:
        return [f for f in self.findings if f.code == code]

    def clear(self) -> None:
        with self._lock:
            self._findings.clear()
            self._seen.clear()

    # ---- persistence ----------------------------------------------------
    def persist(self, path: Path) -> None:
        """Append-merge findings into a JSON file (sanitized runs persist;
        ``dnetlint --json`` folds the file into the ANALYSIS record)."""
        existing: List[dict] = []
        if path.is_file():
            try:
                existing = json.loads(path.read_text()).get("findings", [])
            except (ValueError, OSError):
                existing = []
        merged = {json.dumps(e, sort_keys=True) for e in existing}
        for f in self.findings:
            merged.add(json.dumps(f.to_json(), sort_keys=True))
        path.write_text(json.dumps(
            {"tool": "dsan",
             "findings": [json.loads(m) for m in sorted(merged)]},
            indent=2, sort_keys=True,
        ) + "\n")


_sanitizer = Sanitizer()


def default_report_path() -> Path:
    """Where a sanitized run persists findings: ``DNET_SAN_REPORT`` when
    set, else the repo root — the same place ``runtime_section``/dnetlint
    merge from, so findings survive a server started from any cwd."""
    from dnet_tpu.config import get_settings

    configured = get_settings().san.san_report
    return Path(configured) if configured else _REPO_ROOT / DEFAULT_REPORT_NAME


def get_sanitizer() -> Sanitizer:
    return _sanitizer


def reset_sanitizer() -> None:
    """Drop findings and dedup state (tests).  The sink object itself is
    stable so detector handles never go stale — mirrors reset_obs()."""
    _sanitizer.clear()


def runtime_section(root: Path, report_path: Optional[Path] = None) -> dict:
    """The ``runtime`` section of an ANALYSIS record: the DS check catalog
    plus any findings a sanitized run persisted (empty when none ran —
    the section is always present so dashboards can rely on its shape)."""
    src: Optional[Path] = report_path
    if src is None:
        from dnet_tpu.config import get_settings

        configured = get_settings().san.san_report
        src = Path(configured) if configured else root / DEFAULT_REPORT_NAME
    findings: List[dict] = []
    source = None
    if src.is_file():
        try:
            findings = json.loads(src.read_text()).get("findings", [])
            source = str(src)
        except (ValueError, OSError):
            findings = []
    return {
        "tool": "dsan",
        "enabled_env": "DNET_SAN",
        "checks": [
            {"code": c, "name": n, "description": d}
            for c, n, d in RUNTIME_CHECKS
        ],
        "findings": findings,
        "source": source,
    }
