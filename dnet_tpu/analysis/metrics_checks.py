"""Metric/observability contract passes (DL010+), folded in from
``scripts/check_metrics_names.py`` (which remains as a thin CLI shim).

These are *runtime* checks (``requires_runtime = True``): they import the
live registry, exercise the paged-KV pool, and round-trip the federation
path — so they run from the full-repo suite (CLI and tier-1 wrapper), not
over synthetic fixture projects.

Pass catalog (the original scripts/check_metrics_names.py passes 1-8):

- DL010 registry      — every registered family name matches
  ``dnet_[a-z0-9_]+`` and carries a help string
- DL011 source-scan   — literal ``counter(/gauge(/histogram(`` calls in the
  tree conform even when registered lazily
- DL012 federation    — two-node relabel/merge round trip re-parses, one
  ``node`` label per sample, required families present
- DL013 paged-pool    — alloc/share/COW/release script keeps the block
  books balanced and the gauges honest
- DL014 chaos-points  — chaos injection points <-> pre-touched series, both
  directions
- DL015 admission     — reject-reason / deadline-stage labels <-> declared
  enums, both directions
- DL016 membership    — stale-epoch kinds / recovery outcomes <-> declared
  enums, both directions
- DL017 attribution   — step phases / jit fns / device-mem kinds <->
  declared enums, both directions
- DL018 sanitizer     — dsan check codes / zombie-thread kinds <->
  declared enums, both directions (pass 9)
- DL019 scheduler     — sched queue states / batch kinds / preemption
  reasons <-> declared enums, both directions (pass 10)
- DL020 jit-coverage  — instrument_jit call-site name literals <->
  obs/phases.py JIT_FNS, both directions (pass 11): a new jitted entry
  point cannot ship uninstrumented under a stray label, and a declared
  name cannot outlive its last call site (a stale series on the compile
  dashboards)
- DL026 wire          — wire-pipeline dir labels <-> obs/phases.py
  WIRE_DIRS both directions + the dnet_wire_* families required
  (pass 12; DL021-DL025 are the flow-sensitive tier, analysis/flow/)
- the TP collective op labels cross-checked against obs/phases.py TP_OPS
  both directions + the dnet_tp_* families required (pass 13)
- DL028 critical-path — request-segment labels <-> obs/phases.py
  REQUEST_SEGMENTS both directions, the attribution map + trace track
  routing (obs/critical_path.py, obs/trace.py) consistent with the
  declared segment enum, and the segment-histogram + tick-record
  families required (pass 14)
- DL030 events        — wide-event name labels <-> obs/phases.py
  EVENT_NAMES both directions (pass 15; DL029 is the static
  logging-hygiene check, checks_logging.py)
"""

from __future__ import annotations

import re
import sys
from pathlib import Path
from typing import Iterable

from dnet_tpu.analysis.core import Check, Finding, Project

REPO = Path(__file__).resolve().parents[2]
if str(REPO) not in sys.path:  # runnable via the scripts/ shim
    sys.path.insert(0, str(REPO))

# metric-registration calls with a literal name; help must be the next
# argument and a non-empty string literal
_CALL_RE = re.compile(
    r"""\.\s*(counter|gauge|histogram)\(\s*
        (?P<q>['"])(?P<name>[^'"]+)(?P=q)\s*,\s*
        (?P<rest>.{0,120})""",
    re.VERBOSE | re.DOTALL,
)
_HELP_RE = re.compile(r"""^(?P<q>['"])(?P<help>[^'"]*)""")

_SCAN_DIRS = ("dnet_tpu", "scripts")
_SCAN_FILES = ("bench.py",)


def _check_name(name: str, where: str, errors: list) -> None:
    from dnet_tpu.obs import METRIC_NAME_RE

    if not METRIC_NAME_RE.match(name):
        errors.append(
            f"{where}: metric name {name!r} does not match "
            f"{METRIC_NAME_RE.pattern}"
        )


def check_registry(errors: list) -> int:
    from dnet_tpu.obs import get_registry

    fams = get_registry().families()
    for name, fam in fams.items():
        _check_name(name, "registry", errors)
        if not fam.help.strip():
            errors.append(f"registry: metric {name} has an empty help string")
    return len(fams)


def _scan_paths() -> list:
    """The source tree both literal-scanning passes walk: the standalone
    entry points plus every .py under the scanned dirs — ONE definition,
    so the passes can never silently diverge on the file set."""
    files = [REPO / f for f in _SCAN_FILES]
    for d in _SCAN_DIRS:
        files.extend(sorted((REPO / d).rglob("*.py")))
    return files


def check_sources(errors: list) -> int:
    n = 0
    for path in _scan_paths():
        if not path.is_file():
            continue
        text = path.read_text()
        for m in _CALL_RE.finditer(text):
            name = m.group("name")
            if not name.startswith("dnet_"):
                continue  # not one of ours (e.g. a generic helper call)
            n += 1
            where = f"{path.relative_to(REPO)}"
            _check_name(name, where, errors)
            hm = _HELP_RE.match(m.group("rest").lstrip())
            if hm is None or not hm.group("help").strip():
                errors.append(
                    f"{where}: metric {name} registered without a literal "
                    f"non-empty help string"
                )
    return n


# families the cluster observability surface registers; their absence means
# a refactor silently dropped a series dashboards/alerts depend on
_REQUIRED_FAMILIES = (
    "dnet_slo_ttft_p95_ms",
    "dnet_slo_decode_p95_ms",
    "dnet_slo_availability",
    "dnet_slo_burning",
    "dnet_prefix_refill_total",
    "dnet_federation_scrape_ok",
    # paged KV pool (dnet_tpu/kv/) — capacity dashboards and the
    # backpressure alert depend on these
    "dnet_kv_blocks_used",
    "dnet_kv_blocks_free",
    "dnet_kv_pool_blocks",
    "dnet_kv_cow_copies_total",
    "dnet_kv_prefix_shared_blocks_total",
    "dnet_kv_admission_rejected_total",
    # resilience (dnet_tpu/resilience/) — the retry/resume dashboards and
    # the chaos-coverage lint (pass 5) depend on these
    "dnet_rpc_retries_total",
    "dnet_stream_reopens_total",
    "dnet_request_resumed_total",
    "dnet_resume_replay_tokens_total",
    "dnet_chaos_injected_total",
    # admission / overload survival (dnet_tpu/admission/) — the shed-rate
    # alert, drain runbook, and the label cross-check (pass 6) depend on
    # these
    "dnet_admit_queue_depth",
    "dnet_admit_inflight",
    "dnet_admit_admitted_total",
    "dnet_admit_wait_ms",
    "dnet_admit_rejected_total",
    "dnet_deadline_exceeded_total",
    "dnet_cancel_propagated_total",
    "dnet_drain_state",
    "dnet_shard_outq_dropped_total",
    # elastic ring membership (dnet_tpu/membership/) — the epoch-fence
    # dashboards, recovery alert, and the label cross-check (pass 7)
    # depend on these
    "dnet_topology_epoch",
    "dnet_stale_epoch_rejected_total",
    "dnet_recovery_total",
    "dnet_recovery_duration_seconds",
    "dnet_shard_rejoins_total",
    # performance attribution (obs/phases.py, obs/jit.py) — the loadgen
    # report's phase/JIT/memory sections and the p99 cross-check (pass 8)
    # depend on these
    "dnet_step_phase_ms",
    "dnet_jit_compiles_total",
    "dnet_jit_compile_ms",
    "dnet_device_mem_bytes",
    "dnet_slo_ttft_p99_ms",
    "dnet_slo_decode_p99_ms",
    # runtime sanitizer (dnet_tpu/analysis/runtime/) — the dsan findings
    # dashboard and the zombie-thread alert (pass 9) depend on these
    "dnet_san_findings_total",
    "dnet_san_zombie_threads_total",
    # iteration-level scheduler (dnet_tpu/sched/) — the tick/composition
    # dashboards and the label cross-check (pass 10) depend on these
    "dnet_sched_tick_ms",
    "dnet_sched_batch_tokens",
    "dnet_sched_preemptions_total",
    "dnet_sched_queue_depth",
    # overlapped wire pipeline (transport/wire_pipeline.py) — the per-hop
    # codec dashboards, the overlap gauge the BENCH_SERVE reports embed,
    # and the label cross-check (pass 12) depend on these
    "dnet_wire_encode_ms",
    "dnet_wire_decode_ms",
    "dnet_wire_bytes_total",
    "dnet_wire_overlap_ratio",
    # critical-path attribution + scheduler tick flight recorder
    # (obs/critical_path.py, sched/flight.py) — the per-request segment
    # ledgers, /v1/debug/sched, and the label cross-check (pass 14)
    # depend on these
    "dnet_request_segment_ms",
    "dnet_sched_tick_records_total",
    "dnet_sched_tick_budget_used_ratio",
    # structured wide events (obs/events.py) — the event-rate dashboards
    # and the vocabulary cross-check (pass 15) depend on this
    "dnet_events_total",
    # fleet routing (dnet_tpu/fleet/) — the per-replica traffic/failover
    # dashboards and the label cross-check (pass 16) depend on these
    "dnet_fleet_requests_total",
    "dnet_fleet_routed_total",
    "dnet_fleet_affinity_hits_total",
    "dnet_fleet_failovers_total",
    "dnet_fleet_replicas",
)


def check_federation(errors: list) -> int:
    """Pass 3: federate the live exposition with itself under two node ids
    and re-validate the merged document sample by sample."""
    from dnet_tpu.obs import get_registry
    from dnet_tpu.obs.federation import _SAMPLE_RE, _family_of, federate

    fams = get_registry().families()
    for req in _REQUIRED_FAMILIES:
        if req not in fams:
            errors.append(f"federation: required family {req} not registered")
    text = get_registry().expose()
    merged, skipped = federate([("api", text), ("shard-0", text)])
    for line in skipped:
        errors.append(f"federation: dropped unparseable line {line!r}")
    n = 0
    typed: set = set()
    for line in merged.splitlines():
        if line.startswith("# TYPE "):
            name = line.split()[2]
            if name in typed:
                errors.append(f"federation: duplicate TYPE for {name}")
            typed.add(name)
            continue
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            errors.append(f"federation: emitted unparseable sample {line!r}")
            continue
        n += 1
        _check_name(_family_of(m.group("name")), "federation", errors)
        if line.count('node="') != 1:
            errors.append(
                f"federation: sample must carry exactly one node label: "
                f"{line!r}"
            )
    return n


def check_paged_conservation(errors: list) -> int:
    """Pass 4: exercise the paged KV pool through an alloc / share / COW /
    table-release / prefix-eviction script and assert the books balance at
    every step — used + free == pool (shared blocks counted once), the
    free list stays duplicate-free and disjoint, refcounts match holders,
    and the gauges report exactly what the pool says."""
    from dnet_tpu.kv import BlockPool, KVPoolExhausted, PagedKVConfig, PageTable
    from dnet_tpu.obs import metric

    pool = BlockPool(PagedKVConfig(block_tokens=8, pool_blocks=12))
    steps = 0

    def audit(holders):
        nonlocal steps
        steps += 1
        try:
            pool.check_conservation(holders)
        except AssertionError as exc:
            errors.append(f"paged-conservation step {steps}: {exc}")
            return
        used = metric("dnet_kv_blocks_used").value
        free = metric("dnet_kv_blocks_free").value
        if (used, free) != (pool.used, pool.free):
            errors.append(
                f"paged-conservation step {steps}: gauges ({used}, {free}) "
                f"!= pool ({pool.used}, {pool.free})"
            )

    t1, t2 = PageTable(), PageTable()
    entry = pool.alloc(2)  # a prefix entry's blocks
    audit([entry])
    pool.ensure(t1, 20)  # 3 blocks
    audit([entry, t1.blocks])
    t2.blocks.extend(pool.share(entry))  # adoption aliases the entry
    pool.ensure(t2, 30)  # grows past the shared run
    audit([entry, t1.blocks, entry, t2.blocks[2:]])
    old = t2.blocks[1]
    t2.blocks[1] = pool.cow(old)  # diverge mid-run
    audit([entry, t1.blocks, [entry[0]], t2.blocks[1:]])
    try:
        pool.alloc(pool.free + 1)
        errors.append("paged-conservation: overdraw did not raise")
    except KVPoolExhausted:
        pass
    audit([entry, t1.blocks, [entry[0]], t2.blocks[1:]])
    pool.release_table(t1)
    pool.release_table(t2)
    pool.free_blocks(entry)  # prefix eviction
    audit([])
    if pool.used != 0 or pool.free != pool.total:
        errors.append(
            f"paged-conservation: end state leaks ({pool.used} used, "
            f"{pool.free}/{pool.total} free)"
        )
    return steps


def check_chaos_points(errors: list) -> int:
    """Pass 5: every chaos injection point declared in
    dnet_tpu/resilience/chaos.py must have a pre-touched
    dnet_chaos_injected_total{point=} series — a new point cannot ship
    without its observability, and a renamed point cannot strand a stale
    label."""
    from dnet_tpu.obs import get_registry
    from dnet_tpu.resilience.chaos import INJECTION_POINTS

    text = get_registry().expose()
    n = 0
    for point in INJECTION_POINTS:
        n += 1
        if f'dnet_chaos_injected_total{{point="{point}"}}' not in text:
            errors.append(
                f"chaos: injection point {point!r} has no "
                f"dnet_chaos_injected_total label (pre-touch it in "
                f"dnet_tpu.obs._register_core)"
            )
    # reverse direction: no exposed point label without a declaration
    for m in re.finditer(
        r'dnet_chaos_injected_total\{point="([^"]+)"\}', text
    ):
        if m.group(1) not in INJECTION_POINTS:
            errors.append(
                f"chaos: exposed point label {m.group(1)!r} is not declared "
                f"in chaos.INJECTION_POINTS"
            )
    return n


def check_chaos_kinds(errors: list) -> int:
    """Pass 5b: grammar self-test — every declared chaos KIND must parse
    at every declared point (a kind added to the docs/campaign without a
    parser, or a parser branch dropped in a refactor, fails here, not in
    the middle of a chaos campaign)."""
    from dnet_tpu.resilience.chaos import INJECTION_POINTS, KINDS, ChaosInjector

    sample = {
        "error": "0.5", "error_at": "3+5", "delay": "10ms", "partition": "2+3",
    }
    n = 0
    for kind in KINDS:
        n += 1
        if kind not in sample:
            errors.append(
                f"chaos: kind {kind!r} has no grammar self-test sample "
                f"(add one to check_chaos_kinds)"
            )
            continue
        for point in INJECTION_POINTS:
            try:
                ChaosInjector(f"{point}:{kind}:{sample[kind]}", seed=1)
            except ValueError as exc:
                errors.append(
                    f"chaos: declared kind {kind!r} fails to parse at "
                    f"point {point!r}: {exc}"
                )
    return n


def _cross_check_labels(
    errors: list, text: str, family: str, label: str, declared, where: str
) -> int:
    """Exposed `family{label=...}` series must match `declared` EXACTLY in
    both directions: every declared value pre-touched, no stray label."""
    n = 0
    scope = where.split(".", 1)[0]
    for value in declared:
        n += 1
        if f'{family}{{{label}="{value}"}}' not in text:
            errors.append(
                f"{scope}: {where} value {value!r} has no {family} "
                f"series (pre-touch it in dnet_tpu.obs._register_core)"
            )
    for m in re.finditer(rf'{family}\{{{label}="([^"]+)"\}}', text):
        if m.group(1) not in declared:
            errors.append(
                f"{scope}: exposed {family} {label} label "
                f"{m.group(1)!r} is not declared in {where}"
            )
    return n


def check_admission_labels(errors: list) -> int:
    """Pass 6: the admission surface's labeled families must agree with
    the declared enums (dnet_tpu/admission/reasons.py) both ways — a new
    reject reason or deadline stage cannot ship without its series, and a
    renamed one cannot strand a stale label on dashboards."""
    from dnet_tpu.admission.reasons import DEADLINE_STAGES, REJECT_REASONS
    from dnet_tpu.obs import get_registry

    text = get_registry().expose()
    n = _cross_check_labels(
        errors, text, "dnet_admit_rejected_total", "reason",
        REJECT_REASONS, "admission.reasons.REJECT_REASONS",
    )
    n += _cross_check_labels(
        errors, text, "dnet_deadline_exceeded_total", "stage",
        DEADLINE_STAGES, "admission.reasons.DEADLINE_STAGES",
    )
    return n


def check_membership_labels(errors: list) -> int:
    """Pass 7: the membership surface's labeled families must agree with
    the declared enums (dnet_tpu/membership/epoch.py) both ways — a new
    stale-epoch kind or recovery outcome cannot ship without its series,
    and a renamed one cannot strand a stale label on dashboards.  Same
    pattern as passes 5-6."""
    from dnet_tpu.membership.epoch import RECOVERY_OUTCOMES, STALE_EPOCH_KINDS
    from dnet_tpu.obs import get_registry

    text = get_registry().expose()
    n = _cross_check_labels(
        errors, text, "dnet_stale_epoch_rejected_total", "kind",
        STALE_EPOCH_KINDS, "membership.epoch.STALE_EPOCH_KINDS",
    )
    n += _cross_check_labels(
        errors, text, "dnet_recovery_total", "outcome",
        RECOVERY_OUTCOMES, "membership.epoch.RECOVERY_OUTCOMES",
    )
    return n


def check_attribution_labels(errors: list) -> int:
    """Pass 8: the performance-attribution families must agree with the
    declared enums (dnet_tpu/obs/phases.py) both ways.  Histogram families
    expose per-label `_bucket`/`_sum`/`_count` series, so presence is
    checked on `_count` and strays on any exposition suffix."""
    from dnet_tpu.obs import get_registry
    from dnet_tpu.obs.phases import DEVICE_MEM_KINDS, JIT_FNS, STEP_PHASES

    text = get_registry().expose()
    n = 0
    for phase in STEP_PHASES:
        n += 1
        if f'dnet_step_phase_ms_count{{phase="{phase}"}}' not in text:
            errors.append(
                f"attribution: obs.phases.STEP_PHASES value {phase!r} has "
                f"no dnet_step_phase_ms series (pre-touch it in "
                f"dnet_tpu.obs._register_core)"
            )
    for m in re.finditer(
        r'dnet_step_phase_ms(?:_bucket|_sum|_count)\{phase="([^"]+)"', text
    ):
        if m.group(1) not in STEP_PHASES:
            errors.append(
                f"attribution: exposed dnet_step_phase_ms phase label "
                f"{m.group(1)!r} is not declared in obs.phases.STEP_PHASES"
            )
    n += _cross_check_labels(
        errors, text, "dnet_jit_compiles_total", "fn",
        JIT_FNS, "obs.phases.JIT_FNS",
    )
    n += _cross_check_labels(
        errors, text, "dnet_device_mem_bytes", "kind",
        DEVICE_MEM_KINDS, "obs.phases.DEVICE_MEM_KINDS",
    )
    return n


def check_san_labels(errors: list) -> int:
    """Pass 9: the runtime sanitizer's labeled families must agree with
    the declared enums (dnet_tpu/analysis/runtime/domains.py) both ways —
    a new DS check or zombie-able worker thread cannot ship without its
    series, and a renamed one cannot strand a stale label.  Same pattern
    as passes 5-8."""
    from dnet_tpu.analysis.runtime.domains import (
        RUNTIME_CHECK_CODES,
        ZOMBIE_THREAD_KINDS,
    )
    from dnet_tpu.obs import get_registry

    text = get_registry().expose()
    n = _cross_check_labels(
        errors, text, "dnet_san_findings_total", "check",
        RUNTIME_CHECK_CODES, "analysis.runtime.domains.RUNTIME_CHECK_CODES",
    )
    n += _cross_check_labels(
        errors, text, "dnet_san_zombie_threads_total", "thread",
        ZOMBIE_THREAD_KINDS, "analysis.runtime.domains.ZOMBIE_THREAD_KINDS",
    )
    return n


def check_sched_labels(errors: list) -> int:
    """Pass 10: the scheduler's labeled families must agree with the
    declared enums (dnet_tpu/sched/kinds.py) both ways — a new queue
    state, batch kind, or preemption reason cannot ship without its
    series, and a renamed one cannot strand a stale label.  The
    histogram family is checked on its exposition suffixes, like the
    attribution pass."""
    from dnet_tpu.obs import get_registry
    from dnet_tpu.sched.kinds import BATCH_KINDS, PREEMPT_REASONS, QUEUE_STATES

    text = get_registry().expose()
    n = 0
    for kind in BATCH_KINDS:
        n += 1
        if f'dnet_sched_batch_tokens_count{{kind="{kind}"}}' not in text:
            errors.append(
                f"sched: sched.kinds.BATCH_KINDS value {kind!r} has no "
                f"dnet_sched_batch_tokens series (pre-touch it in "
                f"dnet_tpu.obs._register_core)"
            )
    for m in re.finditer(
        r'dnet_sched_batch_tokens(?:_bucket|_sum|_count)\{kind="([^"]+)"',
        text,
    ):
        if m.group(1) not in BATCH_KINDS:
            errors.append(
                f"sched: exposed dnet_sched_batch_tokens kind label "
                f"{m.group(1)!r} is not declared in sched.kinds.BATCH_KINDS"
            )
    n += _cross_check_labels(
        errors, text, "dnet_sched_preemptions_total", "reason",
        PREEMPT_REASONS, "sched.kinds.PREEMPT_REASONS",
    )
    n += _cross_check_labels(
        errors, text, "dnet_sched_queue_depth", "state",
        QUEUE_STATES, "sched.kinds.QUEUE_STATES",
    )
    return n


def check_jit_instrumentation(errors: list) -> int:
    """Pass 11: every `instrument_jit(..., "name")` call site in the tree
    must use a name declared in obs/phases.py JIT_FNS, and every declared
    name must have at least one call site — both directions, resolved by
    AST so nested jax.jit(...) argument parens can't confuse a regex.  A
    non-literal name argument is itself a violation (the contract is
    lintable only over literals)."""
    import ast

    from dnet_tpu.obs.phases import JIT_FNS

    seen: dict = {}
    n = 0
    for path in _scan_paths():
        if not path.is_file():
            continue
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError as exc:
            errors.append(f"jit-coverage: {path.relative_to(REPO)} "
                          f"unparseable: {exc}")
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else getattr(fn, "id", "")
            if name != "instrument_jit":
                continue
            where = f"{path.relative_to(REPO)}:{node.lineno}"
            n += 1
            label = node.args[1] if len(node.args) > 1 else None
            if label is None:
                for kw in node.keywords:
                    if kw.arg == "name":
                        label = kw.value
            if not (isinstance(label, ast.Constant) and isinstance(label.value, str)):
                errors.append(
                    f"jit-coverage: {where} passes a non-literal jit fn "
                    f"name (the JIT_FNS contract is checked over literals)"
                )
                continue
            seen.setdefault(label.value, []).append(where)
            if label.value not in JIT_FNS:
                errors.append(
                    f"jit-coverage: {where} instruments undeclared jit fn "
                    f"{label.value!r} (declare it in obs.phases.JIT_FNS)"
                )
    for declared in JIT_FNS:
        if declared not in seen:
            errors.append(
                f"jit-coverage: obs.phases.JIT_FNS declares {declared!r} "
                f"but no instrument_jit call site uses it (remove the "
                f"stale label or restore its entry point)"
            )
    return n


def check_wire_labels(errors: list) -> int:
    """Pass 12: the wire pipeline's labeled family must agree with the
    declared dir enum (dnet_tpu/obs/phases.py WIRE_DIRS) both ways, and
    the dnet_wire_* families must exist — a renamed direction cannot
    strand a stale label, and a refactor cannot silently drop the series
    the BENCH_SERVE wire meta and overlap dashboards read."""
    from dnet_tpu.obs import get_registry
    from dnet_tpu.obs.phases import WIRE_DIRS

    text = get_registry().expose()
    n = _cross_check_labels(
        errors, text, "dnet_wire_bytes_total", "dir",
        WIRE_DIRS, "obs.phases.WIRE_DIRS",
    )
    fams = get_registry().families()
    for req in ("dnet_wire_encode_ms", "dnet_wire_decode_ms",
                "dnet_wire_overlap_ratio"):
        n += 1
        if req not in fams:
            errors.append(f"wire: required family {req} not registered")
    return n


def check_tp_labels(errors: list) -> int:
    """Pass 13: the TP collective families must agree with the declared
    op enum (dnet_tpu/obs/phases.py TP_OPS) both ways — a renamed or new
    collective op cannot strand a stale label or ship without its series
    — and the dnet_tp_* families the TP parity tests and BENCH_SERVE
    meta.tp read must exist."""
    from dnet_tpu.obs import get_registry
    from dnet_tpu.obs.phases import TP_OPS

    text = get_registry().expose()
    n = 0
    for op in TP_OPS:  # histogram children expose _bucket/_sum/_count
        n += 1
        if f'dnet_tp_collective_ms_count{{op="{op}"}}' not in text:
            errors.append(
                f"obs: obs.phases.TP_OPS value {op!r} has no "
                f"dnet_tp_collective_ms series (pre-touch it in "
                f"dnet_tpu.obs._register_core)"
            )
    for m in re.finditer(
        r'dnet_tp_collective_ms(?:_bucket|_sum|_count)\{op="([^"]+)"', text
    ):
        if m.group(1) not in TP_OPS:
            errors.append(
                f"obs: exposed dnet_tp_collective_ms op label "
                f"{m.group(1)!r} is not declared in obs.phases.TP_OPS"
            )
    n += _cross_check_labels(
        errors, text, "dnet_tp_collective_bytes_total", "op",
        TP_OPS, "obs.phases.TP_OPS",
    )
    fams = get_registry().families()
    n += 1
    if "dnet_tp_degree" not in fams:
        errors.append("tp: required family dnet_tp_degree not registered")
    return n


def check_request_segment_labels(errors: list) -> int:
    """Pass 14: the critical-path surface must stay self-consistent with
    the declared segment enum (obs/phases.py REQUEST_SEGMENTS), both
    directions:

    - every declared segment has a pre-touched dnet_request_segment_ms
      series, and no exposed segment label is undeclared;
    - every obs/critical_path.py SPAN_SEGMENTS target is a declared
      segment, and every declared segment except `other` (the residual
      bucket) is reachable from at least one span mapping — a segment no
      span can feed is a stale ledger row;
    - the Perfetto track routing (obs/trace.py) only names spans the
      attribution map knows (plus the instant-only flow-rx marker), its
      compute/tx sets are disjoint, and flow arrows only leave tx spans;
    - the tick flight recorder's queue-depth keys are exactly
      sched/kinds.py QUEUE_STATES, so /v1/debug/sched and the
      dnet_sched_queue_depth gauges tell the same story."""
    from dnet_tpu.obs import get_registry
    from dnet_tpu.obs import trace as obs_trace
    from dnet_tpu.obs.critical_path import SPAN_SEGMENTS
    from dnet_tpu.obs.phases import REQUEST_SEGMENTS, SEG_OTHER
    from dnet_tpu.sched.flight import TickFlightRecorder
    from dnet_tpu.sched.kinds import QUEUE_STATES

    text = get_registry().expose()
    n = 0
    for seg in REQUEST_SEGMENTS:
        n += 1
        if f'dnet_request_segment_ms_count{{segment="{seg}"}}' not in text:
            errors.append(
                f"critical-path: obs.phases.REQUEST_SEGMENTS value {seg!r} "
                f"has no dnet_request_segment_ms series (pre-touch it in "
                f"dnet_tpu.obs._register_core)"
            )
    for m in re.finditer(
        r'dnet_request_segment_ms(?:_bucket|_sum|_count)\{segment="([^"]+)"',
        text,
    ):
        if m.group(1) not in REQUEST_SEGMENTS:
            errors.append(
                f"critical-path: exposed dnet_request_segment_ms segment "
                f"label {m.group(1)!r} is not declared in "
                f"obs.phases.REQUEST_SEGMENTS"
            )

    mapped_targets = {seg for seg, _prio in SPAN_SEGMENTS.values()}
    for seg in mapped_targets:
        n += 1
        if seg not in REQUEST_SEGMENTS:
            errors.append(
                f"critical-path: SPAN_SEGMENTS maps to {seg!r}, which is "
                f"not declared in obs.phases.REQUEST_SEGMENTS"
            )
    for seg in REQUEST_SEGMENTS:
        if seg != SEG_OTHER and seg not in mapped_targets:
            errors.append(
                f"critical-path: declared segment {seg!r} is unreachable — "
                f"no obs/critical_path.py SPAN_SEGMENTS entry feeds it"
            )

    routed = obs_trace.COMPUTE_SPANS | obs_trace.TX_SPANS
    overlap_names = obs_trace.COMPUTE_SPANS & obs_trace.TX_SPANS
    if overlap_names:
        errors.append(
            f"critical-path: trace track sets overlap: {sorted(overlap_names)}"
        )
    known = set(SPAN_SEGMENTS) | {obs_trace.FLOW_RX_SPAN}
    for name in sorted(routed - known):
        errors.append(
            f"critical-path: obs/trace.py routes span {name!r} to a thread "
            f"track but obs/critical_path.py SPAN_SEGMENTS does not "
            f"attribute it"
        )
    n += len(routed)
    for name in sorted(obs_trace.FLOW_TX_SPANS - obs_trace.TX_SPANS):
        errors.append(
            f"critical-path: flow arrow source {name!r} is not on the "
            f"tx-stage track"
        )

    states = TickFlightRecorder().snapshot()["states"]
    n += 1
    if tuple(states) != tuple(QUEUE_STATES):
        errors.append(
            f"critical-path: tick-record states {states!r} != "
            f"sched.kinds.QUEUE_STATES {tuple(QUEUE_STATES)!r}"
        )
    return n


def check_event_labels(errors: list) -> int:
    """Pass 15: the wide-event vocabulary (obs/phases.py EVENT_NAMES) must
    agree with the dnet_events_total exposition both ways — a new event
    cannot ship without its pre-touched counter series, and a renamed one
    cannot strand a stale name label on dashboards.  log_event() itself
    asserts membership at emit time; this pass catches the drift BEFORE a
    process ever emits."""
    from dnet_tpu.obs import get_registry
    from dnet_tpu.obs.phases import EVENT_NAMES

    text = get_registry().expose()
    return _cross_check_labels(
        errors, text, "dnet_events_total", "name",
        EVENT_NAMES, "obs.phases.EVENT_NAMES",
    )


def check_fleet_labels(errors: list) -> int:
    """Pass 16: the fleet-routing surface must agree with the declared
    enums (fleet/states.py) both ways — a new replica state or routing
    reason cannot ship without its pre-touched series, and a renamed one
    cannot strand a stale label on dashboards.  The `replica` label of
    dnet_fleet_requests_total is deployment-assigned (r0, r1, ...) and
    intentionally NOT enum-checked."""
    from dnet_tpu.fleet.states import REPLICA_STATES, ROUTE_REASONS
    from dnet_tpu.obs import get_registry

    text = get_registry().expose()
    n = _cross_check_labels(
        errors, text, "dnet_fleet_replicas", "state",
        REPLICA_STATES, "fleet.states.REPLICA_STATES",
    )
    n += _cross_check_labels(
        errors, text, "dnet_fleet_routed_total", "reason",
        ROUTE_REASONS, "fleet.states.ROUTE_REASONS",
    )
    return n


def main() -> int:
    """The scripts/check_metrics_names.py CLI contract, verbatim: exit 0
    and the 'ok: ...' summary on clean, the FAIL lines and exit 1 on
    violations (tests/test_metrics_lint.py asserts this format)."""
    errors: list[str] = []
    n_reg = check_registry(errors)
    n_src = check_sources(errors)
    n_fed = check_federation(errors)
    n_pool = check_paged_conservation(errors)
    n_chaos = check_chaos_points(errors)
    n_kinds = check_chaos_kinds(errors)
    n_admit = check_admission_labels(errors)
    n_member = check_membership_labels(errors)
    n_attr = check_attribution_labels(errors)
    n_san = check_san_labels(errors)
    n_sched = check_sched_labels(errors)
    n_jit = check_jit_instrumentation(errors)
    n_wire = check_wire_labels(errors)
    n_tp = check_tp_labels(errors)
    n_seg = check_request_segment_labels(errors)
    n_evt = check_event_labels(errors)
    n_fleet = check_fleet_labels(errors)
    if errors:
        for e in errors:
            print(f"FAIL {e}")
        return 1
    print(f"ok: {n_reg} registered families, {n_src} source-literal "
          f"registrations, {n_fed} federated samples, {n_pool} paged-pool "
          f"audits, {n_chaos} chaos points, {n_kinds} chaos kinds, "
          f"{n_admit} admission labels, "
          f"{n_member} membership labels, {n_attr} attribution labels, "
          f"{n_san} sanitizer labels, {n_sched} scheduler labels, "
          f"{n_jit} jit call sites, {n_wire} wire labels, "
          f"{n_tp} tp labels, {n_seg} critical-path labels, "
          f"{n_evt} event labels, {n_fleet} fleet labels, all conform")
    return 0


# ---- framework wrappers ---------------------------------------------------


class _MetricsCheck(Check):
    """Adapter: one legacy errors-list pass -> one DL01x check."""

    requires_runtime = True
    severity = "error"
    pass_name = ""  # looked up in this module at run time

    def run_project(self, project: Project) -> Iterable[Finding]:
        errors: list = []
        fn = globals()[self.pass_name]
        try:
            fn(errors)
        except Exception as exc:  # a crashed pass is itself a finding
            yield self.finding(
                "dnet_tpu/analysis/metrics_checks.py", 0,
                f"{self.pass_name} crashed: {type(exc).__name__}: {exc}",
            )
            return
        for e in errors:
            yield self.finding("dnet_tpu/analysis/metrics_checks.py", 0, e)


class MetricRegistryNames(_MetricsCheck):
    code = "DL010"
    name = "metric-registry-names"
    description = "registered families match dnet_[a-z0-9_]+ with help text"
    pass_name = "check_registry"


class MetricSourceLiterals(_MetricsCheck):
    code = "DL011"
    name = "metric-source-literals"
    description = "literal counter/gauge/histogram registrations conform"
    pass_name = "check_sources"


class FederationRoundTrip(_MetricsCheck):
    code = "DL012"
    name = "federation-round-trip"
    description = "two-node relabel/merge re-parses; required families exist"
    pass_name = "check_federation"


class PagedPoolConservation(_MetricsCheck):
    code = "DL013"
    name = "paged-pool-conservation"
    description = "block books balance through alloc/share/COW/release"
    pass_name = "check_paged_conservation"


class ChaosPointCoverage(_MetricsCheck):
    code = "DL014"
    name = "chaos-point-coverage"
    description = "chaos injection points <-> pre-touched series, both ways"
    pass_name = "check_chaos_points"


class AdmissionLabelContract(_MetricsCheck):
    code = "DL015"
    name = "admission-label-contract"
    description = "reject/deadline labels <-> declared enums, both ways"
    pass_name = "check_admission_labels"


class MembershipLabelContract(_MetricsCheck):
    code = "DL016"
    name = "membership-label-contract"
    description = "epoch/recovery labels <-> declared enums, both ways"
    pass_name = "check_membership_labels"


class AttributionLabelContract(_MetricsCheck):
    code = "DL017"
    name = "attribution-label-contract"
    description = "phase/jit/mem labels <-> declared enums, both ways"
    pass_name = "check_attribution_labels"


class SanLabelContract(_MetricsCheck):
    code = "DL018"
    name = "san-label-contract"
    description = "dsan check/zombie labels <-> declared enums, both ways"
    pass_name = "check_san_labels"


class SchedLabelContract(_MetricsCheck):
    code = "DL019"
    name = "sched-label-contract"
    description = "sched state/kind/reason labels <-> declared enums, both ways"
    pass_name = "check_sched_labels"


class JitInstrumentationContract(_MetricsCheck):
    code = "DL020"
    name = "jit-instrumentation-contract"
    description = "instrument_jit call-site names <-> JIT_FNS, both ways"
    pass_name = "check_jit_instrumentation"


class WireLabelContract(_MetricsCheck):
    # DL021-DL025 belong to the flow-sensitive tier (analysis/flow/)
    code = "DL026"
    name = "wire-label-contract"
    description = "wire dir labels <-> WIRE_DIRS + dnet_wire_* families exist"
    pass_name = "check_wire_labels"


class TpLabelContract(_MetricsCheck):
    code = "DL027"
    name = "tp-label-contract"
    description = "tp collective op labels <-> TP_OPS + dnet_tp_* families exist"
    pass_name = "check_tp_labels"


class RequestSegmentContract(_MetricsCheck):
    code = "DL028"
    name = "request-segment-contract"
    description = (
        "segment labels <-> REQUEST_SEGMENTS + trace tracks consistent"
    )
    pass_name = "check_request_segment_labels"


class EventLabelContract(_MetricsCheck):
    # DL029 is the static logging-hygiene check (checks_logging.py)
    code = "DL030"
    name = "event-label-contract"
    description = "wide-event name labels <-> EVENT_NAMES, both ways"
    pass_name = "check_event_labels"


class FleetLabelContract(_MetricsCheck):
    code = "DL031"
    name = "fleet-label-contract"
    description = "fleet state/reason labels <-> declared enums, both ways"
    pass_name = "check_fleet_labels"


class ChaosKindGrammar(_MetricsCheck):
    code = "DL032"
    name = "chaos-kind-grammar"
    description = "every declared chaos kind parses at every point"
    pass_name = "check_chaos_kinds"


METRICS_CHECKS = [
    MetricRegistryNames(),
    MetricSourceLiterals(),
    FederationRoundTrip(),
    PagedPoolConservation(),
    ChaosPointCoverage(),
    AdmissionLabelContract(),
    MembershipLabelContract(),
    AttributionLabelContract(),
    SanLabelContract(),
    SchedLabelContract(),
    JitInstrumentationContract(),
    WireLabelContract(),
    TpLabelContract(),
    RequestSegmentContract(),
    EventLabelContract(),
    FleetLabelContract(),
    ChaosKindGrammar(),
]
