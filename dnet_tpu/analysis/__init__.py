"""dnet_tpu.analysis — repo-native static analysis (dnetlint).

CLI: ``python scripts/dnetlint.py``; tier-1 hook:
tests/test_static_analysis.py.  See core.py for the framework and the
README "Static analysis" section for the check catalog + suppression
syntax (``# dnetlint: disable=DLxxx <reason>``).
"""

from dnet_tpu.analysis.checks_async import (
    BlockingCallInAsync,
    DroppedCoroutine,
    LockAcrossAwait,
)
from dnet_tpu.analysis.checks_contract import (
    ContractDrift,
    EnvReadOutsideConfig,
    SilentExceptionSwallow,
)
from dnet_tpu.analysis.checks_dsan import OwnershipRegistryDrift
from dnet_tpu.analysis.checks_jit import JitPurity, UngatedDeviceSync
from dnet_tpu.analysis.checks_logging import LoggingHygiene
from dnet_tpu.analysis.flow import FLOW_CHECKS
from dnet_tpu.analysis.core import (
    DEFAULT_BASELINE,
    Check,
    Finding,
    Project,
    Report,
    SourceFile,
    analyze_texts,
    load_baseline,
    next_report_path,
    run_analysis,
    write_baseline,
    write_report_json,
)
from dnet_tpu.analysis.metrics_checks import METRICS_CHECKS

#: the full suite, DL-code order; metrics checks carry requires_runtime
ALL_CHECKS = [
    BlockingCallInAsync(),
    LockAcrossAwait(),
    DroppedCoroutine(),
    JitPurity(),
    UngatedDeviceSync(),
    EnvReadOutsideConfig(),
    SilentExceptionSwallow(),
    ContractDrift(),
    OwnershipRegistryDrift(),
    LoggingHygiene(),
    *METRICS_CHECKS,
    *FLOW_CHECKS,
]

__all__ = [
    "ALL_CHECKS",
    "Check",
    "DEFAULT_BASELINE",
    "Finding",
    "Project",
    "Report",
    "SourceFile",
    "analyze_texts",
    "load_baseline",
    "next_report_path",
    "run_analysis",
    "write_baseline",
    "write_report_json",
]
