"""Logging-hygiene check: the structured-logging contract, machine-checked.

DL029 — two rules that keep the PR 17 wide-event layer trustworthy:

(a) **Raw ``logging.getLogger(...)`` outside utils/logger.py and tui.py.**
    Every module must log through ``dnet_tpu.utils.logger.get_logger()``:
    a raw getLogger invents a parallel logger tree that misses the
    ``ContextStampFilter`` (so its records carry no rid/node/epoch), the
    ``[PROFILE]`` gating, and the per-process file handlers — the exact
    drift ops/flash_attention.py shipped with (a ``"dnet"`` logger that
    never existed).  utils/logger.py owns the tree; tui.py attaches its
    live-feed handler to it by name.

(b) **Eager interpolation in log calls on serving paths.**  An f-string,
    ``.format(...)``, or ``"..." % ...`` argument renders even when the
    level is filtered — on the per-token path that is real work thrown
    away — and defeats rate-limit-by-template tooling.  Lazy ``%s`` args
    only: ``log.info("sent %s", rid)``.
"""

from __future__ import annotations

import ast
from typing import Iterable

from dnet_tpu.analysis.core import (
    Check,
    Finding,
    Project,
    SourceFile,
    dotted,
    is_serving_path,
)

#: rel-paths where raw logging.getLogger is the point, not a violation
DL029_ALLOWLIST = (
    "dnet_tpu/utils/logger.py",  # owns the "dnet_tpu" logger tree
    "dnet_tpu/tui.py",  # attaches the live-feed handler to it by name
)

_LOG_METHODS = {"debug", "info", "warning", "error", "exception", "critical"}

#: receiver spellings that identify a logger object in this repo's idiom
#: (``log = get_logger()`` at module scope; ``logger`` in older modules)
_LOG_RECEIVERS = {"log", "logger", "get_logger()"}


def _is_log_call(node: ast.Call) -> bool:
    if not isinstance(node.func, ast.Attribute):
        return False
    if node.func.attr not in _LOG_METHODS:
        return False
    recv = node.func.value
    if isinstance(recv, ast.Name) and recv.id in _LOG_RECEIVERS:
        return True
    if isinstance(recv, ast.Attribute) and recv.attr in ("log", "logger"):
        return True  # self.log.info(...) / module.log.warning(...)
    if isinstance(recv, ast.Call) and dotted(recv.func).endswith(
        "get_logger"
    ):
        return True  # get_logger().warning(...)
    return False


def _eager_kind(arg: ast.expr) -> str:
    """Why this message argument renders eagerly, or ''."""
    if isinstance(arg, ast.JoinedStr) and any(
        isinstance(v, ast.FormattedValue) for v in arg.values
    ):
        return "f-string"
    if (
        isinstance(arg, ast.Call)
        and isinstance(arg.func, ast.Attribute)
        and arg.func.attr == "format"
    ):
        return ".format()"
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Mod) and (
        isinstance(arg.left, (ast.Constant, ast.JoinedStr))
    ):
        return "eager %-interpolation"
    return ""


class LoggingHygiene(Check):
    code = "DL029"
    name = "logging-hygiene"
    description = (
        "raw logging.getLogger outside utils/logger.py (misses the "
        "context stamp + profile gate) and eager f-string/.format()/% "
        "interpolation in log calls on serving paths (lazy %s only)"
    )

    def run_file(self, src: SourceFile, project: Project) -> Iterable[Finding]:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if (
                dotted(node.func) == "logging.getLogger"
                and src.rel not in DL029_ALLOWLIST
            ):
                yield self.finding(
                    src.rel, node.lineno,
                    "raw logging.getLogger() builds a logger outside the "
                    "dnet_tpu tree — no rid/node context stamp, no "
                    "[PROFILE] gate; use dnet_tpu.utils.logger.get_logger()",
                    col=node.col_offset,
                )
                continue
            if not is_serving_path(src.rel):
                continue
            if _is_log_call(node) and node.args:
                kind = _eager_kind(node.args[0])
                if kind:
                    yield self.finding(
                        src.rel, node.lineno,
                        f"{kind} in a log call renders even when the level "
                        f"is filtered — use lazy %s args",
                        col=node.col_offset,
                    )
