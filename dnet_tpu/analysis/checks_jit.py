"""JIT-tracing checks: traced code must stay pure and sync-free.

DL004 — JIT purity: a function traced by ``jax.jit`` runs its Python body
ONCE per compile, then never again.  ``time.*`` / host RNG /
``os.environ`` / metrics observers inside traced code either bake a
stale value into the compiled program or silently stop recording after
warmup — both lie.  The check walks the intra-module call graph from
every jitted entry point (``jax.jit(fn)`` call sites, ``@jit`` /
``@partial(jax.jit, ...)`` decorators, and ``instrument_jit``-wrapped
entries declared in ``obs.phases.JIT_FNS``).

DL005 — forced device syncs: ``.item()`` / ``block_until_ready`` /
``jax.device_get`` on the serving path outside ``obs_enabled()``-style
gating.  The PR 7 contract: phase attribution may fence the device ONLY
when observability asked for it, otherwise async dispatch must stay
async — an ungated sync is a silent decode-throughput regression.  A
sync is considered gated when an enclosing ``if``/``while`` test
mentions an obs/sync gate (``obs_enabled``, ``attribute``, ``*sync*``,
``*profile*``).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Set, Tuple

from dnet_tpu.analysis.core import (
    Check,
    Finding,
    Project,
    SourceFile,
    dotted,
    is_serving_path,
)

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
_PARTIAL_NAMES = {"partial", "functools.partial"}

_IMPURE_PREFIX = (
    "time.",
    "random.",
    "np.random.",
    "numpy.random.",
    "os.environ",
    "os.getenv",
    "subprocess.",
)
_IMPURE_EXACT = {"print", "input", "metric", "get_recorder", "obs_enabled"}

_SYNC_ATTRS = {"block_until_ready", "item"}
_SYNC_DOTTED = {"jax.block_until_ready", "jax.device_get"}
# NOTE: 'sync' must NOT match inside 'async' (an async-heavy codebase would
# silently exempt itself), and 'attribute' is word-bounded so arbitrary
# attribute-ish identifiers don't count as gates
_GATE_RE = re.compile(r"obs_enabled|\battribute\b|(?<!a)sync|profile", re.I)


def _collect_defs(tree: ast.AST) -> Dict[str, List[ast.AST]]:
    defs: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    return defs


def _jit_entries(tree: ast.AST) -> List[Tuple[str, ast.AST]]:
    """(label, entry) pairs: entry is a def node or a Lambda, label the
    name shown in findings."""
    defs = _collect_defs(tree)
    entries: List[Tuple[str, ast.AST]] = []
    seen: Set[int] = set()

    def add_name(name: str) -> None:
        for fd in defs.get(name, ()):
            if id(fd) not in seen:
                seen.add(id(fd))
                entries.append((name, fd))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and dotted(node.func) in _JIT_NAMES:
            if node.args:
                arg0 = node.args[0]
                if isinstance(arg0, ast.Name):
                    add_name(arg0.id)
                elif isinstance(arg0, ast.Attribute):
                    add_name(arg0.attr)
                elif isinstance(arg0, ast.Lambda) and id(arg0) not in seen:
                    seen.add(id(arg0))
                    entries.append(("<lambda>", arg0))
    for name, fds in defs.items():
        for fd in fds:
            for dec in getattr(fd, "decorator_list", ()):
                d = dotted(dec)
                if d in _JIT_NAMES:
                    add_name(name)
                elif (
                    isinstance(dec, ast.Call)
                    and dotted(dec.func) in _PARTIAL_NAMES
                    and dec.args
                    and dotted(dec.args[0]) in _JIT_NAMES
                ):
                    add_name(name)
                elif isinstance(dec, ast.Call) and dotted(dec.func) in _JIT_NAMES:
                    add_name(name)
    return entries


def _is_impure(d: str) -> bool:
    if not d:
        return False
    if d in _IMPURE_EXACT:
        return True
    if d == "random" or d.startswith(_IMPURE_PREFIX):
        return True
    return False


class JitPurity(Check):
    code = "DL004"
    name = "jit-purity"
    description = (
        "functions reachable from jitted entry points must not call "
        "time.*, host RNG, metrics observers, or os.environ — traced "
        "Python runs once per compile, so side effects bake in or vanish"
    )

    def run_file(self, src: SourceFile, project: Project) -> Iterable[Finding]:
        defs = _collect_defs(src.tree)
        emitted: Set[Tuple[int, str]] = set()
        for label, entry in _jit_entries(src.tree):
            stack = [entry]
            visited: Set[int] = set()
            while stack:
                fn = stack.pop()
                if id(fn) in visited:
                    continue
                visited.add(id(fn))
                for node in ast.walk(fn):
                    if isinstance(node, ast.Subscript) and dotted(
                        node.value
                    ) == "os.environ":
                        key = (node.lineno, "os.environ[]")
                        if key not in emitted:
                            emitted.add(key)
                            yield self.finding(
                                src.rel, node.lineno,
                                f"os.environ read inside jit-traced "
                                f"'{label}' — traced once, stale forever",
                                col=node.col_offset,
                            )
                    if not isinstance(node, ast.Call):
                        continue
                    d = dotted(node.func)
                    if _is_impure(d):
                        key = (node.lineno, d)
                        if key not in emitted:
                            emitted.add(key)
                            yield self.finding(
                                src.rel, node.lineno,
                                f"impure call {d}() reachable from "
                                f"jit-traced entry '{label}'",
                                col=node.col_offset,
                            )
                        continue
                    last = d.split(".")[-1]
                    if last and (d == last or d.startswith(("self.", "cls."))):
                        stack.extend(defs.get(last, ()))


class UngatedDeviceSync(Check):
    code = "DL005"
    name = "ungated-device-sync"
    description = (
        ".item() / block_until_ready / device_get on the serving path "
        "outside obs_enabled()-style gating — the PR 7 device-sync "
        "contract: fence only when observability asked for it"
    )

    def run_file(self, src: SourceFile, project: Project) -> Iterable[Finding]:
        if not is_serving_path(src.rel):
            return
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            is_sync = d in _SYNC_DOTTED or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _SYNC_ATTRS
                and not node.args
                and not node.keywords
            )
            if not is_sync or self._gated(src, node):
                continue
            what = d or node.func.attr
            yield self.finding(
                src.rel, node.lineno,
                f"forced device sync {what}() outside obs_enabled() "
                f"gating on a serving path",
                col=node.col_offset,
            )

    @staticmethod
    def _gated(src: SourceFile, node: ast.AST) -> bool:
        for anc in src.ancestors(node):
            if isinstance(anc, (ast.If, ast.While)):
                try:
                    test_src = ast.unparse(anc.test)
                except Exception:
                    test_src = ""
                if _GATE_RE.search(test_src):
                    return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if _GATE_RE.search(anc.name):
                    return True
        return False
