"""dnetlint core: the repo-native static-analysis framework.

The serving stack is held together by conventions — device-sync only under
``obs_enabled()``, typed errors mapped to status codes, ``DNET_*`` config
routed through ``config.py``, epoch/deadline headers stamped on every wire
frame — that nothing enforced except reviewer memory.  This package turns
each convention into a machine-checked *check* with a stable ``DLxxx`` code,
run from tier-1 (tests/test_static_analysis.py) and from the CLI
(``scripts/dnetlint.py``).

Framework pieces (all dependency-free, stdlib ``ast`` only):

- :class:`Finding` — one violation: (path, line, col, code, message,
  severity).  Ordering is total and deterministic.
- :class:`SourceFile` — a parsed module plus its inline-suppression map.
  Suppression syntax: ``# dnetlint: disable=DL001 <reason>`` — trailing on
  the offending line or standalone on the line above; the reason is
  MANDATORY (a bare disable is itself reported as DL000).
- :class:`Project` — the scanned file set; cross-file checks look other
  modules up by path suffix.
- :class:`Check` — base class.  ``run_file`` fires per module,
  ``run_project`` once per run (cross-file / runtime checks).  Checks with
  ``requires_runtime = True`` import live dnet_tpu modules (the metrics
  passes) and are skipped by ``analyze_texts`` and ``--ast-only``.
- Baseline — a committed file of grandfathered fingerprints
  (``.dnetlint-baseline``); matched findings report as *baselined* and do
  not fail the run, stale entries DO fail (a baseline cannot rot).
- :func:`run_analysis` — discover -> check -> suppress -> baseline ->
  sort -> :class:`Report` (with ``--json`` emission for ANALYSIS_r<NN>.json).

Adding a check: subclass :class:`Check` in a ``checks_*`` module, set
``code``/``name``/``description``, implement ``run_file`` or
``run_project``, append it to ``ALL_CHECKS`` in ``__init__.py``, and add a
firing + quiet fixture pair in tests/test_static_analysis.py.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

SEVERITY_ERROR = "error"
SEVERITY_WARN = "warn"

#: repo-relative scan roots for a full run
SCAN_DIRS = ("dnet_tpu", "scripts")
SCAN_FILES = ("bench.py", "bench_serve.py")

#: prefixes NOT on the serving path: async-safety / sync-contract checks
#: (DL001/2/3/5/7) stay out of CLI glue, offline tooling, and pure compute
#: layers; repo-global checks (DL004/6/8) ignore this scope.
NON_SERVING_PREFIXES = (
    "dnet_tpu/cli/",
    "dnet_tpu/tui.py",
    "dnet_tpu/utils/",
    "dnet_tpu/models/",
    "dnet_tpu/ops/",
    "dnet_tpu/parallel/",
    "dnet_tpu/analysis/",
    "scripts/",
    "bench.py",
    "bench_serve.py",
)

DEFAULT_BASELINE = ".dnetlint-baseline"

_SUPPRESS_RE = re.compile(
    r"#\s*dnetlint:\s*disable=(?P<codes>[A-Za-z0-9_,]+)(?:\s+(?P<reason>\S.*))?"
)


def is_serving_path(rel: str) -> bool:
    return not any(rel.startswith(p) for p in NON_SERVING_PREFIXES)


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One violation.  Field order IS the sort order (path, line, col,
    code) so reports are deterministic across runs and machines."""

    path: str
    line: int
    col: int
    code: str
    message: str
    severity: str = SEVERITY_ERROR

    def fingerprint(self) -> str:
        """Baseline identity: stable across reruns of the same tree."""
        return f"{self.code} {self.path}:{self.line} {self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class SourceFile:
    """A parsed module: AST, line table, suppression map, parent links."""

    def __init__(self, rel: str, text: str) -> None:
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.parse_error: Optional[str] = None
        try:
            self.tree: Optional[ast.Module] = ast.parse(text)
        except SyntaxError as exc:
            self.tree = None
            self.parse_error = f"syntax error: {exc.msg} (line {exc.lineno})"
        # line -> set of codes suppressed there; malformed -> DL000
        self.suppressed: Dict[int, set] = {}
        self.bad_suppressions: List[int] = []
        self._parents: Optional[Dict[int, ast.AST]] = None
        self._scan_suppressions()

    def _scan_suppressions(self) -> None:
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            if not (m.group("reason") or "").strip():
                self.bad_suppressions.append(i)
                continue
            codes = {c.strip().upper() for c in m.group("codes").split(",") if c.strip()}
            # standalone comment line applies to the NEXT line; a trailing
            # comment applies to its own line
            target = i + 1 if line.lstrip().startswith("#") else i
            self.suppressed.setdefault(target, set()).update(codes)

    def is_suppressed(self, line: int, code: str) -> bool:
        return code in self.suppressed.get(line, ())

    def parents(self) -> Dict[int, ast.AST]:
        """id(node) -> parent node map, built lazily."""
        if self._parents is None:
            self._parents = {}
            if self.tree is not None:
                for parent in ast.walk(self.tree):
                    for child in ast.iter_child_nodes(parent):
                        self._parents[id(child)] = parent
        return self._parents

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        parents = self.parents()
        cur = parents.get(id(node))
        while cur is not None:
            yield cur
            cur = parents.get(id(cur))


class Project:
    """The file set under analysis plus the repo root (runtime checks and
    the CLI need the real tree; synthetic projects in tests pass texts)."""

    def __init__(self, files: Sequence[SourceFile], root: Optional[Path] = None):
        self.files = list(files)
        self.root = root
        self._by_rel = {f.rel: f for f in self.files}

    def get(self, rel: str) -> Optional[SourceFile]:
        return self._by_rel.get(rel)

    def find_suffix(self, suffix: str) -> Optional[SourceFile]:
        if suffix in self._by_rel:
            return self._by_rel[suffix]
        for f in self.files:
            if f.rel.endswith(suffix):
                return f
        return None


class Check:
    """Base check.  Subclasses set the class attrs and implement one of
    the two hooks; both yield :class:`Finding`."""

    code: str = "DL000"
    name: str = "meta"
    description: str = ""
    severity: str = SEVERITY_ERROR
    #: True: imports live dnet_tpu modules (registry/pool/federation); run
    #: only in full-repo mode, never on synthetic fixture projects.
    requires_runtime: bool = False

    def run_file(self, src: SourceFile, project: Project) -> Iterable[Finding]:
        return ()

    def run_project(self, project: Project) -> Iterable[Finding]:
        return ()

    def finding(self, path: str, line: int, message: str, col: int = 0) -> Finding:
        return Finding(
            path=path, line=line, col=col, code=self.code,
            message=message, severity=self.severity,
        )


# ---- shared AST helpers ---------------------------------------------------


def dotted(node: ast.AST) -> str:
    """Best-effort dotted name of an expression (``self._lock`` ->
    ``self._lock``); empty string when it isn't a plain name chain."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


def scoped_walk(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk ``fn``'s body without descending into nested function/class
    scopes (their lines belong to the nested scope's own visit)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def contains_await(nodes: Iterable[ast.AST]) -> Optional[ast.Await]:
    for node in nodes:
        if isinstance(node, ast.Await):
            return node
    return None


# ---- baseline -------------------------------------------------------------


def load_baseline(path: Path) -> Dict[str, str]:
    """fingerprint -> justification.  Format, one entry per line::

        DL005 dnet_tpu/core/x.py:42 message text  # why this is grandfathered
    """
    entries: Dict[str, str] = {}
    if not path.is_file():
        return entries
    for raw in path.read_text().splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        fp, _, justification = line.partition("  # ")
        entries[fp.strip()] = justification.strip()
    return entries


def write_baseline(path: Path, findings: Sequence[Finding]) -> None:
    lines = [
        "# dnetlint baseline — grandfathered findings.",
        "# One per line: '<code> <path>:<line> <message>  # justification'.",
        "# Prefer fixing or inline-suppressing (with a reason) over baselining;",
        "# stale entries FAIL the run, so this file cannot rot.",
    ]
    for f in sorted(findings):
        if f.path == "<baseline>":
            # a stale-entry meta-finding can never match a scanned file —
            # writing it would poison every subsequent run
            continue
        lines.append(f"{f.fingerprint()}  # TODO justify")
    path.write_text("\n".join(lines) + "\n")


# ---- runner ---------------------------------------------------------------


@dataclasses.dataclass
class Report:
    findings: List[Finding]          # new (failing) findings
    baselined: List[Finding]         # grandfathered by the baseline file
    suppressed: int                  # inline-suppressed count
    files_scanned: int
    checks_run: List[str]
    baseline_size: int
    counts: Dict[str, int]           # per-code NEW finding counts

    @property
    def clean(self) -> bool:
        return not any(f.severity == SEVERITY_ERROR for f in self.findings)

    def to_json(self) -> dict:
        return {
            "tool": "dnetlint",
            "clean": self.clean,
            "files_scanned": self.files_scanned,
            "checks_run": self.checks_run,
            "counts": self.counts,
            "findings": [f.to_json() for f in self.findings],
            "baselined": [f.to_json() for f in self.baselined],
            "baseline_size": self.baseline_size,
            "suppressed": self.suppressed,
        }


def discover_files(root: Path) -> List[SourceFile]:
    paths: List[Path] = []
    for d in SCAN_DIRS:
        base = root / d
        if base.is_dir():
            paths.extend(sorted(base.rglob("*.py")))
    for f in SCAN_FILES:
        p = root / f
        if p.is_file():
            paths.append(p)
    out = []
    for p in paths:
        rel = p.relative_to(root).as_posix()
        if "__pycache__" in rel:
            continue
        try:
            text = p.read_text()
        except OSError:
            continue
        out.append(SourceFile(rel, text))
    return out


def _fingerprint_path(fp: str) -> str:
    """The path component of a baseline fingerprint
    (``'DL005 dnet_tpu/x.py:42 message'`` -> ``'dnet_tpu/x.py'``)."""
    parts = fp.split(" ", 2)
    if len(parts) < 2:
        return ""
    return parts[1].rsplit(":", 1)[0]


def run_checks(
    project: Project,
    checks: Sequence[Check],
    baseline: Optional[Dict[str, str]] = None,
    only_files: Optional[set] = None,
) -> Report:
    """``only_files`` (a set of rel paths) is the ``--diff`` incremental
    mode: per-file checks run only on those files, project-check findings
    and baseline staleness are filtered to them — the whole project is
    still loaded so cross-file checks keep their context, which is what
    makes a diff run agree with the full run on the files it covers."""
    raw: List[Finding] = []
    meta = Check()  # DL000 emitter

    def in_scope(rel: str) -> bool:
        return only_files is None or rel in only_files

    for src in project.files:
        if not in_scope(src.rel):
            continue
        if src.parse_error:
            raw.append(meta.finding(src.rel, 1, src.parse_error))
        for line in src.bad_suppressions:
            raw.append(meta.finding(
                src.rel, line,
                "suppression without a reason: use "
                "'# dnetlint: disable=DLxxx <why>'",
            ))
    for check in checks:
        for src in project.files:
            if src.tree is None or not in_scope(src.rel):
                continue
            raw.extend(check.run_file(src, project))
        raw.extend(
            f for f in check.run_project(project) if in_scope(f.path)
        )

    suppressed = 0
    kept: List[Finding] = []
    for f in raw:
        src = project.get(f.path)
        if src is not None and f.code != "DL000" and src.is_suppressed(f.line, f.code):
            suppressed += 1
            continue
        kept.append(f)

    baseline = baseline or {}
    matched_fps = set()
    new: List[Finding] = []
    grandfathered: List[Finding] = []
    for f in sorted(set(kept)):
        if f.fingerprint() in baseline:
            matched_fps.add(f.fingerprint())
            grandfathered.append(f)
        else:
            new.append(f)
    # staleness is judged only against the checks that actually ran (a
    # partial run — --select / --ast-only — must not flag entries
    # belonging to deliberately-skipped checks) and, in diff mode, only
    # against entries for the files that were actually linted
    run_codes = {c.code for c in checks} | {"DL000"}
    for fp in sorted(set(baseline) - matched_fps):
        if fp.split(" ", 1)[0] not in run_codes:
            continue
        if not in_scope(_fingerprint_path(fp)):
            continue
        new.append(meta.finding(
            "<baseline>", 0,
            f"stale baseline entry (finding no longer fires): {fp}",
        ))

    new.sort()
    counts: Dict[str, int] = {}
    for f in new:
        counts[f.code] = counts.get(f.code, 0) + 1
    return Report(
        findings=new,
        baselined=grandfathered,
        suppressed=suppressed,
        files_scanned=len(project.files),
        checks_run=[c.code for c in checks],
        baseline_size=len(baseline),
        counts=counts,
    )


def analyze_texts(
    texts: Dict[str, str], checks: Optional[Sequence[Check]] = None
) -> List[Finding]:
    """Fixture entry point: run the AST checks over in-memory sources.
    Returns NEW findings (suppressions applied, no baseline)."""
    from dnet_tpu.analysis import ALL_CHECKS

    project = Project([SourceFile(rel, text) for rel, text in texts.items()])
    selected = [
        c for c in (checks if checks is not None else ALL_CHECKS)
        if not c.requires_runtime
    ]
    return run_checks(project, selected).findings


def run_analysis(
    root: Path,
    checks: Optional[Sequence[Check]] = None,
    include_runtime: bool = True,
    baseline_path: Optional[Path] = None,
    ignore_baseline: bool = False,
    only_files: Optional[set] = None,
) -> Report:
    """Full-repo run: discover files under ``root``, apply the baseline.
    ``ignore_baseline=True`` reports every finding as new — the
    ``--write-baseline`` path, so still-firing grandfathered entries are
    re-captured instead of dropped.  ``only_files`` restricts linting to
    those rel paths (the ``--diff`` mode; see :func:`run_checks`)."""
    from dnet_tpu.analysis import ALL_CHECKS

    selected = list(checks if checks is not None else ALL_CHECKS)
    if not include_runtime:
        selected = [c for c in selected if not c.requires_runtime]
    project = Project(discover_files(root), root=root)
    bp = baseline_path if baseline_path is not None else root / DEFAULT_BASELINE
    baseline = {} if ignore_baseline else load_baseline(bp)
    return run_checks(
        project, selected, baseline=baseline, only_files=only_files
    )


def changed_files(root: Path, rev: str) -> Optional[set]:
    """Rel paths of ``.py`` files changed vs ``rev`` (working tree diff
    plus untracked), or None when git cannot answer (not a repo, bad
    rev) — the caller falls back to a full run rather than linting
    nothing."""
    import subprocess

    out: set = set()
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", rev, "--", "*.py"],
            capture_output=True, text=True, cwd=root, timeout=30,
        )
        if diff.returncode != 0:
            return None
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard", "--", "*.py"],
            capture_output=True, text=True, cwd=root, timeout=30,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    for line in diff.stdout.splitlines() + (
        untracked.stdout.splitlines() if untracked.returncode == 0 else []
    ):
        rel = line.strip()
        if rel:
            out.add(rel)
    return out


def next_report_path(root: Path) -> Path:
    """ANALYSIS_r<NN>.json numbering: continue the BENCH_r* sequence so
    lint debt is tracked across PRs the way perf is."""
    nums = [0]
    for pat in ("ANALYSIS_r*.json", "BENCH_r*.json"):
        for p in root.glob(pat):
            m = re.search(r"_r(\d+)\.json$", p.name)
            if m:
                nums.append(int(m.group(1)))
    return root / f"ANALYSIS_r{max(nums) + 1:02d}.json"


def write_report_json(
    report: Report, path: Path, extra: Optional[dict] = None
) -> None:
    """Emit the JSON record; ``extra`` merges additional top-level
    sections (the CLI adds the dsan ``runtime`` section here)."""
    data = report.to_json()
    if extra:
        data.update(extra)
    path.write_text(json.dumps(data, indent=2, sort_keys=True) + "\n")
