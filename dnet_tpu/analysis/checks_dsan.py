"""DL009 — ownership-registry drift: the dsan declarations must match
the code, and thread->loop bridges must stay in sanctioned modules.

Two halves:

1. Every entry of :data:`dnet_tpu.analysis.runtime.domains.OWNERSHIP_DOMAINS`
   names (module, class, attribute[, lock attribute]).  The class and the
   ``self.<attr>`` assignment must exist in that module — a refactor that
   renames ``recv_q`` or moves ``_buffered`` would otherwise leave the
   runtime sanitizer silently guarding nothing.  The registry half only
   runs on trees that SHIP the registry (``analysis/runtime/domains.py``
   present): there a missing module is itself a finding, while synthetic
   fixture trees stay independent of the real declarations.

2. ``call_soon_threadsafe`` / ``run_coroutine_threadsafe`` calls outside
   :data:`~dnet_tpu.analysis.runtime.domains.BRIDGE_MODULES` are findings:
   ad-hoc thread->loop bridges are exactly the seams dsan fences, so a new
   one must be declared (and its shared state annotated) or routed through
   an existing bridge.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Set, Tuple

from dnet_tpu.analysis.core import (
    Check,
    Finding,
    Project,
    SourceFile,
    dotted,
)
from dnet_tpu.analysis.runtime.domains import BRIDGE_MODULES, OWNERSHIP_DOMAINS

_BRIDGE_CALLS = ("call_soon_threadsafe", "run_coroutine_threadsafe")


def _class_attrs(src: SourceFile, cls_name: str) -> Optional[Set[str]]:
    """Attribute names assigned as ``self.<name>`` (or annotated / declared
    at class level) anywhere in class ``cls_name``; None when the class
    itself is missing."""
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            attrs: Set[str] = set()
            for sub in ast.walk(node):
                targets: Tuple[ast.AST, ...] = ()
                if isinstance(sub, ast.Assign):
                    targets = tuple(sub.targets)
                elif isinstance(sub, (ast.AnnAssign, ast.AugAssign)):
                    targets = (sub.target,)
                for t in targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        attrs.add(t.attr)
                    elif isinstance(t, ast.Name):
                        attrs.add(t.id)  # class-level declaration
            return attrs
    return None


class OwnershipRegistryDrift(Check):
    code = "DL009"
    name = "ownership-registry-drift"
    description = (
        "dsan ownership declarations (analysis/runtime/domains.py) must "
        "match the code, and call_soon_threadsafe / "
        "run_coroutine_threadsafe must stay in sanctioned bridge modules"
    )

    def run_project(self, project: Project) -> Iterable[Finding]:
        registry = project.find_suffix("dnet_tpu/analysis/runtime/domains.py")
        if registry is None:
            return  # fixture tree without the registry: nothing to drift
        for entry in OWNERSHIP_DOMAINS:
            module, cls, attr, kind, arg = entry
            src = project.find_suffix(module)
            if src is None or src.tree is None:
                yield self.finding(
                    registry.rel, 0,
                    f"ownership declaration for {cls}.{attr} names "
                    f"missing module {module}",
                )
                continue
            attrs = _class_attrs(src, cls)
            if attrs is None:
                yield self.finding(
                    src.rel, 0,
                    f"ownership declaration names missing class {cls} "
                    f"(declared for attribute {attr})",
                )
                continue
            if attr not in attrs:
                yield self.finding(
                    src.rel, 0,
                    f"ownership declaration names missing attribute "
                    f"{cls}.{attr} [{kind}]",
                )
            if kind == "lock" and arg not in attrs:
                yield self.finding(
                    src.rel, 0,
                    f"ownership declaration guarded-by({arg}) for "
                    f"{cls}.{attr} names a lock attribute {cls}.{arg} "
                    f"that does not exist",
                )

    def run_file(self, src: SourceFile, project: Project) -> Iterable[Finding]:
        if src.rel in BRIDGE_MODULES or src.rel.endswith("/conftest.py"):
            return
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            leaf = d.split(".")[-1]
            if leaf in _BRIDGE_CALLS:
                yield self.finding(
                    src.rel, node.lineno,
                    f"{leaf}() outside the sanctioned bridge modules "
                    f"({', '.join(BRIDGE_MODULES)}): declare the bridge in "
                    f"analysis/runtime/domains.py and annotate its shared "
                    f"state, or route through an existing bridge",
                    col=node.col_offset,
                )
