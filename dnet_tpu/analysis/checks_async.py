"""Async-safety checks: the event loop owns the serving path.

DL001 — blocking calls inside ``async def``: one ``time.sleep`` /
``subprocess.run`` / sync-socket call in a coroutine stalls EVERY
in-flight request on the loop (TTFT cliffs that profile as "mystery
scheduler jitter").

DL002 — locks held across an ``await``: a ``threading.Lock`` held over a
suspension point blocks the loop thread itself (latent deadlock with any
other coroutine wanting the lock); an ``asyncio.Lock`` held across a
sleep serializes unrelated requests behind a timer.

DL003 — dropped coroutines/tasks: a bare ``foo()`` where ``foo`` is
``async def`` never runs; a bare ``asyncio.create_task(...)`` whose
result is dropped can be garbage-collected MID-FLIGHT (CPython keeps no
strong reference) and its exceptions vanish.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List

from dnet_tpu.analysis.core import (
    Check,
    Finding,
    Project,
    SourceFile,
    dotted,
    is_serving_path,
    scoped_walk,
)

_BLOCKING_EXACT = {
    "time.sleep",
    "os.system",
    "os.popen",
    "os.waitpid",
    "socket.create_connection",
    "socket.getaddrinfo",
    "socket.gethostbyname",
    "grpc.insecure_channel",
    "grpc.secure_channel",
    "urllib.request.urlopen",
}
_BLOCKING_PREFIX = ("subprocess.", "requests.", "urllib.request.", "http.client.")

_LOCKISH_RE = re.compile(r"(?:^|[._])(?:lock|mutex|semaphore|sem)s?$", re.I)
_SLEEPISH_RE = re.compile(r"(?:^|\.)sleep$")

_SPAWN_EXACT = {"asyncio.create_task", "asyncio.ensure_future", "ensure_future"}
_SPAWN_SUFFIX = (".create_task", ".ensure_future")


def _async_defs(tree: ast.AST) -> List[ast.AsyncFunctionDef]:
    return [n for n in ast.walk(tree) if isinstance(n, ast.AsyncFunctionDef)]


class BlockingCallInAsync(Check):
    code = "DL001"
    name = "blocking-call-in-async"
    description = (
        "time.sleep / subprocess / sync socket-gRPC-urllib I/O inside an "
        "async def on a serving path stalls the whole event loop"
    )

    def run_file(self, src: SourceFile, project: Project) -> Iterable[Finding]:
        if not is_serving_path(src.rel):
            return
        for fn in _async_defs(src.tree):
            for node in scoped_walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                if d in _BLOCKING_EXACT or d.startswith(_BLOCKING_PREFIX):
                    yield self.finding(
                        src.rel, node.lineno,
                        f"blocking call {d}() inside async def "
                        f"{fn.name}() stalls the event loop",
                        col=node.col_offset,
                    )


class LockAcrossAwait(Check):
    code = "DL002"
    name = "lock-across-await"
    description = (
        "a threading lock held across an await blocks the loop thread; an "
        "asyncio lock held across a sleep serializes requests behind a timer"
    )

    def run_file(self, src: SourceFile, project: Project) -> Iterable[Finding]:
        if not is_serving_path(src.rel):
            return
        for fn in _async_defs(src.tree):
            for node in scoped_walk(fn):
                if isinstance(node, ast.With):
                    name = self._lockish_item(node)
                    if name is None:
                        continue
                    hit = self._first_await(node.body)
                    if hit is not None:
                        yield self.finding(
                            src.rel, hit.lineno,
                            f"sync 'with {name}:' held across an await in "
                            f"{fn.name}() — a threading lock here blocks "
                            f"the event loop thread",
                            col=hit.col_offset,
                        )
                elif isinstance(node, ast.AsyncWith):
                    name = self._lockish_item(node)
                    if name is None:
                        continue
                    for sub in self._scoped_body(node.body):
                        if isinstance(sub, ast.Await) and _SLEEPISH_RE.search(
                            dotted(getattr(sub.value, "func", sub.value))
                        ):
                            yield self.finding(
                                src.rel, sub.lineno,
                                f"'async with {name}:' holds the lock "
                                f"across a sleep in {fn.name}()",
                                col=sub.col_offset,
                            )

    @staticmethod
    def _lockish_item(node):
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):  # e.g. lock.acquire_timeout(...)
                expr = expr.func
            d = dotted(expr)
            if d and _LOCKISH_RE.search(d):
                return d
        return None

    @staticmethod
    def _scoped_body(body) -> Iterable[ast.AST]:
        for stmt in body:
            yield stmt
            if not isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                yield from scoped_walk(stmt)

    @classmethod
    def _first_await(cls, body):
        for sub in cls._scoped_body(body):
            if isinstance(sub, ast.Await):
                return sub
        return None


class DroppedCoroutine(Check):
    code = "DL003"
    name = "dropped-coroutine"
    description = (
        "a coroutine called without await never runs; a create_task / "
        "ensure_future result dropped without retention can be GC'd "
        "mid-flight and its exceptions vanish"
    )

    def run_file(self, src: SourceFile, project: Project) -> Iterable[Finding]:
        if not is_serving_path(src.rel):
            return
        local_async = {fn.name for fn in _async_defs(src.tree)}
        for node in ast.walk(src.tree):
            call = None
            if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
                call = node.value
            elif (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "_"
                and isinstance(node.value, ast.Call)
            ):
                call = node.value
            if call is None:
                continue
            d = dotted(call.func)
            if d in _SPAWN_EXACT or d.endswith(_SPAWN_SUFFIX):
                yield self.finding(
                    src.rel, call.lineno,
                    f"{d}(...) result dropped — keep a reference (the loop "
                    f"holds only a weak one) or await it",
                    col=call.col_offset,
                )
                continue
            last = d.split(".")[-1]
            if last in local_async and (d == last or d == f"self.{last}"):
                yield self.finding(
                    src.rel, call.lineno,
                    f"coroutine {d}(...) is never awaited — the call "
                    f"builds the coroutine object and discards it",
                    col=call.col_offset,
                )
