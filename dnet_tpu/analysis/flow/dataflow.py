"""Worklist dataflow over :mod:`dnet_tpu.analysis.flow.cfg` graphs.

Three small, composable pieces:

- :func:`node_defs` / :func:`node_uses` — dotted-name def/use extraction
  for one CFG node, at the granularity the checks reason in (``x``,
  ``self.kv_store.kv``); subscript/attribute stores on a tracked name
  count as *uses* of the base object, not kills (mutating a donated
  buffer is a read of freed memory, not a rebind).
- :func:`solve_forward` / :func:`solve_backward` — generic worklist
  solvers over set-valued facts with a pluggable join (union = may,
  intersection = must).
- :func:`reaching_definitions`, :func:`live_names`,
  :func:`definitely_assigned` — the three instantiations the DL021-025
  passes use, exposed for the CFG/solver unit tests.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from dnet_tpu.analysis.core import dotted
from dnet_tpu.analysis.flow.cfg import CFG, Node

__all__ = [
    "node_defs",
    "node_uses",
    "solve_forward",
    "solve_backward",
    "reaching_definitions",
    "live_names",
    "definitely_assigned",
]

_OPAQUE = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)


def _walk_shallow(node: ast.AST) -> Iterable[ast.AST]:
    """Walk without descending into nested function/class scopes."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        if isinstance(cur, _OPAQUE) and cur is not node:
            continue
        stack.extend(ast.iter_child_nodes(cur))


def anchor_roots(stmt: Optional[ast.AST]) -> List[ast.AST]:
    """The expressions a node actually *evaluates*: a branch anchor
    evaluates only its test/iter/context items, NOT its body — the body's
    statements are their own CFG nodes, and double-scanning them here
    would smear their defs/uses onto the header."""
    if stmt is None:
        return []
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, ast.ExceptHandler):
        return [stmt.type] if stmt.type is not None else []
    return [stmt]


_COMPOUND = (
    ast.If, ast.While, ast.For, ast.AsyncFor, ast.With, ast.AsyncWith,
    ast.ExceptHandler,
)


def _target_names(target: ast.AST) -> Set[str]:
    """Names *bound* (killed) by an assignment target.  Only plain names
    and exact dotted chains rebind; ``x[i] = v`` / ``x.attr[i] = v``
    mutate, which is a use of ``x``, not a kill."""
    out: Set[str] = set()
    if isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            out |= _target_names(elt)
    elif isinstance(target, ast.Starred):
        out |= _target_names(target.value)
    elif isinstance(target, (ast.Name, ast.Attribute)):
        d = dotted(target)
        if d:
            out.add(d)
    return out


def node_defs(node: Node) -> Set[str]:
    """Dotted names this node (re)binds."""
    stmt = node.stmt
    out: Set[str] = set()
    if stmt is None:
        return out
    if isinstance(stmt, _COMPOUND):
        # only the header's own bindings: for-targets, with-as names, the
        # except name, and walrus bindings inside the evaluated exprs —
        # the body's assignments belong to the body's own nodes
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            out |= _target_names(stmt.target)
        elif isinstance(stmt, ast.ExceptHandler) and stmt.name:
            out.add(stmt.name)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if item.optional_vars is not None:
                    out |= _target_names(item.optional_vars)
        for root in anchor_roots(stmt):
            for sub in _walk_shallow(root):
                if isinstance(sub, ast.NamedExpr):
                    out |= _target_names(sub.target)
        return out
    for sub in _walk_shallow(stmt):
        if isinstance(sub, ast.Assign):
            for t in sub.targets:
                out |= _target_names(t)
        elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
            out |= _target_names(sub.target)
        elif isinstance(sub, ast.NamedExpr):
            out |= _target_names(sub.target)
        elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            out.add(sub.name)
        elif isinstance(sub, (ast.Import, ast.ImportFrom)):
            for alias in sub.names:
                out.add((alias.asname or alias.name).split(".")[0])
    return out


def node_uses(node: Node) -> Set[str]:
    """Dotted names this node reads.  Every prefix of a read chain counts
    (``self.kv_store.kv`` uses ``self.kv_store.kv`` AND ``self.kv_store``)
    so a taint on either level is seen; AugAssign targets and
    subscript/attribute stores read their base."""
    out: Set[str] = set()

    def add_chain(d: str) -> None:
        parts = d.split(".")
        for i in range(1, len(parts) + 1):
            out.add(".".join(parts[:i]))

    for root in anchor_roots(node.stmt):
        for sub in _walk_shallow(root):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                add_chain(sub.id)
            elif isinstance(sub, ast.Attribute) and isinstance(
                sub.ctx, ast.Load
            ):
                d = dotted(sub)
                if d:
                    add_chain(d)
            elif isinstance(sub, ast.AugAssign):
                d = dotted(sub.target)
                if d:
                    add_chain(d)
            elif isinstance(sub, (ast.Subscript, ast.Attribute)) and isinstance(
                sub.ctx, ast.Store
            ):
                d = dotted(sub.value)
                if d:
                    add_chain(d)  # mutating store: reads the base object
    return out


Fact = FrozenSet
_Transfer = Callable[[Node, FrozenSet], FrozenSet]
_Join = Callable[[List[FrozenSet]], FrozenSet]


def _solve(
    cfg: CFG,
    transfer: _Transfer,
    join: _Join,
    init: FrozenSet,
    boundary: FrozenSet,
    forward: bool,
) -> Tuple[Dict[int, FrozenSet], Dict[int, FrozenSet]]:
    """Generic worklist fixpoint.  Returns ``(in_facts, out_facts)`` by
    node idx (for backward problems "in" is still the pre-transfer side,
    i.e. facts at node exit)."""
    if forward:
        start, edges_in = cfg.entry, lambda n: n.preds
    else:
        start, edges_in = cfg.exit, lambda n: n.succs
    in_f: Dict[int, FrozenSet] = {n.idx: init for n in cfg.nodes}
    out_f: Dict[int, FrozenSet] = {n.idx: init for n in cfg.nodes}
    in_f[start] = boundary
    out_f[start] = transfer(cfg.nodes[start], boundary)
    work = [n.idx for n in cfg.nodes]
    while work:
        idx = work.pop(0)
        node = cfg.nodes[idx]
        preds = edges_in(node)
        if preds:
            in_f[idx] = join([out_f[p] for p in preds])
        elif idx != start:
            in_f[idx] = join([])
        new_out = transfer(node, in_f[idx])
        if new_out != out_f[idx]:
            out_f[idx] = new_out
            nxt = node.succs if forward else node.preds
            for s in nxt:
                if s not in work:
                    work.append(s)
    return in_f, out_f


def solve_forward(cfg, transfer, join, init=frozenset(), boundary=frozenset()):
    return _solve(cfg, transfer, join, init, boundary, forward=True)


def solve_backward(cfg, transfer, join, init=frozenset(), boundary=frozenset()):
    return _solve(cfg, transfer, join, init, boundary, forward=False)


def _union(facts: List[FrozenSet]) -> FrozenSet:
    out: Set = set()
    for f in facts:
        out |= f
    return frozenset(out)


def reaching_definitions(cfg: CFG) -> Dict[int, FrozenSet]:
    """May-analysis: ``in[n]`` = set of ``(name, def_node_idx)`` pairs
    that can reach node ``n``.  A def of ``x`` kills every other def of
    ``x`` (exact-name kill — see :func:`_target_names`)."""

    def transfer(node: Node, facts: FrozenSet) -> FrozenSet:
        defs = node_defs(node)
        if not defs:
            return facts
        kept = {(n, d) for (n, d) in facts if n not in defs}
        kept |= {(n, node.idx) for n in defs}
        return frozenset(kept)

    in_f, _ = solve_forward(cfg, transfer, _union)
    return in_f


def live_names(cfg: CFG) -> Dict[int, FrozenSet]:
    """Backward may-analysis: names live (read later on some path) at
    each node's exit."""

    def transfer(node: Node, facts: FrozenSet) -> FrozenSet:
        return frozenset((facts - node_defs(node)) | node_uses(node))

    in_f, _ = solve_backward(cfg, transfer, _union)
    return in_f


def definitely_assigned(
    cfg: CFG, within: Optional[Set[int]] = None, start: Optional[int] = None
) -> Dict[int, FrozenSet]:
    """Must-analysis: names assigned on EVERY path from ``start``
    (default: entry) to each node's entry.  With ``within`` (a node-id
    region, e.g. one loop body), paths are confined to the region — the
    loop-carried-dependency test for DL024: a name NOT definitely
    assigned before its use inside the body may flow in from a previous
    iteration."""
    region = within if within is not None else {n.idx for n in cfg.nodes}
    start = start if start is not None else cfg.entry
    universe = frozenset().union(*(node_defs(n) for n in cfg.nodes)) or frozenset()

    def inter(facts: List[FrozenSet]) -> FrozenSet:
        if not facts:
            return universe  # unreached: vacuously all-assigned
        out = facts[0]
        for f in facts[1:]:
            out &= f
        return out

    in_f: Dict[int, FrozenSet] = {n.idx: universe for n in cfg.nodes}
    out_f: Dict[int, FrozenSet] = {n.idx: universe for n in cfg.nodes}
    in_f[start] = frozenset()
    out_f[start] = frozenset(node_defs(cfg.nodes[start]))
    work = [i for i in region if i != start]
    while work:
        idx = work.pop(0)
        node = cfg.nodes[idx]
        preds = [p for p in node.preds if p in region]
        in_f[idx] = inter([out_f[p] for p in preds]) if preds else universe
        new_out = frozenset(in_f[idx] | node_defs(node))
        if new_out != out_f[idx]:
            out_f[idx] = new_out
            for s in node.succs:
                if s in region and s not in work:
                    work.append(s)
    return in_f
