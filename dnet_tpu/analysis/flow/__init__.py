"""dnet_tpu.analysis.flow — the flow-sensitive dnetlint tier.

An intraprocedural CFG builder (cfg.py), a generic worklist dataflow
solver with reaching-definitions / liveness / definite-assignment
instantiations (dataflow.py), a jitted-callable resolution model
(jitmodel.py), and the five DL021-DL025 checks built on top (checks.py).
See checks.py's module docstring for the check catalog and the README
"Flow-sensitive analysis" section for how to read a DL021 trace.
"""

from dnet_tpu.analysis.flow.cfg import CFG, Node, build_cfg, function_cfgs
from dnet_tpu.analysis.flow.checks import (
    FLOW_CHECKS,
    DonationAfterUse,
    HostSyncInHotLoop,
    RetraceHazard,
    SequentialAwaitFanout,
    WireDtypeDrift,
)
from dnet_tpu.analysis.flow.dataflow import (
    definitely_assigned,
    live_names,
    node_defs,
    node_uses,
    reaching_definitions,
    solve_backward,
    solve_forward,
)
from dnet_tpu.analysis.flow.jitmodel import JitSpec, jit_bindings, resolve_jit_call

__all__ = [
    "CFG",
    "Node",
    "build_cfg",
    "function_cfgs",
    "FLOW_CHECKS",
    "DonationAfterUse",
    "RetraceHazard",
    "HostSyncInHotLoop",
    "SequentialAwaitFanout",
    "WireDtypeDrift",
    "JitSpec",
    "jit_bindings",
    "resolve_jit_call",
    "definitely_assigned",
    "live_names",
    "node_defs",
    "node_uses",
    "reaching_definitions",
    "solve_backward",
    "solve_forward",
]
