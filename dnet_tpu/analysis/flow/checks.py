"""Flow-sensitive checks DL021-DL025 (dnetlint v2).

Per-node pattern matching (DL001-DL020) cannot see "used *after*" or
"inside *this* loop".  These five passes run the CFG + dataflow tier
(flow/cfg.py, flow/dataflow.py) over each function:

DL021 — donation-after-use: a name passed at a ``donate_argnums`` /
``donate_argnames`` position of a jitted callable (resolved through
``instrument_jit`` wrappers, factory methods, and ``*args`` tuples — see
flow/jitmodel.py) is read on some CFG path after the call without being
reassigned.  XLA frees donated buffers; on CPU the read silently works,
on TPU it is garbage.  The sanctioned quiet pattern is the
donate-and-rebind idiom: ``self.kv_store.kv = step(self.kv_store.kv,
...)`` — the rebind kills the stale name on every path.

DL022 — retrace hazards: (a) a raw Python numeric literal or a
``.shape``-derived scalar passed at a NON-static position of a jitted
callable — wrap it in ``jnp.asarray``/``jnp.int32`` (traced array) or
declare the position static; a host scalar that varies re-traces per
value, which is PR 12's mid-run width-compile stall; (b) call sites of
the same jitted callable whose keyword sets (or positional arity, when
the callee's signature cannot absorb the difference) drift — every
distinct signature is a separate compiled program.

DL023 — host sync in a hot loop: the flow refinement of DL005, scoped to
the decode/tick modules (core/batch.py, core/engine.py, sched/).  A
``.item()`` / ``np.asarray`` / ``device_get`` / ``block_until_ready``
INSIDE a per-token or per-tick loop serializes the async dispatch
pipeline once per iteration.  Straight-line packed readbacks (the one
sanctioned per-dispatch sample read) are outside any loop and stay
quiet naturally; obs-gated phase fences are exempted by the same gate
test as DL005.

DL024 — sequential independent awaits in a loop: an ``await`` inside a
``for`` whose iterations carry no data dependency (checked with a
must-assigned analysis confined to the loop body: every name the await
statement reads is either loop-invariant or definitely assigned earlier
in the SAME iteration) serializes a fan-out — N round trips instead of
one ``asyncio.gather``.  Ordered sinks (``.write``/``.drain``), pacing
(``asyncio.sleep``), executor hops (``run_in_executor`` — the compute
executor serializes by ownership contract), latency-measurement loops
(a host clock read in the body: the sequencing IS the measurement), and
loops with ``break``/``return`` early exits are exempt.

DL025 — activation-wire dtype drift: a tensor serialized onto the ring
(``tensor_to_bytes``) or reconstructed from a frame
(``bytes_to_tensor``) with a hard-coded FLOAT dtype — a literal
``np.float32`` construction or a ``"bfloat16"`` string — instead of the
configured wire dtype (``self.wire_dtype`` / model config).  When the
operator flips ``wire_dtype``, a literal site silently keeps shipping
the old width.  Integer/bool payloads (token frames are int32 by
protocol) and the sentinel frame tags (``"tokens"``/``"error"``) are
exempt.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from dnet_tpu.analysis.core import (
    Check,
    Finding,
    Project,
    SourceFile,
    dotted,
    is_serving_path,
    scoped_walk,
)
from dnet_tpu.analysis.flow.cfg import CFG, Node, build_cfg
from dnet_tpu.analysis.flow.dataflow import (
    anchor_roots,
    definitely_assigned,
    node_defs,
    node_uses,
)
from dnet_tpu.analysis.flow.jitmodel import (
    JitSpec,
    jit_bindings,
    resolve_jit_call,
)

_FN_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _functions(tree: ast.AST) -> Iterable[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, _FN_DEFS):
            yield node


def _file_bindings(src: SourceFile) -> Dict[str, JitSpec]:
    """jit_bindings memoized on the SourceFile — several flow checks need
    the same pure result for the same unchanged AST."""
    cached = getattr(src, "_flow_jit_bindings", None)
    if cached is None:
        cached = jit_bindings(src)
        src._flow_jit_bindings = cached
    return cached


def _fn_cfg(src: SourceFile, fn: ast.AST) -> CFG:
    """build_cfg memoized per (file, function def)."""
    cache = getattr(src, "_flow_cfg_cache", None)
    if cache is None:
        cache = {}
        src._flow_cfg_cache = cache
    cfg = cache.get(id(fn))
    if cfg is None:
        cfg = build_cfg(fn)
        cache[id(fn)] = cfg
    return cfg


def _anchor_calls(node: Node) -> Iterable[ast.Call]:
    """Calls evaluated by this CFG node (shallow: nested defs opaque;
    compound headers contribute only their test/iter/context exprs)."""
    stack = list(anchor_roots(node.stmt))
    while stack:
        cur = stack.pop()
        if isinstance(cur, _FN_DEFS + (ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(cur, ast.Call):
            yield cur
        stack.extend(ast.iter_child_nodes(cur))


def _positional_exprs(
    call: ast.Call, fn: ast.AST
) -> Optional[List[ast.AST]]:
    """The call's effective positional expressions, resolving a single
    ``*args`` splat through the unique local ``args = (...)`` tuple
    assignment (the ``self._step(*args)`` idiom).  None when a splat
    cannot be resolved."""
    out: List[ast.AST] = []
    for arg in call.args:
        if not isinstance(arg, ast.Starred):
            out.append(arg)
            continue
        name = dotted(arg.value)
        if not name:
            return None
        tuples = [
            a.value
            for a in ast.walk(fn)
            if isinstance(a, ast.Assign)
            and isinstance(a.value, ast.Tuple)
            and any(dotted(t) == name for t in a.targets)
        ]
        if len(tuples) != 1:
            return None
        out.extend(tuples[0].elts)
    return out


# ---- DL021 ----------------------------------------------------------------


class DonationAfterUse(Check):
    code = "DL021"
    name = "donation-after-use"
    description = (
        "a name passed at a donate_argnums position of a jitted callable "
        "is read on a CFG path after the call without reassignment — XLA "
        "freed that buffer; rebind the result (self.kv = step(self.kv, ...))"
    )

    def run_file(self, src: SourceFile, project: Project) -> Iterable[Finding]:
        bindings = _file_bindings(src)
        if not bindings:
            return
        for fn in _functions(src.tree):
            yield from self._check_fn(src, fn, bindings)

    def _check_fn(self, src, fn, bindings) -> Iterable[Finding]:
        cfg = _fn_cfg(src, fn)
        emitted: Set[Tuple[int, str]] = set()
        for node in cfg.nodes:
            for call in _anchor_calls(node):
                spec = resolve_jit_call(call, bindings, src)
                if spec is None or not spec.exact:
                    continue
                if not spec.donate and not spec.donate_names:
                    continue
                for pos, name in self._donated_names(call, fn, spec):
                    yield from self._trace(
                        src, cfg, node, call, spec, pos, name, emitted
                    )

    @staticmethod
    def _donated_names(
        call: ast.Call, fn: ast.AST, spec: JitSpec
    ) -> Iterable[Tuple[str, str]]:
        """(position-label, dotted-name) pairs actually donated here."""
        exprs = _positional_exprs(call, fn)
        if exprs is not None:
            for i in spec.donate:
                if i < len(exprs):
                    d = dotted(exprs[i])
                    if d:
                        yield f"arg {i}", d
        for kw in call.keywords:
            if kw.arg is not None and kw.arg in spec.donate_names:
                d = dotted(kw.value)
                if d:
                    yield f"arg {kw.arg!r}", d

    def _trace(
        self, src, cfg: CFG, node: Node, call, spec, pos, name, emitted
    ) -> Iterable[Finding]:
        # the donate-and-rebind idiom: the calling statement itself
        # rebinds the donated name (self.kv = self._scatter(self.kv, ...))
        if name in node_defs(node):
            return
        seen: Set[int] = set()
        stack = list(node.succs)
        while stack:
            idx = stack.pop()
            if idx in seen:
                continue
            seen.add(idx)
            cur = cfg.nodes[idx]
            if name in node_uses(cur):
                key = (cur.line, name)
                if key not in emitted:
                    emitted.add(key)
                    yield self.finding(
                        src.rel, cur.line,
                        f"'{name}' was donated to {spec.label}() ({pos}, "
                        f"donate_argnums at line {spec.lineno}) and is read "
                        f"here without reassignment — XLA freed that "
                        f"buffer; rebind the call's result first",
                    )
                continue  # report the first use per path
            if name in node_defs(cur):
                continue  # rebound: this path is safe
            stack.extend(cur.succs)


# ---- DL022 ----------------------------------------------------------------


def _scalar_hazard(expr: ast.AST) -> Optional[str]:
    """'Python literal' / '.shape-derived scalar' when ``expr`` is a raw
    host scalar of that kind; None otherwise.  Anything wrapped in a call
    (jnp.asarray(...), jnp.int32(...)) is already an array — quiet."""
    if isinstance(expr, ast.Constant):
        if type(expr.value) in (int, float):
            return "Python literal"
        return None
    if isinstance(expr, ast.UnaryOp):
        return _scalar_hazard(expr.operand)
    if isinstance(expr, ast.Subscript):
        base = dotted(expr.value)
        if base.endswith(".shape") or base == "shape":
            return ".shape-derived scalar"
        return None
    if isinstance(expr, ast.Attribute):
        return None
    if isinstance(expr, ast.BinOp):
        left = _scalar_hazard(expr.left)
        right = _scalar_hazard(expr.right)
        if left is None and right is None:
            return None
        sides = []
        for side, hazard in ((expr.left, left), (expr.right, right)):
            if hazard is None and not isinstance(side, ast.Constant):
                return None  # mixed with a real array/name: not a raw scalar
            sides.append(hazard)
        return next(
            (h for h in sides if h == ".shape-derived scalar"),
            next((h for h in sides if h), None),
        )
    return None


class RetraceHazard(Check):
    code = "DL022"
    name = "retrace-hazard"
    description = (
        "a raw Python literal or .shape-derived scalar at a non-static "
        "position of a jitted callable, or call-site keyword/arity drift "
        "across sites — each distinct host signature is a fresh trace + "
        "compile (the mid-run width-compile stall)"
    )

    def run_file(self, src: SourceFile, project: Project) -> Iterable[Finding]:
        bindings = _file_bindings(src)
        if not bindings:
            return
        callee_spans = self._callee_spans(src)
        #: one jit binding (spec) -> list of (line, n_pos, kwset)
        sites: Dict[JitSpec, List[Tuple[int, int, frozenset]]] = {}
        for fn in _functions(src.tree):
            # shallow walk: a nested def's calls belong to the nested
            # scope's own visit (whose locals resolve *args tuples)
            for node in scoped_walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                spec = resolve_jit_call(node, bindings, src)
                if spec is None:
                    continue
                exprs = _positional_exprs(node, fn)
                if exprs is None:
                    continue
                if spec.exact:
                    yield from self._scalar_findings(src, node, spec, exprs)
                kws = frozenset(
                    kw.arg for kw in node.keywords if kw.arg is not None
                )
                sites.setdefault(spec, []).append(
                    (node.lineno, len(exprs), kws)
                )
        yield from self._drift_findings(src, sites, callee_spans)

    def _scalar_findings(self, src, call, spec, exprs) -> Iterable[Finding]:
        for i, expr in enumerate(exprs):
            if i in spec.static:
                continue
            hazard = _scalar_hazard(expr)
            if hazard is not None:
                yield self.finding(
                    src.rel, expr.lineno,
                    f"{hazard} passed at non-static position {i} of jitted "
                    f"{spec.label}() — a varying host scalar re-traces per "
                    f"value; pass a jnp array or declare the position "
                    f"static_argnums",
                    col=expr.col_offset,
                )
        for kw in call.keywords:
            if kw.arg is None or kw.arg in spec.static_names:
                continue
            hazard = _scalar_hazard(kw.value)
            if hazard is not None:
                yield self.finding(
                    src.rel, kw.value.lineno,
                    f"{hazard} passed at non-static keyword {kw.arg!r} of "
                    f"jitted {spec.label}() — pass a jnp array or declare "
                    f"it static_argnames",
                    col=kw.value.col_offset,
                )

    @staticmethod
    def _callee_spans(src: SourceFile) -> Dict[str, Tuple[int, int, bool]]:
        """def name -> (required positional, total positional, *args?)
        so optional-parameter differences across sites don't count as
        drift."""
        spans: Dict[str, Tuple[int, int, bool]] = {}
        for fn in _functions(src.tree):
            args = fn.args
            total = len(args.posonlyargs) + len(args.args)
            required = total - len(args.defaults)
            spans[fn.name] = (required, total, args.vararg is not None)
        return spans

    def _drift_findings(self, src, sites, callee_spans) -> Iterable[Finding]:
        for spec, calls in sorted(
            sites.items(), key=lambda kv: (kv[0].label, kv[0].lineno)
        ):
            if len(calls) < 2:
                continue
            span = callee_spans.get(spec.fn_name)

            def absorbed(n1: int, n2: int) -> bool:
                """Both arities are valid fills of the callee's signature
                (defaulted trailing params / *args) — one contract, not
                drift."""
                return span is not None and (
                    span[2]
                    or (span[0] <= n1 <= span[1] and span[0] <= n2 <= span[1])
                )

            # each differing site is judged per dimension: a kwarg-set
            # difference is always drift (jit caches kwargs separately),
            # an arity difference only when the callee cannot absorb it
            ref_line, ref_n, ref_kws = calls[0]
            for line, n, kws in calls[1:]:
                if kws != ref_kws:
                    what = f"keywords {sorted(kws)} vs {sorted(ref_kws)}"
                elif n != ref_n and not absorbed(n, ref_n):
                    what = f"arity {n} vs {ref_n}"
                else:
                    continue
                yield self.finding(
                    src.rel, line,
                    f"call-site signature of jitted {spec.label}() drifts "
                    f"across sites ({what}, first site at line "
                    f"{ref_line}) — every distinct host signature "
                    f"is a separate compiled program",
                )


# ---- DL023 ----------------------------------------------------------------

_SYNC_ATTRS = {"item", "block_until_ready"}
_SYNC_DOTTED = {
    "jax.block_until_ready",
    "jax.device_get",
    "np.asarray",
    "np.array",
    "numpy.asarray",
    "numpy.array",
}

#: the decode/tick hot-loop surface
HOT_LOOP_FILES = ("dnet_tpu/core/batch.py", "dnet_tpu/core/engine.py")
HOT_LOOP_PREFIXES = ("dnet_tpu/sched/",)


class HostSyncInHotLoop(Check):
    code = "DL023"
    name = "host-sync-in-hot-loop"
    description = (
        ".item() / np.asarray / device_get / block_until_ready inside a "
        "per-token or per-tick loop of the decode modules, outside obs "
        "gating — one forced sync per iteration serializes the dispatch "
        "pipeline (flow-refined DL005)"
    )

    def run_file(self, src: SourceFile, project: Project) -> Iterable[Finding]:
        if src.rel not in HOT_LOOP_FILES and not src.rel.startswith(
            HOT_LOOP_PREFIXES
        ):
            return
        from dnet_tpu.analysis.checks_jit import UngatedDeviceSync

        for fn in _functions(src.tree):
            cfg = _fn_cfg(src, fn)
            for node in cfg.nodes:
                if not node.loops:
                    continue
                for call in _anchor_calls(node):
                    what = self._sync_name(call)
                    if what is None:
                        continue
                    if UngatedDeviceSync._gated(src, call):
                        continue
                    yield self.finding(
                        src.rel, call.lineno,
                        f"forced host sync {what}() inside the "
                        f"{fn.name}() loop at line "
                        f"{cfg.nodes[node.loops[-1]].line} — one device "
                        f"fence per iteration; hoist it out of the loop "
                        f"or gate it on obs",
                        col=call.col_offset,
                    )

    @staticmethod
    def _sync_name(call: ast.Call) -> Optional[str]:
        d = dotted(call.func)
        if d in _SYNC_DOTTED:
            return d
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in _SYNC_ATTRS
            and not call.args
            and not call.keywords
        ):
            return d or call.func.attr
        return None


# ---- DL024 ----------------------------------------------------------------

_CLOCKS = {"time.perf_counter", "time.monotonic", "time.time", "loop.time"}
_AWAIT_EXEMPT_SUFFIX = (".run_in_executor", ".write", ".drain")
_AWAIT_EXEMPT_EXACT = {"asyncio.sleep"}


class SequentialAwaitFanout(Check):
    code = "DL024"
    name = "sequential-await-in-loop"
    description = (
        "await in a for loop with no loop-carried data dependency — N "
        "sequential round trips where one asyncio.gather would do; "
        "ordered sinks, sleeps, executor hops, measurement loops, and "
        "break/return loops are exempt"
    )

    def run_file(self, src: SourceFile, project: Project) -> Iterable[Finding]:
        if not is_serving_path(src.rel):
            return
        for fn in _functions(src.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            cfg = _fn_cfg(src, fn)
            for header in cfg.loop_headers():
                if not isinstance(header.stmt, ast.For):
                    continue  # async-for iterators and while loops are
                    # inherently sequential / state-driven
                finding = self._check_loop(src, fn, cfg, header)
                if finding is not None:
                    yield finding

    def _check_loop(self, src, fn, cfg: CFG, header: Node) -> Optional[Finding]:
        body = [n for n in cfg.nodes if header.idx in n.loops]
        own = [n for n in body if n.loops and n.loops[-1] == header.idx]
        # early-exit loops: sequencing is the semantics
        for n in body:
            if isinstance(n.stmt, (ast.Break, ast.Return)):
                return None
        # measurement loops: a host clock read means the await is being
        # timed — gathering would corrupt the measurement
        for n in body:
            for call in _anchor_calls(n):
                if dotted(call.func) in _CLOCKS:
                    return None
        region = {header.idx} | {n.idx for n in body}
        assigned = definitely_assigned(cfg, within=region, start=header.idx)
        written: Set[str] = set()
        for n in body:
            written |= node_defs(n)
        awaits: List[Tuple[Node, ast.Await]] = []
        for n in own:
            stack = list(anchor_roots(n.stmt))
            while stack:
                cur = stack.pop()
                if isinstance(cur, _FN_DEFS + (ast.ClassDef, ast.Lambda)):
                    continue
                if isinstance(cur, ast.Await):
                    awaits.append((n, cur))
                stack.extend(ast.iter_child_nodes(cur))
        for node, awaited in awaits:
            if self._exempt_await(awaited):
                continue
            reads = node_uses(node)
            carried = {
                name
                for name in reads & written
                if name not in assigned[node.idx]
            }
            if carried:
                continue
            return self.finding(
                src.rel, awaited.lineno,
                f"sequential await in the {fn.name}() loop at line "
                f"{header.line} with no loop-carried dependency — fan "
                f"out with asyncio.gather instead of one round trip per "
                f"iteration",
                col=awaited.col_offset,
            )
        return None

    @staticmethod
    def _exempt_await(awaited: ast.Await) -> bool:
        value = awaited.value
        if not isinstance(value, ast.Call):
            return False
        d = dotted(value.func)
        return (
            d in _AWAIT_EXEMPT_EXACT
            or d.startswith("asyncio.sleep")
            or d.endswith(_AWAIT_EXEMPT_SUFFIX)
        )


# ---- DL025 ----------------------------------------------------------------

_FLOAT_DTYPE_STRINGS = {
    "float32", "float16", "bfloat16", "float64", "f32", "f16", "bf16",
    "f64", "float8_e4m3", "float8_e5m2",
}
_FLOAT_DTYPE_DOTTED = {
    "np.float32", "np.float16", "np.float64", "numpy.float32",
    "numpy.float16", "numpy.float64", "jnp.float32", "jnp.float16",
    "jnp.bfloat16", "jax.numpy.bfloat16", "ml_dtypes.bfloat16",
    "ml_dtypes.float8_e4m3fn", "ml_dtypes.float8_e5m2",
}

#: modules that build / parse wire frames
_WIRE_PREFIXES = ("dnet_tpu/shard/", "dnet_tpu/transport/", "dnet_tpu/api/")


def _float_literal_dtype(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value in _FLOAT_DTYPE_STRINGS
    return dotted(expr) in _FLOAT_DTYPE_DOTTED


def _construction_dtype_literal(expr: ast.AST) -> Optional[ast.AST]:
    """The literal FLOAT dtype node inside a tensor-construction
    expression (np.zeros(..., np.float32), x.astype('float32'), ...)."""
    for sub in ast.walk(expr):
        if not isinstance(sub, ast.Call):
            continue
        for kw in sub.keywords:
            if kw.arg == "dtype" and _float_literal_dtype(kw.value):
                return kw.value
        func = sub.func
        name = func.attr if isinstance(func, ast.Attribute) else dotted(func)
        if name.split(".")[-1] in (
            "zeros", "ones", "full", "empty", "asarray", "array", "astype"
        ):
            for arg in sub.args:
                if _float_literal_dtype(arg):
                    return arg
    return None


class WireDtypeDrift(Check):
    code = "DL025"
    name = "wire-dtype-drift"
    description = (
        "an activation serialized (tensor_to_bytes) or parsed "
        "(bytes_to_tensor) at a hard-coded float dtype instead of the "
        "configured wire dtype — flipping wire_dtype would silently skip "
        "this site; int/bool token payloads are protocol-fixed and exempt"
    )

    def run_file(self, src: SourceFile, project: Project) -> Iterable[Finding]:
        if not src.rel.startswith(_WIRE_PREFIXES):
            return
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted(node.func).split(".")[-1]
            if fname == "tensor_to_bytes":
                yield from self._check_serialize(src, node)
            elif fname in ("bytes_to_tensor", "bytes_to_device"):
                yield from self._check_parse(src, node)

    def _check_serialize(self, src, call: ast.Call) -> Iterable[Finding]:
        wire = call.args[1] if len(call.args) > 1 else None
        for kw in call.keywords:
            if kw.arg == "wire_dtype":
                wire = kw.value
        if wire is not None and _float_literal_dtype(wire):
            yield self.finding(
                src.rel, wire.lineno,
                "wire dtype hard-coded at a tensor_to_bytes call — derive "
                "it from the configured wire_dtype (config/model), not a "
                "literal",
                col=wire.col_offset,
            )
            return
        if wire is None and call.args:
            literal = _construction_dtype_literal(call.args[0])
            if literal is not None:
                yield self.finding(
                    src.rel, literal.lineno,
                    "activation built at a literal float dtype and "
                    "serialized without a wire_dtype — pass the configured "
                    "wire dtype to tensor_to_bytes or derive the "
                    "construction dtype from config",
                    col=literal.col_offset,
                )

    def _check_parse(self, src, call: ast.Call) -> Iterable[Finding]:
        dtype = call.args[1] if len(call.args) > 1 else None
        for kw in call.keywords:
            if kw.arg == "dtype":
                dtype = kw.value
        if dtype is not None and _float_literal_dtype(dtype):
            yield self.finding(
                src.rel, dtype.lineno,
                "frame payload parsed at a hard-coded float dtype — use "
                "the dtype the frame header declares",
                col=dtype.col_offset,
            )


FLOW_CHECKS = [
    DonationAfterUse(),
    RetraceHazard(),
    HostSyncInHotLoop(),
    SequentialAwaitFanout(),
    WireDtypeDrift(),
]
