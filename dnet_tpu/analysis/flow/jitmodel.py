"""Resolution of jitted callables and their donation/static contracts.

The flow passes need to know, for a call like ``self._step(*args)``, that
``self._step`` is ``jax.jit(fn, donate_argnums=(3, 8))`` — possibly
wrapped in ``instrument_jit`` (the ``JIT_FNS`` seed set from
``obs.phases``) and possibly produced by a factory method
(``self._chunk_fn(R)`` returning a per-width jitted program).  This module
builds that map per source file with the same call-graph spirit as DL004:

- direct bindings: ``x = jax.jit(f, ...)``, ``self._step =
  instrument_jit(jax.jit(f, donate_argnums=(3, 8)), "batched_step")``,
  dict-literal bindings (``self._programs = {"head": jax.jit(...)}``)
  keyed by their constant string;
- decorator entries: ``@jax.jit`` / ``@partial(jax.jit, ...)`` defs;
- factories: a function whose return value resolves to a jit binding
  (returning the jit call directly, or a local name bound to one)
  registers under ``<fname>()`` so ``self._chunk_fn(R)(*args)`` resolves.

``donate_argnums`` / ``static_argnums`` are honoured only when literal
ints/tuples — a computed tuple (``donate_argnums=donate``) yields a spec
with unknown donation, which the passes treat as "don't know, stay
quiet" rather than guessing.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Tuple

from dnet_tpu.analysis.core import SourceFile, dotted

__all__ = ["JitSpec", "jit_bindings", "resolve_jit_call"]

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
_WRAPPERS = {"instrument_jit", "obs.jit.instrument_jit"}
_PARTIAL = {"partial", "functools.partial"}


@dataclasses.dataclass(frozen=True)
class JitSpec:
    """One jitted callable's call contract."""

    label: str                       #: display name (binding or JIT_FNS label)
    donate: Tuple[int, ...] = ()     #: literal donate_argnums
    donate_names: Tuple[str, ...] = ()
    static: Tuple[int, ...] = ()     #: literal static_argnums
    static_names: Tuple[str, ...] = ()
    lineno: int = 0
    #: the wrapped function's name (jax.jit's first arg) when it is a
    #: plain name — lets DL022 look the callee's signature span up
    fn_name: str = ""
    #: False when donate/static kwargs were present but not literal —
    #: the passes must not reason about positions they cannot see
    exact: bool = True


def _int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[int] = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant) and isinstance(elt.value, int)):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


def _str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[str] = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant) and isinstance(elt.value, str)):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


def _spec_from_jit_call(call: ast.Call, label: str) -> JitSpec:
    donate: Tuple[int, ...] = ()
    donate_names: Tuple[str, ...] = ()
    static: Tuple[int, ...] = ()
    static_names: Tuple[str, ...] = ()
    exact = True
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            got = _int_tuple(kw.value)
            if got is None:
                exact = False
            else:
                donate = got
        elif kw.arg == "donate_argnames":
            got_s = _str_tuple(kw.value)
            if got_s is None:
                exact = False
            else:
                donate_names = got_s
        elif kw.arg == "static_argnums":
            got = _int_tuple(kw.value)
            if got is None:
                exact = False
            else:
                static = got
        elif kw.arg == "static_argnames":
            got_s = _str_tuple(kw.value)
            if got_s is None:
                exact = False
            else:
                static_names = got_s
    fn_name = dotted(call.args[0]).split(".")[-1] if call.args else ""
    return JitSpec(
        label=label, donate=donate, donate_names=donate_names,
        static=static, static_names=static_names,
        lineno=call.lineno, fn_name=fn_name, exact=exact,
    )


def _unwrap_jit(node: ast.AST) -> Optional[Tuple[ast.Call, Optional[str]]]:
    """``(jit_call, instrument_label)`` if ``node`` is a jax.jit call,
    possibly wrapped in instrument_jit / functools.partial."""
    if not isinstance(node, ast.Call):
        return None
    d = dotted(node.func)
    if d in _JIT_NAMES:
        return node, None
    if (d in _WRAPPERS or d.split(".")[-1] == "instrument_jit") and node.args:
        inner = _unwrap_jit(node.args[0])
        if inner is not None:
            label = None
            if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
                label = str(node.args[1].value)
            return inner[0], label or inner[1]
    if d in _PARTIAL and node.args:
        return _unwrap_jit(node.args[0])
    return None


def _returned_spec(fn: ast.AST) -> Optional[JitSpec]:
    """Spec of the jitted callable a factory returns: either the jit call
    directly, or a local name bound to one anywhere in the factory."""
    local: Dict[str, JitSpec] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            hit = _unwrap_jit(node.value)
            if hit is None:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    local[t.id] = _spec_from_jit_call(
                        hit[0], hit[1] or t.id
                    )
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            hit = _unwrap_jit(node.value)
            if hit is not None:
                return _spec_from_jit_call(hit[0], hit[1] or fn.name)
            d = dotted(node.value)
            if d in local:
                return local[d]
    return None


def scope_chain(src: SourceFile, node: ast.AST) -> Tuple[str, ...]:
    """Names of the function defs enclosing ``node``, outermost first."""
    names: List[str] = []
    for anc in src.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.append(anc.name)
    return tuple(reversed(names))


def _scoped_key(chain: Tuple[str, ...], name: str) -> str:
    return f"{'/'.join(chain)}:{name}" if chain else name


def jit_bindings(src: SourceFile) -> Dict[str, JitSpec]:
    """dotted binding -> :class:`JitSpec` for one module.

    Keys are the names call sites use: ``self._step``, ``step_fn``,
    ``self._programs['head']`` (dict-literal bindings), and
    ``self._chunk_fn()`` / ``_make_chunk()`` (factories — the trailing
    ``()`` marks "the value this callable returns").  Plain-name bindings
    inside a function are scoped to it (``'outer/inner:name'``) so two
    factories' local ``jitted`` variables never collide; dotted
    (``self.*``) bindings are module-wide."""
    out: Dict[str, JitSpec] = {}
    tree = src.tree
    if tree is None:
        return out
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            hit = _unwrap_jit(node.value)
            if hit is not None:
                for t in node.targets:
                    d = dotted(t)
                    if not d:
                        continue
                    if isinstance(t, ast.Name):
                        chain = scope_chain(src, node)
                        out[_scoped_key(chain, d)] = _spec_from_jit_call(
                            hit[0], hit[1] or d
                        )
                    else:
                        out[d] = _spec_from_jit_call(hit[0], hit[1] or d)
                continue
            if isinstance(node.value, ast.Dict):
                for k, v in zip(node.value.keys, node.value.values):
                    hit = _unwrap_jit(v)
                    if hit is None or not (
                        isinstance(k, ast.Constant) and isinstance(k.value, str)
                    ):
                        continue
                    for t in node.targets:
                        d = dotted(t)
                        if d:
                            key = f"{d}[{k.value!r}]"
                            out[key] = _spec_from_jit_call(hit[0], hit[1] or key)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if dotted(dec) in _JIT_NAMES:
                    out[node.name] = JitSpec(label=node.name, lineno=node.lineno)
                elif isinstance(dec, ast.Call):
                    hit = _unwrap_jit(dec)
                    if hit is not None:
                        out[node.name] = _spec_from_jit_call(hit[0], node.name)
            spec = _returned_spec(node)
            if spec is not None:
                out[f"{node.name}()"] = spec
                out[f"self.{node.name}()"] = spec
    return out


def resolve_jit_call(
    call: ast.Call,
    bindings: Dict[str, JitSpec],
    src: Optional[SourceFile] = None,
) -> Optional[JitSpec]:
    """The spec a call site dispatches to, or None.

    Handles ``self._step(...)`` (direct), ``self._chunk_fn(R)(...)``
    (factory result), and ``self._programs['head'](...)`` (dict
    binding).  With ``src``, plain-name lookups walk the call's scope
    chain innermost-out, matching the function-scoped binding keys."""
    func = call.func
    d = dotted(func)
    if d:
        if isinstance(func, ast.Name) and src is not None:
            chain = scope_chain(src, call)
            for i in range(len(chain), -1, -1):
                spec = bindings.get(_scoped_key(chain[:i], d))
                if spec is not None:
                    return spec
        spec = bindings.get(d)
        if spec is not None:
            return spec
        short = d.split(".", 1)[-1] if d.startswith("self.") else d
        return bindings.get(short)
    if isinstance(func, ast.Call):
        fd = dotted(func.func)
        if fd:
            return bindings.get(f"{fd}()") or bindings.get(
                f"{fd.split('.', 1)[-1] if fd.startswith('self.') else fd}()"
            )
    if isinstance(func, ast.Subscript):
        base = dotted(func.value)
        if base and isinstance(func.slice, ast.Constant) and isinstance(
            func.slice.value, str
        ):
            return bindings.get(f"{base}[{func.slice.value!r}]")
    return None
