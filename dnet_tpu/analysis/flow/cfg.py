"""Intraprocedural control-flow graphs over stdlib ``ast``.

One :class:`CFG` per function def: statement-granularity nodes connected
by control edges, built for the flow-sensitive dnetlint passes (DL021+).
The graph answers the two questions per-node pattern matching cannot:
"what can execute AFTER this statement" (donation-after-use) and "is this
statement INSIDE that loop" (hot-loop sync / sequential-await passes).

Design points:

- Nodes are single simple statements or branch anchors.  A compound
  statement contributes its *header* as a node (``If``/``While`` -> the
  test, ``For`` -> the iter+target bind) and its body statements as
  their own nodes — so a finding anchors to a real source line.
- Loop context is explicit: every node carries the node ids of its
  enclosing loop headers (innermost last), and back edges are recorded,
  so "reachable inside this loop" needs no dominator machinery.
- ``try`` is conservative: every node of the try body gets an edge to
  every handler entry (any statement may raise), and the ``finally``
  suite is joined on the normal exit.  That over-approximates paths —
  exactly what a may-analysis (reaching defs, reachable-use) wants.
- ``return``/``raise`` edge to the synthetic exit; ``break``/``continue``
  edge to the loop's after-node/header.  ``raise`` inside a ``try``
  edges to the handlers instead.
- Nested function/class defs are opaque single nodes (their bodies are
  their own CFG's business — same scoping rule as ``scoped_walk``).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = ["CFG", "Node", "build_cfg", "function_cfgs"]


@dataclasses.dataclass
class Node:
    """One CFG node.  ``stmt`` anchors findings and feeds def/use
    extraction; for branch anchors it is the governing expression's
    statement (the ``If``/``While``/``For`` node itself)."""

    idx: int
    stmt: Optional[ast.AST]
    kind: str  # 'entry' | 'exit' | 'stmt' | 'branch' | 'loop'
    succs: List[int] = dataclasses.field(default_factory=list)
    preds: List[int] = dataclasses.field(default_factory=list)
    #: enclosing loop-header node ids, innermost last
    loops: Tuple[int, ...] = ()

    @property
    def line(self) -> int:
        return getattr(self.stmt, "lineno", 0)


class CFG:
    def __init__(self, fn: ast.AST) -> None:
        self.fn = fn
        self.nodes: List[Node] = []
        self.entry = self._new(None, "entry")
        self.exit = self._new(None, "exit")
        self.back_edges: Set[Tuple[int, int]] = set()

    # ---- construction helpers ----------------------------------------
    def _new(self, stmt: Optional[ast.AST], kind: str, loops: Tuple[int, ...] = ()) -> int:
        node = Node(idx=len(self.nodes), stmt=stmt, kind=kind, loops=loops)
        self.nodes.append(node)
        return node.idx

    def _edge(self, a: int, b: int) -> None:
        if b not in self.nodes[a].succs:
            self.nodes[a].succs.append(b)
            self.nodes[b].preds.append(a)

    # ---- queries ------------------------------------------------------
    def node_for_stmt(self, stmt: ast.AST) -> Optional[Node]:
        for n in self.nodes:
            if n.stmt is stmt:
                return n
        return None

    def nodes_in_loop(self, header_idx: int) -> List[Node]:
        return [n for n in self.nodes if header_idx in n.loops]

    def loop_headers(self) -> List[Node]:
        return [n for n in self.nodes if n.kind == "loop"]

    def reachable_from(self, idx: int) -> Iterable[Node]:
        """Nodes reachable from ``idx`` (exclusive of it unless cyclic)."""
        seen: Set[int] = set()
        stack = list(self.nodes[idx].succs)
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            yield self.nodes[cur]
            stack.extend(self.nodes[cur].succs)


class _Builder:
    def __init__(self, fn: ast.AST) -> None:
        self.cfg = CFG(fn)
        #: headers of the enclosing loops, innermost last
        self.loop_stack: List[int] = []
        #: handler entry node ids for each enclosing try (innermost last)
        self.try_stack: List[List[int]] = []
        #: loop-header idx -> break-node idxs waiting for the after-loop join
        self.breaks: Dict[int, List[int]] = {}

    # `frontier` is the set of node ids whose control falls through to
    # whatever comes next; an empty frontier means the path terminated.
    def build(self) -> CFG:
        body = getattr(self.cfg.fn, "body", [])
        frontier = self._seq(body, [self.cfg.entry])
        for idx in frontier:
            self.cfg._edge(idx, self.cfg.exit)
        return self.cfg

    def _loops(self) -> Tuple[int, ...]:
        return tuple(self.loop_stack)

    def _stmt_node(self, stmt: ast.stmt, kind: str = "stmt") -> int:
        idx = self.cfg._new(stmt, kind, self._loops())
        # any statement under a try may transfer to its handlers
        for handlers in self.try_stack:
            for h in handlers:
                self.cfg._edge(idx, h)
        return idx

    def _seq(self, stmts: List[ast.stmt], frontier: List[int]) -> List[int]:
        for stmt in stmts:
            if not frontier:
                break  # unreachable code after return/raise/break
            frontier = self._stmt(stmt, frontier)
        return frontier

    def _join(self, frontier: List[int], idx: int) -> None:
        for f in frontier:
            self.cfg._edge(f, idx)

    def _stmt(self, stmt: ast.stmt, frontier: List[int]) -> List[int]:
        cfg = self.cfg
        if isinstance(stmt, ast.If):
            head = self._stmt_node(stmt, "branch")
            self._join(frontier, head)
            out = self._seq(stmt.body, [head])
            out += self._seq(stmt.orelse, [head]) if stmt.orelse else [head]
            return out
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            head = self._stmt_node(stmt, "loop")
            self._join(frontier, head)
            self.loop_stack.append(head)
            body_out = self._seq(stmt.body, [head])
            self.loop_stack.pop()
            for idx in body_out:
                cfg._edge(idx, head)
                cfg.back_edges.add((idx, head))
            # the header falls through when the loop doesn't run (or its
            # test goes false); `else:` runs on that normal exit only
            normal = [head]
            if stmt.orelse:
                normal = self._seq(stmt.orelse, normal)
            return normal + self.breaks.pop(head, [])
        if isinstance(stmt, ast.Try):
            handler_entries: List[int] = []
            handler_anchors: List[Tuple[ast.ExceptHandler, int]] = []
            for handler in stmt.handlers:
                h = self.cfg._new(handler, "branch", self._loops())
                handler_entries.append(h)
                handler_anchors.append((handler, h))
            # a statement can raise BEFORE its own bindings commit, so the
            # handlers also join the state at the try's ENTRY (each body
            # node's own handler edge covers mid-body raises; this edge
            # covers the first statement failing before it binds anything)
            for f in frontier:
                for h in handler_entries:
                    self.cfg._edge(f, h)
            self.try_stack.append(handler_entries)
            body_out = self._seq(stmt.body, frontier)
            self.try_stack.pop()
            out = self._seq(stmt.orelse, body_out) if stmt.orelse else body_out
            for handler, h in handler_anchors:
                out += self._seq(handler.body, [h])
            if stmt.finalbody:
                # finally runs on every path; join the normal exits on it
                fin_in = out
                out = self._seq(stmt.finalbody, fin_in)
            return out
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            head = self._stmt_node(stmt, "stmt")
            self._join(frontier, head)
            return self._seq(stmt.body, [head])
        if isinstance(stmt, (ast.Return, ast.Raise)):
            idx = self._stmt_node(stmt)
            self._join(frontier, idx)
            cfg._edge(idx, cfg.exit)
            return []
        if isinstance(stmt, ast.Break):
            idx = self._stmt_node(stmt)
            self._join(frontier, idx)
            if self.loop_stack:
                self.breaks.setdefault(self.loop_stack[-1], []).append(idx)
            return []
        if isinstance(stmt, ast.Continue):
            idx = self._stmt_node(stmt)
            self._join(frontier, idx)
            if self.loop_stack:
                head = self.loop_stack[-1]
                cfg._edge(idx, head)
                cfg.back_edges.add((idx, head))
            return []
        # simple statement (incl. nested def/class: opaque)
        idx = self._stmt_node(stmt)
        self._join(frontier, idx)
        return [idx]


def build_cfg(fn: ast.AST) -> CFG:
    """CFG for one ``FunctionDef`` / ``AsyncFunctionDef``."""
    return _Builder(fn).build()


def function_cfgs(tree: ast.AST) -> Iterable[CFG]:
    """A CFG per function def in the module (nested defs included — each
    gets its own graph; bodies are opaque to the enclosing graph)."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield build_cfg(node)
