"""Admission control: bounded queueing, load shedding, deadlines, drain.

The front door of the serving path (ROADMAP: survive heavy traffic, not
just failures).  `controller.AdmissionController` replaces the decode
driver's raw semaphore; `controller.Deadline` objects ride activation
frame headers so every hop — including the shard compute-queue dequeue —
can drop work nobody is waiting for.  `reasons` declares the reject-
reason and deadline-stage label sets the metrics lint cross-checks.

Import submodules directly (``from dnet_tpu.admission.controller import
AdmissionController``).  This ``__init__`` stays import-free on purpose:
the metrics registry's core registration imports ``reasons`` for the
label sets, and an eager ``controller`` import here would re-enter the
registry lock through its module-level `metric()` handles.
"""

__all__ = ["controller", "reasons"]
