"""Bounded admission with load shedding, deadlines, and graceful drain.

The serving path used to gate concurrency with a bare unbounded-FIFO
`asyncio.Semaphore`: under a burst every request queued forever, nothing
carried a deadline, and shutdown simply cancelled in-flight work.  The
`AdmissionController` replaces it with the admission-aware front end a
fixed-capacity TPU ring / paged-KV block pool actually needs (PAPERS.md,
"Ragged Paged Attention"):

- **Bounded wait queue** — at most ``DNET_ADMIT_QUEUE_DEPTH`` requests
  wait for a slot; the next one is shed *immediately* with
  `AdmissionRejected(reason="queue_full")`, which the HTTP layer maps to
  429 + ``Retry-After``.  Queued requests that outwait
  ``DNET_ADMIT_QUEUE_TIMEOUT_S`` shed with ``queue_timeout``.
- **Deadline-aware shedding** — a request whose *estimated* queue wait
  (from the observed per-request service-time EMA) already exceeds its
  deadline is shed at arrival (``reason="deadline"``) instead of queueing
  toward certain failure.
- **Retry-After from the observed service rate** — every rejection
  carries ``retry_after_s`` derived from the service-time EMA and the
  current queue, so well-behaved clients back off by exactly the time a
  slot should take to appear, not by a magic constant.
- **Drain mode** — `begin_drain()` flips the controller into shutdown:
  new arrivals shed with ``draining`` (HTTP 503 + Retry-After), queued
  waiters are failed fast, and `wait_drained()` bounds how long in-flight
  requests may finish (``DNET_DRAIN_DEADLINE_S``) before the caller
  proceeds to tear adapters down.

Slot accounting uses direct handoff: `release()` passes the freed slot to
the oldest waiter without ever letting `_active` dip below capacity, so a
same-tick arrival cannot barge past the queue.  Everything runs on the
event loop — no locks.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Optional

from dnet_tpu.admission.reasons import DEADLINE_STAGES, REJECT_REASONS
from dnet_tpu.obs import metric
from dnet_tpu.obs.events import log_event
from dnet_tpu.resilience import chaos
from dnet_tpu.utils.logger import get_logger

log = get_logger()

_QUEUE_DEPTH = metric("dnet_admit_queue_depth")
_INFLIGHT = metric("dnet_admit_inflight")
_ADMITTED = metric("dnet_admit_admitted_total")
_REJECTED = metric("dnet_admit_rejected_total")
_WAIT_MS = metric("dnet_admit_wait_ms")
_DEADLINE_EXCEEDED = metric("dnet_deadline_exceeded_total")
_DRAIN_STATE = metric("dnet_drain_state")


class AdmissionRejected(Exception):
    """A request shed at admission.  `reason` is one of
    `admission.reasons.REJECT_REASONS`; `retry_after_s` feeds the HTTP
    ``Retry-After`` header (429, or 503 while draining)."""

    def __init__(self, reason: str, message: str, retry_after_s: float) -> None:
        assert reason in REJECT_REASONS, reason
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = float(retry_after_s)


@dataclass(frozen=True)
class Deadline:
    """An absolute end-to-end request deadline.

    Wall clock (`time.time()`), not monotonic, because the deadline rides
    activation frame headers to other NODES (`ActivationFrame.deadline`)
    — a shard checks expiry against its own wall clock, so the check is
    accurate to cross-host NTP skew, which is orders of magnitude smaller
    than any sane deadline."""

    t_deadline: float  # epoch seconds

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(time.time() + float(seconds))

    @property
    def expired(self) -> bool:
        return time.time() >= self.t_deadline

    def remaining(self) -> float:
        return max(0.0, self.t_deadline - time.time())


def request_deadline(
    override_s: Optional[float], default_s: float
) -> Optional[Deadline]:
    """Resolve a request's deadline: per-request ``deadline_s`` override,
    else the ``DNET_REQUEST_DEADLINE_S`` default; 0/None disables."""
    seconds = default_s if override_s is None else override_s
    if not seconds or seconds <= 0:
        return None
    return Deadline.after(seconds)


def deadline_expired(stage: str) -> None:
    """Count one deadline expiry at `stage` (pre-touched label set)."""
    assert stage in DEADLINE_STAGES, stage
    _DEADLINE_EXCEEDED.labels(stage=stage).inc()


class _Slot:
    """Context manager pairing one successful `acquire` with its
    `release`, so a slot can never leak on an exception path.  Release
    feeds the admit->release wall time into the controller's service-time
    EMA (the denominator of every Retry-After estimate)."""

    def __init__(self, controller: "AdmissionController") -> None:
        self._controller = controller
        self._released = False
        self._t_admit = time.monotonic()

    async def __aenter__(self) -> "_Slot":
        return self

    async def __aexit__(self, *exc) -> None:
        self.release()

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._controller._observe_service(
                time.monotonic() - self._t_admit
            )
            self._controller.release()


class AdmissionController:
    # Retry-After bounds: never tell a client "0" (it would hammer), never
    # more than a minute (the queue picture a minute out is fiction)
    RETRY_AFTER_MIN_S = 1.0
    RETRY_AFTER_MAX_S = 60.0
    SERVICE_EMA_ALPHA = 0.2  # same smoothing as the ring-hop RTT EMA

    def __init__(
        self,
        max_concurrent: int,
        queue_depth: int = 32,
        queue_timeout_s: float = 10.0,
    ) -> None:
        self._default_capacity = max(int(max_concurrent), 1)
        self._capacity = self._default_capacity
        self.queue_depth = max(int(queue_depth), 0)
        self.queue_timeout_s = float(queue_timeout_s)
        self._active = 0
        self._waiters: Deque[asyncio.Future] = deque()
        self._service_ema_s = 0.0
        self._draining = False
        self._drained = asyncio.Event()
        _DRAIN_STATE.set(0.0)
        self._sync_gauges()

    # ---- introspection --------------------------------------------------
    @property
    def active(self) -> int:
        return self._active

    @property
    def queued(self) -> int:
        return len(self._waiters)

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def draining(self) -> bool:
        return self._draining

    def _sync_gauges(self) -> None:
        _QUEUE_DEPTH.set(float(len(self._waiters)))
        _INFLIGHT.set(float(self._active))

    # ---- capacity -------------------------------------------------------
    def set_capacity(self, n: Optional[int]) -> None:
        """Re-cap admission (ring lanes: the shard lane pools hold exactly
        `lanes` KV rows, so admitting more mid-decode requests than lanes
        would hard-fail the overflow instead of queueing it).  None
        restores the configured default.  Requests already admitted finish
        under the old cap — `release` simply stops waking waiters while
        `_active` exceeds the new one."""
        cap = (
            self._default_capacity
            if n is None
            else min(int(n), self._default_capacity)
        )
        self._capacity = max(cap, 1)
        # a RAISED cap admits queued waiters right now.  Each wake grants
        # a NEW slot — `_active` must count it — unlike release's
        # `_wake_one`, which hands over an existing slot already counted.
        while self._waiters and self._active < self._capacity:
            fut = self._waiters.popleft()
            if not fut.done():
                self._active += 1
                fut.set_result(True)
        self._sync_gauges()

    # ---- service-rate observation --------------------------------------
    def _observe_service(self, dt_s: float) -> None:
        self._service_ema_s = (
            dt_s
            if self._service_ema_s <= 0
            else (1 - self.SERVICE_EMA_ALPHA) * self._service_ema_s
            + self.SERVICE_EMA_ALPHA * dt_s
        )

    def estimated_wait_s(self, position: int) -> float:
        """Expected queue wait for a request at `position` (0 = front),
        from the observed per-request service-time EMA: with `capacity`
        servers each turning a slot over every `ema` seconds, the
        (position+1)-th waiter starts after ~ceil((position+1)/capacity)
        turnovers.  0 before any request completed (optimistic: the first
        requests must not be shed on no evidence)."""
        if self._service_ema_s <= 0:
            return 0.0
        turnovers = -(-(position + 1) // self._capacity)  # ceil div
        return self._service_ema_s * turnovers

    def retry_after_s(self) -> float:
        """Seconds a shed client should wait before retrying: the
        estimated wait for the CURRENT backlog to clear one slot."""
        est = self.estimated_wait_s(len(self._waiters))
        return min(max(est, self.RETRY_AFTER_MIN_S), self.RETRY_AFTER_MAX_S)

    # ---- admission ------------------------------------------------------
    def _reject(self, reason: str, message: str) -> AdmissionRejected:
        _REJECTED.labels(reason=reason).inc()
        retry_after_s = self.retry_after_s()
        log_event(
            "shed", reason=reason,
            retry_after_s=round(retry_after_s, 3),
            queued=len(self._waiters), inflight=self._active,
        )
        return AdmissionRejected(reason, message, retry_after_s)

    def _admit(self, wait_s: float = 0.0) -> _Slot:
        _ADMITTED.inc()
        _WAIT_MS.observe(wait_s * 1000.0)
        log_event(
            "admitted", wait_ms=round(wait_s * 1000.0, 3),
            queued=len(self._waiters), inflight=self._active,
        )
        self._sync_gauges()
        return _Slot(self)

    async def acquire(self, deadline: Optional[Deadline] = None) -> _Slot:
        """Admit the calling request or raise `AdmissionRejected`.

        Prefer ``async with controller.slot(...)`` — it guarantees the
        release.  The chaos point ``admit`` sits first, so an injected
        delay backs the queue up exactly like a slow burst would."""
        await chaos.inject_async("admit")
        if self._draining:
            raise self._reject("draining", "server is draining for shutdown")
        if deadline is not None and deadline.expired:
            deadline_expired("admission")
            raise self._reject("deadline", "request deadline already expired")
        if self._active < self._capacity and not self._waiters:
            self._active += 1
            return self._admit()
        if len(self._waiters) >= self.queue_depth:
            raise self._reject(
                "queue_full",
                f"admission queue full ({self.queue_depth} waiting, "
                f"{self._active} executing)",
            )
        est = self.estimated_wait_s(len(self._waiters))
        if deadline is not None and est > deadline.remaining():
            deadline_expired("admission")
            raise self._reject(
                "deadline",
                f"estimated queue wait {est:.1f}s exceeds the request "
                f"deadline ({deadline.remaining():.1f}s left)",
            )
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._waiters.append(fut)
        self._sync_gauges()
        timeout = self.queue_timeout_s
        deadline_cut = False
        if deadline is not None and deadline.remaining() < timeout:
            timeout = deadline.remaining()
            deadline_cut = True
        t0 = time.monotonic()
        try:
            await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            self._unqueue(fut)
            if deadline_cut:
                deadline_expired("admission")
                raise self._reject(
                    "deadline", "request deadline expired in the admission queue"
                ) from None
            raise self._reject(
                "queue_timeout",
                f"no slot within {self.queue_timeout_s:.1f}s "
                f"(DNET_ADMIT_QUEUE_TIMEOUT_S)",
            ) from None
        except asyncio.CancelledError:
            self._unqueue(fut)
            raise
        except AdmissionRejected:
            # drain failed the queued future itself
            self._sync_gauges()
            raise
        # slot handed over by release(); _active already counts us
        return self._admit(time.monotonic() - t0)

    def _unqueue(self, fut: asyncio.Future) -> None:
        """Remove a dead waiter; if `release` resolved it concurrently the
        handed-over slot must be passed on, not leaked."""
        try:
            self._waiters.remove(fut)
        except ValueError:
            if fut.done() and not fut.cancelled() and fut.exception() is None:
                self.release()
        self._sync_gauges()

    def slot(self, deadline: Optional[Deadline] = None):
        """``async with controller.slot(deadline):`` — acquire + guaranteed
        release."""
        return _SlotAcquire(self, deadline)

    # ---- release --------------------------------------------------------
    def release(self) -> None:
        if self._active <= 0:
            log.warning("admission release without a matching acquire")
            return
        if self._waiters and self._active <= self._capacity and not self._draining:
            # direct handoff: the slot transfers without _active dipping,
            # so a same-tick arrival cannot barge past the queue
            self._wake_one()
        else:
            self._active -= 1
            if self._draining and self._active == 0:
                self._drained.set()
        self._sync_gauges()

    def _wake_one(self) -> None:
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(True)
                return
        # nobody viable took the handoff: the slot is simply free
        self._active -= 1
        if self._draining and self._active == 0:
            self._drained.set()

    # ---- drain ----------------------------------------------------------
    def begin_drain(self) -> None:
        """Enter drain: shed new arrivals and queued waiters with
        ``draining``; in-flight requests keep their slots."""
        if self._draining:
            return
        self._draining = True
        _DRAIN_STATE.set(1.0)
        log.info(
            "drain started: %d in flight, %d queued (queued are shed)",
            self._active, len(self._waiters),
        )
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                _REJECTED.labels(reason="draining").inc()
                log_event(
                    "shed", reason="draining",
                    queued=len(self._waiters), inflight=self._active,
                )
                fut.set_exception(
                    AdmissionRejected(
                        "draining",
                        "server is draining for shutdown",
                        self.retry_after_s(),
                    )
                )
        if self._active == 0:
            self._drained.set()
        self._sync_gauges()

    async def wait_drained(self, timeout_s: float) -> bool:
        """Block until every in-flight request released its slot, bounded
        by `timeout_s` (``DNET_DRAIN_DEADLINE_S``).  True = clean drain;
        False = deadline hit with work still in flight (the caller
        proceeds to shutdown regardless — bounded beats graceful)."""
        if not self._draining:
            self.begin_drain()
        try:
            await asyncio.wait_for(self._drained.wait(), timeout_s)
            return True
        except asyncio.TimeoutError:
            log.warning(
                "drain deadline (%.1fs) hit with %d request(s) in flight",
                timeout_s, self._active,
            )
            return False


class _SlotAcquire:
    """The awaitable-context form of acquire/release."""

    def __init__(
        self, controller: AdmissionController, deadline: Optional[Deadline]
    ) -> None:
        self._controller = controller
        self._deadline = deadline
        self._slot: Optional[_Slot] = None

    async def __aenter__(self) -> _Slot:
        self._slot = await self._controller.acquire(self._deadline)
        return self._slot

    async def __aexit__(self, *exc) -> None:
        if self._slot is not None:
            self._slot.release()
