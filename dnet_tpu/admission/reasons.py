"""Admission reject reasons + deadline stages (leaf module, no imports).

`dnet_tpu.obs` pre-touches one `dnet_admit_rejected_total{reason=}` series
per declared reason and one `dnet_deadline_exceeded_total{stage=}` series
per declared stage, and the metrics lint (scripts/check_metrics_names.py
pass 6) cross-checks both directions — a new reason/stage cannot ship
without its observability, and a renamed one cannot strand a stale label.
This lives apart from the controller so obs can import the enums without
pulling the controller (which itself imports obs) into a cycle.
"""

from __future__ import annotations

from typing import Tuple

# Why the admission controller refused a request (HTTP mapping in
# api/http.py: draining -> 503, everything else -> 429, all with
# Retry-After derived from the observed service rate).
REJECT_REASONS: Tuple[str, ...] = (
    "queue_full",     # wait queue at DNET_ADMIT_QUEUE_DEPTH
    "queue_timeout",  # queued longer than DNET_ADMIT_QUEUE_TIMEOUT_S
    "deadline",       # estimated wait exceeds the request deadline
    "draining",       # server is shutting down (SIGTERM drain window)
)

# Where an end-to-end deadline was found expired.  `shard_dequeue` is the
# whole point of riding deadlines in frame headers: the shard drops the
# frame before spending any compute on work nobody is waiting for.
DEADLINE_STAGES: Tuple[str, ...] = (
    "admission",      # expired while waiting in the admission queue
    "api_step",       # driver noticed expiry between decode steps
    "shard_dequeue",  # shard dropped the frame at compute-queue pickup
    "lane_flush",     # expired lane member shed at batch-frame flush
)
