"""SchedulerAdapter: the iteration-level tick loop behind DNET_SCHED=1.

One adapter replaces the kick-coalescing BatchedLocalAdapter AND the
monolithic per-request prefill: every tick the policy packs a token
budget of chunked-prefill segments plus one decode step per running
sequence into a single :class:`~dnet_tpu.sched.policy.TickPlan`, the
compute thread executes it (``sched/step.py``), and the loop applies the
results to the per-request state machines (``sched/queue.py``).  The
driver protocol (``ApiAdapterBase``) is unchanged — InferenceManager and
the HTTP layer cannot tell this engine from the legacy ones, which is
what makes the byte-identical parity test possible.

Admission is a function of free paged-KV blocks and batch slots;
deadlines stamped by the admission controller order both admission and
preemption.  Preempted sequences return to WAITING with their paged
prefix aliased into the prefix cache and resume transparently — the
pending driver step rides along and resolves from the resume's adopt
sample.
"""

from __future__ import annotations

import asyncio
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

from dnet_tpu.analysis.runtime import ownership as dsan
from dnet_tpu.api.strategies import (
    ApiAdapterBase,
    _embed_on_executor,
    _reap,
    _TokenFutures,
)
from dnet_tpu.core.types import DecodingParams, TokenResult
from dnet_tpu.obs import metric, obs_enabled
from dnet_tpu.obs.events import log_event
from dnet_tpu.sched.flight import get_tick_recorder
from dnet_tpu.sched.kinds import QUEUE_STATES, STATE_DECODING
from dnet_tpu.sched.policy import SchedulerPolicy, TickPlan
from dnet_tpu.sched.queue import SchedQueue
from dnet_tpu.sched.step import MAX_STARVED_REQUEUES, TickResult, execute_tick
from dnet_tpu.transport.wire_pipeline import wire_pipeline_enabled
from dnet_tpu.utils.logger import get_logger

log = get_logger()

_TICK_MS = metric("dnet_sched_tick_ms")
_BATCH_TOKENS = metric("dnet_sched_batch_tokens")
_PREEMPTIONS = metric("dnet_sched_preemptions_total")


def sched_enabled() -> bool:
    """THE flag gate: DNET_SCHED=1 (SchedSettings.sched).  A raw env read
    (config.env_flag, the sanctioned DL006 escape hatch) backs the
    settings value so tests toggling os.environ after the settings cache
    warmed still see the flip — the same contract as kv.paged_enabled."""
    from dnet_tpu.config import env_flag, get_settings

    if get_settings().sched.sched:
        return True
    return env_flag("DNET_SCHED")


class SchedulerAdapter(ApiAdapterBase):
    """Iteration-level continuous batching over a batched engine.

    Needs the full chunked-prefill serving surface BatchedEngine exposes
    (``reserve_slot`` / ``seed_from_prefix`` / ``prefill_chunk`` /
    ``adopt_prefilled`` / ``decode_batch`` + slot lifecycle).  Engines
    without it (PipelinedMeshEngine prefills in one ring pass) keep the
    legacy BatchedLocalAdapter — model_manager falls back with a
    warning."""

    SWEEP_INTERVAL_S = 60.0

    def __init__(self, engine, token_budget: Optional[int] = None,
                 prefill_chunk: Optional[int] = None) -> None:
        from dnet_tpu.config import get_settings

        sched = get_settings().sched
        if not hasattr(engine, "prefill_chunk"):
            raise TypeError(
                f"SchedulerAdapter needs the chunked-prefill engine "
                f"surface; {type(engine).__name__} does not expose it"
            )
        self.engine = engine
        self.policy = SchedulerPolicy(
            token_budget=token_budget or sched.sched_token_budget,
            prefill_chunk=prefill_chunk or sched.sched_prefill_chunk,
        )
        self.queue = SchedQueue()
        self._futures = _TokenFutures()
        self._executor: Optional[ThreadPoolExecutor] = None
        self._kick: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        self._sweep_task: Optional[asyncio.Task] = None
        # deadline stamped by the driver BEFORE step 0 arrives (the
        # set_deadline call precedes the first send); loop-owned,
        # declared in analysis/runtime/domains.py
        self._deadlines: Dict[str, float] = dsan.guard_dict(
            {}, dsan.loop_domain(), "SchedulerAdapter._deadlines"
        )

    # ---- lifecycle ----------------------------------------------------
    async def start(self) -> None:
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="compute"
        )
        self._kick = asyncio.Event()
        self._task = asyncio.ensure_future(self._tick_loop())
        self._sweep_task = asyncio.ensure_future(self._sweep_loop())

    async def shutdown(self) -> None:
        task, self._task = self._task, None
        await _reap(task, "scheduler tick loop")
        sweep, self._sweep_task = self._sweep_task, None
        await _reap(sweep, "session sweep")
        if self._executor:
            self._executor.shutdown(wait=False, cancel_futures=True)
            self._executor = None

    async def _sweep_loop(self) -> None:
        """Periodic TTL sweep (same contract as the legacy adapters): a
        client that vanished without reset_cache must not pin its slot —
        or its queue entry — forever."""
        loop = asyncio.get_running_loop()
        while True:
            await asyncio.sleep(self.SWEEP_INTERVAL_S)
            if self._executor is None:
                return
            try:

                def _sweep_once():
                    # residency snapshot taken ON the compute thread, in
                    # the same executor task as the sweep: slot_of is
                    # compute-owned, and a tick running between sweep and
                    # a loop-side read could preempt a request that would
                    # then be removed as "swept" (its pending step lost)
                    n_swept = self.engine.sweep_sessions()
                    return n_swept, set(self.engine.slot_of)

                n, resident = await loop.run_in_executor(
                    self._executor, _sweep_once
                )
                # a swept DECODING session lost its engine residency: drop
                # the stale queue entry so its slot estimate frees too
                for req in list(self.queue.decoding()):
                    if req.nonce not in resident:
                        self.queue.remove(req.nonce)
                if n:
                    log.info("TTL sweep freed %d idle sessions", n)
                    self._wake()
            except Exception:
                log.exception("session sweep failed")

    # ---- driver surface -----------------------------------------------
    async def reset_cache(self, nonce: str) -> None:
        self.queue.remove(nonce)
        self._deadlines.pop(nonce, None)
        if self._executor is not None:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(
                self._executor, self.engine.end_session, nonce
            )
        self._futures.cancel_nonce(nonce)
        self._wake()  # a freed slot / freed blocks may unblock admission

    def set_deadline(self, nonce: str, deadline_ts: float) -> None:
        req = self.queue.get(nonce)
        if req is not None:
            req.deadline_ts = deadline_ts
        else:
            self._deadlines[nonce] = deadline_ts

    def max_seq(self) -> Optional[int]:
        return self.engine.max_seq

    async def embed(self, ids_list: List[List[int]]) -> List[List[float]]:
        inner = getattr(self.engine, "eng", None) or getattr(
            self.engine, "_inner", None
        )
        fn = getattr(inner, "hidden_states", None)
        if fn is None:
            raise NotImplementedError(
                f"embeddings unsupported on {type(self.engine).__name__}"
            )
        return await _embed_on_executor(fn, self._executor, ids_list)

    async def send_tokens(
        self,
        nonce: str,
        token_ids: List[int],
        decoding: DecodingParams,
        step: int,
        budget: Optional[int] = None,
    ) -> None:
        if self._executor is None or self._kick is None:
            raise RuntimeError("adapter not started")
        self._futures.expect(nonce, step)
        if step == 0:
            req = self.queue.add(
                nonce, list(token_ids), decoding,
                deadline_ts=self._deadlines.pop(nonce, None),
            )
            req.pending_step = 0
            req.pending_budget = budget
        else:
            req = self.queue.get(nonce)
            if req is None:
                # mid-generation loss (TTL sweep / reset race): fail fast
                # instead of silently re-prefilling from one token
                self._futures.resolve(
                    TokenResult(
                        nonce=nonce, token_id=-1, step=step,
                        error=f"session expired for request {nonce}",
                    )
                )
                return
            # the driver echoes the accepted token as this step's input:
            # appending here keeps `ids` the exact replay source
            req.ids.append(token_ids[-1])
            req.pending_step = step
            req.pending_budget = budget
        self._wake()

    async def await_token(
        self, nonce: str, step: int, timeout: float
    ) -> TokenResult:
        return await self._futures.wait(nonce, step, timeout)

    def resolve_token(self, result: TokenResult) -> None:
        self._futures.resolve(result)

    # ---- tick loop ----------------------------------------------------
    def _wake(self) -> None:
        if self._kick is not None:
            self._kick.set()

    async def _tick_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            await self._kick.wait()
            self._kick.clear()
            await asyncio.sleep(0)  # coalesce: let concurrent senders enqueue
            plan = None
            # the WHOLE tick body is guarded: an exception escaping this
            # loop would kill the task silently and wedge every current
            # and future request behind a kick event nobody waits on
            try:
                plan = self.policy.plan(self.queue, self.engine)
                if plan.empty():
                    continue
                t0 = time.perf_counter()
                on_decode = None
                if plan.prefills and wire_pipeline_enabled():
                    # wire-pipeline tick dispatch: decode results leave the
                    # compute thread the moment the batched dispatch lands,
                    # so their futures resolve while this tick's prefill
                    # chunks are still burning — decode TPOT stops paying
                    # for co-scheduled prompt work.  call_soon_threadsafe
                    # is the sanctioned bridge (domains.BRIDGE_MODULES);
                    # FIFO loop ordering guarantees every early resolve
                    # runs before the executor future resumes _apply.
                    on_decode = lambda nonce, sample: loop.call_soon_threadsafe(  # noqa: E731
                        self._dispatch_decode, plan, nonce, sample
                    )
                result = await loop.run_in_executor(
                    self._executor, execute_tick, self.engine, plan, on_decode
                )
                tick_ms = (time.perf_counter() - t0) * 1000.0
                _TICK_MS.observe(tick_ms)
                _BATCH_TOKENS.labels(kind="prefill").observe(
                    float(result.prefill_tokens)
                )
                _BATCH_TOKENS.labels(kind="decode").observe(
                    float(result.decode_lanes)
                )
                self._apply(plan, result)
                if obs_enabled():
                    self._record_tick(tick_ms, result)
                if self.policy.has_work(self.queue, self.engine):
                    self._wake()
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                log.exception("scheduler tick failed")
                if plan is not None:
                    self._fail_plan(plan, str(exc))
                else:
                    # planning itself failed — deterministic over the same
                    # queue, so it would fail every tick: error the pending
                    # futures instead of wedging them to their timeouts
                    self._futures.fail_all(str(exc))
                continue

    def _record_tick(self, tick_ms: float, result: TickResult) -> None:
        """One TickRecord into the flight ring (sched/flight.py): the
        black-box row GET /v1/debug/sched and the trace export replay.
        Queue depths are read AFTER _apply so the record reflects the
        state the tick left behind (matching the synced gauges)."""
        get_tick_recorder().record(
            tick_ms=tick_ms,
            budget_tokens=self.policy.token_budget,
            prefill_tokens=result.prefill_tokens,
            decode_lanes=result.decode_lanes,
            preempted=len(result.preempted),
            requeued=len(result.requeued),
            errors=len(result.errors),
            queue_depths={
                state: len(self.queue.by_state(state))
                for state in QUEUE_STATES
            },
            kv_blocks_used=int(metric("dnet_kv_blocks_used").value),
            kv_blocks_free=int(metric("dnet_kv_blocks_free").value),
            kv_pool_blocks=int(metric("dnet_kv_pool_blocks").value),
        )

    def _dispatch_decode(self, plan: TickPlan, nonce: str, sample) -> None:
        """Early decode resolution (wire-pipeline tick dispatch): runs on
        the loop via call_soon_threadsafe while the tick's prefill chunks
        are still executing.  _apply later skips nonces listed in
        TickResult.dispatched, so a result resolves exactly once."""
        step = plan.steps.get(nonce)
        if step is None:
            return
        self._resolve_step(nonce, step, sample=sample)

    def _fail_plan(self, plan: TickPlan, error: str) -> None:
        """A tick that died wholesale (executor torn down mid-flight):
        every participating pending step gets the error result."""
        for nonce, step in plan.steps.items():
            self._resolve_step(nonce, step, error=error)
        for chunk in plan.prefills:
            self._resolve_step(chunk.nonce, chunk.pending_step, error=error)

    def _resolve_step(
        self, nonce: str, step: int, sample=None, error: Optional[str] = None
    ) -> None:
        req = self.queue.get(nonce)
        if error is not None:
            self._futures.resolve(
                TokenResult(nonce=nonce, token_id=-1, step=step, error=error)
            )
            self.queue.remove(nonce)
            return
        decoding = req.decoding if req is not None else DecodingParams()
        self._futures.resolve(
            self.engine.token_result(nonce, sample, step=step, decoding=decoding)
        )
        if req is not None and req.pending_step == step:
            req.pending_step = None
            req.pending_budget = None

    def _apply(self, plan: TickPlan, result: TickResult) -> None:
        for nonce in result.preempted:
            self.queue.requeue(nonce, reason_preempt=True)
            log_event("preempted", rid=nonce, reason="policy")
        for nonce in result.requeued:
            req = self.queue.get(nonce)
            if req is None:
                continue
            if req.starved + 1 >= MAX_STARVED_REQUEUES:
                self._resolve_step(
                    nonce,
                    req.pending_step if req.pending_step is not None else 0,
                    error=(
                        "paged KV pool exhausted: prefill starved after "
                        f"{req.starved + 1} requeues"
                    ),
                )
                continue
            self.queue.requeue(nonce, reason_preempt=False)
            _PREEMPTIONS.labels(reason="starved_requeue").inc()
            log_event("preempted", rid=nonce, reason="starved_requeue")
        for nonce, pos in result.progress.items():
            req = self.queue.get(nonce)
            if req is not None and req.state not in (STATE_DECODING,):
                req.prefilled = pos
        for nonce, sample in result.adopted.items():
            req = self.queue.get(nonce)
            if req is None:
                continue
            req.state = STATE_DECODING
            req.prefilled = len(req.ids)
            req.starved = 0
            step = req.pending_step if req.pending_step is not None else 0
            self._resolve_step(nonce, step, sample=sample)
        dispatched = set(result.dispatched)
        for nonce, sample in result.decode_results.items():
            if nonce in dispatched:
                continue  # already resolved mid-tick (wire-pipeline path)
            step = plan.steps.get(nonce)
            if step is None:
                continue
            self._resolve_step(nonce, step, sample=sample)
        for nonce, msg in result.errors.items():
            step = plan.steps.get(nonce)
            if step is None:
                req = self.queue.get(nonce)
                step = (
                    req.pending_step
                    if req is not None and req.pending_step is not None
                    else 0
                )
            self._resolve_step(nonce, step, error=msg)
        self.queue.sync_gauges()
