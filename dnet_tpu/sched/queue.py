"""Per-request scheduler state machine and priority queue.

Each request admitted by the API driver becomes one :class:`SchedRequest`
walking WAITING -> PREFILLING -> DECODING -> FINISHED.  Ordering is
deadline-first, then arrival (FIFO): the deadline is the one the PR 5
admission controller stamped on the request (``Deadline.t_deadline`` epoch
seconds, ridden through ``ApiAdapterBase.set_deadline``), so the scheduler
and the shedding layer agree on who is most urgent.  Preemption returns a
DECODING request to WAITING with its ``arrival`` unchanged — priority is a
stable total order, resources only ever flow up it, so preemption cannot
cycle.

The queue itself is loop-owned (declared in
``analysis/runtime/domains.py``, enforced under ``DNET_SAN=1``): policy
and bookkeeping run on the event loop; the compute thread only ever sees
plain snapshots inside a :class:`~dnet_tpu.sched.policy.TickPlan`.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from dnet_tpu.analysis.runtime import ownership as dsan
from dnet_tpu.core.types import DecodingParams
from dnet_tpu.obs import metric
from dnet_tpu.sched.kinds import (
    QUEUE_STATES,
    STATE_DECODING,
    STATE_FINISHED,
    STATE_PREFILLING,
    STATE_WAITING,
)

_QUEUE_DEPTH = metric("dnet_sched_queue_depth")


@dataclass
class SchedRequest:
    """One request's scheduler-side state.

    ``ids`` is the replay source: the prompt plus every generated token
    the driver has sent back (the driver echoes each accepted token as the
    next step's input, so appending at ``send_tokens`` time keeps ``ids``
    exactly one step ahead of the engine's committed KV).  A preempted
    request re-prefills ``ids`` wholesale; the prefix blocks aliased at
    eviction time make that mostly a block-table walk, not compute.
    """

    nonce: str
    ids: List[int]
    decoding: DecodingParams
    arrival: int
    prompt_len: int
    deadline_ts: Optional[float] = None
    state: str = STATE_WAITING
    #: inner-engine staging position: tokens of ``ids`` committed by
    #: chunked prefill so far (absolute, prefix-cache skips included)
    prefilled: int = 0
    #: the driver's outstanding step awaiting a token, or None
    pending_step: Optional[int] = None
    #: remaining token allowance the driver advertised with the pending
    #: step (widens decode dispatches into fused chunks)
    pending_budget: Optional[int] = None
    preemptions: int = 0
    #: consecutive starved requeues (bounded before the typed error)
    starved: int = 0
    extra: dict = field(default_factory=dict)

    def priority(self) -> Tuple[float, int]:
        """Sort key, smaller = more urgent: (deadline, arrival)."""
        return (
            self.deadline_ts if self.deadline_ts is not None else math.inf,
            self.arrival,
        )


class SchedQueue:
    """nonce -> SchedRequest map with priority views and depth gauges."""

    def __init__(self) -> None:
        self._arrival = 0
        self._reqs: Dict[str, SchedRequest] = dsan.guard_dict(
            {}, dsan.loop_domain(), "SchedQueue._reqs"
        )

    def __len__(self) -> int:
        return len(self._reqs)

    def __contains__(self, nonce: str) -> bool:
        return nonce in self._reqs

    def get(self, nonce: str) -> Optional[SchedRequest]:
        return self._reqs.get(nonce)

    def add(
        self,
        nonce: str,
        prompt_ids: List[int],
        decoding: DecodingParams,
        deadline_ts: Optional[float] = None,
    ) -> SchedRequest:
        self._arrival += 1
        req = SchedRequest(
            nonce=nonce,
            ids=list(prompt_ids),
            decoding=decoding,
            arrival=self._arrival,
            prompt_len=len(prompt_ids),
            deadline_ts=deadline_ts,
        )
        self._reqs[nonce] = req
        self.sync_gauges()
        return req

    def remove(self, nonce: str) -> Optional[SchedRequest]:
        req = self._reqs.pop(nonce, None)
        if req is not None:
            req.state = STATE_FINISHED
            self.sync_gauges()
        return req

    def by_state(self, state: str) -> List[SchedRequest]:
        return [r for r in self._reqs.values() if r.state == state]

    def waiting(self) -> List[SchedRequest]:
        """WAITING requests, most urgent first."""
        return sorted(self.by_state(STATE_WAITING), key=SchedRequest.priority)

    def prefilling(self) -> List[SchedRequest]:
        """PREFILLING requests, most urgent first."""
        return sorted(
            self.by_state(STATE_PREFILLING), key=SchedRequest.priority
        )

    def decoding(self) -> List[SchedRequest]:
        return self.by_state(STATE_DECODING)

    def victims(self) -> List[str]:
        """DECODING nonces, LEAST urgent first — the eviction order when
        the block pool starves."""
        return [
            r.nonce
            for r in sorted(
                self.by_state(STATE_DECODING),
                key=SchedRequest.priority,
                reverse=True,
            )
        ]

    def requeue(self, nonce: str, reason_preempt: bool) -> None:
        """Return a running request to WAITING (preemption / starvation);
        its staged prefill is gone but ``arrival`` — and so priority — is
        unchanged."""
        req = self._reqs.get(nonce)
        if req is None:
            return
        req.state = STATE_WAITING
        req.prefilled = 0
        if reason_preempt:
            req.preemptions += 1
        else:
            req.starved += 1
        self.sync_gauges()

    def active(self) -> int:
        """Requests currently holding engine-side residency."""
        return len(self.by_state(STATE_PREFILLING)) + len(
            self.by_state(STATE_DECODING)
        )

    def sync_gauges(self) -> None:
        counts = {s: 0 for s in QUEUE_STATES}
        for r in self._reqs.values():
            if r.state in counts:
                counts[r.state] += 1
        for state, n in counts.items():
            _QUEUE_DEPTH.labels(state=state).set(float(n))
