"""Scheduler tick flight-recorder: a bounded ring of per-tick records.

`dnet_sched_tick_ms` / `dnet_sched_batch_tokens` tell you the DISTRIBUTION
of tick cost and batch shape; they cannot answer "what did tick N look
like" — which ticks wasted budget, what the queue looked like when a
preemption fired, whether the block pool was pinned when a prefill
starved.  This module captures one :class:`TickRecord` per executed tick
(under ``obs_enabled()``, from ``sched/engine.py``'s tick loop) into a
bounded ring — the scheduler's black box, surfaced raw via
``GET /v1/debug/sched`` (api/http.py) and as counter tracks in the
Perfetto export (obs/trace.py).

Bounded by ``DNET_OBS_TICK_RECORDS`` (ObsSettings.tick_records; 0 disables
capture), so retention is O(1) regardless of traffic.  Every captured tick
also increments ``dnet_sched_tick_records_total`` and observes the
budget-used ratio into ``dnet_sched_tick_budget_used_ratio`` — the
aggregate twins the debug endpoint's ring is cross-checked against in the
ring acceptance test.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

from dnet_tpu.sched.kinds import QUEUE_STATES


@dataclass
class TickRecord:
    """One executed scheduler tick, as the policy planned and the compute
    thread delivered it."""

    seq: int                 # monotone capture index (not reset by eviction)
    t_unix: float            # wall clock at capture (tick end)
    tick_ms: float           # execute_tick wall time on the compute thread
    budget_tokens: int       # the policy's per-tick token budget
    budget_used: int         # prefill tokens + decode lanes packed
    budget_wasted: int       # budget - used (0 on a saturated tick)
    prefill_tokens: int      # prompt tokens chunk-prefilled this tick
    decode_lanes: int        # decode lanes stepped this tick
    preempted: int           # sequences evicted back to WAITING
    requeued: int            # starved prefills requeued
    errors: int              # per-nonce errors the tick surfaced
    queue_depths: Dict[str, int] = field(default_factory=dict)
    kv_blocks_used: int = 0
    kv_blocks_free: int = 0
    kv_pool_blocks: int = 0

    def as_dict(self) -> dict:
        return asdict(self)


class TickFlightRecorder:
    """Bounded ring of TickRecords (thread-safe: the tick loop records
    from the event loop, /v1/debug/sched snapshots from a handler)."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        # None = read ObsSettings.tick_records lazily (the process-global
        # instance is built before settings are)
        self._capacity = capacity
        self._lock = threading.Lock()
        self._records: "deque[TickRecord]" = deque()
        self._seq = 0

    def capacity(self) -> int:
        n = self._capacity
        if n is None:
            try:
                from dnet_tpu.config import get_settings

                n = get_settings().obs.tick_records
            except Exception:
                n = 256
        return max(int(n), 0)

    def record(
        self,
        *,
        tick_ms: float,
        budget_tokens: int,
        prefill_tokens: int,
        decode_lanes: int,
        preempted: int,
        requeued: int,
        errors: int,
        queue_depths: Optional[Dict[str, int]] = None,
        kv_blocks_used: int = 0,
        kv_blocks_free: int = 0,
        kv_pool_blocks: int = 0,
    ) -> Optional[TickRecord]:
        """Capture one tick; returns the record (None when capture is
        disabled via DNET_OBS_TICK_RECORDS=0)."""
        cap = self.capacity()
        if cap <= 0:
            return None
        used = int(prefill_tokens) + int(decode_lanes)
        rec = TickRecord(
            seq=0,
            t_unix=time.time(),
            tick_ms=round(float(tick_ms), 3),
            budget_tokens=int(budget_tokens),
            budget_used=used,
            budget_wasted=max(int(budget_tokens) - used, 0),
            prefill_tokens=int(prefill_tokens),
            decode_lanes=int(decode_lanes),
            preempted=int(preempted),
            requeued=int(requeued),
            errors=int(errors),
            queue_depths=dict(queue_depths or {}),
            kv_blocks_used=int(kv_blocks_used),
            kv_blocks_free=int(kv_blocks_free),
            kv_pool_blocks=int(kv_pool_blocks),
        )
        with self._lock:
            rec.seq = self._seq
            self._seq += 1
            self._records.append(rec)
            while len(self._records) > cap:
                self._records.popleft()
        from dnet_tpu.obs import metric

        metric("dnet_sched_tick_records_total").inc()
        if rec.budget_tokens > 0:
            metric("dnet_sched_tick_budget_used_ratio").observe(
                min(used / rec.budget_tokens, 1.0)
            )
        return rec

    def records(self) -> List[TickRecord]:
        with self._lock:
            return list(self._records)

    def snapshot(self) -> dict:
        """JSON-ready ring dump + aggregate summary — the
        GET /v1/debug/sched payload."""
        records = self.records()
        n = len(records)
        summary = {
            "ticks_captured": self._seq,
            "ticks_retained": n,
            "capacity": self.capacity(),
        }
        if n:
            ticks_ms = [r.tick_ms for r in records]
            summary.update({
                "tick_ms_mean": round(sum(ticks_ms) / n, 3),
                "tick_ms_max": round(max(ticks_ms), 3),
                "prefill_tokens": sum(r.prefill_tokens for r in records),
                "decode_lanes": sum(r.decode_lanes for r in records),
                "budget_wasted": sum(r.budget_wasted for r in records),
                "budget_used_ratio": round(
                    sum(r.budget_used for r in records)
                    / max(sum(r.budget_tokens for r in records), 1),
                    4,
                ),
                "preempted": sum(r.preempted for r in records),
                "requeued": sum(r.requeued for r in records),
                "errors": sum(r.errors for r in records),
                "queue_depths_last": records[-1].queue_depths,
            })
        return {
            "summary": summary,
            "states": list(QUEUE_STATES),
            "records": [r.as_dict() for r in records],
        }

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self._seq = 0


_tick_recorder = TickFlightRecorder()


def get_tick_recorder() -> TickFlightRecorder:
    """The process-global tick ring (cleared by obs.reset_obs)."""
    return _tick_recorder
