"""Iteration-level continuous-batching scheduler (DNET_SCHED=1).

One serving engine for mixed prefill + decode: each tick packs a token
budget of chunked-prefill segments and one decode step per running
sequence into a single batch plan, admits work as a function of free
paged-KV blocks, and preempts by block starvation with the paged prefix
kept intact.  See README "Continuous batching" and ROADMAP item 1.

This ``__init__`` resolves its exports LAZILY (PEP 562): the metrics
registry's core registration imports ``sched.kinds`` for the label
declarations, and an eager ``engine``/``queue`` import here would
re-enter the registry lock through their module-level ``metric()``
handles — the same hazard ``dnet_tpu/admission/__init__.py`` documents.
"""

from __future__ import annotations

_EXPORTS = {
    "BATCH_KINDS": "dnet_tpu.sched.kinds",
    "PREEMPT_REASONS": "dnet_tpu.sched.kinds",
    "QUEUE_STATES": "dnet_tpu.sched.kinds",
    "PrefillChunk": "dnet_tpu.sched.policy",
    "SchedulerPolicy": "dnet_tpu.sched.policy",
    "TickPlan": "dnet_tpu.sched.policy",
    "SchedQueue": "dnet_tpu.sched.queue",
    "SchedRequest": "dnet_tpu.sched.queue",
    "SchedulerAdapter": "dnet_tpu.sched.engine",
    "sched_enabled": "dnet_tpu.sched.engine",
    "TickResult": "dnet_tpu.sched.step",
    "execute_tick": "dnet_tpu.sched.step",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    target = _EXPORTS.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(target), name)
