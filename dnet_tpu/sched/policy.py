"""Tick packing: token budget -> one mixed prefill+decode batch plan.

Every tick the policy packs at most ``DNET_SCHED_TOKEN_BUDGET`` tokens of
work into one :class:`TickPlan`:

1. **Decode first.**  Every DECODING request with a pending step gets one
   token (decode is what the per-token SLO measures; a long prompt must
   never starve running streams for more than one tick).  Fused-chunk
   budgets ride along so the engine may still batch R device steps per
   dispatch — the active set is fixed per tick, so streams stay
   bit-identical to serial stepping.
2. **Chunked prefill fills the remainder.**  PREFILLING requests continue
   (most urgent first) in ``DNET_SCHED_PREFILL_CHUNK``-bounded segments.
3. **Admission.**  WAITING requests are admitted most-urgent-first while
   a batch slot is free and the paged-KV pool can cover their whole
   prompt (``BlockPool.can_cover`` — admission is a function of FREE
   BLOCKS, not worst-case length).  When nothing is running at all, the
   top request is admitted regardless so an oversized prompt fails fast
   with the typed backpressure error instead of queueing forever.

The policy runs on the event loop and snapshots everything the compute
thread needs into the plan; it never reads compute-thread-owned engine
state (slot occupancy is derived from the queue's own books, the block
pool is lock-guarded).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from dnet_tpu.core.types import DecodingParams
from dnet_tpu.sched.kinds import STATE_PREFILLING
from dnet_tpu.sched.queue import SchedQueue, SchedRequest


@dataclass
class PrefillChunk:
    """One chunked-prefill segment of one request for this tick."""

    nonce: str
    ids: List[int]  # full replay ids (prompt + driver-confirmed tokens)
    start: int  # staging position this chunk assumes
    end: int  # staging position after this chunk
    first: bool  # reserve a slot + prefix-cache seed before this chunk
    last: bool  # store prefix + adopt into a batch lane after this chunk
    decoding: DecodingParams
    pending_step: int  # the driver step this request's next sample resolves
    seed: Optional[int]
    #: strictly-lower-priority DECODING nonces this prefill may evict on
    #: pool starvation (least urgent first); resources only flow up the
    #: priority order, so preemption cannot cycle
    victims: List[str] = field(default_factory=list)


@dataclass
class TickPlan:
    prefills: List[PrefillChunk] = field(default_factory=list)
    #: nonce -> (last token, decoding) for this tick's batched decode
    decode: Dict[str, Tuple[int, DecodingParams]] = field(default_factory=dict)
    budgets: Dict[str, Optional[int]] = field(default_factory=dict)
    steps: Dict[str, int] = field(default_factory=dict)
    #: replay ids for EVERY decoding request (preemption stash source)
    ids: Dict[str, List[int]] = field(default_factory=dict)
    #: decode eviction order on block starvation, least urgent first
    victims: List[str] = field(default_factory=list)
    admitted: List[str] = field(default_factory=list)
    prefill_tokens: int = 0

    def empty(self) -> bool:
        return not self.prefills and not self.decode


class SchedulerPolicy:
    def __init__(self, token_budget: int, prefill_chunk: int) -> None:
        self.token_budget = max(int(token_budget), 1)
        self.prefill_chunk = max(int(prefill_chunk), 1)

    # ---- admission ----------------------------------------------------
    @staticmethod
    def admissible(req: SchedRequest, engine) -> bool:
        """Can the paged pool cover this request's whole prompt (plus one
        decode block) right now?  Dense engines admit on slots alone.
        Conservative for preempted requests — their aliased prefix blocks
        make the actual prefill cheaper, but counting on a cache hit for
        admission would thrash the pool."""
        pool = getattr(engine, "kv_pool", None)
        if pool is None:
            return True
        cfg = engine._kv_cfg
        need = cfg.blocks_for(min(len(req.ids) + 1, engine.max_seq))
        return pool.can_cover(need)

    def has_work(self, queue: SchedQueue, engine) -> bool:
        """Would the next plan be non-empty?  (The tick loop parks when
        not — progress then comes from a send/reset kick.)"""
        if any(r.pending_step is not None for r in queue.decoding()):
            return True
        if queue.prefilling():
            return True
        # a preempted request whose next driver step has not arrived yet
        # is not schedulable: its resume sample would have no future to
        # resolve (the send that names the step is moments away)
        waiting = [r for r in queue.waiting() if r.pending_step is not None]
        if not waiting:
            return False
        if queue.active() == 0:
            return True  # top request is admitted regardless (fail fast)
        slots_free = getattr(engine, "slots", 1) - queue.active()
        return slots_free > 0 and any(
            self.admissible(r, engine) for r in waiting
        )

    # ---- packing ------------------------------------------------------
    def plan(self, queue: SchedQueue, engine) -> TickPlan:
        out = TickPlan()
        budget = self.token_budget

        decoding = queue.decoding()
        # replay-id snapshots are only consumed on preemption (the prefix
        # alias of an evicted victim), so the O(lanes x seq_len) copies are
        # taken only under pool pressure; a mis-predicted eviction without
        # its snapshot just skips the alias and re-prefills on resume
        pool = getattr(engine, "kv_pool", None)
        pressure = False
        if pool is not None:
            bt = engine._kv_cfg.block_tokens
            margin = len(decoding) + self.token_budget // bt + 4
            pressure = pool.free < margin
        for r in decoding:
            if pressure:
                out.ids[r.nonce] = list(r.ids)
            if r.pending_step is None:
                continue
            out.decode[r.nonce] = (r.ids[-1], r.decoding)
            out.budgets[r.nonce] = r.pending_budget
            out.steps[r.nonce] = r.pending_step
        budget -= len(out.decode)
        out.victims = queue.victims()
        prios = {r.nonce: r.priority() for r in decoding}

        def chunk_for(r: SchedRequest, first: bool) -> int:
            remaining = len(r.ids) - r.prefilled
            return max(min(self.prefill_chunk, budget, remaining), 0)

        def emit(r: SchedRequest, first: bool) -> None:
            nonlocal budget
            n = chunk_for(r, first)
            end = r.prefilled + n
            out.prefills.append(
                PrefillChunk(
                    nonce=r.nonce,
                    ids=list(r.ids),
                    start=r.prefilled,
                    end=end,
                    first=first,
                    last=end >= len(r.ids),
                    decoding=r.decoding,
                    pending_step=r.pending_step if r.pending_step is not None else 0,
                    seed=r.decoding.seed,
                    victims=[
                        v for v in out.victims if prios[v] > r.priority()
                    ],
                )
            )
            out.prefill_tokens += n
            budget -= n

        for r in queue.prefilling():
            if budget <= 0:
                break
            emit(r, first=(r.prefilled == 0))

        # admission: slot occupancy from the queue's own books (the
        # engine's free list is compute-thread state; a lost race is a
        # clean requeue in step.py, never a client error)
        slots_free = max(getattr(engine, "slots", 1) - queue.active(), 0)
        nothing_active = queue.active() == 0
        for r in queue.waiting():
            if budget <= 0 or slots_free <= 0:
                break
            if r.pending_step is None:
                continue  # preempted; its next driver step names the future
            if not self.admissible(r, engine) and not (
                nothing_active and not out.admitted
            ):
                continue
            r.state = STATE_PREFILLING
            r.prefilled = 0
            out.admitted.append(r.nonce)
            slots_free -= 1
            emit(r, first=True)
        queue.sync_gauges()
        return out
