"""Tick execution: one TickPlan against the batched engine's internals.

``execute_tick`` runs ON THE COMPUTE THREAD (the adapter's single-worker
executor — the same ownership model as every other engine touch).  One
tick is one mixed launch sequence:

- the batched decode dispatch first (every running stream advances before
  any prompt token burns — decode latency is what the per-token SLO
  measures), with block-starvation preemption resolved BEFORE the
  dispatch so a pool shortfall evicts the lowest-priority sequence
  instead of erroring an arbitrary lane; under DNET_KV_RAGGED=1 the
  dispatch attends the block pool in place through the page tables
  (ops/paged_attention.py) — the gather/scatter round trip and its
  kv_gather/kv_scatter phases stop existing, while this module's block
  accounting (_decode_need, preemption) is unchanged because admission
  was always a function of blocks, never of the dense view;
- then the tick's chunked-prefill segments on the engine's B=1 bucket
  programs, each segment's KV commit riding the existing gather/scatter
  paths; a segment that completes its prompt is adopted into its batch
  lane and its first token sampled in the same tick.

Preemption keeps the paged prefix intact: the victim's live page table is
aliased into the PagedPrefixCache (zero copy, refcounted) before the slot
is released, so its eventual resume re-prefills only what the cache
cannot cover.  Victims holding engine-buffered fused-chunk tokens are
skipped — their device position is ahead of the driver-confirmed stream,
so their table cannot be snapshotted consistently.

The executor only reads the plan (loop-side snapshots) and the engine; it
never touches the scheduler queue.  Results flow back as plain data in a
:class:`TickResult` the loop applies.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from dnet_tpu.kv import KVPoolExhausted
from dnet_tpu.obs import metric
from dnet_tpu.sched.policy import PrefillChunk, TickPlan
from dnet_tpu.utils.logger import get_logger

log = get_logger()

_PREEMPTIONS = metric("dnet_sched_preemptions_total")

#: consecutive starved requeues before a prefill surfaces the typed
#: backpressure error instead of waiting for blocks that may never free
MAX_STARVED_REQUEUES = 8


@dataclass
class TickResult:
    #: nonce -> SampleResult from the batched decode dispatch
    decode_results: Dict[str, object] = field(default_factory=dict)
    #: nonce -> SampleResult sampled at prefill completion (adopt)
    adopted: Dict[str, object] = field(default_factory=dict)
    #: nonce -> absolute staged-token position after this tick's chunk
    progress: Dict[str, int] = field(default_factory=dict)
    errors: Dict[str, str] = field(default_factory=dict)
    #: DECODING sequences evicted back to WAITING (block starvation)
    preempted: List[str] = field(default_factory=list)
    #: PREFILLING requests that gave their staged work back (starved /
    #: lost the slot race) and should retry from WAITING
    requeued: List[str] = field(default_factory=list)
    #: nonces whose decode result was already handed off mid-tick through
    #: the wire-pipeline dispatch seam (execute_tick's on_decode) — the
    #: loop-side apply must not resolve these a second time
    dispatched: List[str] = field(default_factory=list)
    prefill_tokens: int = 0
    decode_lanes: int = 0


def _decode_need(engine, nonces) -> int:
    """Fresh blocks the pool must cover for one decode step across these
    lanes (R=1 floor; the engine's own extension shrinks wider fused
    chunks down to it under pressure)."""
    cfg = engine._kv_cfg
    need = 0
    for n in nonces:
        slot = engine.slot_of.get(n)
        if slot is None:
            continue
        tbl = engine._tables[slot]
        have = len(tbl.blocks) if tbl is not None else 0
        need += max(cfg.blocks_for(int(engine.pos[slot]) + 1) - have, 0)
    return need


def _preempt(engine, nonce: str, ids: List[int]) -> None:
    """Evict one DECODING sequence: alias its committed KV into the prefix
    cache (paged prefix intact — resume re-prefills only the uncovered
    tail), then release its slot, blocks, and inner session.  A lane whose
    device position ran ahead of the driver-confirmed stream (engine-
    buffered fused-chunk tokens) skips the alias — store_prefix refuses
    the inconsistent snapshot — and its resume recomputes the dropped
    lookahead (greedy-deterministic, so the stream is unchanged)."""
    slot = engine.slot_of.get(nonce)
    if slot is not None and ids:
        committed = ids[: int(engine.pos[slot])]
        try:
            engine.store_prefix(nonce, committed)
        except Exception as exc:
            # losing the alias only costs the resume a re-prefill
            log.debug("preemption prefix store for %s skipped: %s", nonce, exc)
    engine.end_session(nonce)
    _PREEMPTIONS.labels(reason="block_starvation").inc()


def _preempt_for_decode(engine, plan: TickPlan, reqs: dict, res: TickResult) -> None:
    """Evict lowest-priority lanes until the pool covers this tick's
    decode extensions.  The most urgent lane is never evicted."""
    victims = [v for v in plan.victims if v in engine.slot_of]
    while len(victims) > 1 and reqs:
        need = _decode_need(engine, reqs)
        if need <= engine.kv_pool.free:
            return
        v = victims.pop(0)
        _preempt(engine, v, plan.ids.get(v, []))
        res.preempted.append(v)
        reqs.pop(v, None)


def _run_prefill_chunk(
    engine, plan: TickPlan, chunk: PrefillChunk, res: TickResult
) -> None:
    nonce = chunk.nonce
    if chunk.first:
        try:
            engine.reserve_slot(nonce)
        except RuntimeError as exc:
            if "no free batch slots" in str(exc):
                # the loop-side slot estimate lost a race (TTL sweep /
                # concurrent teardown): a clean retry, never a client error
                res.requeued.append(nonce)
                return
            raise
        engine.seed_from_prefix(nonce, chunk.ids, chunk.seed)
    sess = engine.eng.sessions.get(nonce)
    cur = int(sess.pos) if sess is not None else 0
    end = max(min(chunk.end, len(chunk.ids)), cur)
    piece = chunk.ids[cur:] if chunk.last else chunk.ids[cur:end]
    logits = None
    if piece:
        try:
            logits = engine.prefill_chunk(nonce, piece, chunk.seed)
        except KVPoolExhausted as exc:
            _handle_prefill_starvation(engine, plan, chunk, res, cur, exc)
            return
        res.prefill_tokens += len(piece)
    res.progress[nonce] = cur + len(piece)
    if not chunk.last:
        return
    while True:
        try:
            engine.store_prefix(nonce, chunk.ids)
            sample = engine.adopt_prefilled(nonce, logits, chunk.decoding)
        except KVPoolExhausted as exc:
            victims = [
                v
                for v in chunk.victims
                if v in engine.slot_of and v not in res.preempted
            ]
            if victims:
                # evict and retry IN THIS TICK: end_session frees the
                # victim's blocks synchronously, and a next-tick retry is
                # impossible here — the chunks are fully committed, so a
                # re-driven tick would have no logits left to adopt from
                _preempt(engine, victims[0], plan.ids.get(victims[0], []))
                res.preempted.append(victims[0])
                continue
            _handle_prefill_starvation(engine, plan, chunk, res, cur, exc)
            return
        except Exception as exc:
            log.exception("scheduler prefill adopt failed for %s", nonce)
            engine.abandon_prefill(nonce)
            res.errors[nonce] = str(exc)
            return
        break
    res.adopted[nonce] = sample


def _handle_prefill_starvation(
    engine,
    plan: TickPlan,
    chunk: PrefillChunk,
    res: TickResult,
    cur: int,
    exc: KVPoolExhausted,
) -> None:
    """A prefill segment the pool refused before committing anything.

    With a strictly-lower-priority DECODING victim available: evict it
    (its blocks free now) and keep this request's staged session — the
    next tick retries the same segment against the refilled pool (safe
    here because the chunk pre-check raises before any KV commits; the
    adopt-time starvation retries in-tick instead, see the caller).  With
    no victim but other residents: give the staged work back and retry
    from WAITING once their blocks free (bounded by the loop's starved
    counter).  Alone: surface the typed backpressure error — nothing will
    ever free the blocks this prompt needs."""
    victims = [
        v
        for v in chunk.victims
        if v in engine.slot_of and v not in res.preempted
    ]
    if victims:
        v = victims[0]
        _preempt(engine, v, plan.ids.get(v, []))
        res.preempted.append(v)
        res.progress[chunk.nonce] = cur  # staged work kept; retry next tick
        return
    others = [n for n in engine.slot_of if n != chunk.nonce]
    engine.abandon_prefill(chunk.nonce)
    if others:
        res.requeued.append(chunk.nonce)
        return
    res.errors[chunk.nonce] = str(exc)


def execute_tick(engine, plan: TickPlan, on_decode=None) -> TickResult:
    """One tick on the compute thread.  ``on_decode`` is the wire-pipeline
    dispatch seam (DNET_WIRE_PIPELINE=1): when set, each decode result is
    handed off the moment the batched dispatch lands — BEFORE this tick's
    prefill chunks run — so decode futures resolve (and, on a ring, the
    next hop's frames launch) while prompt tokens are still burning,
    instead of barriering the whole tick behind its slowest segment.
    Results dispatched this way are also recorded in ``dispatched`` so the
    loop-side apply doesn't resolve them twice."""
    res = TickResult()
    reqs = dict(plan.decode)
    if reqs and getattr(engine, "kv_pool", None) is not None:
        _preempt_for_decode(engine, plan, reqs, res)
    if reqs:
        budgets = {n: plan.budgets.get(n) for n in reqs}
        out, errs = engine.decode_batch(reqs, budgets=budgets)
        res.decode_results.update(out)
        res.errors.update(errs)
        res.decode_lanes = len(reqs)
        if on_decode is not None:
            for nonce, sample in out.items():
                try:
                    on_decode(nonce, sample)
                    res.dispatched.append(nonce)
                except Exception:
                    # a failed early dispatch falls back to the barriered
                    # apply path — the result is still in decode_results
                    log.exception("early decode dispatch failed for %s", nonce)
    for chunk in plan.prefills:
        if chunk.nonce in res.preempted:
            continue
        try:
            _run_prefill_chunk(engine, plan, chunk, res)
        except Exception as exc:
            log.exception("scheduler prefill chunk failed for %s", chunk.nonce)
            try:
                engine.abandon_prefill(chunk.nonce)
            except Exception as inner:
                log.debug("abandon_prefill after failure: %s", inner)
            res.errors[chunk.nonce] = str(exc)
    return res
