"""Scheduler declaration data — label enums for the dnet_sched_* families.

A LEAF module (stdlib only, imports nothing from dnet_tpu) so that
``dnet_tpu/obs`` can pre-touch the label sets at registry init without a
cycle, and the metrics lint (pass 10, DL019) can cross-check the exposed
series against these declarations from either direction — the same
pattern as ``admission/reasons.py`` and ``membership/epoch.py``.
"""

from __future__ import annotations

#: Per-request scheduler states (queue.py state machine).  ``finished`` is
#: terminal and never holds queue residency, so the queue-depth gauge only
#: carries the three live states below.
STATE_WAITING = "waiting"
STATE_PREFILLING = "prefilling"
STATE_DECODING = "decoding"
STATE_FINISHED = "finished"

#: Label set of dnet_sched_queue_depth{state=}: requests resident in the
#: scheduler queue by state.
QUEUE_STATES = (STATE_WAITING, STATE_PREFILLING, STATE_DECODING)

#: Label set of dnet_sched_batch_tokens{kind=}: per-tick batch composition
#: — how many prompt tokens rode chunked-prefill segments and how many
#: sequences took a decode step in the same tick.
BATCH_KINDS = ("prefill", "decode")

#: Label set of dnet_sched_preemptions_total{reason=}.
#: ``block_starvation`` — the paged-KV pool could not cover a decode
#: extension or a prefill chunk, so the lowest-priority running sequence
#: was evicted back to WAITING (paged prefix aliased into the prefix
#: cache where possible, so resume re-prefills only the uncovered tail).
#: ``starved_requeue`` — a PREFILLING request gave its staged work back
#: and returned to WAITING because the pool could not cover its next
#: chunk and no lower-priority victim existed.
PREEMPT_REASONS = ("block_starvation", "starved_requeue")
