"""Wire compression for DCN activation hops.

Reference: src/dnet/compression/ (8 Metal kernels + sparse wire formats,
SURVEY.md §2.4).  On TPU the in-slice hops are ICI collectives inside one
XLA program (no wire at all); compression only matters for cross-host DCN /
gRPC hops, where column sparsification cuts activation bytes at a small
accuracy cost.  Kernels are Pallas (TPU) with a jnp fallback.
"""

from dnet_tpu.compression.ops import (
    column_l2_norms,
    column_sparsify,
    gather_columns,
    scatter_columns,
)
from dnet_tpu.compression.wire import (
    DeviceEncode,
    codec_name,
    compress_tensor,
    decompress_tensor,
    decompress_tensor_device,
    is_compressed_dtype,
    launch_encode,
)

__all__ = [
    "column_l2_norms",
    "column_sparsify",
    "gather_columns",
    "scatter_columns",
    "DeviceEncode",
    "codec_name",
    "compress_tensor",
    "decompress_tensor",
    "decompress_tensor_device",
    "is_compressed_dtype",
    "launch_encode",
]
