"""Sparse wire formats for DCN activation hops.

Reference: src/dnet/compression/wire.py:80-171 — two true-sparse formats
with metadata smuggled through the frame's dtype string:

  sparse_v1   (bf16 kept columns, exact on kept data):
    dtype   = "<base>|fmt=sparse_v1|pct=<drop_frac>|orig=<C>"
    payload = [column bitmask ceil(C/8)] + [kept columns <base>]

  qsparse8_v1 (int8-affine kept columns, ~4x denser than bf16 kept):
    dtype   = "<base>|fmt=qsparse8_v1|pct=<drop_frac>|orig=<C>|gs=<G>"
    payload = [column bitmask] + [uint8 codes R*K] +
              [f32 scales R*ceil(K/gs)] + [f32 biases R*ceil(K/gs)]
    codes are per-(row, group-of-kept-columns) affine: v = code*scale + bias
    (the analog of the reference's uint8 codes + compact scales/biases,
    wire.py:112-171; scales stay f32 because the KEPT columns are exactly
    the large-norm activations that can overflow fp16; <base> is the
    dequantized output dtype).

Column selection and the gather run on device (compression.ops Pallas
kernels); the byte packing is host-side — the wire is host-bound anyway.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from dnet_tpu.compression.ops import (
    _topk_column_mask,
    column_l2_norms,
    gather_columns,
)
from dnet_tpu.utils.serialization import numpy_dtype

FMT_TAG = "fmt=sparse_v1"
QFMT_TAG = "fmt=qsparse8_v1"


def is_compressed_dtype(dtype: str) -> bool:
    return "|" in dtype and (FMT_TAG in dtype or QFMT_TAG in dtype)


def compress_tensor(
    x,
    drop_frac: float,
    wire_dtype: str = "bfloat16",
    quant_bits: int = 0,
    group_size: int = 64,
) -> Tuple[bytes, str, Tuple[int, ...]]:
    """[B, T, D] (or [R, D]) activations -> sparse payload.

    Column selection runs on device (norms + top-k + Pallas gather); only
    the kept columns leave the host.  quant_bits=8 selects qsparse8_v1
    (int8-affine kept columns with per-(row, group) f32 scales/biases);
    0 keeps sparse_v1 (kept columns verbatim in wire_dtype).
    Returns (payload, tagged dtype string, original shape).
    """
    import jax.numpy as jnp

    orig_shape = tuple(x.shape)
    D = orig_shape[-1]
    x2 = jnp.reshape(x, (-1, D))
    keep = max(int(round(D * (1.0 - drop_frac))), 1)
    mask_np = np.asarray(_topk_column_mask(column_l2_norms(x2), keep))
    idx = np.nonzero(mask_np)[0]
    kept_dev = gather_columns(x2, jnp.asarray(idx, dtype=jnp.int32))
    bitmask = np.packbits(mask_np)

    if quant_bits == 0:
        nd = numpy_dtype(wire_dtype)
        kept = np.asarray(kept_dev).astype(nd)
        payload = bitmask.tobytes() + np.ascontiguousarray(kept).tobytes()
        dtype = f"{wire_dtype}|{FMT_TAG}|pct={drop_frac:g}|orig={D}"
        return payload, dtype, orig_shape
    if quant_bits != 8:
        raise NotImplementedError(f"compress quant_bits={quant_bits} (0 or 8)")

    # qsparse8_v1: per-(row, group) affine uint8 over the KEPT columns
    R, K = kept_dev.shape
    gs = max(int(group_size), 1)
    G = -(-K // gs)
    pad = G * gs - K
    kf = jnp.pad(kept_dev.astype(jnp.float32), ((0, 0), (0, pad))).reshape(R, G, gs)
    mn = jnp.min(kf, axis=-1)
    mx = jnp.max(kf, axis=-1)
    scale = jnp.maximum((mx - mn) / 255.0, 1e-12)
    codes = jnp.clip(
        jnp.round((kf - mn[..., None]) / scale[..., None]), 0, 255
    ).astype(jnp.uint8)
    codes_np = np.asarray(codes).reshape(R, G * gs)[:, :K]
    payload = (
        bitmask.tobytes()
        + np.ascontiguousarray(codes_np).tobytes()
        + np.asarray(scale, dtype=np.float32).tobytes()
        + np.asarray(mn, dtype=np.float32).tobytes()
    )
    dtype = f"{wire_dtype}|{QFMT_TAG}|pct={drop_frac:g}|orig={D}|gs={gs}"
    return payload, dtype, orig_shape


def _parse_header(payload: bytes, dtype: str, shape: Tuple[int, ...]):
    """Shared wire-header parse: (base dtype, fields, D, mask_bytes,
    bitmask[D] bool, K kept columns, R rows)."""
    if not is_compressed_dtype(dtype):
        raise ValueError(f"not a compressed dtype tag: {dtype!r}")
    base = dtype.split("|", 1)[0]
    fields = dict(
        part.split("=", 1) for part in dtype.split("|")[1:] if "=" in part
    )
    D = int(fields["orig"])
    mask_bytes = (D + 7) // 8
    bitmask = np.unpackbits(
        np.frombuffer(payload[:mask_bytes], dtype=np.uint8), count=D
    ).astype(bool)
    K = int(bitmask.sum())
    R = int(np.prod(shape[:-1])) if len(shape) > 1 else 1
    return base, fields, D, mask_bytes, bitmask, K, R


def decompress_tensor(payload: bytes, dtype: str, shape: Tuple[int, ...]) -> np.ndarray:
    """Inverse of compress_tensor: (dequantize and) scatter kept columns
    back to zeros.  Host-side numpy — kept for tools/tests; the serving
    receive path uses decompress_tensor_device."""
    base, fields, D, mask_bytes, bitmask, K, R = _parse_header(payload, dtype, shape)
    nd = numpy_dtype(base)

    if QFMT_TAG in dtype:
        gs = int(fields["gs"])
        G = -(-K // gs)
        codes_end = mask_bytes + R * K
        scales_end = codes_end + R * G * 4
        codes = np.frombuffer(
            payload[mask_bytes:codes_end], dtype=np.uint8
        ).reshape(R, K)
        scale = np.frombuffer(
            payload[codes_end:scales_end], dtype=np.float32
        ).reshape(R, G)
        bias = np.frombuffer(
            payload[scales_end:], dtype=np.float32
        ).reshape(R, G)
        pad = G * gs - K
        cf = np.pad(codes.astype(np.float32), ((0, 0), (0, pad))).reshape(R, G, gs)
        kept = (cf * scale[..., None] + bias[..., None]).reshape(R, G * gs)[:, :K]
        kept = kept.astype(nd)
    else:
        kept = np.frombuffer(payload[mask_bytes:], dtype=nd).reshape(R, K)
    out = np.zeros((R, D), dtype=nd)
    out[:, bitmask] = kept
    return out.reshape(shape)


def _scatter_impl(kept, idx, D: int):
    from dnet_tpu.compression.ops import scatter_columns

    return scatter_columns(kept, idx, D)


def _dequant_scatter_impl(codes, scale, bias, idx, D: int, gs: int):
    """Fused dequant + scatter, all on device: codes [R, K] uint8 with
    per-(row, group) affine params -> [R, D] with zeros at dropped columns.
    On TPU the scatter is the Pallas MXU one-hot matmul and XLA fuses the
    elementwise dequant into its operand read (the analog of the
    reference's fused k_dequant_scatter_q8, compression/kernels.py:164-225).
    """
    import jax.numpy as jnp

    from dnet_tpu.compression.ops import scatter_columns

    R, K = codes.shape
    G = scale.shape[1]
    pad = G * gs - K
    cf = jnp.pad(codes.astype(jnp.float32), ((0, 0), (0, pad))).reshape(R, G, gs)
    kept = (cf * scale[..., None] + bias[..., None]).reshape(R, G * gs)[:, :K]
    return scatter_columns(kept, idx, D)


def _jitted(fn, *static):
    import functools

    import jax

    return functools.cache(lambda: jax.jit(fn, static_argnames=static))


_scatter = _jitted(_scatter_impl, "D")
_dequant_scatter = _jitted(_dequant_scatter_impl, "D", "gs")


def decompress_tensor_device(payload: bytes, dtype: str, shape: Tuple[int, ...]):
    """Device-side inverse of compress_tensor: the header is parsed on the
    host (tiny), only the COMPACT buffers (codes/kept + scales/biases) are
    uploaded, and dequant + scatter run on device — the DCN receive path
    pays no host-side dequant/scatter detour before upload (VERDICT r2
    missing #1; reference decompresses on-GPU, wire.py:196-402).  Returns a
    device array of the BASE dtype in the original shape."""
    import jax.numpy as jnp

    base, fields, D, mask_bytes, bitmask, K, R = _parse_header(payload, dtype, shape)
    idx = jnp.asarray(np.nonzero(bitmask)[0], dtype=jnp.int32)
    out_dtype = jnp.dtype(numpy_dtype(base))

    if QFMT_TAG in dtype:
        gs = int(fields["gs"])
        G = -(-K // gs)
        codes_end = mask_bytes + R * K
        scales_end = codes_end + R * G * 4
        codes = jnp.asarray(
            np.frombuffer(payload[mask_bytes:codes_end], dtype=np.uint8).reshape(R, K)
        )
        scale = jnp.asarray(
            np.frombuffer(payload[codes_end:scales_end], dtype=np.float32).reshape(R, G)
        )
        bias = jnp.asarray(
            np.frombuffer(payload[scales_end:], dtype=np.float32).reshape(R, G)
        )
        out = _dequant_scatter()(codes, scale, bias, idx, D=D, gs=gs)
    else:
        kept = jnp.asarray(
            np.frombuffer(payload[mask_bytes:], dtype=numpy_dtype(base)).reshape(R, K)
        )
        out = _scatter()(kept, idx, D=D)
    return out.astype(out_dtype).reshape(shape)
