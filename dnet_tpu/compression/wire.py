"""Sparse wire formats for DCN activation hops.

Reference: src/dnet/compression/wire.py:80-171 — two true-sparse formats
with metadata smuggled through the frame's dtype string:

  sparse_v1   (bf16 kept columns, exact on kept data):
    dtype   = "<base>|fmt=sparse_v1|pct=<drop_frac>|orig=<C>"
    payload = [column bitmask ceil(C/8)] + [kept columns <base>]

  qsparse8_v1 (int8-affine kept columns, ~4x denser than bf16 kept):
    dtype   = "<base>|fmt=qsparse8_v1|pct=<drop_frac>|orig=<C>|gs=<G>"
    payload = [column bitmask] + [uint8 codes R*K] +
              [f32 scales R*ceil(K/gs)] + [f32 biases R*ceil(K/gs)]
    codes are per-(row, group-of-kept-columns) affine: v = code*scale + bias
    (the analog of the reference's uint8 codes + compact scales/biases,
    wire.py:112-171; scales stay f32 because the KEPT columns are exactly
    the large-norm activations that can overflow fp16; <base> is the
    dequantized output dtype).  ``gs=0`` marks the PER-TENSOR fallback for
    frames too small for group quant (fewer kept columns than one group):
    payload carries exactly one f32 scale + one f32 bias for the whole
    tensor instead of the R*G grids.  (gs=0 extends the v1 format in
    place; a pre-PR-14 decoder would div-by-zero on it — but the frame
    schema itself is versionless and PR 14 also grew ActivationFrame, so
    mixed-version rings were never a supported deployment: the load
    fan-out ships one version to every shard.)

Column selection and the gather run on device (compression.ops Pallas
kernels); the byte packing is host-side — the wire is host-bound anyway.
Under the overlapped wire pipeline (transport/wire_pipeline.py) the device
half LAUNCHES through :func:`launch_encode` (donated activation, outputs
left on device) and the byte packing happens later on the tx stage via
:meth:`DeviceEncode.finalize` — same formats, same bytes, different thread.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from dnet_tpu.compression.ops import (
    _topk_column_mask,
    column_l2_norms,
    gather_columns,
)
from dnet_tpu.utils.serialization import numpy_dtype

FMT_TAG = "fmt=sparse_v1"
QFMT_TAG = "fmt=qsparse8_v1"


def is_compressed_dtype(dtype: str) -> bool:
    return "|" in dtype and (FMT_TAG in dtype or QFMT_TAG in dtype)


def compress_tensor(
    x,
    drop_frac: float,
    wire_dtype: str = "bfloat16",
    quant_bits: int = 0,
    group_size: int = 64,
) -> Tuple[bytes, str, Tuple[int, ...]]:
    """[B, T, D] (or [R, D]) activations -> sparse payload.

    Column selection runs on device (norms + top-k + Pallas gather); only
    the kept columns leave the host.  quant_bits=8 selects qsparse8_v1
    (int8-affine kept columns with per-(row, group) f32 scales/biases);
    0 keeps sparse_v1 (kept columns verbatim in wire_dtype).
    Returns (payload, tagged dtype string, original shape).
    """
    import jax.numpy as jnp

    orig_shape = tuple(x.shape)
    D = orig_shape[-1]
    x2 = jnp.reshape(x, (-1, D))
    keep = max(int(round(D * (1.0 - drop_frac))), 1)
    mask_np = np.asarray(_topk_column_mask(column_l2_norms(x2), keep))
    idx = np.nonzero(mask_np)[0]
    kept_dev = gather_columns(x2, jnp.asarray(idx, dtype=jnp.int32))
    bitmask = np.packbits(mask_np)

    if quant_bits == 0:
        nd = numpy_dtype(wire_dtype)
        kept = np.asarray(kept_dev).astype(nd)
        payload = bitmask.tobytes() + np.ascontiguousarray(kept).tobytes()
        dtype = f"{wire_dtype}|{FMT_TAG}|pct={drop_frac:g}|orig={D}"
        return payload, dtype, orig_shape
    if quant_bits != 8:
        raise NotImplementedError(f"compress quant_bits={quant_bits} (0 or 8)")

    # qsparse8_v1: affine uint8 over the KEPT columns via the shared
    # quantize_q8 math (compression/ops.py — the one definition of the
    # scale epsilon / clip / padding).  A frame too small for group quant
    # (fewer kept columns than one group) falls back to ONE per-tensor
    # scale/bias pair (gs=0 tag) — zero-padding a mostly-empty group
    # would skew its min/max.
    from dnet_tpu.compression.ops import quantize_q8

    K = kept_dev.shape[1]
    gs = _effective_group(K, group_size)
    codes, scale, bias = quantize_q8(kept_dev, gs)
    payload = (
        bitmask.tobytes()
        + np.ascontiguousarray(np.asarray(codes)).tobytes()
        + np.asarray(scale, dtype=np.float32).tobytes()
        + np.asarray(bias, dtype=np.float32).tobytes()
    )
    dtype = f"{wire_dtype}|{QFMT_TAG}|pct={drop_frac:g}|orig={D}|gs={gs}"
    return payload, dtype, orig_shape


def _effective_group(K: int, group_size: int) -> int:
    """The group size a K-kept-column frame actually quantizes with:
    0 (per-tensor scales) when the frame cannot fill one group."""
    gs = max(int(group_size), 0)
    return 0 if K < gs or gs == 0 else gs


def _parse_header(payload: bytes, dtype: str, shape: Tuple[int, ...]):
    """Shared wire-header parse: (base dtype, fields, D, mask_bytes,
    bitmask[D] bool, K kept columns, R rows)."""
    if not is_compressed_dtype(dtype):
        raise ValueError(f"not a compressed dtype tag: {dtype!r}")
    base = dtype.split("|", 1)[0]
    fields = dict(
        part.split("=", 1) for part in dtype.split("|")[1:] if "=" in part
    )
    D = int(fields["orig"])
    mask_bytes = (D + 7) // 8
    bitmask = np.unpackbits(
        np.frombuffer(payload[:mask_bytes], dtype=np.uint8), count=D
    ).astype(bool)
    K = int(bitmask.sum())
    R = int(np.prod(shape[:-1])) if len(shape) > 1 else 1
    return base, fields, D, mask_bytes, bitmask, K, R


def decompress_tensor(payload: bytes, dtype: str, shape: Tuple[int, ...]) -> np.ndarray:
    """Inverse of compress_tensor: (dequantize and) scatter kept columns
    back to zeros.  Host-side numpy — kept for tools/tests; the serving
    receive path uses decompress_tensor_device."""
    base, fields, D, mask_bytes, bitmask, K, R = _parse_header(payload, dtype, shape)
    nd = numpy_dtype(base)

    if QFMT_TAG in dtype:
        gs = int(fields["gs"])
        codes_end = mask_bytes + R * K
        codes = np.frombuffer(
            payload[mask_bytes:codes_end], dtype=np.uint8
        ).reshape(R, K)
        if gs == 0:  # per-tensor fallback: one f32 scale + one f32 bias
            scale = np.frombuffer(payload[codes_end:codes_end + 4], np.float32)[0]
            bias = np.frombuffer(payload[codes_end + 4:codes_end + 8], np.float32)[0]
            kept = (codes.astype(np.float32) * scale + bias).astype(nd)
        else:
            G = -(-K // gs)
            scales_end = codes_end + R * G * 4
            scale = np.frombuffer(
                payload[codes_end:scales_end], dtype=np.float32
            ).reshape(R, G)
            bias = np.frombuffer(
                payload[scales_end:], dtype=np.float32
            ).reshape(R, G)
            pad = G * gs - K
            cf = np.pad(codes.astype(np.float32), ((0, 0), (0, pad))).reshape(R, G, gs)
            kept = (cf * scale[..., None] + bias[..., None]).reshape(R, G * gs)[:, :K]
            kept = kept.astype(nd)
    else:
        kept = np.frombuffer(payload[mask_bytes:], dtype=nd).reshape(R, K)
    out = np.zeros((R, D), dtype=nd)
    out[:, bitmask] = kept
    return out.reshape(shape)


def _scatter_impl(kept, idx, D: int):
    from dnet_tpu.compression.ops import scatter_columns

    return scatter_columns(kept, idx, D)


def _dequant_scatter_impl(codes, scale, bias, idx, D: int, gs: int):
    """Fused dequant + scatter, all on device: codes [R, K] uint8 with
    per-(row, group) affine params -> [R, D] with zeros at dropped columns.
    On TPU the scatter is the Pallas MXU one-hot matmul and XLA fuses the
    elementwise dequant into its operand read (the analog of the
    reference's fused k_dequant_scatter_q8, compression/kernels.py:164-225).
    gs == 0 is the per-tensor fallback: scale/bias are 1-element arrays
    broadcast over the whole code grid.
    """
    import jax.numpy as jnp

    from dnet_tpu.compression.ops import scatter_columns

    R, K = codes.shape
    if gs == 0:
        kept = codes.astype(jnp.float32) * scale[0] + bias[0]
        return scatter_columns(kept, idx, D)
    G = scale.shape[1]
    pad = G * gs - K
    cf = jnp.pad(codes.astype(jnp.float32), ((0, 0), (0, pad))).reshape(R, G, gs)
    kept = (cf * scale[..., None] + bias[..., None]).reshape(R, G * gs)[:, :K]
    return scatter_columns(kept, idx, D)


def _jitted(fn, *static):
    import functools

    import jax

    return functools.cache(lambda: jax.jit(fn, static_argnames=static))


_scatter = _jitted(_scatter_impl, "D")
_dequant_scatter = _jitted(_dequant_scatter_impl, "D", "gs")


def decompress_tensor_device(payload: bytes, dtype: str, shape: Tuple[int, ...]):
    """Device-side inverse of compress_tensor: the header is parsed on the
    host (tiny), only the COMPACT buffers (codes/kept + scales/biases) are
    uploaded, and dequant + scatter run on device — the DCN receive path
    pays no host-side dequant/scatter detour before upload (VERDICT r2
    missing #1; reference decompresses on-GPU, wire.py:196-402).  Returns a
    device array of the BASE dtype in the original shape."""
    import jax.numpy as jnp

    base, fields, D, mask_bytes, bitmask, K, R = _parse_header(payload, dtype, shape)
    idx = jnp.asarray(np.nonzero(bitmask)[0], dtype=jnp.int32)
    out_dtype = jnp.dtype(numpy_dtype(base))

    if QFMT_TAG in dtype:
        gs = int(fields["gs"])
        codes_end = mask_bytes + R * K
        codes = jnp.asarray(
            np.frombuffer(payload[mask_bytes:codes_end], dtype=np.uint8).reshape(R, K)
        )
        if gs == 0:  # per-tensor fallback: single f32 scale + bias
            scale = jnp.asarray(
                np.frombuffer(payload[codes_end:codes_end + 4], np.float32)
            )
            bias = jnp.asarray(
                np.frombuffer(payload[codes_end + 4:codes_end + 8], np.float32)
            )
        else:
            G = -(-K // gs)
            scales_end = codes_end + R * G * 4
            scale = jnp.asarray(
                np.frombuffer(payload[codes_end:scales_end], dtype=np.float32).reshape(R, G)
            )
            bias = jnp.asarray(
                np.frombuffer(payload[scales_end:], dtype=np.float32).reshape(R, G)
            )
        out = _dequant_scatter()(codes, scale, bias, idx, D=D, gs=gs)
    else:
        kept = jnp.asarray(
            np.frombuffer(payload[mask_bytes:], dtype=numpy_dtype(base)).reshape(R, K)
        )
        out = _scatter()(kept, idx, D=D)
    return out.astype(out_dtype).reshape(shape)


# ---- overlapped encode (wire pipeline) ------------------------------------


def codec_name(dtype: str) -> str:
    """Human/metrics name of the hop codec a frame's dtype tag selects."""
    if QFMT_TAG in dtype:
        return "qsparse8_v1"
    if FMT_TAG in dtype:
        return "sparse_v1"
    return dtype  # plain wire dtype = the lossless codec


class DeviceEncode:
    """A LAUNCHED on-device hop encode whose bytes are not host-side yet.

    Construction (on the compute thread, via :func:`launch_encode`) only
    dispatches the jitted encode — the activation buffer is donated and
    the outputs stay on device.  :meth:`finalize` (on the transport tx
    stage, any thread) blocks on the device results, packs the payload
    bytes, and is the ONLY point that pays D2H time.  ``dtype`` and
    ``shape`` are known at launch, so the frame header can be built before
    the bytes exist."""

    __slots__ = ("kind", "bufs", "dtype", "shape")

    def __init__(self, kind: str, bufs: tuple, dtype: str, shape: tuple) -> None:
        self.kind = kind  # "cast" | "sparse" | "q8"
        self.bufs = bufs
        self.dtype = dtype
        self.shape = tuple(shape)

    def finalize(self) -> bytes:
        """D2H readback + byte packing.  cast/sparse payloads match the
        synchronous encoders (tensor_to_bytes / compress_tensor) byte for
        byte; q8 scales may differ from compress_tensor's by 1 ulp (jit
        vs eager reduction order) — DECODED values agree, but do not
        assert byte equality across the two encode paths."""
        if self.kind == "cast":
            (arr,) = self.bufs
            return np.ascontiguousarray(np.asarray(arr)).tobytes()
        if self.kind == "sparse":
            mask, kept = self.bufs
            return (
                np.packbits(np.asarray(mask)).tobytes()
                + np.ascontiguousarray(np.asarray(kept)).tobytes()
            )
        mask, codes, scale, bias = self.bufs
        return (
            np.packbits(np.asarray(mask)).tobytes()
            + np.ascontiguousarray(np.asarray(codes)).tobytes()
            + np.asarray(scale, dtype=np.float32).tobytes()
            + np.asarray(bias, dtype=np.float32).tobytes()
        )


def launch_encode(
    x,
    drop_frac: float,
    wire_dtype: str = "bfloat16",
    quant_bits: int = 0,
    group_size: int = 64,
) -> DeviceEncode:
    """Dispatch the on-device half of the hop codec and return the pending
    encode.  ``x`` ([B, T, D] or [R, D] device array) is DONATED to the
    jitted encode — callers must treat it as dead afterwards (the DL021
    contract).  Codec selection mirrors compress_tensor: quant_bits=8 ->
    qsparse8_v1 (drop_frac may be 0.0: pure int8 over every column),
    drop_frac>0 with quant_bits=0 -> sparse_v1, else the lossless
    wire-dtype cast."""
    import jax.numpy as jnp

    from dnet_tpu.compression.ops import wire_cast, wire_q8, wire_sparse

    orig_shape = tuple(x.shape)
    nd = numpy_dtype(wire_dtype)
    D = orig_shape[-1]
    # every branch traces on the flattened [R, D] view so the compiled
    # programs key on row count alone — (1, T, D) and (T, 1, D) frames
    # share one program and the shard's load-time warm covers both (the
    # payload bytes are unchanged: the reshape is contiguous and the
    # frame header carries orig_shape)
    x2 = jnp.reshape(jnp.asarray(x), (-1, D))
    if drop_frac <= 0 and quant_bits == 0:
        arr = wire_cast()(x2, wire_np_dtype=nd)
        return DeviceEncode("cast", (arr,), wire_dtype, orig_shape)
    keep = max(int(round(D * (1.0 - drop_frac))), 1)
    if quant_bits == 0:
        mask, kept = wire_sparse()(x2, keep=keep)
        dtype = f"{wire_dtype}|{FMT_TAG}|pct={drop_frac:g}|orig={D}"
        return DeviceEncode(
            "sparse", (mask, kept.astype(jnp.dtype(nd))), dtype, orig_shape
        )
    if quant_bits != 8:
        raise NotImplementedError(f"wire quant_bits={quant_bits} (0 or 8)")
    gs = _effective_group(keep, group_size)
    mask, codes, scale, bias = wire_q8()(x2, keep=keep, gs=gs, wire_np_dtype=nd)
    dtype = f"{wire_dtype}|{QFMT_TAG}|pct={drop_frac:g}|orig={D}|gs={gs}"
    return DeviceEncode("q8", (mask, codes, scale, bias), dtype, orig_shape)
