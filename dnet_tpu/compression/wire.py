"""Sparse wire format for DCN activation hops.

Reference: src/dnet/compression/wire.py:80-171 — `sparse_v1` packs a column
bitmask + the kept fp16 columns, with metadata smuggled through the frame's
dtype string.  Same scheme here:

  dtype = "<base>|fmt=sparse_v1|pct=<drop_frac>|orig=<C>"
  payload = [bitmask bytes (ceil(C/8))] + [kept columns, column-major f16]

Compression/decompression are host-side (the wire is host-bound anyway);
the column selection runs on device via compression.ops.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from dnet_tpu.compression.ops import _topk_column_mask, column_l2_norms
from dnet_tpu.utils.serialization import numpy_dtype

FMT_TAG = "fmt=sparse_v1"


def is_compressed_dtype(dtype: str) -> bool:
    return "|" in dtype and FMT_TAG in dtype


def compress_tensor(
    x, drop_frac: float, wire_dtype: str = "bfloat16"
) -> Tuple[bytes, str, Tuple[int, ...]]:
    """[B, T, D] (or [R, D]) activations -> sparse payload.

    Column selection runs on device (norms + top-k); only the kept columns
    leave the host.  wire_dtype defaults to bf16 — activations can exceed
    fp16 range, and the kept columns are exactly the large-norm ones.
    Returns (payload, tagged dtype string, original shape).
    """
    import jax.numpy as jnp

    orig_shape = tuple(x.shape)
    D = orig_shape[-1]
    x2 = jnp.reshape(x, (-1, D))
    keep = max(int(round(D * (1.0 - drop_frac))), 1)
    mask_np = np.asarray(_topk_column_mask(column_l2_norms(x2), keep))
    nd = numpy_dtype(wire_dtype)
    kept = np.asarray(x2)[:, mask_np].astype(nd)
    bitmask = np.packbits(mask_np)
    payload = bitmask.tobytes() + np.ascontiguousarray(kept).tobytes()
    dtype = f"{wire_dtype}|{FMT_TAG}|pct={drop_frac:g}|orig={D}"
    return payload, dtype, orig_shape


def decompress_tensor(payload: bytes, dtype: str, shape: Tuple[int, ...]) -> np.ndarray:
    """Inverse of compress_tensor: scatter kept columns back to zeros."""
    if not is_compressed_dtype(dtype):
        raise ValueError(f"not a compressed dtype tag: {dtype!r}")
    base = dtype.split("|", 1)[0]
    nd = numpy_dtype(base)
    fields = dict(
        part.split("=", 1) for part in dtype.split("|")[1:] if "=" in part
    )
    D = int(fields["orig"])
    mask_bytes = (D + 7) // 8
    bitmask = np.unpackbits(
        np.frombuffer(payload[:mask_bytes], dtype=np.uint8), count=D
    ).astype(bool)
    kept_count = int(bitmask.sum())
    R = int(np.prod(shape[:-1])) if len(shape) > 1 else 1
    kept = np.frombuffer(payload[mask_bytes:], dtype=nd).reshape(R, kept_count)
    out = np.zeros((R, D), dtype=nd)
    out[:, bitmask] = kept
    return out.reshape(shape)
