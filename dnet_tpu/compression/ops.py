"""Column sparsification ops (Pallas on TPU, jnp fallback elsewhere).

Reference: src/dnet/compression/ops.py:104-190 (`column_sparsify_tensor`
dispatching hand-written Metal kernels) — the op zeroes the k columns with
the smallest L2 norms so the wire layer can ship only the kept columns.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dnet_tpu.utils.logger import get_logger

log = get_logger()

_LANE = 128


def _norms_kernel(x_ref, out_ref):
    """Accumulate per-column sum of squares over row tiles.

    Grid: one program per row-tile; out is revisited by every program
    (TPU grid is sequential, so accumulation is safe)."""
    import jax.experimental.pallas as pl

    i = pl.program_id(0)
    xf = x_ref[:].astype(jnp.float32)
    partial = jnp.sum(xf * xf, axis=0, keepdims=True)  # [1, C_tile]

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += partial


def _column_sq_norms_pallas(x: jnp.ndarray, row_tile: int = 256) -> jnp.ndarray:
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, C = x.shape
    assert R % row_tile == 0, "caller guards exact tiling"
    grid = (R // row_tile,)
    return pl.pallas_call(
        _norms_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((row_tile, C), lambda i: (i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec((1, C), lambda i: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, C), jnp.float32),
    )(x)[0]


def column_l2_norms(x: jnp.ndarray) -> jnp.ndarray:
    """Squared L2 norm per column of a 2D tensor [R, C] -> [C] f32.

    Pallas kernel on TPU when the shape tiles cleanly; jnp otherwise
    (XLA fuses the fallback fine — the kernel exists for the DCN egress
    hot path where activations are large and lane-aligned).
    """
    R, C = x.shape
    on_tpu = jax.devices()[0].platform == "tpu"
    row_tile = R if R <= 256 else 256
    # tail row-blocks would be silently skipped by the grid: only use the
    # kernel when the tiling divides exactly
    if on_tpu and C % _LANE == 0 and R % 8 == 0 and R % row_tile == 0:
        try:
            return _column_sq_norms_pallas(x, row_tile=row_tile)
        except Exception as exc:  # pallas unavailable/mosaic error: fall back
            log.debug("pallas column_sq_norms fell back to jnp: %s", exc)
    xf = x.astype(jnp.float32)
    return jnp.sum(xf * xf, axis=0)


def _matmul_kernel(a_ref, b_ref, o_ref):
    """Tiled matmul with accumulation over the contraction grid axis (TPU
    grids run sequentially, so revisiting o_ref is safe)."""
    import jax.experimental.pallas as pl

    d = pl.program_id(2)

    @pl.when(d == 0)
    def _():
        o_ref[:] = jnp.zeros_like(o_ref)

    o_ref[:] += jax.lax.dot_general(
        a_ref[:].astype(jnp.float32),
        b_ref[:].astype(jnp.float32),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _tile(n: int, candidates) -> int:
    """Largest candidate tile that divides n exactly (grids must cover n —
    a floor-division remainder would silently skip rows)."""
    for c in candidates:
        if n % c == 0:
            return c
    return 0


def _pallas_matmul(a: jnp.ndarray, b: jnp.ndarray):
    """a [R, D] @ b [D, K] on the MXU via Pallas (gather/scatter engine:
    b is a one-hot selection matrix, reference kernels.py k_gather_cols /
    k_scatter_from_compact)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, D = a.shape
    _, K = b.shape
    tr = _tile(R, (256, 128, 64, 32, 16, 8))
    td = _tile(D, (512, 256, 128))
    tk = _tile(K, (256, 128))
    assert tr and td and tk, "caller guards exact tiling"
    grid = (R // tr, K // tk, D // td)
    out = pl.pallas_call(
        _matmul_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tr, td), lambda i, k, d: (i, d), memory_space=pltpu.VMEM),
            pl.BlockSpec((td, tk), lambda i, k, d: (d, k), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (tr, tk), lambda i, k, d: (i, k), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((R, K), jnp.float32),
    )(a, b)
    return out


def _pallas_selectable(rows: int, contraction: int, out: int) -> bool:
    return (
        jax.devices()[0].platform == "tpu"
        and _tile(rows, (256, 128, 64, 32, 16, 8)) > 0
        and _tile(contraction, (512, 256, 128)) > 0
        and _tile(out, (256, 128)) > 0
    )


def gather_columns(x: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """[R, D] -> [R, K]: select columns `idx` (MXU one-hot select on TPU —
    the analog of the reference's k_gather_cols Metal kernel; a plain
    O(R*K) take elsewhere)."""
    R, D = x.shape
    K = idx.shape[0]
    if _pallas_selectable(R, D, K):
        onehot = (jnp.arange(D)[:, None] == idx[None, :]).astype(jnp.float32)
        try:
            return _pallas_matmul(x, onehot).astype(x.dtype)
        except Exception as exc:  # pallas/mosaic unavailable: fall back
            log.debug("pallas gather_columns fell back to jnp: %s", exc)
    return jnp.take(x, idx, axis=1)


def scatter_columns(kept: jnp.ndarray, idx: jnp.ndarray, D: int) -> jnp.ndarray:
    """[R, K] -> [R, D]: scatter kept columns back, zeros elsewhere
    (reference k_scatter_from_compact analog)."""
    R, K = kept.shape
    if _pallas_selectable(R, K, D):
        onehot = (idx[:, None] == jnp.arange(D)[None, :]).astype(jnp.float32)
        try:
            return _pallas_matmul(kept, onehot).astype(kept.dtype)
        except Exception as exc:  # pallas/mosaic unavailable: fall back
            log.debug("pallas scatter_columns fell back to jnp: %s", exc)
    return jnp.zeros((R, D), dtype=kept.dtype).at[:, idx].set(kept)


@functools.partial(jax.jit, static_argnames=("keep",))
def _topk_column_mask(norms: jnp.ndarray, keep: int) -> jnp.ndarray:
    C = norms.shape[0]
    _, idx = jax.lax.top_k(norms, keep)
    return jnp.zeros((C,), dtype=bool).at[idx].set(True)


# ---- wire-pipeline encode entry points ------------------------------------
#
# One jitted launch per hop codec, with the ACTIVATION BUFFER DONATED: the
# sliced hop activation is dead after the encode, so XLA reuses its buffer
# for the outputs and the compute thread's only serial cost is the dispatch.
# Every output stays on device — transport/wire_pipeline.py reads them back
# on the tx stage, off the compute thread (the overlap the wire pipeline
# exists for).  Kept columns come out in ascending column order (the wire
# bitmask convention decompress relies on).


def _wire_cast_impl(x2, wire_np_dtype):
    """Lossless hop codec: cast to the wire dtype on device."""
    return x2.astype(wire_np_dtype)


def _wire_sparse_impl(x2, keep):
    """sparse_v1 device half: (mask bool[D], kept [R, keep]) — top-k
    column selection by L2 norm, gathered in ascending column order."""
    norms = column_l2_norms(x2)
    _, idx = jax.lax.top_k(norms, keep)
    idx = jnp.sort(idx)
    mask = jnp.zeros(norms.shape, dtype=bool).at[idx].set(True)
    return mask, gather_columns(x2, idx)


def quantize_q8(kept: jnp.ndarray, gs: int):
    """THE affine-uint8 quant math, shared by the synchronous encoder
    (wire.compress_tensor) and the jitted wire-pipeline launch — one
    definition of the scale epsilon / clip bounds / padding scheme.

    kept [R, K] -> (codes uint8 [R, K], scale f32, bias f32).  gs > 0:
    per-(row, group-of-kept-columns) params, zero padding included (note
    jit-compiled reductions may differ from eager by 1 ulp in a scale, so
    the two paths are value-equivalent, not byte-identical).  gs == 0:
    ONE per-tensor scale/bias pair — the fallback for frames too small
    for group quant."""
    R, K = kept.shape
    if gs == 0:
        kf = kept.astype(jnp.float32)
        mn = jnp.min(kf)
        scale = jnp.maximum((jnp.max(kf) - mn) / 255.0, 1e-12)
        codes = jnp.clip(jnp.round((kf - mn) / scale), 0, 255).astype(jnp.uint8)
        return codes, scale.reshape(1), mn.reshape(1)
    G = -(-K // gs)
    pad = G * gs - K
    kf = jnp.pad(kept.astype(jnp.float32), ((0, 0), (0, pad))).reshape(R, G, gs)
    mn = jnp.min(kf, axis=-1)
    mx = jnp.max(kf, axis=-1)
    scale = jnp.maximum((mx - mn) / 255.0, 1e-12)
    codes = jnp.clip(
        jnp.round((kf - mn[..., None]) / scale[..., None]), 0, 255
    ).astype(jnp.uint8)
    return codes.reshape(R, G * gs)[:, :K], scale, mn


def _wire_q8_impl(x2, keep, gs, wire_np_dtype):
    """qsparse8_v1 device half: (mask, codes u8, scale f32, bias f32) —
    top-k column selection + the shared quantize_q8 math.
    wire_np_dtype only tags the dequantized output; it is threaded as a
    static arg so the (dtype-bearing) tag string can be built host-side
    without reading anything back."""
    del wire_np_dtype  # static: part of the cache key / dtype tag only
    norms = column_l2_norms(x2)
    _, idx = jax.lax.top_k(norms, keep)
    idx = jnp.sort(idx)
    mask = jnp.zeros(norms.shape, dtype=bool).at[idx].set(True)
    kept = gather_columns(x2, idx)
    codes, scale, bias = quantize_q8(kept, gs)
    return mask, codes, scale, bias


def _jitted_wire_encode(fn, *static):
    """Cached jit of one encode impl with the activation donated; wrapped
    by instrument_jit so a shape leak shows up on the compile dashboards
    instead of as a mystery per-hop latency cliff."""

    @functools.cache
    def build():
        from dnet_tpu.obs.jit import instrument_jit

        return instrument_jit(
            jax.jit(fn, static_argnames=static, donate_argnums=(0,)),
            "wire_encode",
        )

    return build


wire_cast = _jitted_wire_encode(_wire_cast_impl, "wire_np_dtype")
wire_sparse = _jitted_wire_encode(_wire_sparse_impl, "keep")
wire_q8 = _jitted_wire_encode(_wire_q8_impl, "keep", "gs", "wire_np_dtype")


def column_sparsify(x: jnp.ndarray, drop_frac: float) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Zero the `drop_frac` fraction of columns with smallest L2 norm.

    x: [R, C] (activations flattened to 2D, columns = features).
    Returns (sparsified x, keep mask [C] bool).
    """
    R, C = x.shape
    keep = max(int(round(C * (1.0 - drop_frac))), 1)
    norms = column_l2_norms(x)
    mask = _topk_column_mask(norms, keep)
    return jnp.where(mask[None, :], x, jnp.zeros_like(x)), mask
