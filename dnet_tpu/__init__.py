"""dnet-tpu: TPU-native distributed LLM inference.

A from-scratch TPU-first framework with the capabilities of dnet
(distributed pipelined-ring LLM inference): an OpenAI-compatible API node
drives a ring of shard nodes, each computing a contiguous window of
transformer layers on TPU via jit-compiled JAX, with activations hopping
between shards over ICI (`lax.ppermute` inside one XLA program) when they
share a slice, or over gRPC/DCN when they do not.  Layer weights stream
between host DRAM and TPU HBM so models larger than total HBM can run.
"""

__version__ = "0.4.0"

import os as _os

if _os.environ.get("JAX_PLATFORMS"):
    # Re-assert an explicit JAX_PLATFORMS through jax.config: environments
    # whose sitecustomize registers a TPU plugin before env vars are
    # consulted would otherwise hang every CPU-only run (server, tests,
    # smoke benches) on an unreachable TPU backend.
    try:
        import jax as _jax

        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])
    # dnetlint: disable=DL007 pre-import bootstrap: jax absent or already initialized; the logger does not exist yet
    except Exception:  # pragma: no cover - jax absent or already initialized
        pass
