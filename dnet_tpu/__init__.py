"""dnet-tpu: TPU-native distributed LLM inference.

A from-scratch TPU-first framework with the capabilities of dnet
(distributed pipelined-ring LLM inference): an OpenAI-compatible API node
drives a ring of shard nodes, each computing a contiguous window of
transformer layers on TPU via jit-compiled JAX, with activations hopping
between shards over ICI (`lax.ppermute` inside one XLA program) when they
share a slice, or over gRPC/DCN when they do not.  Layer weights stream
between host DRAM and TPU HBM so models larger than total HBM can run.
"""

__version__ = "0.1.0"
