"""Shard-node process wiring (reference: src/cli/shard.py:18-136).

Composes ShardRuntime + RingAdapter + gRPC + HTTP with ordered shutdown.
"""

from __future__ import annotations

import asyncio
import contextlib
import signal
import socket
from typing import Optional

from dnet_tpu.config import get_settings
from dnet_tpu.shard.adapter import RingAdapter
from dnet_tpu.shard.grpc_servicer import ShardRingServicer
from dnet_tpu.shard.http import ShardHTTPServer, ShardLoadModelRequest
from dnet_tpu.shard.runtime import ShardRuntime
from dnet_tpu.utils.logger import get_logger

log = get_logger()


class Shard:
    """Facade over runtime + adapter (reference: src/dnet/shard/shard.py)."""

    def __init__(self, shard_id: str, runtime: ShardRuntime, adapter: RingAdapter) -> None:
        self.shard_id = shard_id
        self.runtime = runtime
        self.adapter = adapter

    async def start(self) -> None:
        self.runtime.start(asyncio.get_running_loop())
        await self.adapter.start()

    async def stop(self) -> None:
        await self.adapter.shutdown()
        self.runtime.stop()

    async def load_model(self, req: ShardLoadModelRequest) -> None:
        from dnet_tpu.api.model_manager import resolve_model_dir

        model_dir = resolve_model_dir(
            req.model_path, get_settings().shard.models_dir
        )
        if model_dir is None:
            raise FileNotFoundError(f"model {req.model_path!r} not found on shard")
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(
            None,
            lambda: self.runtime.load_model_core(
                str(model_dir),
                req.layers,
                max_seq=req.max_seq_len,
                param_dtype=req.param_dtype,
                wire_dtype=req.wire_dtype,
                wire_codec=req.wire_codec,
                window_size=req.window_size,
                residency_size=req.residency_size,
                kv_bits=req.kv_bits,
                weight_quant_bits=req.weight_quant_bits,
                # 0 = the shard's own deployment default (each host knows
                # its chip count better than the API node does); lanes
                # compose with either resolution (r5)
                mesh_tp=req.mesh_tp or get_settings().shard.mesh_tp,
                mesh_sp=req.mesh_sp or get_settings().shard.mesh_sp,
                # 0 = this shard's own DNET_TP default (ShardCompute
                # resolves); the solver's mesh-slice placement overrides
                tp_degree=req.tp_degree,
                spec_lookahead=req.spec_lookahead,
                lanes=req.lanes,
                prefix_cache=req.prefix_cache,
                epoch=req.epoch,
                # engine ignores it unless plan_policy chose a streaming
                # policy — no second copy of that decision here
                repack_dir=get_settings().shard.repack_dir,
            ),
        )
        next_addr = f"{req.next_node.host}:{req.next_node.grpc_port}" if req.next_node else ""
        self.adapter.configure_topology(next_addr)

    async def update_topology(self, req) -> None:
        """Delta reconfiguration (dnet_tpu/membership/): this shard's load
        parameters are unchanged in the new topology, so it keeps its
        weights and only (1) proves it actually holds what the API thinks
        it holds, (2) drops every per-request state (KV sessions, lanes,
        prefix snapshots, stream dedup keys), (3) pins the new epoch, and
        (4) rewires its next pointer.  Raises ValueError when the proof
        fails — the HTTP layer answers 409 and the API full-loads."""
        from dnet_tpu.api.model_manager import resolve_model_dir
        from dnet_tpu.resilience.chaos import inject_async

        # chaos point: a fault here is this shard unreachable for the
        # delta — the API's call_with_retry runs, and a persistent fault
        # ends in the full-reload fallback (the 409 path's twin)
        await inject_async("update_topology")
        compute = self.runtime.compute
        if compute is None:
            raise ValueError("no model loaded; cannot delta-update")
        model_dir = resolve_model_dir(
            req.model_path, get_settings().shard.models_dir
        )
        if model_dir is None or str(model_dir) != self.runtime.model_path:
            raise ValueError(
                f"loaded model {self.runtime.model_path!r} does not match "
                f"requested {req.model_path!r}"
            )
        if sorted(compute.layers) != sorted(req.layers):
            raise ValueError(
                f"loaded layers {sorted(compute.layers)} do not match "
                f"requested {sorted(req.layers)}"
            )
        # drop per-request state minted under the old epoch: stale lanes /
        # KV must not leak into the new ring, queued old-epoch frames must
        # not burn compute on results the fences will reject, and the old
        # next-hop streams (possibly pointed at a fenced-out shard) must
        # close
        await self.adapter.reset_topology()
        self.runtime.drain_ingress()
        compute.reset("")
        # pin the epoch off-loop: set_epoch takes _model_lock, and a
        # concurrent full reload holds that lock in an executor for the
        # whole multi-second weight read — acquiring it here on the loop
        # thread would stall every stream on this shard for the duration
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self.runtime.set_epoch, req.epoch)
        next_addr = (
            f"{req.next_node.host}:{req.next_node.grpc_port}"
            if req.next_node
            else ""
        )
        self.adapter.configure_topology(next_addr)
        log.info(
            "shard %s delta-updated to epoch %d (next=%s, weights kept)",
            self.shard_id, req.epoch, next_addr or "<tail>",
        )

    async def unload_model(self) -> None:
        await self.adapter.reset_topology()
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self.runtime.unload_model_core)


async def serve_async(args) -> None:
    s = get_settings()
    # runtime sanitizer (DNET_SAN=1): the shard is the hottest thread/loop
    # boundary (ShardRuntime's compute worker vs the event loop), so the
    # stall watchdog + task audit cover its whole serving lifetime too;
    # install() is a no-op (None) when dsan is off
    from dnet_tpu.analysis.runtime import serving as dsan_serving

    san = dsan_serving.install(asyncio.get_running_loop())
    # fail fast on a malformed DNET_CHAOS (and bannerize an armed one)
    # before any model state exists — never mid-request
    from dnet_tpu.resilience.chaos import validate_startup

    validate_startup(role="shard")
    shard_id = args.shard_name or f"shard-{socket.gethostname()}-{args.grpc_port}"
    runtime = ShardRuntime(shard_id, queue_size=args.queue_size)
    adapter = RingAdapter(
        runtime,
        stream_idle_s=s.transport.stream_idle_sweep_s,
        backoff_s=s.transport.stream_backoff_s,
    )
    shard = Shard(shard_id, runtime, adapter)

    from dnet_tpu.transport.grpc_transport import (
        ring_service_handlers,
        start_grpc_server,
    )

    await shard.start()
    grpc_server = await start_grpc_server(
        args.host, args.grpc_port, ring_service_handlers(ShardRingServicer(adapter, runtime))
    )
    http = ShardHTTPServer(shard)
    await http.start(args.host, args.http_port)

    discovery = None
    if getattr(args, "discovery", "none") == "udp":
        try:
            from dnet_tpu.utils.p2p import UdpDiscovery

            discovery = UdpDiscovery(
                shard_id, args.http_port, args.grpc_port,
                udp_port=getattr(args, "udp_port", 58899),
                target_addr=getattr(args, "udp_target", "255.255.255.255"),
                cluster=getattr(args, "cluster", "default"),
            )
            log.info("UDP discovery announcing as %s", shard_id)
        except Exception as exc:
            log.warning("UDP discovery unavailable (%s); hostfile mode only", exc)

    sweeper = asyncio.ensure_future(runtime.sweeper())

    tui = None
    tui_task = None
    if getattr(args, "tui", False):
        from dnet_tpu.tui import DnetTUI

        tui = DnetTUI(role="shard", title=shard_id)
        tui.start_background()

        async def _feed_tui() -> None:
            while True:
                compute = runtime.compute
                tui.update_status(
                    state="serving" if compute else "idle",
                    queue=runtime.queue_depth,
                )
                if compute is not None:
                    resident = (
                        compute.engine.weight_cache.resident_layers()
                        if compute.engine.weight_cache is not None
                        else list(compute.layers)
                    )
                    tui.update_model_info(runtime.model_path, list(compute.layers), resident)
                else:
                    tui.update_model_info(None, [])
                await asyncio.sleep(1.0)

        tui_task = asyncio.ensure_future(_feed_tui())

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    log.info("dnet-shard %s ready (grpc %d, http %d)", shard_id, args.grpc_port, args.http_port)
    await stop.wait()

    log.info("shard shutting down")
    # cancel AND await the periodic tasks (the runtime twin of DL003): a
    # dropped cancellation leaves them to die unobserved at loop close —
    # and a DS005 finding under DNET_SAN=1
    if tui_task is not None:
        tui_task.cancel()
        with contextlib.suppress(asyncio.CancelledError):
            await tui_task
    if tui is not None:
        tui.stop()
    if discovery is not None:
        discovery.stop()
    sweeper.cancel()
    with contextlib.suppress(asyncio.CancelledError):
        await sweeper
    await http.stop()
    await grpc_server.stop(grace=2)
    await shard.stop()
    if san is not None:
        san.teardown(log)


def serve(args) -> None:
    asyncio.run(serve_async(args))
