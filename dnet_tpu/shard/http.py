"""Shard control-plane HTTP server (aiohttp).

Reference: src/dnet/shard/http_api.py:222-336 — /health, /load_model,
/unload_model, /measure_latency (gRPC probes to peers per payload size),
/profile (device microbench).  Plus the obs surface: `GET /metrics` (this
process's Prometheus exposition — transport rx bytes, token RPC latency,
snapshot-cache counters live HERE, not on the API node) and
`GET /v1/debug/timeline/{rid}` (this shard's recorded spans for a nonce).
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import List, Optional

from aiohttp import web
from pydantic import BaseModel, Field, ValidationError

from dnet_tpu.utils.logger import get_logger

log = get_logger()


class NextNode(BaseModel):
    host: str
    grpc_port: int


class ShardLoadModelRequest(BaseModel):
    """Reference: ShardLoadModelRequest (src/dnet/shard/models.py:10-33)."""

    model_path: str
    layers: List[int]
    next_node: Optional[NextNode] = None
    window_size: int = 0
    residency_size: int = 0
    kv_bits: int = 0
    max_seq_len: int = 4096
    api_callback_address: str = ""
    param_dtype: str = "bfloat16"
    wire_dtype: str = "bfloat16"
    # hop codec for this shard's outgoing hidden frames ("lossless" |
    # "qsparse8"; "" = the shard's own DNET_WIRE_CODEC default).  The API
    # resolves "auto" per hop: qsparse8 when the next shard is on another
    # host, lossless for same-host/loopback hops (greedy SSE parity).
    wire_codec: str = ""
    weight_quant_bits: int = 0
    # host-local mesh axes for this shard's window (parallel/shard_mesh.py):
    # 0 = use the shard's own DNET_SHARD_MESH_* defaults; -1 tp = all chips
    mesh_tp: int = 0
    mesh_sp: int = 0
    # NamedSharding tensor parallelism (parallel/tp.py): the solver's
    # mesh-slice placement ships the shard's tp degree here; 0 = the
    # shard's own DNET_TP default, 1 = single-chip.  Mutually exclusive
    # with a >1 mesh_tp/mesh_sp (one TP substrate per shard).
    tp_degree: int = 0
    # ring speculation (head drafts / tail verifies, shard/compute.py);
    # the API only sets this on single-round rewind-safe rings
    spec_lookahead: int = 0
    # batched lanes (shard/lanes.py): >1 allocates a pooled KV cache so the
    # API may coalesce that many concurrent nonces into one ring pass
    lanes: int = 0
    # ring prefix caching (shard/compute.py): per-shard KV snapshot count;
    # the API keys every store/hit through the prompt frames
    prefix_cache: int = 0
    # topology epoch this load pins (dnet_tpu/membership/): the shard
    # rejects frames/RPCs carrying any other nonzero epoch afterwards
    epoch: int = 0


class UpdateTopologyRequest(BaseModel):
    """Delta reconfiguration (dnet_tpu/membership/): bump the epoch, drop
    per-request state, rewire the next pointer — WITHOUT re-reading
    weights.  The shard verifies it really holds `model_path` + `layers`
    (a restarted shard holds neither) and answers 409 so the API falls
    back to a full /load_model."""

    model_path: str
    layers: List[int]
    epoch: int = 0
    next_node: Optional[NextNode] = None


class MeasureLatencyRequest(BaseModel):
    peers: List[str]  # "host:grpc_port"
    payload_sizes: List[int] = Field(default_factory=lambda: [1024, 65536, 1048576])
    rounds: int = 3


class ShardHTTPServer:
    def __init__(self, shard) -> None:
        self.shard = shard  # Shard facade (runtime + adapter)
        self.app = web.Application(client_max_size=16 * 1024 * 1024)
        self.app.router.add_get("/health", self.health)
        self.app.router.add_get("/metrics", self.metrics)
        self.app.router.add_get(
            "/v1/debug/timeline/{rid}", self.debug_timeline
        )
        self.app.router.add_get("/v1/debug/events", self.debug_events)
        self.app.router.add_post("/load_model", self.load_model)
        self.app.router.add_post("/update_topology", self.update_topology)
        self.app.router.add_post("/unload_model", self.unload_model)
        self.app.router.add_post("/measure_latency", self.measure_latency)
        self.app.router.add_post("/profile", self.profile)
        self.app.router.add_post("/probe_stage", self.probe_stage)
        self.app.router.add_post("/cleanup_repacked", self.cleanup_repacked)
        self._runner: Optional[web.AppRunner] = None

    async def start(self, host: str, port: int) -> None:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        log.info("shard HTTP listening on %s:%d", host, port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()
            self._runner = None

    # ---- handlers -----------------------------------------------------
    async def metrics(self, request: web.Request) -> web.Response:
        """Prometheus text exposition of this shard process's registry."""
        from dnet_tpu.obs.http import metrics_response

        return await metrics_response(request)

    async def debug_timeline(self, request: web.Request) -> web.Response:
        """This shard's recorded spans for one request nonce — the
        shard-side half (transport_recv, token_rpc, layer_compute, ...) of
        the timeline the API server exposes under the same path.  The 404
        shape follows this server's `{"status": "error"}` convention."""
        from dnet_tpu.obs.http import find_timeline

        rid = request.match_info["rid"]
        timeline = find_timeline(rid)
        if timeline is None:
            return web.json_response(
                {"status": "error",
                 "message": f"no recorded timeline for {rid!r}"},
                status=404,
            )
        return web.json_response(timeline)

    async def debug_events(self, request: web.Request) -> web.Response:
        """This shard's wide-event ring (obs/events.py), filtered by
        ?rid= / ?name= / ?last_s=.  `t_wall` is stamped at response build
        so the API's `?cluster=1` fetch doubles as the clock probe that
        rebases these events onto the driver's clock — the same trick the
        cluster timeline fetch uses."""
        import time as _time

        from dnet_tpu.obs.events import get_event_ring

        try:
            last_s = float(request.query.get("last_s", "") or 0.0)
        except ValueError:
            return web.json_response(
                {"status": "error", "message": "last_s must be a number"},
                status=400,
            )
        ring = get_event_ring()
        events = ring.query(
            rid=request.query.get("rid", "").strip(),
            name=request.query.get("name", "").strip(),
            last_s=last_s,
        )
        return web.json_response({
            "events": events,
            "dropped": ring.dropped,
            "t_wall": _time.time(),
        })

    async def health(self, request: web.Request) -> web.Response:
        rt = self.shard.runtime
        compute = rt.compute
        mesh = {}
        if compute is not None:
            eng = compute.engine
            mesh = {"mesh_tp": getattr(eng, "tp", 1), "mesh_sp": getattr(eng, "sp", 1)}
            from dnet_tpu.parallel.tp import TpEngine

            if isinstance(eng, TpEngine):
                mesh = {
                    "tp_degree": eng.tp,
                    "tp_collective": eng.collective_mode,
                }
            if compute.prefix_snaps is not None:
                mesh["prefix_cache"] = dict(compute.prefix_snaps.stats)
        from dnet_tpu.resilience.chaos import armed_summary

        chaos = armed_summary()
        if chaos is not None:
            mesh["chaos"] = chaos
        return web.json_response(
            {
                "status": "ok",
                "role": "shard",
                "shard_id": rt.shard_id,
                "model": rt.model_path or None,
                "layers": list(compute.layers) if compute else [],
                "queue_depth": rt.queue_depth,
                "epoch": rt.epoch,
                **mesh,
            }
        )

    async def load_model(self, request: web.Request) -> web.Response:
        try:
            req = ShardLoadModelRequest.model_validate(await request.json())
        except (json.JSONDecodeError, ValidationError) as exc:
            return web.json_response(
                {"status": "error", "message": f"invalid request: {exc}"}, status=400
            )
        t0 = time.perf_counter()
        try:
            await self.shard.load_model(req)
        except FileNotFoundError as exc:
            return web.json_response(
                {"status": "error", "message": str(exc)}, status=404
            )
        except Exception as exc:
            log.exception("shard load_model failed")
            return web.json_response(
                {"status": "error", "message": str(exc)}, status=500
            )
        return web.json_response(
            {"status": "ok", "load_time_s": time.perf_counter() - t0}
        )

    async def update_topology(self, request: web.Request) -> web.Response:
        """Delta reload's cheap half: epoch bump + state drop + rewire for
        a shard whose layer range (and every other load parameter) is
        unchanged.  409 when this shard cannot prove it holds the expected
        model/layers — the API then ships a full /load_model instead."""
        try:
            req = UpdateTopologyRequest.model_validate(await request.json())
        except (json.JSONDecodeError, ValidationError) as exc:
            return web.json_response(
                {"status": "error", "message": f"invalid request: {exc}"}, status=400
            )
        try:
            await self.shard.update_topology(req)
        except ValueError as exc:
            # holds nothing / wrong model / wrong layers: a delta update
            # would serve garbage — refuse so the caller full-loads
            return web.json_response(
                {"status": "error", "message": str(exc)}, status=409
            )
        except Exception as exc:
            log.exception("shard update_topology failed")
            return web.json_response(
                {"status": "error", "message": str(exc)}, status=500
            )
        return web.json_response(
            {"status": "ok", "epoch": self.shard.runtime.epoch}
        )

    async def unload_model(self, request: web.Request) -> web.Response:
        await self.shard.unload_model()
        return web.json_response({"status": "ok"})

    async def cleanup_repacked(self, request: web.Request) -> web.Response:
        """Delete repack caches: the current model's subtree when a model is
        loaded, otherwise the whole cache dir (reference
        shard/http_api.py:222-336 + utils/repack.py:220-313)."""
        import asyncio
        import shutil
        from pathlib import Path

        from dnet_tpu.config import get_settings

        rt = self.shard.runtime

        def cleanup():
            # under the model lock: a concurrent /load_model can't be mid-
            # construction (it holds the same lock), so the streams check and
            # the delete are atomic w.r.t. loads
            with rt._model_lock:
                compute = rt.compute
                if compute is not None and compute.engine.plan.streams_weights:
                    return None, 0  # refuse: live engine reads this cache
                base = Path(get_settings().shard.repack_dir).expanduser()
                target = base
                if rt.model_path:
                    target = base / Path(rt.model_path).name
                freed = 0
                if target.is_dir():
                    freed = sum(
                        f.stat().st_size for f in target.rglob("*") if f.is_file()
                    )
                    shutil.rmtree(target, ignore_errors=True)
                return str(target), freed

        loop = asyncio.get_running_loop()
        removed, freed = await loop.run_in_executor(None, cleanup)
        if removed is None:
            return web.json_response(
                {
                    "status": "error",
                    "message": "model is streaming from the repack cache; "
                    "POST /unload_model first",
                },
                status=409,
            )
        return web.json_response(
            {"status": "ok", "removed": removed, "freed_bytes": freed}
        )

    async def measure_latency(self, request: web.Request) -> web.Response:
        """Probe each peer over gRPC with increasing payloads; return
        median RTT seconds per (peer, size) (reference shard/http_api.py:85-204)."""
        try:
            req = MeasureLatencyRequest.model_validate(await request.json())
        except (json.JSONDecodeError, ValidationError) as exc:
            return web.json_response(
                {"status": "error", "message": f"invalid request: {exc}"}, status=400
            )
        from dnet_tpu.obs.clock import ClockSync
        from dnet_tpu.transport.grpc_transport import RingClient
        from dnet_tpu.transport.protocol import LatencyProbe

        results = {}
        clocks = ClockSync()  # min-RTT offset per peer from the same probes
        for peer in req.peers:
            client = RingClient(peer)
            peer_res = {}
            try:
                for size in req.payload_sizes:
                    rtts = []
                    payload = b"\x00" * size
                    for _ in range(req.rounds):
                        t0 = time.perf_counter()
                        t0_wall = time.time()
                        try:
                            echo = await client.measure_latency(
                                LatencyProbe(t_sent=t0_wall, payload=payload)
                            )
                            rtts.append(time.perf_counter() - t0)
                            if getattr(echo, "t_remote", 0.0):
                                clocks.update(
                                    peer, t0_wall, echo.t_remote, time.time()
                                )
                        except Exception as exc:
                            log.warning("latency probe to %s failed: %s", peer, exc)
                    if rtts:
                        rtts.sort()
                        peer_res[str(size)] = rtts[len(rtts) // 2]
            finally:
                await client.close()
            results[peer] = peer_res
        offsets = {
            peer: {
                "offset_s": est.offset_s,
                "rtt_s": est.rtt_s,
            }
            for peer in req.peers
            if (est := clocks.estimate(peer)) is not None
        }
        return web.json_response(
            {"status": "ok", "latency": results, "clock_offsets": offsets}
        )

    async def probe_stage(self, request: web.Request) -> web.Response:
        """Measured seconds/token for this shard's loaded stage (solver
        calibration input; parallel/calibrate.py)."""
        rt = self.shard.runtime
        if rt.compute is None:
            return web.json_response(
                {"status": "error", "message": "no model loaded"}, status=409
            )
        try:
            steps = int(request.query.get("steps", "3"))
        except ValueError:
            return web.json_response(
                {"status": "error", "message": "steps must be an integer"},
                status=400,
            )
        loop = asyncio.get_running_loop()
        try:
            stage_s = await loop.run_in_executor(
                None, rt.compute.probe_stage_time, max(1, min(steps, 16))
            )
        except Exception as exc:
            log.exception("stage probe failed")
            return web.json_response(
                {"status": "error", "message": str(exc)}, status=500
            )
        return web.json_response({"status": "ok", "stage_time_s": stage_s})

    async def profile(self, request: web.Request) -> web.Response:
        """Device microbenchmark: subprocess-isolated when the accelerator
        allows a second client, in-process otherwise (reference
        utils/profile_subproc.py pattern)."""
        from dnet_tpu.parallel.profiler import profile_device_subprocess

        loop = asyncio.get_running_loop()
        result = await loop.run_in_executor(None, profile_device_subprocess)
        return web.json_response({"status": "ok", "profile": result})
