"""ShardRuntime: queues + dedicated compute thread + model lifecycle.

Mirrors the reference's topology-agnostic runtime
(src/dnet/shard/runtime.py): a bounded ingress queue feeds ONE compute
thread (XLA dispatch never blocks the event loop), results flow to an
asyncio-side output queue, per-nonce KV sessions expire by TTL.
"""

from __future__ import annotations

import asyncio
import contextvars
import queue
import threading
import time
from typing import Optional

from dnet_tpu.analysis.runtime import ownership as dsan
from dnet_tpu.core.types import ActivationMessage
from dnet_tpu.obs import get_recorder, metric
from dnet_tpu.obs.events import bind, log_event
from dnet_tpu.resilience import chaos
from dnet_tpu.shard.compute import ShardCompute
from dnet_tpu.utils.logger import get_logger

log = get_logger()

_OUTQ_DROPPED = metric("dnet_shard_outq_dropped_total")
_DEADLINE_EXCEEDED = metric("dnet_deadline_exceeded_total")
_ZOMBIES = metric("dnet_san_zombie_threads_total")


def _error_final(
    msg: ActivationMessage, error: str, members: Optional[list] = None
) -> ActivationMessage:
    """Payload-free error final for `msg` — the ONE shape every failure
    path emits upstream (compute failure, deadline drop, outq overflow).
    `members` ({"nonce", "seq"} dicts) fails each batch-frame member
    individually; without it the frame's own nonce carries the error."""
    out = ActivationMessage(
        nonce=msg.nonce, layer_id=msg.layer_id, seq=msg.seq,
        dtype="error", shape=(), pos=msg.pos,
        callback_url=msg.callback_url, is_final=True, epoch=msg.epoch,
    )
    if members:
        out.lane_finals = [
            {
                "nonce": m["nonce"],
                "step": int(m["seq"]),
                "token_id": -1,
                "error": error,
            }
            for m in members
        ]
    else:
        out.token_id = -1
        out.error = error
    return out


class ShardRuntime:
    def __init__(self, shard_id: str, queue_size: int = 256) -> None:
        self.shard_id = shard_id
        self.compute: Optional[ShardCompute] = None
        self.model_path: str = ""
        # topology epoch pinned at load (dnet_tpu/membership/): the
        # adapter's ingress fence rejects frames from any other epoch, and
        # every egress message carries it so the fence holds end to end.
        # 0 = unfenced (no epoch-aware load yet).
        self.epoch: int = 0
        # dsan ownership domains (analysis/runtime/domains.py): only the
        # compute thread CONSUMES ingress; epoch writes hold _model_lock.
        # With DNET_SAN unset every dsan.* factory returns its argument
        # unchanged — the plain queue/lock below, zero instrumentation.
        self._model_lock = dsan.san_lock("ShardRuntime._model_lock")
        self._epoch_domain = dsan.maybe_lock_domain(self._model_lock)
        self.recv_q: queue.Queue = dsan.guard_methods(
            queue.Queue(maxsize=queue_size),
            dsan.thread_domain("shard-compute"),
            "ShardRuntime.recv_q",
            methods=("get", "get_nowait"),
        )
        self.out_q: Optional[asyncio.Queue] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._sweeper_task = None
        # awaited puts of overflow-replacement error finals (_put_out):
        # held so the tasks aren't GC'd mid-flight
        self._pending_errs: set = set()

    # ---- lifecycle ------------------------------------------------------
    def start(self, loop: asyncio.AbstractEventLoop) -> None:
        self._loop = loop
        # asyncio.Queue is NOT thread-safe: loop-only by contract (the
        # compute thread reaches it only through the _emit bridge)
        self.out_q = dsan.guard_methods(
            asyncio.Queue(maxsize=1024),
            dsan.loop_domain(loop),
            "ShardRuntime.out_q",
            methods=("put", "put_nowait", "get", "get_nowait", "qsize",
                     "empty", "full"),
        )
        self._pending_errs = dsan.guard_set(
            set(self._pending_errs),
            dsan.loop_domain(loop),
            "ShardRuntime._pending_errs",
        )
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._compute_worker, name="shard-compute", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self.recv_q.put_nowait(None)  # wake the worker; full queue is fine,
        except queue.Full:  # the worker exits on the next timeout poll
            pass
        # frames parked in out_q may hold wire-pipeline encode-ring slots;
        # with the egress worker already gone nobody will finalize them,
        # and a compute thread blocked in EncodeRing.acquire would ride
        # out its full wait budget and blow the join below — release the
        # slots (no readback) so the worker can reach the stop flag
        from dnet_tpu.transport.wire_pipeline import PendingWirePayload

        if self.out_q is not None:
            try:
                while True:
                    out = self.out_q.get_nowait()
                    if isinstance(out.data, PendingWirePayload):
                        out.data.discard()
            except asyncio.QueueEmpty:
                pass
        if self._thread:
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                # a worker wedged in XLA dispatch cannot be killed from
                # here; leaking it silently would hide the wedge, so make
                # it count (alert surface) and log where we left it
                _ZOMBIES.labels(thread="shard-compute").inc()
                log.warning(
                    "compute thread failed to join within 5s; leaking it "
                    "as a daemon zombie (likely wedged in device dispatch "
                    "or a blocking queue op)"
                )
            self._thread = None

    # ---- model ----------------------------------------------------------
    def load_model_core(
        self,
        model_dir: str,
        layers: list[int],
        max_seq: int = 4096,
        param_dtype: str = "bfloat16",
        wire_dtype: str = "bfloat16",
        kv_ttl_s: float = 600.0,
        window_size: int = 0,
        residency_size: int = 0,
        repack_dir: str | None = None,
        kv_bits: int = 0,
        weight_quant_bits: int = 0,
        mesh_tp: int = 1,
        mesh_sp: int = 1,
        tp_degree: int = 0,
        tp_collective: str = "",
        spec_lookahead: int = 0,
        lanes: int = 0,
        prefix_cache: int = 0,
        epoch: int = 0,
        wire_codec: str = "",
    ) -> None:
        """Blocking (call from an executor)."""
        with self._model_lock:
            t0 = time.perf_counter()
            if self.compute is not None:  # reload: free the old engine first
                self.compute.engine.close()
                self.compute = None
            self.compute = ShardCompute(
                model_dir,
                layers,
                max_seq=max_seq,
                param_dtype=param_dtype,
                wire_dtype=wire_dtype,
                kv_ttl_s=kv_ttl_s,
                window_size=window_size,
                residency_size=residency_size,
                repack_dir=repack_dir,
                kv_bits=kv_bits,
                weight_quant_bits=weight_quant_bits,
                mesh_tp=mesh_tp,
                mesh_sp=mesh_sp,
                tp_degree=tp_degree,
                tp_collective=tp_collective,
                spec_lookahead=spec_lookahead,
                lanes=lanes,
                prefix_cache=prefix_cache,
                wire_codec=wire_codec,
            )
            self.model_path = str(model_dir)
            self._set_epoch_locked(epoch)
            log.info(
                "shard %s loaded layers %s..%s (epoch %d) in %.1fs",
                self.shard_id,
                min(layers),
                max(layers),
                self.epoch,
                time.perf_counter() - t0,
            )

    def set_epoch(self, epoch: int) -> None:
        """Pin the topology epoch this shard serves under and publish it
        (dnet_topology_epoch) for the federation scrape.  Takes the model
        lock: epoch writes race model (re)loads otherwise — the delta
        /update_topology path writes from the event loop while a full
        reload may be pinning in an executor."""
        with self._model_lock:
            self._set_epoch_locked(epoch)

    def _set_epoch_locked(self, epoch: int) -> None:
        """Write half; caller holds _model_lock (load/unload already do)."""
        from dnet_tpu.membership import set_epoch_gauge

        dsan.check_access("ShardRuntime.epoch", self._epoch_domain, "write")
        self.epoch = int(epoch)
        set_epoch_gauge(self.epoch)

    def unload_model_core(self) -> None:
        with self._model_lock:
            self._drain_queue()
            if self.compute is not None:
                self.compute.engine.close()
            self.compute = None
            self.model_path = ""
            self._set_epoch_locked(0)
            import gc

            gc.collect()

    def _drain_queue(self) -> None:
        # deliberate cross-thread consume (unload runs in an executor,
        # delta reconfiguration drains from the loop): queue.Queue's own
        # lock makes the pop benign, and the epoch fence rejects anything
        # a racing worker might still pick up — so the thread("shard-
        # compute") consume domain is waived here, on the record
        with dsan.allowed("ShardRuntime.recv_q"):
            try:
                while True:
                    self.recv_q.get_nowait()
            except queue.Empty:
                pass

    def drain_ingress(self) -> None:
        """Discard queued-but-unprocessed frames (delta reconfiguration:
        frames admitted under the old epoch would otherwise run against
        freshly-cleared KV and emit old-epoch outputs downstream fences
        reject anyway — wasted compute, guaranteed-dropped results)."""
        self._drain_queue()

    # ---- data path --------------------------------------------------------
    def submit(self, msg: ActivationMessage, timeout: float = 5.0) -> bool:
        """Called from the event loop / gRPC thread; bounded for backpressure."""
        try:
            self.recv_q.put(msg, timeout=timeout)
            return True
        except queue.Full:
            return False

    @property
    def queue_depth(self) -> int:
        return self.recv_q.qsize()

    def _compute_worker(self) -> None:
        while not self._stop.is_set():
            try:
                msg = self.recv_q.get(timeout=0.5)
            except queue.Empty:
                continue
            if msg is None:
                continue
            compute = self.compute
            if compute is None:
                log.warning("dropping frame for %s: no model loaded", msg.nonce)
                continue
            # request identity for everything this frame touches on the
            # compute thread: rid (== nonce) + epoch arrive ON the frame,
            # node is this shard — every log line and event below carries
            # them without plumbing (obs/events.py); _emit snapshots the
            # context so the loop-side half keeps the binding too
            with bind(
                rid=msg.nonce,
                node=self.shard_id,
                epoch=(msg.epoch or None),
            ):
                self._process_frame(msg)

    def _process_frame(self, msg: ActivationMessage) -> None:
        """One frame, on the compute thread, inside its bind() scope."""
        compute = self.compute
        if compute is None:
            return
        if msg.deadline and time.time() >= msg.deadline:
            # the request's end-to-end deadline expired while this frame
            # sat in the ingress queue: nobody is waiting for the result,
            # so drop it BEFORE spending compute.  A tiny error final
            # still flows upstream so the driver fails fast instead of
            # burning its await timeout on a token that will never come.
            self._drop_expired(msg)
            return
        try:
            # per-hop trace spans, keyed by the request id (== nonce):
            # dequeue (ingress -> compute thread pickup, the queue
            # wait) and compute (this shard's window).  tx is recorded
            # by the adapter's egress worker — together they are the
            # shard half of the cluster-stitched timeline
            # (GET /v1/debug/timeline/{rid}?cluster=1).
            t_deq = time.perf_counter()
            msg.t_enq = t_deq
            rec = get_recorder()
            if msg.t_recv:
                rec.span(
                    msg.nonce, "shard_dequeue",
                    (t_deq - msg.t_recv) * 1000.0, seq=msg.seq,
                )
            # chaos point: an injected ChaosError here takes the exact
            # path a real compute failure takes (error final -> driver)
            chaos.inject("shard_compute")
            out = compute.process(msg)
            # the deadline and epoch ride every downstream hop (compute
            # builds fresh messages; stamping here covers all of them)
            out.deadline = msg.deadline
            out.epoch = msg.epoch
            rec.span(
                msg.nonce, "shard_compute",
                (time.perf_counter() - t_deq) * 1000.0,
                seq=msg.seq, layer_id=msg.layer_id,
            )
            self._emit(out)
        except Exception as exc:
            log.exception("compute failed for nonce %s", msg.nonce)
            # a batch frame's carrier nonce has no future API-side:
            # fail every MEMBER so their drivers surface the error
            # instead of blocking the full request timeout
            self._emit(_error_final(msg, str(exc), msg.lanes))

    def _drop_expired(self, msg: ActivationMessage) -> None:
        """Shed one deadline-expired frame at dequeue: zero compute spent,
        counted per stage, and an error final surfaced upstream (batch
        frames fail every member so each driver sees it)."""
        _DEADLINE_EXCEEDED.labels(stage="shard_dequeue").inc()
        # the shard half of the request's event story: rid/node/epoch come
        # from the enclosing bind() — the journal row joins the API's
        # request_complete on rid across /v1/debug/events
        log_event("shed", reason="deadline", stage="shard_dequeue", seq=msg.seq)
        get_recorder().span(
            msg.nonce, "deadline_drop", 0.0, seq=msg.seq,
            deadline=msg.deadline,
        )
        log.warning(
            "dropping expired frame for %s seq=%d (deadline %.3f past)",
            msg.nonce, msg.seq, time.time() - msg.deadline,
        )
        self._emit(
            _error_final(msg, "deadline exceeded at shard dequeue", msg.lanes)
        )

    def _emit(self, out: ActivationMessage) -> None:
        if self._loop is None or self.out_q is None:
            return
        out.t_tx_enq = time.perf_counter()
        # carry the compute thread's bind() scope across the thread->loop
        # hop: _put_out's log lines (outq overflow) keep the rid/node
        # stamp even though they run on the event loop
        ctx = contextvars.copy_context()
        self._loop.call_soon_threadsafe(ctx.run, self._put_out, out)

    def _put_out(self, out: ActivationMessage) -> None:
        try:
            self.out_q.put_nowait(out)
        except asyncio.QueueFull:
            # never lose the token silently: count the drop and surface a
            # payload-free error final in its place.  The replacement is
            # enqueued through an awaited put (runs when the egress worker
            # frees a slot), so the driver gets a prompt, explicit failure
            # instead of hanging its full request timeout on a frame that
            # evaporated here.
            _OUTQ_DROPPED.inc()
            log.error(
                "output queue full; dropping frame for %s seq=%d "
                "(error surfaced upstream)", out.nonce, out.seq,
            )
            # a dropped pipelined frame still holds an encode-ring slot:
            # release it (no readback) or the compute thread wedges behind
            # a payload nobody will ever finalize
            from dnet_tpu.transport.wire_pipeline import PendingWirePayload

            if isinstance(out.data, PendingWirePayload):
                out.data.discard()
            # a dropped batch frame must fail every member driver (a
            # dropped lane-finals message names its members by `step`)
            members = out.lanes or [
                {"nonce": f["nonce"], "seq": f["step"]}
                for f in (out.lane_finals or [])
            ]
            err = _error_final(
                out, "shard output queue overflowed; frame dropped", members
            )
            task = asyncio.ensure_future(self.out_q.put(err))
            self._pending_errs.add(task)
            task.add_done_callback(self._pending_errs.discard)

    # ---- maintenance ------------------------------------------------------
    async def sweeper(self, interval_s: float = 30.0) -> None:
        while True:
            await asyncio.sleep(interval_s)
            if self.compute is not None:
                n = self.compute.sweep_sessions()
                if n:
                    log.info("swept %d expired KV sessions", n)
