"""RingAdapter: the shard's transport glue.

Faithful to the reference's four-worker design
(src/dnet/shard/adapters/ring.py:88-299): an ingress path that either admits
a frame to local compute or relays it toward the owner of the next layer, an
egress task routing computed results (hidden-state -> next hop stream;
final token -> unary callback to the API), lazy next-hop connection, and an
idle-stream sweeper.  Channel factories are injectable so tests run the whole
adapter with fakes (tests/fakes pattern, no network).
"""

from __future__ import annotations

import asyncio
import time
from collections import OrderedDict
from typing import Callable, Dict, Optional

from dnet_tpu.core.types import ActivationMessage, TokenResult
from dnet_tpu.membership import epoch as epoch_fence
from dnet_tpu.obs import get_recorder, metric
from dnet_tpu.resilience import chaos
from dnet_tpu.resilience.policy import call_with_retry
from dnet_tpu.transport.protocol import ActivationFrame, TokenPayload
from dnet_tpu.transport.stream_manager import StreamManager
from dnet_tpu.transport.wire_pipeline import PendingWirePayload, WireTxStage
from dnet_tpu.utils.logger import get_logger

log = get_logger()

_RX_BYTES = metric("dnet_transport_rx_bytes_total")
_WIRE_BYTES = metric("dnet_wire_bytes_total")
_TOKEN_RPC_MS = metric("dnet_token_rpc_ms")


def parse_callback(url: str) -> str:
    """grpc://host:port -> host:port (reference ring.py:301-408 parses the
    same scheme)."""
    if url.startswith("grpc://"):
        return url[len("grpc://"):]
    return url


class RingAdapter:
    def __init__(
        self,
        runtime,
        ring_client_factory: Optional[Callable[[str], object]] = None,
        callback_client_factory: Optional[Callable[[str], object]] = None,
        stream_idle_s: float = 30.0,
        backoff_s: float = 0.25,
    ) -> None:
        from dnet_tpu.transport.grpc_transport import ApiCallbackClient, RingClient

        self.runtime = runtime
        self._make_ring_client = ring_client_factory or (lambda addr: RingClient(addr))
        self._make_cb_client = callback_client_factory or (
            lambda addr: ApiCallbackClient(addr)
        )
        self.next_addr: str = ""
        self._next_client = None
        self._streams: Optional[StreamManager] = None
        self._cb_clients: Dict[str, object] = {}  # callback addr -> client
        self._tasks: list[asyncio.Task] = []
        self._stream_idle_s = stream_idle_s
        self._backoff_s = backoff_s
        # wire-pipeline tx stage (transport/wire_pipeline.py): finalizes
        # pending device encodes on its own executor thread so the egress
        # worker's D2H readback overlaps the compute thread's next step
        self._wire_tx = WireTxStage()
        # ingress dedup: a sender whose stream broke re-opens and re-sends
        # the in-flight frame; if the first copy already made it into the
        # compute queue the duplicate must be ACKed, not re-computed.  Key
        # includes layer_id because multi-round rings legitimately pass the
        # same (nonce, seq) through a shard once PER ROUND.
        self._seen: "OrderedDict[tuple, bool]" = OrderedDict()

    # ---- lifecycle ------------------------------------------------------
    async def start(self) -> None:
        self._tasks = [
            asyncio.ensure_future(self._egress_worker()),
            asyncio.ensure_future(self._idle_sweeper()),
        ]

    async def shutdown(self) -> None:
        for t in self._tasks:
            t.cancel()
        self._tasks = []
        self._wire_tx.shutdown()
        await self.reset_topology()

    # ---- topology -------------------------------------------------------
    def configure_topology(self, next_addr: str) -> None:
        """next_addr: 'host:grpc_port' of the next shard; '' if we are last."""
        self.next_addr = next_addr
        self._next_client = None
        self._streams = None

    async def reset_topology(self) -> None:
        if self._streams:
            await self._streams.shutdown()
            self._streams = None
        if self._next_client is not None:
            await self._next_client.close()
            self._next_client = None
        # callback channels are independent: close them all at once so a
        # wedged channel cannot stall the topology reset behind it
        outcomes = await asyncio.gather(
            *(c.close() for c in self._cb_clients.values()),
            return_exceptions=True,
        )
        self._cb_clients.clear()
        for exc in outcomes:
            if isinstance(exc, Exception):
                raise exc
        self._seen.clear()
        self.next_addr = ""

    def _ensure_next(self):
        if self._next_client is None:
            if not self.next_addr:
                raise RuntimeError("no next hop configured")
            self._next_client = self._make_ring_client(self.next_addr)
            self._streams = StreamManager(
                self._next_client.open_stream,
                backoff_s=self._backoff_s,
                idle_timeout_s=self._stream_idle_s,
            )
        return self._streams

    DEDUP_CAP = 4096  # admitted-frame keys kept for duplicate detection

    # ---- ingress ----------------------------------------------------------
    async def ingress_frame(self, frame: ActivationFrame) -> tuple[bool, str]:
        """Admit a frame: local compute if the next layer is ours, else relay.
        Returns (ok, message) for the ACK."""
        n_bytes = len(getattr(frame, "payload", b"") or b"")
        _RX_BYTES.inc(n_bytes)
        _WIRE_BYTES.labels(dir="rx").inc(n_bytes)
        # t_sent (the SENDER's wall clock) rides into the span so the
        # cluster-stitched timeline can show per-hop wire time once both
        # endpoints' clock offsets are known (obs/clock.py)
        get_recorder().span(
            frame.nonce, "transport_recv", 0.0,
            bytes=n_bytes, seq=frame.seq, t_sent=frame.t_sent,
        )
        # Topology-epoch fence (dnet_tpu/membership/): a frame minted under
        # a dead epoch — a zombie sender that was fenced out by a re-solve,
        # or a partitioned peer replaying old state — is rejected BEFORE it
        # can reach compute or relay.  The chaos point deterministically
        # simulates a zombie frame so the rejection path is testable
        # without racing a real partition.
        held = self.runtime.epoch
        stale = epoch_fence.is_stale(held, frame.epoch)
        try:
            await chaos.inject_async("zombie_frame")
        except chaos.ChaosError:
            stale = True
        if stale:
            err = epoch_fence.reject("frame", held, frame.epoch)
            log.warning(
                "fenced frame %s seq=%d: %s", frame.nonce, frame.seq, err
            )
            return False, str(err)
        compute = self.runtime.compute
        if compute is not None and compute.wants(frame.layer_id):
            key = (frame.nonce, frame.seq, frame.layer_id)
            if key in self._seen:
                # transport retry replayed a frame this shard already
                # admitted (stream re-open re-sends the in-flight frame):
                # ACK idempotently instead of double-computing the step
                log.info("duplicate frame %s seq=%d deduped", frame.nonce, frame.seq)
                return True, "duplicate"
            msg = frame.to_message()
            msg.t_recv = time.perf_counter()
            if compute.will_predecode(msg, self.runtime.queue_depth):
                # rx half of the wire pipeline: launch H2D + dequant NOW
                # (async dispatch) so this frame's decode overlaps the
                # step the compute thread is currently inside.  The chaos
                # gate is the ASYNC flavor — a delay injection parks this
                # frame's admission, not the whole event loop.
                try:
                    await chaos.inject_async("wire_decode")
                    compute.predecode(msg)
                except Exception as exc:
                    log.error(
                        "wire decode failed for %s seq=%d: %s",
                        frame.nonce, frame.seq, exc,
                    )
                    return False, f"wire decode failed: {exc}"
            if not self.runtime.submit(msg, timeout=0.0 if self.runtime.queue_depth else 5.0):
                return False, "backpressure"
            self._seen[key] = True
            while len(self._seen) > self.DEDUP_CAP:
                self._seen.popitem(last=False)
            return True, ""
        # relay toward the owner (reference ring.py:161-206)
        try:
            streams = self._ensure_next()
            await streams.send(frame.nonce, frame)
            return True, "relayed"
        except Exception as exc:
            log.error("relay failed for %s: %s", frame.nonce, exc)
            return False, f"relay failed: {exc}"

    # ---- egress -------------------------------------------------------------
    async def _egress_worker(self) -> None:
        while True:
            out: ActivationMessage = await self.runtime.out_q.get()
            try:
                if out.is_final:
                    await self._send_token(out)
                else:
                    await self._send_activation(out)
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("egress failed for %s", out.nonce)

    async def _send_activation(self, msg: ActivationMessage) -> None:
        t0 = time.perf_counter()
        if isinstance(msg.data, PendingWirePayload):
            # pipelined hop: the compute thread only launched the encode;
            # the tx stage pays the D2H readback + byte packing HERE, on
            # its own executor, while compute is already in the next step.
            # The frame that goes on the wire is fully encoded — a stream
            # re-open re-sends these exact bytes with this seq (the PR 4
            # dedup/resume contract needs the re-send to be identical).
            pending = msg.data
            msg.data = await self._wire_tx.finalize(
                pending, nonce=msg.nonce, seq=msg.seq
            )
            get_recorder().span(
                msg.nonce, "wire_encode",
                (time.perf_counter() - t0) * 1000.0,
                seq=msg.seq, bytes=len(msg.data),
            )
        streams = self._ensure_next()
        from dnet_tpu.compression.wire import codec_name

        frame = ActivationFrame(
            nonce=msg.nonce,
            seq=msg.seq,
            layer_id=msg.layer_id,
            pos=msg.pos,
            dtype=msg.dtype,
            shape=tuple(msg.shape),
            payload=msg.data if isinstance(msg.data, bytes) else bytes(msg.data),
            codec=codec_name(msg.dtype),
            callback_url=msg.callback_url,
            decoding=_decoding_dict(msg),
            t_sent=time.time(),
            t_sent_mono=t0,
            auto_steps=msg.auto_steps,
            drafts=list(msg.drafts),
            lanes=list(msg.lanes),
            prefix_store=msg.prefix_store,
            prefix_hit=msg.prefix_hit,
            deadline=msg.deadline,
            epoch=msg.epoch,
        )
        await streams.send(msg.nonce, frame)
        # the tx leg of this hop's dequeue -> compute -> tx trace triple
        get_recorder().span(
            msg.nonce, "shard_tx", (time.perf_counter() - t0) * 1000.0,
            seq=msg.seq, bytes=len(frame.payload or b""),
        )

    async def _cb_send(self, client, payload: TokenPayload):
        """Token callback under the send_token retry policy: a transient
        API-side blip (or injected token_cb fault) must not permanently
        lose the token and strand the request until its timeout.  The
        chaos point sits INSIDE the retried callable so an injected error
        is absorbed exactly like a real one."""

        async def _attempt():
            await chaos.inject_async("token_cb")
            return await client.send_token(payload)

        return await call_with_retry(_attempt, method="send_token")

    async def _send_token(self, msg: ActivationMessage) -> None:
        if msg.lane_finals:
            # batched lanes: one callback per member nonce (the batch frame
            # itself has no token of its own)
            addr = parse_callback(msg.callback_url)
            if not addr:
                log.error("lane finals for %s have no callback", msg.nonce)
                return
            client = self._cb_clients.get(addr)
            if client is None:
                client = self._make_cb_client(addr)
                self._cb_clients[addr] = client
            # members are independent nonces (each appears once per batch):
            # fan the callbacks out concurrently instead of paying
            # (N-1) x RTT on every batched step
            await asyncio.gather(
                *(
                    self._cb_send(
                        client,
                        TokenPayload(
                            nonce=f["nonce"],
                            step=int(f["step"]),
                            token_id=int(f["token_id"]),
                            logprob=f.get("logprob"),
                            top_ids=list(f.get("top_ids") or []),
                            top_logprobs=list(f.get("top_logprobs") or []),
                            error=f.get("error", ""),
                            epoch=msg.epoch,
                        ),
                    )
                    for f in msg.lane_finals
                )
            )
            return
        if msg.cont is not None:
            # decode grant: feed the sampled token straight back into the
            # ring BEFORE the API callback — the next step's compute starts
            # while the token is still in flight to the API
            try:
                await self._send_continuation(msg)
            except Exception as exc:
                # the API already skipped sending frames for the granted
                # steps; without a signal it would block request_timeout_s
                # on the next await.  An error token for the NEXT step
                # fails the request fast instead.
                log.exception("continuation injection failed for %s", msg.nonce)
                try:
                    await self._send_error_token(
                        msg, msg.cont[3], f"decode-grant continuation failed: {exc}"
                    )
                except Exception:
                    log.exception("error-token delivery failed for %s", msg.nonce)
        addr = parse_callback(msg.callback_url)
        if not addr:
            log.error("final token for %s has no callback", msg.nonce)
            return
        client = self._cb_clients.get(addr)
        if client is None:
            client = self._make_cb_client(addr)
            self._cb_clients[addr] = client
        payload = TokenPayload(
            nonce=msg.nonce,
            step=msg.seq,
            token_id=int(msg.token_id if msg.token_id is not None else -1),
            logprob=msg.logprob,
            top_ids=[t for t, _ in (msg.top_logprobs or [])],
            top_logprobs=[lp for _, lp in (msg.top_logprobs or [])],
            error=msg.error,
            epoch=msg.epoch,
        )
        t0 = time.perf_counter()
        await self._cb_send(client, payload)
        # a verify block's additionally accepted tokens (ring speculation):
        # one callback per step, in step order behind the primary
        for step, token_id in msg.extra_finals or ():
            # dnetlint: disable=DL024 spec finals are one token stream: arrival in step order is the driver contract, not an independent fan-out
            await self._cb_send(
                client,
                TokenPayload(
                    nonce=msg.nonce, step=step, token_id=int(token_id),
                    epoch=msg.epoch,
                ),
            )
        # record first, then log the RECORDED value (the [PROFILE] line is
        # now a view over the same measurement the registry aggregates)
        rpc_ms = (time.perf_counter() - t0) * 1e3
        _TOKEN_RPC_MS.observe(rpc_ms)
        get_recorder().span(msg.nonce, "token_rpc", rpc_ms, step=msg.seq)
        log.info(
            "[PROFILE] token step=%d nonce=%s n=%d rpc=%.2fms",
            msg.seq,
            msg.nonce,
            1 + len(msg.extra_finals or ()),
            rpc_ms,
        )

    async def _send_error_token(
        self, msg: ActivationMessage, step: int, error: str
    ) -> None:
        addr = parse_callback(msg.callback_url)
        if not addr:
            return
        client = self._cb_clients.get(addr)
        if client is None:
            client = self._make_cb_client(addr)
            self._cb_clients[addr] = client
        await self._cb_send(
            client,
            TokenPayload(
                nonce=msg.nonce, step=step, token_id=-1, error=error,
                epoch=msg.epoch,
            ),
        )

    async def _send_continuation(self, msg: ActivationMessage) -> None:
        """Inject the tail's sampled token as the nonce's next entry frame.
        The tail's ring successor IS the head (assignments are ring-ordered,
        so last.next == first); multi-round rings relay by layer ownership."""
        import numpy as np

        from dnet_tpu.utils.serialization import tensor_to_bytes

        token_id, pos, steps, seq = msg.cont
        payload, _dtype, shape = tensor_to_bytes(
            np.asarray([[token_id]], dtype=np.int32)
        )
        frame = ActivationFrame(
            nonce=msg.nonce,
            seq=seq,
            layer_id=-1,
            pos=pos,
            dtype="tokens",
            shape=shape,
            payload=payload,
            callback_url=msg.callback_url,
            decoding=_decoding_dict(msg),
            auto_steps=steps,
            committed=list(msg.committed),
            t_sent=time.time(),
            t_sent_mono=time.perf_counter(),
            deadline=msg.deadline,
            epoch=msg.epoch,
        )
        streams = self._ensure_next()
        await streams.send(msg.nonce, frame)

    # ---- cache / sweeping ----------------------------------------------------
    async def reset_cache(self, nonce: str = "") -> None:
        if self.runtime.compute is not None:
            self.runtime.compute.reset(nonce)
        if self._streams is not None and nonce:
            await self._streams.end_stream(nonce)
        # dedup keys die with the nonce: a replayed request (prefix refill,
        # resume) legitimately re-sends step 0 after a reset
        if nonce:
            for key in [k for k in self._seen if k[0] == nonce]:
                del self._seen[key]
        else:
            self._seen.clear()

    async def _idle_sweeper(self) -> None:
        while True:
            await asyncio.sleep(self._stream_idle_s)
            if self._streams is not None:
                await self._streams.cleanup_idle()


def _decoding_dict(msg: ActivationMessage) -> dict:
    from dataclasses import asdict

    return asdict(msg.decoding)
