"""Shard gRPC servicer: the ring data-plane endpoints.

Reference: src/dnet/shard/grpc_servicer/servicer.py:21-161 — bidi
StreamActivations with per-frame ACKs and nonce validation, unary
SendActivation, HealthCheck with assigned layers + queue depth, ResetCache,
MeasureLatency echo.
"""

from __future__ import annotations

import time

from dnet_tpu.membership import epoch as epoch_fence
from dnet_tpu.transport.protocol import (
    ActivationFrame,
    Empty,
    HealthInfo,
    LatencyProbe,
    ResetCacheRequest,
    StreamAck,
)
from dnet_tpu.utils.logger import get_logger

log = get_logger()


class ShardRingServicer:
    def __init__(self, adapter, runtime) -> None:
        self.adapter = adapter
        self.runtime = runtime

    async def stream_activations(self, request_iterator, context):
        async for frame in request_iterator:
            ok, message = await self.adapter.ingress_frame(frame)
            yield StreamAck(
                nonce=frame.nonce,
                seq=frame.seq,
                ok=ok,
                backpressure=(message == "backpressure"),
                message=message,
            )

    async def send_activation(self, frame: ActivationFrame, context) -> StreamAck:
        ok, message = await self.adapter.ingress_frame(frame)
        return StreamAck(nonce=frame.nonce, seq=frame.seq, ok=ok, message=message)

    async def health_check(self, request: Empty, context) -> HealthInfo:
        compute = self.runtime.compute
        return HealthInfo(
            ok=True,
            model=self.runtime.model_path,
            layers=list(compute.layers) if compute else [],
            queue_depth=self.runtime.queue_depth,
            epoch=self.runtime.epoch,
        )

    async def reset_cache(self, request: ResetCacheRequest, context) -> Empty:
        # epoch fence: a reset minted under a dead topology (a zombie API
        # adapter, a partitioned peer) must not clear live-ring sessions.
        # Epoch 0 is the unfenced admin reset and always passes.
        held = self.runtime.epoch
        if epoch_fence.is_stale(held, request.epoch):
            raise epoch_fence.reject("reset_cache", held, request.epoch)
        await self.adapter.reset_cache(request.nonce)
        return Empty()

    async def measure_latency(self, probe: LatencyProbe, context) -> LatencyProbe:
        # echo with the same payload; caller computes RTT vs payload size.
        # t_remote stamps THIS node's wall clock so the same handshake
        # yields an NTP-midpoint clock-offset sample (obs/clock.py)
        return LatencyProbe(
            t_sent=probe.t_sent, payload=probe.payload, t_remote=time.time()
        )
