"""Shard compute core: one ActivationMessage in, one out.

The policy-level hot loop of the reference's FitInMemoryPolicy
(src/dnet/shard/policies/fit_in_memory.py:34-209), built on LocalEngine's
jitted shard paths: embed+window (first shard), hidden window (mid), window+
head+sample (last).  Incoming hidden states are padded to power-of-two
buckets so every frame length reuses a compiled program.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dnet_tpu.core.engine import LocalEngine, bucket_length
from dnet_tpu.core.sampler import SampleParams
from dnet_tpu.core.types import ActivationMessage, DecodingParams, TokenResult
from dnet_tpu.utils.logger import get_logger
from dnet_tpu.utils.serialization import bytes_to_device, tensor_to_bytes

log = get_logger()


class ShardCompute:
    """Owns the engine for this shard's layer range."""

    def __init__(
        self,
        model_dir: str | Path,
        layers: Sequence[int],
        max_seq: int = 4096,
        param_dtype: str = "bfloat16",
        wire_dtype: str = "bfloat16",
        kv_ttl_s: float = 600.0,
        window_size: int = 0,
        residency_size: int = 0,
        repack_dir: Optional[str] = None,
        kv_bits: int = 0,
        compress_frac: Optional[float] = None,
        weight_quant_bits: int = 0,
        mesh_tp: int = 1,
        mesh_sp: int = 1,
        mesh_devices: Optional[Sequence] = None,
        tp_degree: int = 0,
        tp_collective: str = "",
        spec_lookahead: int = 0,
        lanes: int = 0,
        prefix_cache: int = 0,
        wire_codec: str = "",
        wire_pipeline: Optional[bool] = None,
    ) -> None:
        from dnet_tpu.core.kvcache import resolve_kv_bits

        kv_dtype, kv_quant_bits = resolve_kv_bits(kv_bits)
        mesh_sp = max(mesh_sp, 1)  # 0/negative = "no sp axis", not "no mesh"
        if mesh_tp == -1:  # every local chip on the tp axis
            n = len(mesh_devices) if mesh_devices is not None else jax.local_device_count()
            mesh_tp = n // mesh_sp
        mesh_tp = max(mesh_tp, 1)
        # NamedSharding tensor parallelism (parallel/tp.py, ROADMAP item
        # 3's TP half): 0 = this shard's DNET_TP default, 1 = pinned off.
        # Precedence: an EXPLICIT tp_degree (solver mesh-slice placement)
        # selects the TP substrate; an explicit mesh request without one
        # keeps the shard_map substrate (the env default must not hijack
        # a caller that asked for mesh_tp/mesh_sp); sequence parallelism
        # always needs the shard_map substrate.
        if tp_degree == 0 and mesh_tp * mesh_sp == 1:
            from dnet_tpu.parallel.tp import tp_enabled_degree

            tp_degree = tp_enabled_degree()
        tp_degree = max(int(tp_degree), 1)
        if tp_degree > 1 and mesh_sp > 1:
            log.warning(
                "tp_degree=%d ignored: sequence parallelism (mesh_sp=%d) "
                "runs on the shard_map mesh substrate", tp_degree, mesh_sp,
            )
            tp_degree = 1
        if tp_degree > 1:
            tp_degree = self._clamp_tp(tp_degree, model_dir, mesh_devices)
        if tp_degree > 1:
            from dnet_tpu.parallel.tp import TpEngine

            self.engine = TpEngine(
                model_dir,
                layers=layers,
                tp=tp_degree,
                collective=tp_collective,
                devices=mesh_devices,
                max_seq=max_seq,
                param_dtype=param_dtype,
                kv_dtype=kv_dtype,
                kv_ttl_s=kv_ttl_s,
                kv_quant_bits=kv_quant_bits,
                weight_quant_bits=weight_quant_bits,
                window_size=window_size,
                residency_size=residency_size,
                repack_dir=repack_dir,
                spec_lookahead=spec_lookahead,
            )
        elif mesh_tp * mesh_sp > 1:
            # mesh-backed shard (VERDICT r3 next #1): this ring node's layer
            # window runs SPMD over the host's local chips; a window/
            # residency plan streams each layer as tp/sp-sharded device_puts
            # (VERDICT r4 next #2 — BASELINE config 3 on the mesh topology)
            from dnet_tpu.parallel.shard_mesh import MeshShardEngine

            self.engine = MeshShardEngine(
                model_dir,
                layers=layers,
                tp=mesh_tp,
                sp=mesh_sp,
                devices=mesh_devices,
                max_seq=max_seq,
                param_dtype=param_dtype,
                kv_dtype=kv_dtype,
                kv_ttl_s=kv_ttl_s,
                kv_quant_bits=kv_quant_bits,
                weight_quant_bits=weight_quant_bits,
                window_size=window_size,
                residency_size=residency_size,
                repack_dir=repack_dir,
            )
        else:
            self.engine = LocalEngine(
                model_dir,
                layers=layers,
                max_seq=max_seq,
                param_dtype=param_dtype,
                kv_dtype=kv_dtype,
                kv_ttl_s=kv_ttl_s,
                shard_mode=True,
                window_size=window_size,
                residency_size=residency_size,
                repack_dir=repack_dir,
                kv_quant_bits=kv_quant_bits,
                weight_quant_bits=weight_quant_bits,
            )
        self.layers = self.engine.model.layers
        self.wire_dtype = wire_dtype
        self.is_first = self.engine.model.is_first
        self.is_last = self.engine.model.is_last
        # k-round ring schedule: a non-contiguous assignment IS its rounds —
        # each contiguous run is one ring visit (reference api/utils.py:62-131)
        self.rounds: list[list[int]] = []
        for a in self.layers:
            if self.rounds and a == self.rounds[-1][-1] + 1:
                self.rounds[-1].append(a)
            else:
                self.rounds.append([a])
        # column-sparsify hidden hops toward the next shard (DCN only —
        # reference gates the same way, config.py:128-135, default off);
        # explicit arg wins, DNET_TRANSPORT_* is the deploy-wide default
        from dnet_tpu.config import get_settings

        t = get_settings().transport
        if compress_frac is None:
            compress_frac = t.compress_pct if t.compress else 0.0
        self.compress_frac = compress_frac
        # 8 -> qsparse8_v1 (int8-affine kept columns), 0 -> sparse_v1
        self.compress_quant_bits = t.compress_quant_bits
        # hop codec + overlapped wire pipeline (transport/wire_pipeline.py).
        # The codec is resolved by the API's load fan-out per hop ("auto" ->
        # qsparse8 for inter-host hops, lossless for same-host/loopback);
        # a shard loaded without one keeps the safe lossless default so
        # greedy SSE parity holds out of the box.  With the pipeline on,
        # _encode_activation only LAUNCHES the device encode and the tx
        # stage finishes it off-thread; the depth-bounded encode ring is
        # the backpressure coupling compute to wire drain.
        from dnet_tpu.transport.wire_pipeline import (
            EncodeRing,
            wire_pipeline_enabled,
        )

        w = get_settings().wire
        if not wire_codec:
            wire_codec = "lossless" if w.codec == "auto" else w.codec
        if wire_codec not in ("lossless", "qsparse8"):
            raise ValueError(
                f"unknown wire codec {wire_codec!r} (lossless | qsparse8)"
            )
        self.wire_codec = wire_codec
        self.wire_pipeline = (
            wire_pipeline_enabled() if wire_pipeline is None
            else bool(wire_pipeline)
        )
        self._wire_pct = w.qsparse_pct
        self._wire_gs = w.group_size
        self._encode_ring = EncodeRing(w.depth) if self.wire_pipeline else None
        # rx pre-decode depth: same knob as the tx ring — each pre-decoded
        # frame pins a fully-expanded activation on device (will_predecode)
        self._rx_depth = max(int(w.depth), 1)
        # ring speculation (composed with decode grants): the HEAD widens
        # granted continuation entries into [tok, drafts] verify blocks
        # (prompt-lookup against a host-side history), the TAIL verifies
        # the block's argmaxes and emits the accepted prefix.  The API's
        # load fan-out only enables this on single-round, non-streaming,
        # rewind-safe rings; each shard re-checks its own invariants.
        self.spec_lookahead = int(spec_lookahead)
        self._spec_ok = (
            self.spec_lookahead > 0
            and len(self.rounds) == 1
            and not self.engine.plan.streams_weights
            and self.engine.model.kv_rewindable(self.engine.max_seq)
        )
        self._hist: dict[str, np.ndarray] = {}  # head-side draft history
        # batched lanes (r5): N concurrent nonces share ONE ring pass; the
        # API coalesces their decode steps into multi-lane frames and this
        # pool serves them with one batched step (shard/lanes.py).  Needs a
        # single-round assignment with resident weights (LanePool refuses
        # streaming plans at construction — load-time, not first-frame);
        # mesh-backed shards compose (shard_map(vmap) lane programs).
        self.lane_pool = None
        if lanes > 1:
            if len(self.rounds) > 1:
                raise NotImplementedError(
                    "batched lanes need a single-round (contiguous) "
                    "assignment; k-round schedules serve batch=1"
                )
            # composes with mesh-backed shards too (r5): MeshShardEngine
            # supplies shard_map(vmap(...)) lane programs — N nonces per
            # ring pass, each pass SPMD over the host's local chips
            from dnet_tpu.shard.lanes import LanePool

            self.lane_pool = LanePool(self.engine, lanes)
        # ring prefix caching (r5): the API keys every store/hit through the
        # frames (it alone sees token ids — mid shards see hidden states);
        # this shard keeps ITS window's KV snapshots under those keys.
        # Needs resident weights (kv is a list under streaming) and a
        # single-round assignment (the prompt visits k times otherwise).
        self.prefix_snaps = None
        if (
            prefix_cache > 0
            and len(self.rounds) == 1
            and not self.engine.plan.streams_weights
        ):
            from dnet_tpu.core.prefix_cache import SnapshotStore

            self.prefix_snaps = SnapshotStore(prefix_cache)
        # jit-launched wire encode covers the closed, warmable frame-width
        # set this shard's hot loop emits: single decode (1), lane widths
        # (2..lanes), and spec verify blocks (1+lookahead) — prompt frames
        # carry their REAL token count and encode synchronously instead
        # (a per-prompt-length compile would be a worse stall than the
        # encode it hides).  Decided HERE, after _spec_ok/lane_pool exist.
        self._wire_jit_rows = max(
            int(lanes),
            1 + self.spec_lookahead if self._spec_ok else 1,
            1,
        )
        if self.wire_pipeline:
            self._warm_wire()

    @staticmethod
    def _clamp_tp(tp: int, model_dir, mesh_devices) -> int:
        """Degrade an over-asked tp_degree instead of bricking the load:
        clamp to the local device count and to the largest value <= tp
        dividing the model's attention AND kv head counts (the solver's
        own clamp rule, parallel/solver.py) — a DNET_TP=8 env default on
        a 2-kv-head model serves tp=2 with a warning, not a 500."""
        n_dev = (
            len(mesh_devices) if mesh_devices is not None
            else jax.local_device_count()
        )
        want = tp
        tp = min(tp, max(n_dev, 1))
        from dnet_tpu.models.base import ModelConfig
        from dnet_tpu.utils.checkpoint import Checkpoint

        cfg = ModelConfig.from_hf(Checkpoint(model_dir).config)
        heads = cfg.num_attention_heads or 0
        kv_heads = cfg.num_key_value_heads or heads
        while tp > 1 and (
            (heads and heads % tp) or (kv_heads and kv_heads % tp)
        ):
            tp -= 1
        if tp != want:
            log.warning(
                "tp_degree=%d clamped to %d (%d local devices, %d/%d "
                "attention/kv heads)", want, tp, n_dev, heads, kv_heads,
            )
        return tp

    def _book_tp_frame(self, tokens: int) -> None:
        """Analytic TP collective byte accounting for one processed frame
        (parallel/tp_collectives.py; host-side shape math, no syncs)."""
        observe = getattr(self.engine, "observe_step_collectives", None)
        if observe is not None:
            observe(tokens)

    def _warm_wire(self) -> None:
        """Pre-compile the jitted hop encode for every frame shape the
        pipeline launches (decode R=1, plus each lane width when lanes are
        pooled): the wire pipeline's whole point is a ~0 serial launch,
        and a mid-flight trace+compile on the compute thread would be
        exactly the stall it exists to remove.  The jits are
        process-cached (compression/ops), so repeated loads re-use the
        compiled programs."""
        frac, qbits = self._wire_params()
        D = self.engine.config.hidden_size
        nd = self.engine.param_dtype
        from dnet_tpu.compression import (
            decompress_tensor_device,
            is_compressed_dtype,
            launch_encode,
        )

        t0 = time.perf_counter()
        for rows in range(1, self._wire_jit_rows + 1):
            x = jnp.zeros((rows, 1, D), dtype=nd)
            # straight DeviceEncode: no ring slot, no chaos, no metrics —
            # this is load-time warmup, not a served frame
            enc = launch_encode(
                x, frac, wire_dtype=self.wire_dtype, quant_bits=qbits,
                group_size=self._wire_gs,
            )
            payload = enc.finalize()
            # warm the DECODE program for the same shape too: ingress
            # predecode runs on the event loop, and a first-frame
            # trace+compile there would stall every stream on this shard
            if is_compressed_dtype(enc.dtype):
                decompress_tensor_device(payload, enc.dtype, enc.shape)
        log.info(
            "wire encode warmed for %d frame shapes (codec=%s) in %.2fs",
            self._wire_jit_rows,
            self.wire_codec if (frac or qbits) else "lossless",
            time.perf_counter() - t0,
        )

    @property
    def max_layer(self) -> int:
        return max(self.layers)

    def wants(self, layer_id: int) -> bool:
        """Is the layer after `layer_id` ours?  (layer_id -1 = raw tokens.)"""
        return (layer_id + 1) in self.engine.model.abs_to_local

    def reset(self, nonce: str = "") -> None:
        if nonce:
            self.engine.end_session(nonce)
            self._hist.pop(nonce, None)
            if self.lane_pool is not None:
                self.lane_pool.release(nonce)
        else:
            self.engine.reset()
            self._hist.clear()
            if self.lane_pool is not None:
                self.lane_pool.reset()
            if self.prefix_snaps is not None:
                self.prefix_snaps.clear()

    def _payload_to_device(self, msg: ActivationMessage):
        """Hidden payload bytes -> device array, THE shared rx decode seam
        (single frames, verify blocks, lane batches).  A frame the wire
        pipeline pre-decoded at ingress (predecode) already carries the
        device array — zero work here, the dequant overlapped the previous
        step's compute.  Compressed frames decompress ON DEVICE (Pallas
        dequant+scatter on TPU): only the compact codes/scales upload, and
        the single-threaded Python receive path never touches per-element
        data (the host-detour gap VERDICT r2 flagged)."""
        if msg.device_data is not None:
            return msg.device_data
        from dnet_tpu.resilience import chaos

        # rx codec fault point, compute-thread flavor (the ingress flavor
        # is the adapter's async inject before predecode — one injection
        # per frame either way)
        chaos.inject("wire_decode")
        return self._decode_to_device(msg, hidden=False)

    def _decode_to_device(self, msg: ActivationMessage, hidden: bool):
        """The ONE rx decode body (device dequant/upload + attribution):
        `hidden` says whether this ran at ingress (overlapped with the
        current step) or on the compute thread."""
        from dnet_tpu.compression import decompress_tensor_device, is_compressed_dtype
        from dnet_tpu.transport.wire_pipeline import observe_decode

        t0 = time.perf_counter()
        if is_compressed_dtype(msg.dtype):
            out = decompress_tensor_device(msg.data, msg.dtype, msg.shape)
        else:
            out = bytes_to_device(msg.data, msg.dtype, msg.shape)
        observe_decode((time.perf_counter() - t0) * 1000.0, hidden=hidden)
        return out

    def will_predecode(self, msg: ActivationMessage, backlog: int) -> bool:
        """Should ingress pre-decode this frame?  Only with the pipeline
        on, for hidden payloads, and only while the compute queue is
        SHALLOW: each pre-decoded frame pins a fully-expanded activation
        on device, so the rx side is depth-bounded exactly like the tx
        encode ring — a backlogged queue keeps compact wire bytes and
        lets the compute thread decode frames as it reaches them."""
        return (
            self.wire_pipeline
            and not msg.is_tokens
            and not msg.is_final
            and bool(msg.data)
            and msg.device_data is None
            and backlog < self._rx_depth
        )

    def predecode(self, msg: ActivationMessage) -> None:
        """rx half of the wire pipeline: launch H2D upload + on-device
        dequant for a frame that is about to be QUEUED, so its decode
        overlaps the step currently computing.  Called at adapter ingress
        (event-loop thread; jax dispatch is async, so this never blocks
        the loop past the dispatch itself) after a `will_predecode`
        check — the chaos gate lives at the call site (async, so a delay
        injection parks only this frame, not the whole loop)."""
        msg.device_data = self._decode_to_device(msg, hidden=True)

    def _decode_payload(self, msg: ActivationMessage, pos: int):
        """Incoming hidden frame -> padded device array + real length."""
        eng = self.engine
        hidden = self._payload_to_device(msg)
        T = hidden.shape[1]
        if pos + T > eng.max_seq:
            raise ValueError(f"sequence {pos + T} exceeds max_seq {eng.max_seq}")
        Tpad = 1 if T == 1 else min(bucket_length(T), eng.max_seq - pos)
        if Tpad != T:
            hidden = jnp.pad(hidden, ((0, 0), (0, Tpad - T), (0, 0)))
        return hidden.astype(eng.param_dtype), T

    def _embed_tokens(self, msg: ActivationMessage, pos: int):
        eng = self.engine
        ids = msg.tokens()
        T = ids.shape[-1]
        if pos + T > eng.max_seq:
            raise ValueError(f"sequence {pos + T} exceeds max_seq {eng.max_seq}")
        Tpad = 1 if T == 1 else min(bucket_length(T), eng.max_seq - pos)
        tokens = np.zeros((eng.batch, Tpad), dtype=np.int32)
        tokens[:, :T] = ids.reshape(1, -1)
        return jnp.asarray(tokens), T

    def _process_round(self, msg: ActivationMessage, sess) -> ActivationMessage:
        """k-round path: apply only the contiguous round starting at the
        incoming target layer, prefetching the NEXT round's window while the
        rest of the ring computes (reference offload.py:395-421 analog)."""
        eng = self.engine
        pos = msg.pos
        target = 0 if msg.is_tokens else msg.layer_id + 1
        try:
            ridx = next(i for i, r in enumerate(self.rounds) if r[0] == target)
        except StopIteration:
            raise ValueError(f"no round of {self.rounds} starts at layer {target}")
        run = self.rounds[ridx]
        nxt_run = self.rounds[(ridx + 1) % len(self.rounds)]
        if msg.is_tokens:
            tokens, T = self._embed_tokens(msg, pos)
            x = eng.model.embed(eng.edge_params, tokens)
        else:
            x, T = self._decode_payload(msg, pos)
        x = eng.apply_round(sess, x, pos, run, t_real=T, prefetch_next=nxt_run)
        sess.pos = pos + T
        sess.last_used = time.time()
        is_tail = run[-1] == eng.config.num_hidden_layers - 1
        return self._emit(msg, sess, x, T, pos, is_tail, run[-1])

    def process(self, msg: ActivationMessage) -> ActivationMessage:
        """Run this shard's window; returns the outgoing message
        (hidden-state hop or final sampled token)."""
        # frame token count for the TP collective byte books (hidden
        # frames are [B, T, D]; token frames carry their id count); read
        # BEFORE dispatch — _spec_widen mutates the shape
        if msg.lanes:
            tokens = len(msg.lanes)
        elif msg.is_tokens:
            tokens = int(np.prod(msg.shape))
        else:
            tokens = int(msg.shape[1]) if len(msg.shape) > 1 else 1
        out = self._process_frame(msg)
        self._book_tp_frame(tokens)
        return out

    def _process_frame(self, msg: ActivationMessage) -> ActivationMessage:
        if msg.lanes:
            return self._process_lane_frame(msg)
        eng = self.engine
        nonce = msg.nonce
        pos = msg.pos
        sess = eng.sessions.get(nonce)
        if sess is None:
            if msg.prefix_hit:
                # prompt frame continuing a cached prefix: seed this
                # shard's session from its snapshot (frame pos = prefix
                # length, payload = the suffix only)
                sess = self._seed_prefix_session(msg)
            elif pos > 0:
                # a mid-stream frame with no session is STALE — a decode
                # grant still circulating after the driver's stop-sequence
                # reset, or a TTL-swept request.  Recreating the session
                # would allocate a full KV cache for garbage compute (and
                # post-reset zombies would pin it until the next sweep);
                # an error final fails the (already-dead) request fast.
                raise ValueError(
                    f"no session for {nonce!r} at pos {pos} "
                    f"(reset or expired); dropping frame"
                )
            else:
                sess = eng.new_session(nonce, msg.decoding.seed)

        if msg.is_tokens and self.is_first and self._spec_ok:
            # HEAD: record entries in the draft history; widen eligible
            # granted continuations into [tok, drafts] verify blocks
            msg = self._spec_widen(msg)

        if msg.drafts and self.is_last:
            # TAIL: a verify block — full-position argmaxes, emit the
            # accepted prefix (1..L+1 tokens per ring lap).  A single-shard
            # ring verifies its own widened token block.
            if not self._spec_ok:
                raise ValueError(
                    "verify block arrived but this shard cannot speculate "
                    "(k rounds, streaming weights, or a rotating cache)"
                )
            return self._spec_verify(msg, sess)

        if len(self.rounds) > 1:
            return self._process_round(msg, sess)

        streams = eng.plan.streams_weights

        if msg.is_tokens:
            if not self.is_first:
                raise ValueError("token frame arrived at a non-first shard")
            tokens, T = self._embed_tokens(msg, pos)
            if streams:
                x = eng.model.embed(eng.edge_params, tokens)
                x = eng.run_layers(sess, x, pos, t_real=T)
            else:
                x, sess.kv = eng._embed_window(
                    eng.window_params, eng.edge_params, tokens,
                    sess.kv, jnp.int32(pos), jnp.int32(T),
                )
        else:
            x, T = self._decode_payload(msg, pos)
            if streams:
                x = eng.run_layers(sess, x, pos, t_real=T)
            elif self.is_last:
                # fused window+head+sample fast path
                sess.key, step_key = jax.random.split(sess.key)
                sp = SampleParams.from_decoding(msg.decoding)
                res, sess.kv, sess.counts = eng._hidden_tail(
                    eng.window_params, eng.edge_params, x, sess.kv,
                    jnp.int32(pos), jnp.int32(T - 1), sp, step_key, sess.counts,
                )
                sess.pos = pos + T
                sess.last_used = time.time()
                self._maybe_snapshot(msg, sess)
                return self._final_message(msg, res, sess)
            else:
                x, sess.kv = eng._hidden(
                    eng.window_params, x, sess.kv, jnp.int32(pos), jnp.int32(T)
                )

        sess.pos = pos + T
        sess.last_used = time.time()
        self._maybe_snapshot(msg, sess)
        return self._emit(msg, sess, x, T, pos, self.is_last, self.max_layer)

    # ---- ring prefix caching -------------------------------------------
    def _seed_prefix_session(self, msg: ActivationMessage):
        """Create the nonce's session from this shard's prefix snapshot.
        A missing/mismatched snapshot fails with a parseable `prefix-miss:`
        error — the API invalidates its index entry so the NEXT request
        re-prefills and re-stores (shards never half-serve a prefix)."""
        if self.prefix_snaps is None:
            raise ValueError(
                f"prefix-miss:{msg.prefix_hit}: prefix caching disabled on "
                f"this shard (streaming, k-round, or capacity 0)"
            )
        hit = self.prefix_snaps.get(msg.prefix_hit)
        if hit is None:
            raise ValueError(
                f"prefix-miss:{msg.prefix_hit}: no snapshot on this shard"
            )
        n, kv = hit
        if n != msg.pos:
            raise ValueError(
                f"prefix-miss:{msg.prefix_hit}: snapshot covers {n} tokens "
                f"but the frame resumes at pos {msg.pos}"
            )
        return self.engine.new_session(msg.nonce, msg.decoding.seed, kv=kv, pos=n)

    def _maybe_snapshot(self, msg: ActivationMessage, sess) -> None:
        """Store this shard's post-prompt KV under the API-chosen key."""
        if msg.prefix_store and self.prefix_snaps is not None:
            self.prefix_snaps.put(msg.prefix_store, sess.pos, sess.kv)

    # ---- batched lanes -------------------------------------------------
    def _process_lane_frame(self, msg: ActivationMessage) -> ActivationMessage:
        """One coalesced decode step for every member nonce (shard/lanes.py).
        Members prefilled on this shard's B=1 programs are adopted into pool
        lanes on their first batched frame."""
        if self.lane_pool is None:
            raise ValueError(
                "batch frame arrived but lanes are not enabled on this shard"
            )
        pool = self.lane_pool
        n = len(msg.lanes)
        if msg.is_tokens:
            if not self.is_first:
                raise ValueError("token batch frame arrived at a non-first shard")
            tokens = msg.tokens().reshape(n, 1).astype(np.int32)
            out = pool.step_entry(msg, tokens, self.is_last)
        else:
            hidden = self._payload_to_device(msg)
            if hidden.shape[0] != n or hidden.shape[1] != 1:
                raise ValueError(
                    f"batch frame payload {hidden.shape} does not match "
                    f"{n} single-token lanes"
                )
            out = pool.step_hidden(msg, hidden, self.is_last)
        if self.is_last:
            return self._lane_finals_message(msg, out)
        return self._emit_lanes(msg, out)

    # ---- wire encode (the single egress seam) --------------------------
    def _wire_params(self) -> tuple:
        """(drop_frac, quant_bits) the hop codec resolves to: the qsparse8
        hop codec is int8 group quant over the kept columns (column drop
        from the transport compression settings when configured, else the
        wire default); the lossless codec keeps the legacy behavior —
        plain wire-dtype cast, or the old sparsify path when transport
        compression is explicitly on."""
        if self.wire_codec == "qsparse8":
            frac = self.compress_frac if self.compress_frac > 0 else self._wire_pct
            return frac, 8
        if self.compress_frac > 0:
            return self.compress_frac, self.compress_quant_bits
        return 0.0, 0

    def _encode_activation(self, x, T: Optional[int] = None,
                           force_sync: bool = False):
        """THE hop-encode seam: every outgoing hidden payload (single
        frames, lane batches, calibration probes) serializes here.
        Returns (data, dtype, shape) — data is payload bytes on the
        synchronous path, or a PendingWirePayload the transport tx stage
        finalizes when the wire pipeline is on (the compute thread only
        pays the jitted encode DISPATCH; D2H readback + byte packing
        overlap the next step's compute).  ``x`` may be a device array;
        with ``T`` the padded tail is sliced off first.  The sliced
        activation is DONATED to the device encode — dead after this call
        (the DL021 contract)."""
        if T is not None:
            x = x[:, :T]
        frac, qbits = self._wire_params()
        # the jitted launch compiles one program per ROW count; decode and
        # lane frames draw from a tiny warmable set (1..lanes), but prompt
        # frames carry their REAL token count — jit-launching those would
        # compile per distinct prompt length, a worse stall than the
        # encode it hides.  The per-token hot loop rides the pipeline;
        # one-per-request prompt frames encode synchronously.
        rows = int(np.prod(x.shape[:-1]))
        if self.wire_pipeline and not force_sync and rows <= self._wire_jit_rows:
            from dnet_tpu.compression import launch_encode
            from dnet_tpu.transport.wire_pipeline import (
                PendingWirePayload,
                overlap,
            )

            t_acq = time.perf_counter()
            acquired = self._encode_ring.acquire()
            t0 = time.perf_counter()
            enc = launch_encode(
                x, frac, wire_dtype=self.wire_dtype, quant_bits=qbits,
                group_size=self._wire_gs,
            )
            pending = PendingWirePayload(
                enc, ring=self._encode_ring if acquired else None
            )
            # serial = the launch dispatch only; a blocked acquire is the
            # depth bound exerting backpressure, booked as stall instead
            overlap.add(
                serial_ms=(time.perf_counter() - t0) * 1000.0,
                stall_ms=(t0 - t_acq) * 1000.0,
            )
            if not acquired:
                # ring wedged past its wait budget (tx stage stuck): pay
                # the readback here rather than deadlock — slower, bounded
                return pending.finalize_sync(), enc.dtype, enc.shape
            return pending, enc.dtype, enc.shape
        from dnet_tpu.resilience import chaos
        from dnet_tpu.transport.wire_pipeline import observe_encode

        out = np.asarray(x)
        t0 = time.perf_counter()
        chaos.inject("wire_encode")
        if frac > 0 or qbits:
            from dnet_tpu.compression import compress_tensor

            payload, dtype, shape = compress_tensor(
                out, frac, wire_dtype=self.wire_dtype, quant_bits=qbits,
                group_size=self._wire_gs,
            )
        else:
            payload, dtype, shape = tensor_to_bytes(out, wire_dtype=self.wire_dtype)
        observe_encode((time.perf_counter() - t0) * 1000.0, hidden=False)
        return payload, dtype, shape

    def _emit_lanes(self, msg: ActivationMessage, x) -> ActivationMessage:
        """Hidden hop of a batch frame: member rows stacked [n, 1, H]."""
        payload, dtype, shape = self._encode_activation(x)
        return ActivationMessage(
            nonce=msg.nonce,
            layer_id=self.max_layer,
            seq=msg.seq,
            dtype=dtype,
            shape=shape,
            data=payload,
            pos=msg.pos,
            callback_url=msg.callback_url,
            decoding=msg.decoding,
            lanes=list(msg.lanes),
        )

    def _lane_finals_message(self, msg: ActivationMessage, results) -> ActivationMessage:
        """Tail of a batch frame: one TokenResult-shaped dict per member,
        fanned out as per-nonce SendToken callbacks by the adapter."""
        finals = []
        for lane, res in zip(msg.lanes, results):
            if res is None:  # faulted member: fail it alone
                finals.append(
                    {
                        "nonce": lane["nonce"],
                        "step": int(lane["seq"]),
                        "token_id": -1,
                        "error": lane.get("error") or "lane failed",
                    }
                )
                continue
            dec = DecodingParams(**(lane.get("decoding") or {}))
            tr = LocalEngine.token_result(
                lane["nonce"], res, step=int(lane["seq"]), decoding=dec
            )
            finals.append(
                {
                    "nonce": tr.nonce,
                    "step": tr.step,
                    "token_id": tr.token_id,
                    "logprob": tr.logprob,
                    "top_ids": [t for t, _ in (tr.top_logprobs or [])],
                    "top_logprobs": [lp for _, lp in (tr.top_logprobs or [])],
                }
            )
        return ActivationMessage(
            nonce=msg.nonce,
            layer_id=self.max_layer,
            seq=msg.seq,
            dtype="token",
            shape=(len(finals),),
            pos=msg.pos,
            callback_url=msg.callback_url,
            decoding=msg.decoding,
            is_final=True,
            lane_finals=finals,
        )

    # ---- ring speculation (head widen / tail verify) -------------------
    def _spec_widen(self, msg: ActivationMessage) -> ActivationMessage:
        """HEAD: maintain the nonce's input history and, for an eligible
        granted continuation (1 greedy token mid-stream), widen it into a
        [tok, d_1..d_L] verify block with prompt-lookup drafts."""
        from dnet_tpu.core.spec import ngram_draft_np

        ids = msg.tokens().reshape(-1)
        pos = msg.pos
        hist = self._hist.get(msg.nonce)
        if hist is None or pos == 0:
            hist = np.zeros(self.engine.max_seq, dtype=np.int64)
            self._hist[msg.nonce] = hist
        k = len(msg.committed)
        if k:  # the previous block's accepted tokens, in input positions
            hist[pos - k + 1 : pos + 1] = msg.committed
        end = min(pos + len(ids), len(hist))
        hist[pos:end] = ids[: end - pos]
        dec = msg.decoding
        L = self.spec_lookahead
        if not (
            msg.auto_steps > 0
            and len(ids) == 1
            and pos > 0
            and dec.temperature == 0.0
            and not dec.logprobs
            and dec.repetition_penalty == 1.0
            and not dec.logit_bias
            and pos + L + 1 <= self.engine.max_seq
        ):
            return msg
        drafts = ngram_draft_np(hist, pos + 1, L)
        hist[pos + 1 : pos + 1 + L] = drafts  # speculative; commits overwrite
        block = np.concatenate([ids, drafts]).astype(np.int32)[None, :]
        msg.data = block.tobytes()
        msg.shape = block.shape
        msg.drafts = [int(d) for d in drafts]
        return msg

    def _spec_verify(self, msg: ActivationMessage, sess) -> ActivationMessage:
        """TAIL: run the verify block through this window, take argmaxes at
        every real position, emit the agreeing prefix + first correction
        (clamped to the grant), and hand the accepted tokens back to the
        head via the continuation for its history."""
        eng = self.engine
        pos = msg.pos
        if msg.is_tokens:  # single-shard ring: embed the widened block here
            tokens, T = self._embed_tokens(msg, pos)
            x = eng.model.embed(eng.edge_params, tokens)
        else:
            x, T = self._decode_payload(msg, pos)
        x, sess.kv = eng._hidden(
            eng.window_params, x, sess.kv, jnp.int32(pos), jnp.int32(T)
        )
        h = eng.model.normalize(eng.edge_params, x[:, :T])
        logits = eng.model.lm_project(eng.edge_params, h)  # [1, T, V]
        preds = np.asarray(jnp.argmax(logits, axis=-1))[0].astype(np.int64)
        drafts = np.asarray(msg.drafts, dtype=np.int64)
        agree = preds[: len(drafts)] == drafts
        n_accept = int(np.argmin(np.concatenate([agree, [False]]).astype(np.int32)))
        # this frame's OWN token (step seq) is free — it was granted by the
        # frame that injected it; only the extras consume the running grant
        emitted = min(n_accept + 1, msg.auto_steps + 1)
        toks = [int(t) for t in preds[:emitted]]
        stops = tuple(msg.decoding.stop_token_ids or ())
        for i, t in enumerate(toks):  # truncate at EOS: later tokens are dead
            if t in stops:
                toks = toks[: i + 1]
                break
        emitted = len(toks)
        sess.pos = pos + emitted
        sess.last_used = time.time()
        out = ActivationMessage(
            nonce=msg.nonce,
            layer_id=self.max_layer,
            seq=msg.seq,
            dtype="token",
            shape=(1,),
            pos=pos,
            callback_url=msg.callback_url,
            decoding=msg.decoding,
            is_final=True,
            token_id=toks[0],
            extra_finals=[(msg.seq + i, toks[i]) for i in range(1, emitted)],
        )
        remaining = msg.auto_steps - (emitted - 1) - 1  # extras, then the
        # next continuation's own token, both come out of this grant
        if remaining >= 0 and toks[-1] not in stops and sess.pos < eng.max_seq:
            out.cont = (toks[-1], sess.pos, remaining, msg.seq + emitted)
            out.committed = toks  # input positions pos+1 .. pos+emitted
        return out

    def _emit(
        self, msg: ActivationMessage, sess, x, T: int, pos: int,
        is_tail: bool, out_layer: int,
    ) -> ActivationMessage:
        eng = self.engine
        if is_tail:
            # tail after a streamed window pass or a single-shard token frame
            sess.key, step_key = jax.random.split(sess.key)
            sp = SampleParams.from_decoding(msg.decoding)
            x_last = jax.lax.dynamic_slice_in_dim(x, T - 1, 1, axis=1)
            x_last = eng.model.normalize(eng.edge_params, x_last)
            logits = eng.model.lm_project(eng.edge_params, x_last)[:, 0]
            from dnet_tpu.core.sampler import sample

            res = sample(logits, sp, step_key, token_counts=sess.counts)
            sess.counts = sess.counts.at[:, int(res.token[0])].add(1)
            return self._final_message(msg, res, sess)

        # hidden hop to the next shard: slice off the padding, encode for
        # the wire (pipelined: launch-only here, tx stage finishes it)
        payload, dtype, shape = self._encode_activation(x, T=T)
        return ActivationMessage(
            nonce=msg.nonce,
            layer_id=out_layer,
            seq=msg.seq,
            dtype=dtype,
            shape=shape,
            data=payload,
            pos=pos,
            callback_url=msg.callback_url,
            decoding=msg.decoding,
            # the decode grant (and any verify drafts) must reach the TAIL:
            # they ride every hop — as do the prefix store/hit keys (every
            # shard snapshots/seeds its own window)
            auto_steps=msg.auto_steps,
            drafts=list(msg.drafts),
            prefix_store=msg.prefix_store,
            prefix_hit=msg.prefix_hit,
        )

    def _final_message(self, msg: ActivationMessage, res, sess) -> ActivationMessage:
        decoding = msg.decoding
        token_result = LocalEngine.token_result(msg.nonce, res, step=msg.seq, decoding=decoding)
        out = ActivationMessage(
            nonce=msg.nonce,
            layer_id=self.max_layer,
            seq=msg.seq,
            dtype="token",
            shape=(1,),
            pos=msg.pos,
            callback_url=msg.callback_url,
            decoding=decoding,
            is_final=True,
            token_id=token_result.token_id,
            logprob=token_result.logprob,
            top_logprobs=token_result.top_logprobs,
        )
        # decode grant (ring self-continuation): with budget left, a
        # non-stop token, and cache capacity, the sampled token re-enters
        # the ring directly — the adapter injects `cont` at the head while
        # the API receives this token in parallel, removing the per-token
        # API round trip the reference pays (its driver re-injects every
        # token, src/dnet/api/strategies/ring.py:125-209)
        stops = tuple(decoding.stop_token_ids or ())
        if (
            msg.auto_steps > 0
            and token_result.token_id not in stops
            and sess.pos < self.engine.max_seq
        ):
            out.cont = (
                token_result.token_id, sess.pos, msg.auto_steps - 1, msg.seq + 1
            )
        return out

    def sweep_sessions(self) -> int:
        n = self.engine.sweep_sessions()
        if self.lane_pool is not None:
            n += self.lane_pool.sweep(self.engine.kv_ttl_s)
        if self._hist:
            # prune draft histories whose session died (TTL sweep, failed
            # reset RPC): each entry pins a max_seq int64 array
            live = self.engine.sessions
            for nonce in [k for k in self._hist if k not in live]:
                self._hist.pop(nonce, None)
        return n

    def health(self) -> dict:
        out = {
            "layers": list(self.layers),
            "sessions": len(self.engine.sessions),
        }
        if self.prefix_snaps is not None:
            out["prefix_cache"] = dict(self.prefix_snaps.stats)
        return out

    def probe_stage_time(self, steps: int = 3) -> float:
        """Measured seconds/token for THIS stage: run the real process()
        hot path on synthetic decode-shaped frames and take the median step
        (first step discarded: it pays compile).  Feeds the solver
        calibration loop (parallel/calibrate.py) — the counterpart of the
        solve-time `predicted_stage_s`.  Multi-round assignments time every
        round a token pass visits.  Synthetic hidden frames ride the same
        _encode_activation seam the real egress uses (sync-forced: the
        probe needs concrete bytes), so the probe measures the true hop
        shape — hop codec and decompress included."""
        nonce = "__calibrate__"
        self.reset(nonce)
        eng = self.engine
        durations: list = []
        try:
            for i in range(steps + 1):
                t0 = time.perf_counter()
                for run in self.rounds:
                    if run[0] == 0:
                        msg = ActivationMessage(
                            nonce=nonce, layer_id=-1, seq=i, dtype="tokens",
                            shape=(1, 1), pos=i,
                            data=np.ones((1, 1), np.int32).tobytes(),
                        )
                    else:
                        hidden = np.zeros(
                            (1, 1, eng.config.hidden_size), np.float32
                        )
                        data, dtype, shape = self._encode_activation(
                            hidden, force_sync=True
                        )
                        msg = ActivationMessage(
                            nonce=nonce, layer_id=run[0] - 1, seq=i,
                            dtype=dtype, shape=shape, data=data, pos=i,
                        )
                    out = self.process(msg)
                    from dnet_tpu.transport.wire_pipeline import (
                        PendingWirePayload,
                    )

                    if isinstance(out.data, PendingWirePayload):
                        # the probe IS the consumer: pay the readback here
                        # (and free the ring slot) so the timing covers
                        # the full hop encode, pipeline or not
                        out.data.finalize_sync()
                    elif out.data is not None and hasattr(out.data, "block_until_ready"):
                        out.data.block_until_ready()  # dnetlint: disable=DL005 latency calibration probe: the sync IS the measurement
                durations.append(time.perf_counter() - t0)
        finally:
            self.reset(nonce)
        timed = sorted(durations[1:]) or durations
        return timed[len(timed) // 2]
