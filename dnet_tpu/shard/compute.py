"""Shard compute core: one ActivationMessage in, one out.

The policy-level hot loop of the reference's FitInMemoryPolicy
(src/dnet/shard/policies/fit_in_memory.py:34-209), built on LocalEngine's
jitted shard paths: embed+window (first shard), hidden window (mid), window+
head+sample (last).  Incoming hidden states are padded to power-of-two
buckets so every frame length reuses a compiled program.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dnet_tpu.core.engine import LocalEngine, bucket_length
from dnet_tpu.core.sampler import SampleParams
from dnet_tpu.core.types import ActivationMessage, DecodingParams, TokenResult
from dnet_tpu.utils.logger import get_logger
from dnet_tpu.utils.serialization import bytes_to_tensor, tensor_to_bytes

log = get_logger()


class ShardCompute:
    """Owns the engine for this shard's layer range."""

    def __init__(
        self,
        model_dir: str | Path,
        layers: Sequence[int],
        max_seq: int = 4096,
        param_dtype: str = "bfloat16",
        wire_dtype: str = "bfloat16",
        kv_ttl_s: float = 600.0,
        window_size: int = 0,
        residency_size: int = 0,
        repack_dir: Optional[str] = None,
        kv_bits: int = 0,
        compress_frac: Optional[float] = None,
        weight_quant_bits: int = 0,
    ) -> None:
        from dnet_tpu.core.kvcache import resolve_kv_bits

        kv_dtype, kv_quant_bits = resolve_kv_bits(kv_bits)
        self.engine = LocalEngine(
            model_dir,
            layers=layers,
            max_seq=max_seq,
            param_dtype=param_dtype,
            kv_dtype=kv_dtype,
            kv_ttl_s=kv_ttl_s,
            shard_mode=True,
            window_size=window_size,
            residency_size=residency_size,
            repack_dir=repack_dir,
            kv_quant_bits=kv_quant_bits,
            weight_quant_bits=weight_quant_bits,
        )
        self.layers = self.engine.model.layers
        self.wire_dtype = wire_dtype
        self.is_first = self.engine.model.is_first
        self.is_last = self.engine.model.is_last
        # column-sparsify hidden hops toward the next shard (DCN only —
        # reference gates the same way, config.py:128-135, default off);
        # explicit arg wins, DNET_TRANSPORT_* is the deploy-wide default
        if compress_frac is None:
            from dnet_tpu.config import get_settings

            t = get_settings().transport
            compress_frac = t.compress_pct if t.compress else 0.0
        self.compress_frac = compress_frac

    @property
    def max_layer(self) -> int:
        return max(self.layers)

    def wants(self, layer_id: int) -> bool:
        """Is the layer after `layer_id` ours?  (layer_id -1 = raw tokens.)"""
        return (layer_id + 1) in self.engine.model.abs_to_local

    def reset(self, nonce: str = "") -> None:
        if nonce:
            self.engine.end_session(nonce)
        else:
            self.engine.reset()

    def process(self, msg: ActivationMessage) -> ActivationMessage:
        """Run this shard's window; returns the outgoing message
        (hidden-state hop or final sampled token)."""
        eng = self.engine
        nonce = msg.nonce
        sess = eng.sessions.get(nonce) or eng.new_session(nonce, msg.decoding.seed)
        pos = msg.pos

        streams = eng.plan.streams_weights

        if msg.is_tokens:
            if not self.is_first:
                raise ValueError("token frame arrived at a non-first shard")
            ids = msg.tokens()
            T = ids.shape[-1]
            # T==1 is the steady-state decode hop: no bucket padding (a
            # dedicated (B,1) program, like the local path's _decode)
            if pos + T > eng.max_seq:
                raise ValueError(f"sequence {pos + T} exceeds max_seq {eng.max_seq}")
            # padded width must fit too (a clamped dynamic_update_slice would
            # silently shift the KV write)
            Tpad = 1 if T == 1 else min(bucket_length(T), eng.max_seq - pos)
            tokens = np.zeros((eng.batch, Tpad), dtype=np.int32)
            tokens[:, :T] = ids.reshape(1, -1)
            if streams:
                x = eng.model.embed(eng.edge_params, jnp.asarray(tokens))
                x = eng.run_layers(sess, x, pos)
            else:
                x, sess.kv = eng._embed_window(
                    eng.window_params, eng.edge_params, jnp.asarray(tokens),
                    sess.kv, jnp.int32(pos),
                )
        else:
            from dnet_tpu.compression import decompress_tensor, is_compressed_dtype

            if is_compressed_dtype(msg.dtype):
                hidden = decompress_tensor(msg.data, msg.dtype, msg.shape)
            else:
                hidden = bytes_to_tensor(msg.data, msg.dtype, msg.shape)
            T = hidden.shape[1]
            if pos + T > eng.max_seq:
                raise ValueError(f"sequence {pos + T} exceeds max_seq {eng.max_seq}")
            Tpad = 1 if T == 1 else min(bucket_length(T), eng.max_seq - pos)
            if Tpad != T:
                pad = np.zeros(
                    (hidden.shape[0], Tpad - T, hidden.shape[2]), dtype=hidden.dtype
                )
                hidden = np.concatenate([hidden, pad], axis=1)
            x = jnp.asarray(hidden).astype(eng.param_dtype)
            if streams:
                x = eng.run_layers(sess, x, pos)
            elif self.is_last:
                # fused window+head+sample fast path
                sess.key, step_key = jax.random.split(sess.key)
                sp = SampleParams.from_decoding(msg.decoding)
                res, sess.kv, sess.counts = eng._hidden_tail(
                    eng.window_params, eng.edge_params, x, sess.kv,
                    jnp.int32(pos), jnp.int32(T - 1), sp, step_key, sess.counts,
                )
                sess.pos = pos + T
                sess.last_used = time.time()
                return self._final_message(msg, res)
            else:
                x, sess.kv = eng._hidden(eng.window_params, x, sess.kv, jnp.int32(pos))

        sess.pos = pos + T
        sess.last_used = time.time()

        if self.is_last:
            # tail after a streamed window pass or a single-shard token frame
            sess.key, step_key = jax.random.split(sess.key)
            sp = SampleParams.from_decoding(msg.decoding)
            x_last = jax.lax.dynamic_slice_in_dim(x, T - 1, 1, axis=1)
            x_last = eng.model.normalize(eng.edge_params, x_last)
            logits = eng.model.lm_project(eng.edge_params, x_last)[:, 0]
            from dnet_tpu.core.sampler import sample

            res = sample(logits, sp, step_key, token_counts=sess.counts)
            sess.counts = sess.counts.at[:, int(res.token[0])].add(1)
            return self._final_message(msg, res)

        # hidden hop to the next shard: slice off the padding, cast to wire
        out = np.asarray(x[:, :T])
        if self.compress_frac > 0:
            from dnet_tpu.compression import compress_tensor

            payload, dtype, shape = compress_tensor(
                out, self.compress_frac, wire_dtype=self.wire_dtype
            )
        else:
            payload, dtype, shape = tensor_to_bytes(out, wire_dtype=self.wire_dtype)
        return ActivationMessage(
            nonce=nonce,
            layer_id=self.max_layer,
            seq=msg.seq,
            dtype=dtype,
            shape=shape,
            data=payload,
            pos=pos,
            callback_url=msg.callback_url,
            decoding=msg.decoding,
        )

    def _final_message(self, msg: ActivationMessage, res) -> ActivationMessage:
        decoding = msg.decoding
        token_result = LocalEngine.token_result(msg.nonce, res, step=msg.seq, decoding=decoding)
        out = ActivationMessage(
            nonce=msg.nonce,
            layer_id=self.max_layer,
            seq=msg.seq,
            dtype="token",
            shape=(1,),
            pos=msg.pos,
            callback_url=msg.callback_url,
            decoding=decoding,
            is_final=True,
            token_id=token_result.token_id,
            logprob=token_result.logprob,
            top_logprobs=token_result.top_logprobs,
        )
        return out

    def sweep_sessions(self) -> int:
        return self.engine.sweep_sessions()

    def health(self) -> dict:
        return {
            "layers": list(self.layers),
            "sessions": len(self.engine.sessions),
        }
