"""Batched lanes for the gRPC ring: N concurrent nonces per ring pass.

VERDICT r4 next #4 — the ring is the multi-host serving path, and until now
it decoded batch=1 per nonce: concurrent chats merely interleaved full ring
passes.  On TPU, decode is weight-bound — lanes 2..N of a batched matmul
are nearly free — so the API adapter now COALESCES concurrent decode steps
into one multi-lane frame (api/ring.py), and each shard serves all members
with ONE batched step over a pooled KV cache.

This module owns the shard-side pool: a fixed set of `slots` KV rows (the
continuous-batching layout of core/batch.py applied to the ring), vmapped
head/mid/tail step programs with per-lane `kv_commit` gating, and the
session->lane adoption that keeps every lane's sampling state (RNG key,
repetition counts, position) byte-identical to a solo run.  Prefill stays
on the engine's B=1 bucket programs; the finished session's KV row moves
into the pool on the nonce's first batched frame (same discipline as
BatchedEngine._move_to_slot).

Reference contrast: the reference serves ONE in-flight sequence per nonce
(src/dnet/api/inference.py:135 — a single driver loop per request, no
cross-request batching anywhere); this is the throughput inversion the
repo's own north star needed most.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dnet_tpu.core.sampler import (
    MAX_LOGIT_BIAS,
    SampleParams,
    SampleResult,
    encode_logit_bias,
    sample,
)
from dnet_tpu.utils.logger import get_logger

log = get_logger()


def lane_sampler(model):
    """Per-lane head projection + sample — the exact RNG/counts discipline
    of BatchedEngine.one (inactive lanes advance nothing).  Shared by the
    plain vmapped programs below and the mesh-shard lane programs
    (parallel/shard_mesh.py), which differ only in the window pass."""

    def sample_one(ep, x, active, sp, key, counts):
        x = model.normalize(ep, x[:, -1:])
        logits = model.lm_project(ep, x)[:, 0]  # [1, V]
        new_key, step_key = jax.random.split(key)
        res = sample(logits, sp, step_key, token_counts=counts[None])
        counts = counts.at[res.token[0]].add(jnp.where(active, 1, 0))
        key = jax.random.wrap_key_data(
            jnp.where(
                active,
                jax.random.key_data(new_key),
                jax.random.key_data(key),
            )
        )
        return res, counts, key

    return sample_one


class LanePool:
    """Pooled per-lane KV + sampling state and the batched step programs."""

    def __init__(self, engine, slots: int) -> None:
        if slots < 2:
            raise ValueError(f"lanes need >= 2 slots, got {slots}")
        if engine.plan.streams_weights:
            raise NotImplementedError(
                "batched lanes need resident weights (fit policy)"
            )
        if not engine.model.supports_kv_commit:
            raise NotImplementedError(
                f"batched lanes not supported for "
                f"{engine.config.model_type} (no gated KV writes)"
            )
        self.eng = engine
        self.model = engine.model
        self.slots = slots
        self.max_seq = engine.max_seq
        m = self.model
        kv = m.init_kv(
            len(m.layers), slots, self.max_seq, engine.kv_dtype,
            quant_bits=engine.kv_quant_bits,
            # sp shards the sequence axis — a rotating SWA ring buffer
            # would alias it (same rule as MeshShardEngine.new_session)
            rotating=(getattr(engine, "sp", 1) == 1),
        )
        # mesh-backed shards place the pool with their kv sharding (slots
        # ride the size-1 dp axis, heads/sequence shard over tp/sp)
        if hasattr(engine, "place_lane_kv"):
            kv = engine.place_lane_kv(kv)
        self.kv = kv
        V = engine.config.vocab_size
        self.counts = jnp.zeros((slots, V), dtype=jnp.int32)
        self.keys = jax.random.split(
            jax.random.key(int.from_bytes(__import__("os").urandom(4), "little")),
            slots,
        )
        self.pos = np.zeros(slots, dtype=np.int64)
        self.last_used = np.zeros(slots, dtype=np.float64)
        self.slot_of: Dict[str, int] = {}
        self._free: List[int] = list(range(slots))
        if hasattr(engine, "build_lane_programs"):
            # mesh-backed shard: shard_map(vmap(...)) programs from the
            # engine (parallel/shard_mesh.py)
            progs = engine.build_lane_programs(self.kv)
        else:
            progs = self._build_local()
        self._head = progs["head"]
        self._mid = progs["mid"]
        self._tail = progs["tail"]
        self._full = progs["full"]

    # ---- programs -----------------------------------------------------
    def _build_local(self) -> dict:
        model = self.model
        kv_axes = jax.tree.map(lambda _: 1, self.kv)
        sp_axes = SampleParams(0, 0, 0, 0, 0, 0, 0, 0)
        sample_one = lane_sampler(model)

        def window_one(wp, x, kv, pos, active):
            """Shared body: one lane's window pass (B=1 re-added)."""
            kv = jax.tree.map(lambda a: a[:, None], kv)
            x, kv = model.apply_window(wp, x, kv, pos, kv_commit=active)
            return x, jax.tree.map(lambda a: a[:, 0], kv)

        def one_head(wp, ep, token, kv, pos, active):
            """First shard: token in, hidden out."""
            x = model.embed(ep, token[None, :])  # [1, 1, D]
            x, kv = window_one(wp, x, kv, pos, active)
            return x[0], kv

        def one_mid(wp, x_row, kv, pos, active):
            """Interior shard: hidden in, hidden out."""
            x, kv = window_one(wp, x_row[None], kv, pos, active)
            return x[0], kv

        def one_tail(wp, ep, x_row, kv, pos, active, sp, key, counts):
            """Last shard: hidden in, sampled token out."""
            x, kv = window_one(wp, x_row[None], kv, pos, active)
            res, counts, key = sample_one(ep, x, active, sp, key, counts)
            return res, kv, counts, key

        def one_full(wp, ep, token, kv, pos, active, sp, key, counts):
            """Single-shard ring: token in, sampled token out."""
            x = model.embed(ep, token[None, :])
            x, kv = window_one(wp, x, kv, pos, active)
            res, counts, key = sample_one(ep, x, active, sp, key, counts)
            return res, kv, counts, key

        return {
            "head": jax.jit(
                jax.vmap(
                    one_head,
                    in_axes=(None, None, 0, kv_axes, 0, 0),
                    out_axes=(0, kv_axes),
                ),
                donate_argnums=(3,),
            ),
            "mid": jax.jit(
                jax.vmap(
                    one_mid,
                    in_axes=(None, 0, kv_axes, 0, 0),
                    out_axes=(0, kv_axes),
                ),
                donate_argnums=(2,),
            ),
            "tail": jax.jit(
                jax.vmap(
                    one_tail,
                    in_axes=(None, None, 0, kv_axes, 0, 0, sp_axes, 0, 0),
                    out_axes=(0, kv_axes, 0, 0),
                ),
                donate_argnums=(3, 8),
            ),
            "full": jax.jit(
                jax.vmap(
                    one_full,
                    in_axes=(None, None, 0, kv_axes, 0, 0, sp_axes, 0, 0),
                    out_axes=(0, kv_axes, 0, 0),
                ),
                donate_argnums=(3, 8),
            ),
        }

    # ---- lane lifecycle ----------------------------------------------
    def adopt(self, nonce: str) -> int:
        """Move the nonce's prefilled B=1 session into a pool lane: KV row,
        RNG key, repetition counts, position.  The continued stream is
        byte-identical to the solo session's."""
        slot = self.slot_of.get(nonce)
        if slot is not None:
            return slot
        sess = self.eng.sessions.get(nonce)
        if sess is None:
            raise ValueError(f"no prefilled session for {nonce!r} to adopt")
        if not self._free:
            raise RuntimeError(f"no free lanes (capacity {self.slots})")
        slot = self._free.pop(0)
        self.slot_of[nonce] = slot
        self.kv = jax.tree.map(
            lambda big, one: big.at[:, slot : slot + 1].set(one.astype(big.dtype)),
            self.kv,
            sess.kv,
        )
        self.counts = self.counts.at[slot].set(sess.counts[0])
        self.keys = self.keys.at[slot].set(sess.key)
        self.pos[slot] = sess.pos
        self.last_used[slot] = time.time()
        self.eng.end_session(nonce)  # the B=1 cache row is now dead weight
        return slot

    def release(self, nonce: str) -> None:
        """Host-side bookkeeping ONLY.  Reset RPCs arrive on the servicer
        thread while a donating batched step may be in flight on the
        compute thread — touching self.counts/kv here would race the
        donated buffers ("Buffer has been deleted or donated").  Device
        rows need no cleanup: adopt() fully overwrites the lane's KV row,
        counts row, and RNG key for the next owner."""
        slot = self.slot_of.pop(nonce, None)
        if slot is not None:
            self.pos[slot] = 0
            self._free.append(slot)

    def reset(self) -> None:
        for nonce in list(self.slot_of):
            self.release(nonce)

    def sweep(self, ttl_s: float) -> int:
        now = time.time()
        dead = [
            n for n, s in self.slot_of.items() if now - self.last_used[s] > ttl_s
        ]
        for n in dead:
            self.release(n)
        return len(dead)

    # ---- batched step -------------------------------------------------
    def _scatter(self, msg) -> tuple:
        """Full-width (slots) arrays from a batch frame's member rows.

        Per-member fault isolation: a bad lane (reset race -> no session to
        adopt, stale pos, capacity) is FLAGGED on its lane dict (the flag
        rides the remaining hops) and skipped — one cancelled request must
        never error-fail its batchmates.  `order` maps member index to
        slot, None for faulted members."""
        active = np.zeros(self.slots, dtype=bool)
        pos = np.zeros(self.slots, dtype=np.int32)
        order: List = []
        used: set = set()
        for lane in msg.lanes:
            if lane.get("error"):  # faulted on an earlier shard
                order.append(None)
                continue
            nonce = lane["nonce"]
            try:
                slot = self.slot_of.get(nonce)
                if slot is None:
                    slot = self.adopt(nonce)
                lpos = int(lane["pos"])
                if lpos != self.pos[slot]:
                    raise ValueError(
                        f"frame pos {lpos} != lane pos {int(self.pos[slot])} "
                        f"(stale or out-of-order frame)"
                    )
                if lpos >= self.max_seq:
                    raise ValueError(
                        f"sequence length {lpos} reached max_seq {self.max_seq}"
                    )
                if slot in used:
                    raise ValueError("duplicate nonce in a batch frame")
            except Exception as exc:
                log.warning("lane %s faulted: %s", nonce, exc)
                lane["error"] = str(exc)
                order.append(None)
                continue
            used.add(slot)
            active[slot] = True
            pos[slot] = lpos
            order.append(slot)
        return active, pos, order

    def _sample_params(self, msg, order) -> SampleParams:
        from dnet_tpu.core.types import DecodingParams

        S = self.slots
        temp = np.zeros(S, dtype=np.float32)
        top_p = np.ones(S, dtype=np.float32)
        top_k = np.zeros(S, dtype=np.int32)
        min_p = np.zeros(S, dtype=np.float32)
        rep = np.ones(S, dtype=np.float32)
        mtk = np.ones(S, dtype=np.int32)
        b_ids = np.full((S, MAX_LOGIT_BIAS), -1, dtype=np.int32)
        b_vals = np.zeros((S, MAX_LOGIT_BIAS), dtype=np.float32)
        for lane, slot in zip(msg.lanes, order):
            if slot is None:
                continue
            dec = DecodingParams(**lane.get("decoding") or {})
            temp[slot] = dec.temperature
            top_p[slot] = dec.top_p
            top_k[slot] = dec.top_k
            min_p[slot] = dec.min_p
            rep[slot] = dec.repetition_penalty
            mtk[slot] = dec.min_tokens_to_keep
            b_ids[slot], b_vals[slot] = encode_logit_bias(dec.logit_bias)
        return SampleParams(
            temperature=jnp.asarray(temp),
            top_p=jnp.asarray(top_p),
            top_k=jnp.asarray(top_k),
            min_p=jnp.asarray(min_p),
            repetition_penalty=jnp.asarray(rep),
            min_tokens_to_keep=jnp.asarray(mtk),
            bias_ids=jnp.asarray(b_ids),
            bias_vals=jnp.asarray(b_vals),
        )

    def step_entry(self, msg, tokens: np.ndarray, is_last: bool):
        """Head-shard batched step.  tokens [n, 1] int32 in member order.
        Returns hidden [n, 1, D] (ring continues) or per-member
        SampleResults (single-shard ring)."""
        active, pos, order = self._scatter(msg)
        if all(o is None for o in order):
            # same contract as step_hidden's all-faulted early return: the
            # flagged lane dicts carry the errors, rows are inert garbage
            if is_last:
                return [None] * len(order)
            return jnp.zeros(
                (len(order), 1, self.eng.config.hidden_size),
                dtype=self.eng.param_dtype,
            )
        token_full = np.zeros((self.slots, 1), dtype=np.int32)
        for (slot, row) in zip(order, tokens):
            if slot is not None:
                token_full[slot] = row
        eng = self.eng
        if is_last:
            sp = self._sample_params(msg, order)
            res, self.kv, self.counts, self.keys = self._full(
                eng.window_params, eng.edge_params, jnp.asarray(token_full),
                self.kv, jnp.asarray(pos), jnp.asarray(active), sp,
                self.keys, self.counts,
            )
            return self._advance_and_slice(res, order)
        x, self.kv = self._head(
            eng.window_params, eng.edge_params, jnp.asarray(token_full),
            self.kv, jnp.asarray(pos), jnp.asarray(active),
        )
        self._advance(order)
        return x[self._gather_idx(order)]

    def step_hidden(self, msg, hidden, is_last: bool):
        """Mid/tail-shard batched step.  hidden [n, 1, D] in member order."""
        active, pos, order = self._scatter(msg)
        good = [i for i, o in enumerate(order) if o is not None]
        if not good:
            # every member faulted (reset races, stale pos, upstream
            # flags): nothing to compute, and np.asarray([]) would build
            # FLOAT64 index arrays that TypeError the .at[] update — which
            # would error-fail the whole frame instead of letting the
            # per-lane errors ride to the tail's finals
            if is_last:
                return [None] * len(order)
            return jnp.asarray(hidden).astype(self.eng.param_dtype)
        D = hidden.shape[-1]
        x_full = jnp.zeros((self.slots, 1, D), dtype=self.eng.param_dtype)
        idx = np.asarray([order[i] for i in good], dtype=np.int64)
        x_full = x_full.at[idx].set(
            jnp.asarray(hidden)[np.asarray(good, dtype=np.int64)]
            .astype(self.eng.param_dtype)
        )
        eng = self.eng
        if is_last:
            sp = self._sample_params(msg, order)
            res, self.kv, self.counts, self.keys = self._tail(
                eng.window_params, eng.edge_params, x_full, self.kv,
                jnp.asarray(pos), jnp.asarray(active), sp,
                self.keys, self.counts,
            )
            return self._advance_and_slice(res, order)
        x, self.kv = self._mid(
            eng.window_params, x_full, self.kv, jnp.asarray(pos),
            jnp.asarray(active),
        )
        self._advance(order)
        return x[self._gather_idx(order)]

    @staticmethod
    def _gather_idx(order) -> np.ndarray:
        """Member-order gather indices; faulted members (slot None) reuse
        row 0 — an inert garbage row their flagged lane metadata marks."""
        return np.asarray([o if o is not None else 0 for o in order])

    def _advance(self, order) -> None:
        now = time.time()
        for slot in order:
            if slot is None:
                continue
            self.pos[slot] += 1
            self.last_used[slot] = now

    def _advance_and_slice(self, res, order) -> List[Optional[SampleResult]]:
        """Per-member B=1 SampleResult views (host-side) from the vmapped
        full-width outputs — each slice drops into LocalEngine.token_result
        unchanged.  Faulted members yield None (error finals upstream)."""
        self._advance(order)
        res = jax.tree.map(np.asarray, res)
        return [
            None
            if slot is None
            else SampleResult(
                token=res.token[slot],
                logprob=res.logprob[slot],
                top_tokens=res.top_tokens[slot],
                top_logprobs=res.top_logprobs[slot],
            )
            for slot in order
        ]
