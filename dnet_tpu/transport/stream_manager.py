"""Per-nonce bidi-stream lifecycle over gRPC aio.

Faithful port of the reference's StreamManager semantics
(src/dnet/core/stream_manager.py:48-130): lazy stream open per nonce, a
background ACK-reader task per stream, backpressure ACKs temporarily
disabling the stream with backoff, and periodic idle sweeping.  The channel
layer is injectable (tests pass fakes; production passes grpc.aio).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from dnet_tpu.analysis.runtime import ownership as dsan
from dnet_tpu.obs import get_recorder, metric
from dnet_tpu.resilience import chaos
from dnet_tpu.resilience.policy import call_with_retry
from dnet_tpu.transport.protocol import ActivationFrame, StreamAck
from dnet_tpu.utils.logger import get_logger

log = get_logger()

_TX_BYTES = metric("dnet_transport_tx_bytes_total")
_TX_FRAMES = metric("dnet_transport_tx_frames_total")
_BACKPRESSURE = metric("dnet_transport_backpressure_total")
_REOPENS = metric("dnet_stream_reopens_total")
_WIRE_BYTES = metric("dnet_wire_bytes_total")


@dataclass
class StreamContext:
    nonce: str
    call: object  # grpc aio stream-stream call
    ack_task: Optional[asyncio.Task] = None
    last_used: float = field(default_factory=time.monotonic)
    disabled_until: float = 0.0
    seq: int = 0

    @property
    def disabled(self) -> bool:
        return time.monotonic() < self.disabled_until


class StreamManager:
    """Owns outbound activation streams keyed by nonce."""

    def __init__(
        self,
        open_stream: Callable[[], object],
        backoff_s: float = 0.25,
        idle_timeout_s: float = 30.0,
        on_nack: Optional[Callable[[StreamAck], None]] = None,
    ) -> None:
        self._open_stream = open_stream  # () -> stream-stream call
        # loop-only by contract (declared in analysis/runtime/domains.py):
        # every touch happens in a coroutine; the asyncio.Lock below only
        # serializes coroutines, it cannot protect against a raw thread
        self._streams: Dict[str, StreamContext] = dsan.guard_dict(
            {}, dsan.loop_domain(), "StreamManager._streams"
        )
        self._backoff_s = backoff_s
        self._idle_timeout_s = idle_timeout_s
        self._lock = asyncio.Lock()
        # outright-rejection observer (non-backpressure NACK): the epoch
        # fence answers fenced frames with a NACK the sender must be able
        # to act on — without this hook a fenced request would hang its
        # full await timeout on a token that can never come
        self._on_nack = on_nack

    async def get_or_create(self, nonce: str) -> StreamContext:
        async with self._lock:
            ctx = self._streams.get(nonce)
            if ctx is None:
                call = self._open_stream()
                ctx = StreamContext(nonce=nonce, call=call)
                ctx.ack_task = asyncio.ensure_future(self._ack_reader(ctx))
                self._streams[nonce] = ctx
            ctx.last_used = time.monotonic()
            return ctx

    async def send(self, nonce: str, frame: ActivationFrame) -> None:
        """Send one frame, respecting backpressure disable windows.

        frame.seq is the caller's end-to-end step identity and is preserved
        (the token callback echoes it; rewriting here would desync futures
        when a stream is recreated mid-request).  ctx.seq only counts frames
        for diagnostics.

        A write failure (peer restarted, channel reset) drops the context
        and — under the send_activation retry policy — re-opens a fresh
        stream and re-sends THIS frame with its original seq; the shard
        side dedups on (nonce, seq, layer_id) in case the first write
        landed before the break was observed.  Retries exhausted (or a
        non-transient error) propagate to the caller as before.
        """
        async def _attempt() -> StreamContext:
            ctx = await self.get_or_create(nonce)
            while ctx.disabled:
                await asyncio.sleep(
                    max(ctx.disabled_until - time.monotonic(), 0.01)
                )
            ctx.seq += 1
            try:
                await chaos.inject_async("send_activation")
                await ctx.call.write(frame)
            except Exception:
                # dead stream: drop the context so the retry (or the next
                # frame) opens a fresh one instead of failing forever
                await self.end_stream(nonce)
                raise
            return ctx

        t0 = time.perf_counter()
        ctx = await call_with_retry(
            _attempt,
            method="send_activation",
            on_retry=lambda *_: _REOPENS.inc(),
        )
        ctx.last_used = time.monotonic()
        n_bytes = len(getattr(frame, "payload", b"") or b"")
        _TX_BYTES.inc(n_bytes)
        _WIRE_BYTES.labels(dir="tx").inc(n_bytes)
        _TX_FRAMES.inc()
        # seq rides along so the Perfetto export (obs/trace.py) can pair
        # this send with the receiving node's transport_recv flow arrow
        get_recorder().span(
            nonce, "transport_send", (time.perf_counter() - t0) * 1000,
            bytes=n_bytes, seq=getattr(frame, "seq", None),
        )

    async def _ack_reader(self, ctx: StreamContext) -> None:
        """Consume ACKs; a backpressure ACK pauses the stream briefly
        (reference stream_manager.py:76-96)."""
        try:
            while True:
                ack = await ctx.call.read()
                if ack is None or ack is getattr(ctx.call, "EOF", None):
                    break
                if isinstance(ack, (bytes, bytearray)):
                    ack = StreamAck.from_bytes(bytes(ack))
                if ack.backpressure:
                    ctx.disabled_until = time.monotonic() + self._backoff_s
                    _BACKPRESSURE.inc()
                    get_recorder().span(
                        ctx.nonce, "backpressure_pause", self._backoff_s * 1000
                    )
                    log.warning(
                        "[PROFILE] stream %s backpressure, pausing %.2fs",
                        ctx.nonce,
                        self._backoff_s,
                    )
                elif not ack.ok:
                    log.warning("stream %s NACK seq=%d: %s", ctx.nonce, ack.seq, ack.message)
                    if self._on_nack is not None:
                        try:
                            self._on_nack(ack)
                        except Exception:
                            log.exception("on_nack handler failed")
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            log.debug("ack reader for %s ended: %s", ctx.nonce, exc)

    async def end_stream(self, nonce: str) -> None:
        async with self._lock:
            ctx = self._streams.pop(nonce, None)
        if ctx is None:
            return
        if ctx.ack_task:
            ctx.ack_task.cancel()
        done = getattr(ctx.call, "done_writing", None)
        if done is not None:
            try:
                await done()
            except Exception as exc:
                # half-close on an already-broken stream: the stream is
                # gone either way, but leave a trace (DL007 contract)
                log.debug("done_writing failed for %s: %s", nonce, exc)

    async def cleanup_idle(self) -> int:
        """Close streams idle past the timeout; returns count closed."""
        now = time.monotonic()
        stale = [
            n
            for n, c in self._streams.items()
            if now - c.last_used > self._idle_timeout_s
        ]
        # stale streams are independent: half-close them all concurrently
        # (end_stream pops under the lock per nonce, so parallel ends on
        # distinct nonces cannot race each other)
        await asyncio.gather(*(self.end_stream(n) for n in stale))
        return len(stale)

    async def shutdown(self) -> None:
        await asyncio.gather(
            *(self.end_stream(n) for n in list(self._streams))
        )
