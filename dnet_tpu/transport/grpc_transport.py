"""gRPC channel/server glue for the ring data plane (generic methods).

Channel tuning mirrors the reference (src/dnet/utils/grpc_config.py:29-53):
64 MiB messages, 1024 streams, conservative keepalive, BDP probe off,
no proxy.  Services register via grpc generic handlers (no codegen).
"""

from __future__ import annotations

from typing import Optional

import grpc
import grpc.aio as aio_grpc

from dnet_tpu.config import get_settings
from dnet_tpu.resilience.policy import call_with_retry
from dnet_tpu.transport import protocol as proto
from dnet_tpu.utils.logger import get_logger

log = get_logger()


def channel_options(settings=None) -> list:
    s = settings or get_settings()
    mb = s.grpc.max_message_mb * 1024 * 1024
    return [
        ("grpc.max_send_message_length", mb),
        ("grpc.max_receive_message_length", mb),
        ("grpc.max_concurrent_streams", s.grpc.max_concurrent_streams),
        ("grpc.keepalive_time_ms", s.grpc.keepalive_time_ms),
        ("grpc.keepalive_timeout_ms", s.grpc.keepalive_timeout_ms),
        ("grpc.http2.bdp_probe", int(s.grpc.http2_bdp_probe)),
        ("grpc.enable_http_proxy", 0),
    ]


def make_channel(addr: str) -> aio_grpc.Channel:
    return aio_grpc.insecure_channel(addr, options=channel_options())


class RingClient:
    """Client side of the ring data plane: streams to a peer shard and the
    unary control RPCs."""

    def __init__(self, addr: str) -> None:
        self.addr = addr
        self.channel = make_channel(addr)
        self._stream_stream = self.channel.stream_stream(
            proto.M_STREAM_ACTIVATIONS,
            request_serializer=lambda f: f.to_bytes(),
            response_deserializer=proto.StreamAck.from_bytes,
        )
        self._send_activation = self.channel.unary_unary(
            proto.M_SEND_ACTIVATION,
            request_serializer=lambda f: f.to_bytes(),
            response_deserializer=proto.StreamAck.from_bytes,
        )
        self._health = self.channel.unary_unary(
            proto.M_HEALTH_CHECK,
            request_serializer=lambda m: m.to_bytes(),
            response_deserializer=proto.HealthInfo.from_bytes,
        )
        self._reset = self.channel.unary_unary(
            proto.M_RESET_CACHE,
            request_serializer=lambda m: m.to_bytes(),
            response_deserializer=proto.Empty.from_bytes,
        )
        self._latency = self.channel.unary_unary(
            proto.M_MEASURE_LATENCY,
            request_serializer=lambda m: m.to_bytes(),
            response_deserializer=proto.LatencyProbe.from_bytes,
        )

    def open_stream(self):
        return self._stream_stream()

    # Unary RPCs retry transient failures (gRPC UNAVAILABLE /
    # DEADLINE_EXCEEDED) under per-class backoff policies
    # (resilience/policy.py).  health_check's class pins ONE attempt: the
    # failure monitor owns probe retry semantics via its fail threshold.
    async def send_activation(self, frame: proto.ActivationFrame, timeout: float = 10.0):
        return await call_with_retry(
            lambda: self._send_activation(frame, timeout=timeout),
            method="send_activation",
        )

    async def health_check(self, timeout: float = 5.0) -> proto.HealthInfo:
        return await call_with_retry(
            lambda: self._health(proto.Empty(), timeout=timeout),
            method="health_check",
        )

    async def reset_cache(
        self, nonce: str = "", timeout: float = 10.0, epoch: int = 0
    ):
        return await call_with_retry(
            lambda: self._reset(
                proto.ResetCacheRequest(nonce=nonce, epoch=epoch),
                timeout=timeout,
            ),
            method="reset_cache",
        )

    async def measure_latency(self, probe: proto.LatencyProbe, timeout: float = 30.0):
        return await call_with_retry(
            lambda: self._latency(probe, timeout=timeout),
            method="measure_latency",
        )

    async def close(self) -> None:
        await self.channel.close()


class ApiCallbackClient:
    """Shard -> API unary token callback (shard_api_comm semantics)."""

    def __init__(self, addr: str) -> None:
        self.addr = addr
        self.channel = make_channel(addr)
        self._send_token = self.channel.unary_unary(
            proto.M_SEND_TOKEN,
            request_serializer=lambda m: m.to_bytes(),
            response_deserializer=proto.Empty.from_bytes,
        )

    async def send_token(self, payload: proto.TokenPayload, timeout: float = 3.0):
        return await self._send_token(payload, timeout=timeout)

    async def close(self) -> None:
        await self.channel.close()


# ---- server-side registration ----------------------------------------------


def ring_service_handlers(servicer) -> grpc.GenericRpcHandler:
    """servicer must provide: stream_activations(iterator, context) async gen,
    send_activation, health_check, reset_cache, measure_latency coroutines."""
    return grpc.method_handlers_generic_handler(
        proto.RING_SERVICE,
        {
            "StreamActivations": grpc.stream_stream_rpc_method_handler(
                servicer.stream_activations,
                request_deserializer=proto.ActivationFrame.from_bytes,
                response_serializer=lambda m: m.to_bytes(),
            ),
            "SendActivation": grpc.unary_unary_rpc_method_handler(
                servicer.send_activation,
                request_deserializer=proto.ActivationFrame.from_bytes,
                response_serializer=lambda m: m.to_bytes(),
            ),
            "HealthCheck": grpc.unary_unary_rpc_method_handler(
                servicer.health_check,
                request_deserializer=proto.Empty.from_bytes,
                response_serializer=lambda m: m.to_bytes(),
            ),
            "ResetCache": grpc.unary_unary_rpc_method_handler(
                servicer.reset_cache,
                request_deserializer=proto.ResetCacheRequest.from_bytes,
                response_serializer=lambda m: m.to_bytes(),
            ),
            "MeasureLatency": grpc.unary_unary_rpc_method_handler(
                servicer.measure_latency,
                request_deserializer=proto.LatencyProbe.from_bytes,
                response_serializer=lambda m: m.to_bytes(),
            ),
        },
    )


def api_service_handlers(servicer) -> grpc.GenericRpcHandler:
    return grpc.method_handlers_generic_handler(
        proto.API_SERVICE,
        {
            "SendToken": grpc.unary_unary_rpc_method_handler(
                servicer.send_token,
                request_deserializer=proto.TokenPayload.from_bytes,
                response_serializer=lambda m: m.to_bytes(),
            ),
        },
    )


async def start_grpc_server(host: str, port: int, *handlers) -> aio_grpc.Server:
    server = aio_grpc.server(options=channel_options())
    server.add_generic_rpc_handlers(tuple(handlers))
    server.add_insecure_port(f"{host}:{port}")
    await server.start()
    log.info("gRPC listening on %s:%d", host, port)
    return server
