"""Overlapped quantized wire pipeline (DNET_WIRE_PIPELINE=1).

The hop codec used to sit SERIALLY inside the shard compute thread: step N
computed, then the thread paid the full encode (device quant/sparsify +
D2H readback + byte packing) before step N+1 could start.  This module is
the machinery that takes it off that path, following EQuARX's
quantize-the-collective-and-overlap framing (arxiv 2506.17615):

- tx: the compute thread only LAUNCHES the on-device encode (jitted, with
  the activation buffer donated — compression/wire.py launch_encode) and
  wraps the pending device buffers in a :class:`PendingWirePayload`.  The
  adapter's egress worker finalizes it on the :class:`WireTxStage`'s
  dedicated executor thread — D2H readback + byte packing + gRPC send all
  happen while the compute thread is already inside the next step.

- backpressure: a bounded :class:`EncodeRing` of encode slots (depth 2 by
  default) couples compute speed to wire drain — the compute thread may
  run at most ``depth`` launched-but-unsent frames ahead; past that,
  ``acquire`` blocks until the tx stage releases a slot.

- rx: the symmetric half lives in ShardCompute.predecode — ingress
  launches H2D upload + on-device dequant for a QUEUED frame so frame
  N+1's decode overlaps frame N's compute; this module only owns the
  shared accounting.

- attribution: ``dnet_wire_encode_ms`` / ``dnet_wire_decode_ms`` split by
  where the time was spent, and :data:`overlap` folds every observation
  into ``dnet_wire_overlap_ratio`` = hidden codec ms / total codec ms
  (1.0 = the wire costs the compute thread nothing but dispatch).

Chaos points ``wire_encode`` / ``wire_decode`` sit inside the codec work
so fault tests can deterministically wedge the tx stage (delay) or fail a
frame's codec (error) — resilience/chaos.py grammar.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from dnet_tpu.analysis.runtime import ownership as dsan
from dnet_tpu.obs import metric
from dnet_tpu.resilience import chaos
from dnet_tpu.utils.logger import get_logger

log = get_logger()

_ENCODE_MS = metric("dnet_wire_encode_ms")
_DECODE_MS = metric("dnet_wire_decode_ms")
_OVERLAP = metric("dnet_wire_overlap_ratio")


def wire_pipeline_enabled() -> bool:
    """THE flag gate: DNET_WIRE_PIPELINE=1 (WireSettings.pipeline).  A raw
    env read (config.env_flag, the sanctioned DL006 escape hatch) backs
    the settings value so tests toggling os.environ after the settings
    cache warmed still see the flip — the sched_enabled contract."""
    from dnet_tpu.config import env_flag, get_settings

    if get_settings().wire.pipeline:
        return True
    return env_flag("DNET_WIRE_PIPELINE")


class _OverlapTracker:
    """Cumulative serial-vs-hidden codec milliseconds -> the overlap gauge.

    ``serial`` ms were paid ON the compute thread (launch dispatch, or the
    whole codec when the pipeline is off); ``hidden`` ms ran on the tx
    stage / at ingress, overlapped with compute.  The gauge is the hidden
    fraction — how much of the codec the pipeline actually took off the
    serial path.

    ``stall`` ms are encode-ring backpressure waits — the compute thread
    intentionally parked because the wire is the bottleneck.  Books-kept
    separately and EXCLUDED from the ratio: backpressure is the depth
    bound doing its job, not codec work on the serial path."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._serial_ms = 0.0
        self._hidden_ms = 0.0
        self._stall_ms = 0.0

    def add(self, serial_ms: float = 0.0, hidden_ms: float = 0.0,
            stall_ms: float = 0.0) -> None:
        with self._lock:
            self._serial_ms += serial_ms
            self._hidden_ms += hidden_ms
            self._stall_ms += stall_ms
            total = self._serial_ms + self._hidden_ms
            ratio = (self._hidden_ms / total) if total > 0 else 0.0
        _OVERLAP.set(round(ratio, 6))

    def snapshot(self) -> dict:
        with self._lock:
            total = self._serial_ms + self._hidden_ms
            return {
                "serial_ms": self._serial_ms,
                "hidden_ms": self._hidden_ms,
                "stall_ms": self._stall_ms,
                "ratio": (self._hidden_ms / total) if total > 0 else 0.0,
            }

    def reset(self) -> None:
        with self._lock:
            self._serial_ms = 0.0
            self._hidden_ms = 0.0
            self._stall_ms = 0.0
        _OVERLAP.set(0.0)


#: process-global overlap books (one wire per process; tests reset())
overlap = _OverlapTracker()


def observe_encode(ms: float, hidden: bool) -> None:
    _ENCODE_MS.observe(ms)
    overlap.add(hidden_ms=ms if hidden else 0.0,
                serial_ms=0.0 if hidden else ms)


def observe_decode(ms: float, hidden: bool) -> None:
    _DECODE_MS.observe(ms)
    overlap.add(hidden_ms=ms if hidden else 0.0,
                serial_ms=0.0 if hidden else ms)


class EncodeRing:
    """Bounded ring of in-flight encode slots — the pipeline's depth-2
    double buffer.  ``acquire`` runs on the compute thread BEFORE the
    encode launches; ``release`` runs on the tx stage after the readback.
    A full ring blocks the compute thread: that is the backpressure that
    keeps device memory bounded and couples compute to wire drain.

    ``acquire`` degrades rather than deadlocks: if no slot frees within
    ``max_wait_s`` (a wedged/failed tx stage), it returns False and the
    caller encodes synchronously — slower, never stuck."""

    #: seconds a full ring may block the compute thread before the caller
    #: falls back to the synchronous encode path
    MAX_WAIT_S = 10.0

    def __init__(self, depth: int = 2) -> None:
        self.depth = max(int(depth), 1)
        self._slots = threading.BoundedSemaphore(self.depth)
        # dsan ownership (analysis/runtime/domains.py): the in-flight
        # count is touched from the compute thread AND the tx executor —
        # guarded-by _lock is the only honest domain for it
        self._lock = dsan.san_lock("EncodeRing._lock")
        self._domain = dsan.maybe_lock_domain(self._lock)
        self._inflight = 0

    def acquire(self, max_wait_s: Optional[float] = None) -> bool:
        budget = self.MAX_WAIT_S if max_wait_s is None else max_wait_s
        if not self._slots.acquire(timeout=budget):
            log.warning(
                "encode ring full for %.1fs (tx stage wedged?); "
                "falling back to synchronous encode", budget,
            )
            return False
        with self._lock:
            dsan.check_access("EncodeRing._inflight", self._domain, "write")
            self._inflight += 1
        return True

    def release(self) -> None:
        with self._lock:
            dsan.check_access("EncodeRing._inflight", self._domain, "write")
            self._inflight -= 1
        self._slots.release()  # BoundedSemaphore: over-release raises

    @property
    def inflight(self) -> int:
        with self._lock:
            dsan.check_access("EncodeRing._inflight", self._domain, "read")
            return self._inflight


class PendingWirePayload:
    """A hop whose payload is still a set of device buffers.

    Rides ActivationMessage.data from the compute thread to the adapter's
    egress worker, which awaits :class:`WireTxStage`.finalize before
    building the gRPC frame.  ``dtype``/``shape`` are final at launch, so
    everything EXCEPT the bytes is already known.  ``finalize`` releases
    the encode-ring slot whatever happens — an encode failure must not
    leak ring capacity and wedge the compute thread forever."""

    __slots__ = ("encode", "ring")

    def __init__(self, encode, ring: Optional[EncodeRing] = None) -> None:
        self.encode = encode  # compression.wire.DeviceEncode
        self.ring = ring

    @property
    def dtype(self) -> str:
        return self.encode.dtype

    @property
    def shape(self) -> tuple:
        return self.encode.shape

    def finalize(self, hidden: bool = True) -> bytes:
        """The ONE finalize body: chaos gate, D2H readback, byte packing,
        ring-slot release whatever happens.  ``hidden=True`` is the tx
        stage (overlapped with compute); ``hidden=False`` attributes the
        time as serial — the compute-thread fallback when the ring is
        full or the probe consumes its own frame."""
        t0 = time.perf_counter()
        try:
            chaos.inject("wire_encode")
            return self.encode.finalize()
        finally:
            if self.ring is not None:
                self.ring.release()
            observe_encode((time.perf_counter() - t0) * 1000.0, hidden=hidden)

    def finalize_sync(self) -> bytes:
        """Compute-thread fallback: same bytes, attributed as serial."""
        return self.finalize(hidden=False)

    def discard(self) -> None:
        """Drop the pending encode WITHOUT reading it back (frame dropped
        before send: output-queue overflow, calibration probe teardown).
        Must still release the ring slot — a leaked slot wedges the
        compute thread behind a frame nobody will ever finalize."""
        ring, self.ring = self.ring, None
        if ring is not None:
            ring.release()


class WireTxStage:
    """The dedicated tx stage: finalizes pending encodes on its own
    single-thread executor so the event loop never blocks on a D2H
    readback and the compute thread never waits for byte packing.  One
    worker keeps per-stream frame order trivially (the egress worker
    awaits each finalize before sending)."""

    def __init__(self) -> None:
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="wire-tx"
        )
        # loop-owned in-flight map (seq -> pending), declared in
        # analysis/runtime/domains.py: the egress worker is the only
        # writer, and a second loop touching it would break frame order
        self._pending = dsan.guard_dict(
            {}, dsan.loop_domain(), "WireTxStage._pending"
        )
        self._seq = 0

    @property
    def inflight(self) -> int:
        return len(self._pending)

    async def finalize(
        self, pending: PendingWirePayload, nonce: str = "",
        seq: int = -1,
    ) -> bytes:
        import asyncio

        key = self._seq
        self._seq += 1
        self._pending[key] = pending
        t0 = time.perf_counter()
        cfut = self._executor.submit(pending.finalize)
        try:
            data = await asyncio.wrap_future(cfut)
            if nonce:
                # the tx-stage leg of the frame's story: executor queue
                # wait + D2H readback + byte packing, rendered on the
                # tx-stage thread track in the Perfetto export
                # (obs/trace.py) under the egress wire_encode umbrella
                from dnet_tpu.obs import get_recorder

                get_recorder().span(
                    nonce, "wire_tx_stage",
                    (time.perf_counter() - t0) * 1000.0,
                    seq=seq, bytes=len(data),
                )
            return data
        except asyncio.CancelledError:
            # egress task cancelled (shutdown) while the finalize was
            # still queued: it will never run, so the ring slot it holds
            # must be released here or the compute thread wedges behind
            # it.  A finalize that already STARTED completes on the
            # executor and releases the slot itself.
            if cfut.cancel() or cfut.cancelled():
                pending.discard()
            raise
        finally:
            self._pending.pop(key, None)

    def shutdown(self) -> None:
        self._executor.shutdown(wait=False, cancel_futures=True)
