"""Ring wire protocol: msgpack-framed messages over gRPC generic methods.

The reference defines three .proto files compiled with protoc
(src/dnet/protos/dnet_ring.proto, shard_api_comm.proto); this image has no
grpc codegen plugin, and protobuf offers nothing on this hot path anyway —
frames are a tiny header + one opaque tensor-bytes blob.  So the wire format
is msgpack (schema below) and services are registered with grpc generic
handlers.  Semantics mirror the reference exactly: nonce+seq framed
activation streaming with per-frame ACKs (dnet_ring.proto:57-68), unary
token callback (shard_api_comm.proto:34-40), health/reset/latency RPCs.

Every message type has a dataclass + pack/unpack pair; `payload` fields are
raw little-endian tensor bytes described by (dtype, shape) — same convention
as dnet_tpu.utils.serialization.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field
from typing import List, Optional, Tuple

import msgpack

from dnet_tpu.core.types import ActivationMessage, DecodingParams, TokenResult

# gRPC method paths (service namespacing mirrors the reference protos)
RING_SERVICE = "dnet.DnetRing"
M_STREAM_ACTIVATIONS = f"/{RING_SERVICE}/StreamActivations"
M_SEND_ACTIVATION = f"/{RING_SERVICE}/SendActivation"
M_HEALTH_CHECK = f"/{RING_SERVICE}/HealthCheck"
M_RESET_CACHE = f"/{RING_SERVICE}/ResetCache"
M_MEASURE_LATENCY = f"/{RING_SERVICE}/MeasureLatency"

API_SERVICE = "dnet.ShardApi"
M_SEND_TOKEN = f"/{API_SERVICE}/SendToken"


def pack(obj: dict) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def unpack(data: bytes) -> dict:
    # strict_map_key off: DecodingParams.logit_bias rides the wire with
    # integer token-id keys
    return msgpack.unpackb(data, raw=False, strict_map_key=False)


# ---- frames ---------------------------------------------------------------


@dataclass
class ActivationFrame:
    """One hop of the ring: token injection, hidden-state, or relay."""

    nonce: str
    seq: int
    layer_id: int  # last layer applied; -1 = raw tokens entering layer 0
    pos: int  # absolute sequence offset of this frame's first token
    dtype: str  # "tokens" | wire dtype name (may carry compression tags)
    shape: Tuple[int, ...]
    payload: bytes
    callback_url: str = ""  # grpc://host:port for the final token
    decoding: dict = field(default_factory=dict)
    t_sent: float = 0.0
    # sender's monotonic clock at send, carried alongside t_sent so a
    # sender-side tool (frame dump, ack-RTT probe) can correlate a frame
    # with that process's perf_counter-based spans without trusting wall
    # time (NTP can step t_sent mid-request).  Only meaningful to the
    # process that stamped it — cross-NODE comparison goes through the
    # obs/clock.py offset estimator, never this field.
    t_sent_mono: float = 0.0
    # decode grant: tokens the tail may self-continue without an API hop
    auto_steps: int = 0
    # ring speculation: drafted token ids riding a widened verify block
    # (head -> tail), and the block's accepted tokens riding the
    # continuation (tail -> head, committed to the head's draft history)
    drafts: List[int] = field(default_factory=list)
    committed: List[int] = field(default_factory=list)
    # batched lanes: per-member {"nonce","seq","pos","decoding"} metadata of
    # a coalesced decode frame (payload rows stacked in the same order)
    lanes: List[dict] = field(default_factory=list)
    # ring prefix caching: store/seed keys on prompt frames (core/types.py)
    prefix_store: str = ""
    prefix_hit: str = ""
    # end-to-end request deadline (sender's wall clock, epoch seconds;
    # 0 = none).  Receivers compare against their OWN wall clock — the
    # error is cross-host NTP skew, negligible against any sane deadline.
    # Shards drop expired frames at compute-queue dequeue.
    deadline: float = 0.0
    # topology epoch this frame was minted under (membership/epoch.py);
    # 0 = unfenced.  Shards pin their epoch at load and NACK any frame
    # from a different epoch — the zombie/split-brain fence.
    epoch: int = 0
    # resolved hop-codec name ("bfloat16" lossless cast, "sparse_v1",
    # "qsparse8_v1" — compression.wire.codec_name): first-class so
    # receivers/benches can attribute per-hop bytes without re-parsing the
    # dtype tag.  "" on frames from senders predating the wire pipeline.
    codec: str = ""

    def to_bytes(self) -> bytes:
        d = asdict(self)
        d["shape"] = list(self.shape)
        return pack(d)

    @classmethod
    def from_bytes(cls, data: bytes) -> "ActivationFrame":
        d = unpack(data)
        d["shape"] = tuple(d["shape"])
        return cls(**d)

    def to_message(self) -> ActivationMessage:
        dec = DecodingParams(**self.decoding) if self.decoding else DecodingParams()
        return ActivationMessage(
            nonce=self.nonce,
            layer_id=self.layer_id,
            seq=self.seq,
            dtype=self.dtype,
            shape=self.shape,
            data=self.payload,
            pos=self.pos,
            callback_url=self.callback_url,
            decoding=dec,
            auto_steps=self.auto_steps,
            drafts=list(self.drafts),
            committed=list(self.committed),
            lanes=list(self.lanes),
            prefix_store=self.prefix_store,
            prefix_hit=self.prefix_hit,
            deadline=self.deadline,
            epoch=self.epoch,
        )


@dataclass
class StreamAck:
    nonce: str
    seq: int
    ok: bool = True
    backpressure: bool = False
    message: str = ""

    def to_bytes(self) -> bytes:
        return pack(asdict(self))

    @classmethod
    def from_bytes(cls, data: bytes) -> "StreamAck":
        return cls(**unpack(data))


@dataclass
class TokenPayload:
    """Last shard -> API: the sampled token (shard_api_comm.proto:34-40)."""

    nonce: str
    step: int
    token_id: int
    logprob: Optional[float] = None
    top_ids: List[int] = field(default_factory=list)
    top_logprobs: List[float] = field(default_factory=list)
    error: str = ""
    # topology epoch the emitting shard held (0 = unfenced): the API
    # rejects tokens minted under a dead epoch, so a zombie shard's late
    # callback can never reach a live SSE stream
    epoch: int = 0

    def to_bytes(self) -> bytes:
        return pack(asdict(self))

    @classmethod
    def from_bytes(cls, data: bytes) -> "TokenPayload":
        return cls(**unpack(data))

    def to_result(self) -> TokenResult:
        top = list(zip(self.top_ids, self.top_logprobs)) if self.top_ids else None
        return TokenResult(
            nonce=self.nonce,
            token_id=self.token_id,
            logprob=self.logprob,
            top_logprobs=top,
            step=self.step,
            error=self.error,
            epoch=self.epoch,
        )

    @classmethod
    def from_result(cls, r: TokenResult) -> "TokenPayload":
        top = r.top_logprobs or []
        return cls(
            nonce=r.nonce,
            step=r.step,
            token_id=r.token_id,
            logprob=r.logprob,
            top_ids=[t for t, _ in top],
            top_logprobs=[lp for _, lp in top],
            error=r.error,
            epoch=r.epoch,
        )


@dataclass
class HealthInfo:
    ok: bool = True
    model: str = ""
    layers: List[int] = field(default_factory=list)
    queue_depth: int = 0
    # topology epoch this shard pinned at load (0 = none pinned)
    epoch: int = 0

    def to_bytes(self) -> bytes:
        return pack(asdict(self))

    @classmethod
    def from_bytes(cls, data: bytes) -> "HealthInfo":
        return cls(**unpack(data))


@dataclass
class ResetCacheRequest:
    nonce: str = ""  # empty = reset all
    # sender's topology epoch (0 = unfenced admin reset, always allowed):
    # a reset minted under a dead epoch must not clear live-ring state
    epoch: int = 0

    def to_bytes(self) -> bytes:
        return pack(asdict(self))

    @classmethod
    def from_bytes(cls, data: bytes) -> "ResetCacheRequest":
        return cls(**unpack(data))


@dataclass
class LatencyProbe:
    """Echo RPC for link profiling (dnet_ring.proto MeasureLatency).

    The echo stamps `t_remote` (the server's wall clock while serving) so
    every latency measurement doubles as an NTP-midpoint clock-offset
    sample (obs/clock.py): offset = t_remote - (t_sent + t_recv)/2."""

    t_sent: float
    payload: bytes = b""
    t_remote: float = 0.0

    def to_bytes(self) -> bytes:
        return pack(asdict(self))

    @classmethod
    def from_bytes(cls, data: bytes) -> "LatencyProbe":
        return cls(**unpack(data))


@dataclass
class Empty:
    ok: bool = True

    def to_bytes(self) -> bytes:
        return pack(asdict(self))

    @classmethod
    def from_bytes(cls, data: bytes) -> "Empty":
        return cls(**unpack(data))