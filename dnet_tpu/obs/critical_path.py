"""Critical-path attribution: span timelines -> exhaustive segment ledger.

A BENCH_SERVE record tells you p99 E2E moved; the FlightRecorder tells you
which spans a request recorded.  Neither answers WHERE the p99 lives — the
spans overlap (a `decode_step` umbrella covers the hop RTT which covers the
shard compute which covers the sampler), so summing them double-counts and
grepping them by eye does not scale past one request.  This module
decomposes one request's recorded spans — a local timeline or a
cluster-stitched one (obs/clock.py stitch_timelines) — into the exhaustive,
non-overlapping segment ledger declared in obs/phases.py REQUEST_SEGMENTS:
every wall-clock millisecond between admission and the closing `request`
span is attributed to EXACTLY one segment, most-specific span wins, and
recorded time no span claims lands in `other` instead of vanishing.

The attribution rule is a priority sweep: spans are mapped to
(segment, specificity) by name, the window is cut at every span boundary,
and each elementary slice goes to the most specific span covering it.
`decode_step` (the API driver's per-token umbrella) is least specific;
`hop_rtt` (send->resolve, which contains the remote shard's whole story)
outranks it; shard compute / prefill outrank the hop; leaf work (sampling,
codec encode, stream writes, SSE flushes) and queue waits outrank
everything.  Because the slices partition the window, the per-request sums
reconcile against measured E2E by construction — the reconciliation the
ring acceptance test (tests/subsystems/) asserts end to end.

`observe()` feeds the ledger into `dnet_request_segment_ms{segment=}` so a
serving window's aggregate attribution is scrapeable;
`critical_path_section()` is the JSON shape `GET /v1/debug/timeline/{rid}`
embeds and loadgen rows carry into the BENCH_SERVE report.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from dnet_tpu.obs.phases import (
    REQUEST_SEGMENTS,
    SEG_ADMISSION_WAIT,
    SEG_DECODE_COMPUTE,
    SEG_HOP_RTT,
    SEG_OTHER,
    SEG_PREFILL_COMPUTE,
    SEG_SAMPLE,
    SEG_SCHED_QUEUE,
    SEG_SHARD_COMPUTE,
    SEG_SSE_FLUSH,
    SEG_WIRE_ENCODE,
    SEG_WIRE_TX,
)

# span name -> (segment, specificity).  Higher specificity wins an
# overlapping slice.  Tier 1 is the driver's per-token umbrella, tier 2
# the cross-node round trip it contains, tier 3 the per-node compute
# windows inside THAT, tier 4 leaf work and explicit waits.  Summary /
# marker spans (`request`, `ttft`, zero-duration breadcrumbs) are absent
# on purpose: they describe the window, they do not occupy it.
SPAN_SEGMENTS: Dict[str, Tuple[str, int]] = {
    "decode_step": (SEG_DECODE_COMPUTE, 1),
    "decode_sync_drain": (SEG_DECODE_COMPUTE, 3),
    "hop_rtt": (SEG_HOP_RTT, 2),
    "token_rpc": (SEG_HOP_RTT, 4),
    "prefill": (SEG_PREFILL_COMPUTE, 3),
    "prefix_refill": (SEG_PREFILL_COMPUTE, 3),
    "shard_compute": (SEG_SHARD_COMPUTE, 3),
    # batched decode sub-phases (core/batch.py, obs/phases.py STEP_PHASES):
    # compute-side leaf work; on a shard node they re-map to shard_compute
    # (see _segment_for) so the local-engine and ring stories agree
    "kv_gather": (SEG_DECODE_COMPUTE, 4),
    "compute": (SEG_DECODE_COMPUTE, 4),
    "kv_scatter": (SEG_DECODE_COMPUTE, 4),
    "sample": (SEG_SAMPLE, 4),
    "wire_encode": (SEG_WIRE_ENCODE, 4),
    # tx-stage leg rides under the egress wire_encode umbrella; tier 3 so
    # the encode leaf wins slices they share and only residual stage time
    # (executor queueing) attributes as wire_encode here
    "wire_tx_stage": (SEG_WIRE_ENCODE, 3),
    "transport_send": (SEG_WIRE_TX, 4),
    "shard_tx": (SEG_WIRE_TX, 4),
    "backpressure_pause": (SEG_WIRE_TX, 4),
    "admission_wait": (SEG_ADMISSION_WAIT, 4),
    "lane_queue_wait": (SEG_SCHED_QUEUE, 4),
    "sched_queue": (SEG_SCHED_QUEUE, 4),
    "shard_dequeue": (SEG_SCHED_QUEUE, 4),
    "sse_flush": (SEG_SSE_FLUSH, 4),
}


def _segment_for(span: dict) -> Optional[Tuple[str, int]]:
    mapped = SPAN_SEGMENTS.get(span.get("name", ""))
    if mapped is None:
        return None
    segment, prio = mapped
    # a stitched timeline tags every span with its node; generic compute
    # sub-phases recorded on a shard are that shard's compute, not the
    # API driver's
    node = span.get("node", "")
    if node and node != "api" and segment == SEG_DECODE_COMPUTE:
        segment = SEG_SHARD_COMPUTE
    return segment, prio


def decompose(timeline: Optional[dict]) -> Optional[dict]:
    """Segment ledger for one timeline (local or cluster-stitched), or
    None when there is nothing to attribute.

    Returns ``{"segments_ms", "total_ms", "e2e_ms", "coverage",
    "dominant", "cluster", "spans_attributed"}`` where ``segments_ms``
    carries every REQUEST_SEGMENTS key (zeros included), ``total_ms`` is
    the attribution window (== sum of the segments, by construction) and
    ``e2e_ms`` the closing `request` span's measured duration when one was
    recorded (else the window itself).
    """
    if not timeline:
        return None
    spans = timeline.get("spans") or []
    e2e_ms = None
    window_end = 0.0
    intervals = []  # (start, end, prio, segment)
    for span in spans:
        name = span.get("name", "")
        t0 = float(span.get("t_ms", 0.0))
        dur = float(span.get("dur_ms", 0.0))
        if name == "request":
            e2e_ms = dur
            window_end = max(window_end, t0 + dur)
            continue
        mapped = _segment_for(span)
        if mapped is None or dur <= 0.0:
            continue
        segment, prio = mapped
        intervals.append((t0, t0 + dur, prio, segment))
        window_end = max(window_end, t0 + dur)
    if not intervals and e2e_ms is None:
        return None
    window_start = min([iv[0] for iv in intervals] + [0.0])
    # clip to the window (a stitched remote span mis-corrected past the
    # end must not inflate the ledger)
    events = []  # (pos, +1/-1, interval index)
    for idx, (s, e, _prio, _seg) in enumerate(intervals):
        s = max(s, window_start)
        e = min(e, window_end)
        if e <= s:
            continue
        events.append((s, 1, idx))
        events.append((e, -1, idx))
    events.sort(key=lambda ev: (ev[0], -ev[1]))
    segments = {seg: 0.0 for seg in REQUEST_SEGMENTS}
    active: Dict[int, Tuple[int, str]] = {}
    pos = window_start
    i = 0
    while i < len(events):
        at = events[i][0]
        if at > pos:
            if active:
                # most specific active span claims the slice; ties go to
                # the latest-opened (innermost) interval
                best = max(active.items(), key=lambda kv: (kv[1][0], kv[0]))
                seg = best[1][1]
            else:
                seg = SEG_OTHER
            segments[seg] += at - pos
            pos = at
        while i < len(events) and events[i][0] == at:
            _at, kind, idx = events[i]
            if kind > 0:
                active[idx] = (intervals[idx][2], intervals[idx][3])
            else:
                active.pop(idx, None)
            i += 1
    if window_end > pos:
        segments[SEG_OTHER] += window_end - pos
    total = window_end - window_start
    segments = {seg: round(ms, 3) for seg, ms in segments.items()}
    measured = e2e_ms if e2e_ms is not None else total
    dominant = max(segments, key=lambda seg: segments[seg]) if total else SEG_OTHER
    return {
        "segments_ms": segments,
        "total_ms": round(total, 3),
        "e2e_ms": round(measured, 3),
        "coverage": round(total / measured, 4) if measured > 0 else None,
        "dominant": dominant,
        "cluster": bool(timeline.get("cluster")),
        "spans_attributed": len(intervals),
    }


def observe(ledger: Optional[dict]) -> None:
    """Feed one request's ledger into dnet_request_segment_ms{segment=}."""
    if not ledger:
        return
    from dnet_tpu.obs import metric

    fam = metric("dnet_request_segment_ms")
    for segment, ms in ledger["segments_ms"].items():
        if ms > 0.0:
            fam.labels(segment=segment).observe(ms)


def critical_path_section(timeline: Optional[dict]) -> Optional[dict]:
    """The `critical_path` block debug/timeline and loadgen rows embed."""
    return decompose(timeline)
