"""Shared aiohttp glue for the obs HTTP surface.

Both server roles (api/http.py, shard/http.py) expose `GET /metrics` and
`GET /v1/debug/timeline/{rid}`; the exposition body and the timeline lookup
live here so the two cannot drift.  Error-shape wrapping stays with each
server (the API wraps 404s as `{"error": {...}}`, the shard as
`{"status": "error", ...}` — each matching its own route convention).
"""

from __future__ import annotations

import time
from typing import Optional

from aiohttp import web

from dnet_tpu.obs import (
    CONTENT_TYPE_LATEST,
    get_recorder,
    get_registry,
    get_slo_tracker,
)


async def metrics_response(request: web.Request) -> web.Response:
    """Prometheus text exposition of this process's registry.  SLO gauges
    refresh lazily here: their values are windowed aggregates, so the
    scrape instant — not the last record_*() call — is when they must be
    current.  Device-memory gauges refresh the same way (they snapshot the
    backend's live allocator, not an event stream)."""
    from dnet_tpu.obs.jit import update_device_mem_gauges

    get_slo_tracker().snapshot()
    update_device_mem_gauges()
    return web.Response(
        body=get_registry().expose().encode("utf-8"),
        headers={"Content-Type": CONTENT_TYPE_LATEST},
    )


def find_timeline(rid: str) -> Optional[dict]:
    """Timeline lookup by public response id.  The recorder keys timelines
    by the internal `chatcmpl-...` nonce; /v1/completions clients hold the
    rewritten `cmpl-...` form (api/inference.py), so that alias is tried
    too — the documented workflow is "rid = the response id", whichever
    endpoint produced it.

    The snapshot carries `t_wall` (this process's wall clock at lookup)
    so a cross-node fetch doubles as an NTP-midpoint clock probe
    (obs/clock.py): the caller brackets the HTTP round trip with its own
    wall clock and estimates this node's offset from the same response
    that delivered the spans."""
    rec = get_recorder()
    timeline = rec.timeline(rid)
    if timeline is None and rid.startswith("cmpl-"):
        timeline = rec.timeline("chat" + rid)
    if timeline is not None:
        timeline["t_wall"] = time.time()
    return timeline
