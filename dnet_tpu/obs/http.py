"""Shared aiohttp glue for the obs HTTP surface.

Both server roles (api/http.py, shard/http.py) expose `GET /metrics` and
`GET /v1/debug/timeline/{rid}`; the exposition body and the timeline lookup
live here so the two cannot drift.  Error-shape wrapping stays with each
server (the API wraps 404s as `{"error": {...}}`, the shard as
`{"status": "error", ...}` — each matching its own route convention).
"""

from __future__ import annotations

from typing import Optional

from aiohttp import web

from dnet_tpu.obs import CONTENT_TYPE_LATEST, get_recorder, get_registry


async def metrics_response(request: web.Request) -> web.Response:
    """Prometheus text exposition of this process's registry."""
    return web.Response(
        body=get_registry().expose().encode("utf-8"),
        headers={"Content-Type": CONTENT_TYPE_LATEST},
    )


def find_timeline(rid: str) -> Optional[dict]:
    """Timeline lookup by public response id.  The recorder keys timelines
    by the internal `chatcmpl-...` nonce; /v1/completions clients hold the
    rewritten `cmpl-...` form (api/inference.py), so that alias is tried
    too — the documented workflow is "rid = the response id", whichever
    endpoint produced it."""
    rec = get_recorder()
    timeline = rec.timeline(rid)
    if timeline is None and rid.startswith("cmpl-"):
        timeline = rec.timeline("chat" + rid)
    return timeline
