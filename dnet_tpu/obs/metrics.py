"""Dependency-free metrics registry with Prometheus text exposition.

The prometheus_client package is not in this image, so this is a minimal
in-process implementation of the three instrument kinds the serving path
needs — Counter, Gauge, Histogram — plus the v0.0.4 text exposition format
scraped at `GET /metrics` (api/http.py, shard/http.py).

Design constraints, in priority order:

- **Hot-path cheap.**  Observations happen per decode step / per frame; an
  observe is a lock acquire + one float add + one bisect.  No string work
  until exposition.
- **Process-global, never replaced.**  Instrumented modules hold family
  handles at import time; `MetricsRegistry.reset()` zeroes values in place
  so those handles never go stale (tests reset between cases).
- **Bounded cardinality.**  A labeled family caps its child count at
  ``MAX_SERIES_PER_METRIC``; past the cap, new label combinations collapse
  into a shared ``_overflow`` child instead of growing without bound (a
  per-nonce label bug must not OOM the server it was meant to observe).
"""

from __future__ import annotations

import bisect
import math
import re
import threading
from collections import OrderedDict
from typing import Dict, Iterable, Optional, Tuple

METRIC_NAME_RE = re.compile(r"^dnet_[a-z0-9_]+$")

# Fixed ms-scale buckets: decode steps land in the 1-100ms decades, ring
# hops and prefills up to seconds; one shared scale keeps every latency
# histogram comparable on the same dashboard.
DEFAULT_MS_BUCKETS: Tuple[float, ...] = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)

OVERFLOW_LABEL = "_overflow"


def _fmt(v: float) -> str:
    """Prometheus sample value: integral floats print as integers."""
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _label_str(names: Tuple[str, ...], values: Tuple[str, ...],
               extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    pairs = list(zip(names, values)) + list(extra)
    if not pairs:
        return ""
    body = ",".join(f'{k}="{_escape(v)}"' for k, v in pairs)
    return "{" + body + "}"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


class _CounterChild:
    __slots__ = ("_lock", "value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, v: float = 1.0) -> None:
        if v < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self.value += v

    def _reset(self) -> None:
        with self._lock:
            self.value = 0.0


class _GaugeChild:
    __slots__ = ("_lock", "value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self.value += v

    def dec(self, v: float = 1.0) -> None:
        with self._lock:
            self.value -= v

    def _reset(self) -> None:
        with self._lock:
            self.value = 0.0


class _HistogramChild:
    __slots__ = ("_lock", "_edges", "counts", "sum", "count")

    def __init__(self, edges: Tuple[float, ...]) -> None:
        self._lock = threading.Lock()
        self._edges = edges
        self.counts = [0] * (len(edges) + 1)  # per-bucket, +Inf last
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        self.observe_n(v, 1)

    def observe_n(self, v: float, n: int) -> None:
        """n identical observations under ONE lock round-trip — the
        amortization convention (a fused R-step chunk or verify block
        records its per-token share tokens-served times) without n
        acquire/release cycles per dispatch."""
        if n <= 0:
            return
        # bucket semantics match Prometheus: le is INCLUSIVE (v == edge
        # lands in that bucket), everything past the last edge is +Inf
        i = bisect.bisect_left(self._edges, v)
        with self._lock:
            self.counts[i] += n
            self.sum += v * n
            self.count += n

    def percentile(self, q: float) -> float:
        """Estimated q-quantile (0..1) by linear interpolation inside the
        containing bucket; observations in +Inf report the last finite
        edge (the histogram cannot see past it)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError("q must be in [0, 1]")
        with self._lock:
            total = self.count
            counts = list(self.counts)
        if total == 0:
            return 0.0
        target = q * total
        cum = 0
        for i, c in enumerate(counts):
            prev_cum = cum
            cum += c
            if cum >= target and c > 0:
                if i >= len(self._edges):
                    return self._edges[-1]
                lo = self._edges[i - 1] if i > 0 else 0.0
                hi = self._edges[i]
                frac = (target - prev_cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        return self._edges[-1]

    def _reset(self) -> None:
        with self._lock:
            self.counts = [0] * (len(self._edges) + 1)
            self.sum = 0.0
            self.count = 0


_CHILD_CLS = {"counter": _CounterChild, "gauge": _GaugeChild,
              "histogram": _HistogramChild}


class MetricFamily:
    """One named metric: the unlabeled value itself, or a set of labeled
    children.  Convenience mutators (inc/set/observe/...) act on the
    default (label-less) child and raise on labeled families."""

    def __init__(
        self,
        name: str,
        kind: str,
        help_text: str,
        labelnames: Tuple[str, ...] = (),
        buckets: Optional[Tuple[float, ...]] = None,
        max_series: int = 64,
    ) -> None:
        if not METRIC_NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} must match {METRIC_NAME_RE.pattern}"
            )
        if not help_text.strip():
            raise ValueError(f"metric {name} needs a help string")
        if kind == "histogram":
            edges = tuple(float(b) for b in (buckets or DEFAULT_MS_BUCKETS))
            if list(edges) != sorted(edges) or len(set(edges)) != len(edges):
                raise ValueError("histogram buckets must be strictly increasing")
            if any(math.isinf(b) for b in edges):
                raise ValueError("+Inf bucket is implicit; pass finite edges")
            self.buckets = edges
        else:
            if buckets is not None:
                raise ValueError(f"{kind} takes no buckets")
            self.buckets = None
        self.name = name
        self.kind = kind
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self.max_series = max_series
        self._lock = threading.Lock()
        self._children: "OrderedDict[Tuple[str, ...], object]" = OrderedDict()
        if not self.labelnames:
            self._children[()] = self._new_child()

    def _new_child(self):
        cls = _CHILD_CLS[self.kind]
        return cls(self.buckets) if self.kind == "histogram" else cls()

    def labels(self, **kv: str):
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, got {tuple(kv)}"
            )
        key = tuple(str(kv[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if len(self._children) >= self.max_series:
                    # cardinality cap: collapse new combos into one shared
                    # overflow series rather than growing without bound
                    key = (OVERFLOW_LABEL,) * len(self.labelnames)
                    child = self._children.get(key)
                    if child is None:
                        child = self._new_child()
                        self._children[key] = child
                else:
                    child = self._new_child()
                    self._children[key] = child
        return child

    def _default(self):
        if self.labelnames:
            raise ValueError(f"{self.name} is labeled; use .labels(...)")
        return self._children[()]

    # -- unlabeled conveniences ----------------------------------------
    def inc(self, v: float = 1.0) -> None:
        self._default().inc(v)

    def set(self, v: float) -> None:
        self._default().set(v)

    def dec(self, v: float = 1.0) -> None:
        self._default().dec(v)

    def observe(self, v: float) -> None:
        self._default().observe(v)

    def observe_n(self, v: float, n: int) -> None:
        self._default().observe_n(v, n)

    def percentile(self, q: float) -> float:
        return self._default().percentile(q)

    @property
    def value(self) -> float:
        return self._default().value

    @property
    def count(self) -> int:
        return self._default().count

    @property
    def sum(self) -> float:
        return self._default().sum

    def series_count(self) -> int:
        with self._lock:
            return len(self._children)

    def reset(self) -> None:
        with self._lock:
            for child in self._children.values():
                child._reset()

    # -- exposition -----------------------------------------------------
    def expose_lines(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} {self.kind}"
        with self._lock:
            items = sorted(self._children.items())
        for key, child in items:
            if self.kind == "histogram":
                # snapshot under the child's lock: a scrape racing an
                # observe() must not emit a _count that disagrees with the
                # +Inf cumulative bucket (Prometheus invariant)
                with child._lock:
                    counts = list(child.counts)
                    h_sum = child.sum
                    h_count = child.count
                cum = 0
                for edge, c in zip(self.buckets, counts):
                    cum += c
                    ls = _label_str(self.labelnames, key, (("le", _fmt(edge)),))
                    yield f"{self.name}_bucket{ls} {cum}"
                cum += counts[-1]
                ls = _label_str(self.labelnames, key, (("le", "+Inf"),))
                yield f"{self.name}_bucket{ls} {cum}"
                ls = _label_str(self.labelnames, key)
                yield f"{self.name}_sum{ls} {_fmt(h_sum)}"
                yield f"{self.name}_count{ls} {h_count}"
            else:
                with child._lock:
                    value = child.value
                ls = _label_str(self.labelnames, key)
                yield f"{self.name}{ls} {_fmt(value)}"


class MetricsRegistry:
    """Name -> family map with idempotent registration and one exposition."""

    MAX_SERIES_PER_METRIC = 64

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: "OrderedDict[str, MetricFamily]" = OrderedDict()

    def _register(
        self,
        name: str,
        kind: str,
        help_text: str,
        labelnames: Tuple[str, ...],
        buckets: Optional[Tuple[float, ...]],
    ) -> MetricFamily:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if existing.kind != kind or existing.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name} already registered as {existing.kind}"
                        f"{existing.labelnames}; cannot re-register as "
                        f"{kind}{tuple(labelnames)}"
                    )
                return existing
            fam = MetricFamily(
                name, kind, help_text, tuple(labelnames), buckets,
                max_series=self.MAX_SERIES_PER_METRIC,
            )
            self._metrics[name] = fam
            return fam

    def counter(self, name: str, help_text: str,
                labelnames: Tuple[str, ...] = ()) -> MetricFamily:
        return self._register(name, "counter", help_text, labelnames, None)

    def gauge(self, name: str, help_text: str,
              labelnames: Tuple[str, ...] = ()) -> MetricFamily:
        return self._register(name, "gauge", help_text, labelnames, None)

    def histogram(
        self,
        name: str,
        help_text: str,
        labelnames: Tuple[str, ...] = (),
        buckets: Optional[Tuple[float, ...]] = None,
    ) -> MetricFamily:
        return self._register(name, "histogram", help_text, labelnames, buckets)

    def get(self, name: str) -> Optional[MetricFamily]:
        with self._lock:
            return self._metrics.get(name)

    def families(self) -> Dict[str, MetricFamily]:
        with self._lock:
            return dict(self._metrics)

    def expose(self) -> str:
        """Prometheus text format v0.0.4, families in registration order."""
        with self._lock:
            fams = list(self._metrics.values())
        lines: list[str] = []
        for fam in fams:
            lines.extend(fam.expose_lines())
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Zero every value IN PLACE (families and children survive, so
        module-level handles taken at import stay valid)."""
        with self._lock:
            fams = list(self._metrics.values())
        for fam in fams:
            fam.reset()

    # ---- dsan (dnet_tpu/analysis/runtime/) -----------------------------
    # The registry is a process-global built at import — before any test
    # can flip DNET_SAN — so its ownership guards are applied IN PLACE by
    # the sanitized fixtures rather than at construction.  Contract as
    # declared in analysis/runtime/domains.py: every _metrics touch under
    # _lock.
    def instrument_dsan(self) -> bool:
        """Swap in the dsan lock + guarded family map; False (no-op) when
        dsan is off or already instrumented."""
        from dnet_tpu.analysis.runtime import ownership as dsan

        if isinstance(self._lock, dsan.SanLock):
            return False
        lock = dsan.san_lock("MetricsRegistry._lock", self._lock)
        if lock is self._lock:  # dsan off: factory returned it unchanged
            return False
        self._lock = lock
        self._metrics = dsan.guard_ordered_dict(
            self._metrics,
            dsan.maybe_lock_domain(lock),
            "MetricsRegistry._metrics",
        )
        return True

    def deinstrument_dsan(self) -> None:
        """Restore the plain lock/map (fixture teardown): instrumentation
        must never outlive the sanitized window."""
        from dnet_tpu.analysis.runtime import ownership as dsan

        if not isinstance(self._lock, dsan.SanLock):
            return
        with dsan.allowed("MetricsRegistry._metrics"):
            self._metrics = OrderedDict(self._metrics.items())
        self._lock = self._lock.inner


CONTENT_TYPE_LATEST = "text/plain; version=0.0.4; charset=utf-8"
