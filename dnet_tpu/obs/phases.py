"""Declared label sets for the performance-attribution metric families.

A LEAF module (like admission/reasons.py and membership/epoch.py): imported
by `dnet_tpu.obs` for pre-touching and by the metrics lint (pass 8), which
cross-checks the exposed label sets against these tuples BOTH directions —
a new phase or instrumented jit entry point cannot ship without its series,
and a renamed one cannot strand a stale label on dashboards.
"""

from __future__ import annotations

# Sub-phases of one batched decode dispatch (core/batch.py decode_batch):
#   kv_gather  — page-table gather building the contiguous per-slot KV view
#                (paged only; the copy the ragged-attention kernel removes)
#   compute    — the jitted forward + on-device sampling program
#   kv_scatter — block write-back of the rows the step touched (paged only)
#   sample     — device->host readback of the sampled token fields
PHASE_KV_GATHER = "kv_gather"
PHASE_COMPUTE = "compute"
PHASE_KV_SCATTER = "kv_scatter"
PHASE_SAMPLE = "sample"
STEP_PHASES = (PHASE_KV_GATHER, PHASE_COMPUTE, PHASE_KV_SCATTER, PHASE_SAMPLE)

# Instrumented jitted entry points (obs/jit.py instrument_jit): the `fn`
# label of dnet_jit_compiles_total.  Every instrument_jit call site must use
# one of these names — the lint fails a stray label either direction.
JIT_FNS = (
    "local_prefill",        # LocalEngine._forward (bucketed prefill)
    "local_decode",         # LocalEngine._decode (fused decode+sample)
    "local_decode_chunk",   # LocalEngine._decode_chunk (R-step scan)
    "batched_step",         # BatchedEngine._step (vmapped decode+sample)
    "batched_chunk",        # BatchedEngine fused R-step chunk programs
    "batched_spec",         # BatchedEngine._spec_step (verify blocks)
    "kv_gather",            # BlockStore page-table gather
    "kv_scatter",           # BlockStore block write-back
    "paged_attend",         # BatchedEngine ragged decode programs (step +
                            # fused chunks) attending the pool in place
    "kv_append",            # BlockStore per-step block-append of new K/V rows
    "wire_encode",          # wire-pipeline hop encode launches (lossless
                            # cast / sparse / qsparse8 — compression/ops.py)
    "tp_window",            # TpEngine shard_map window/step programs over
                            # the ("batch", "model") mesh (parallel/tp.py)
    "tp_collective",        # standalone collective calibration probes
                            # (parallel/tp_collectives.py probe_collective_ms)
)

# dnet_request_segment_ms{segment=}: the exhaustive, non-overlapping
# critical-path segment ledger one request's recorded spans decompose into
# (obs/critical_path.py).  Every wall-clock millisecond between admission
# and the closing request span is attributed to EXACTLY one segment, so the
# per-request sums reconcile against measured E2E and the histogram's
# per-segment totals explain a serving window's p99 without hand-joining
# span families.  The metrics lint (pass DL028) cross-checks these against
# the exposed label set both ways.
#   admission_wait  — queued at the admission gate before a slot opened
#   sched_queue     — admitted but waiting on a scheduler/lane grant
#   prefill_compute — prompt prefill (local engine or replayed prefix)
#   decode_compute  — driver decode-step residual not claimed by a more
#                     specific segment below
#   wire_encode     — activation codec encode on the wire path
#   wire_tx         — writing frames to outbound streams
#   hop_rtt         — in-flight between nodes (send..ingress gap)
#   shard_compute   — shard-side layer compute
#   sample          — on-device sampling + token readback
#   sse_flush       — serializing/flushing SSE chunks to the client
#   other           — recorded wall clock no span claims (gaps)
SEG_ADMISSION_WAIT = "admission_wait"
SEG_SCHED_QUEUE = "sched_queue"
SEG_PREFILL_COMPUTE = "prefill_compute"
SEG_DECODE_COMPUTE = "decode_compute"
SEG_WIRE_ENCODE = "wire_encode"
SEG_WIRE_TX = "wire_tx"
SEG_HOP_RTT = "hop_rtt"
SEG_SHARD_COMPUTE = "shard_compute"
SEG_SAMPLE = "sample"
SEG_SSE_FLUSH = "sse_flush"
SEG_OTHER = "other"
REQUEST_SEGMENTS = (
    SEG_ADMISSION_WAIT,
    SEG_SCHED_QUEUE,
    SEG_PREFILL_COMPUTE,
    SEG_DECODE_COMPUTE,
    SEG_WIRE_ENCODE,
    SEG_WIRE_TX,
    SEG_HOP_RTT,
    SEG_SHARD_COMPUTE,
    SEG_SAMPLE,
    SEG_SSE_FLUSH,
    SEG_OTHER,
)

# dnet_wire_bytes_total{dir=}: activation/token payload bytes by wire
# direction (tx = written to outbound streams, rx = admitted at ingress).
# The metrics lint (pass 12) cross-checks these against the exposed label
# set both ways, the established leaf-enum pattern.
WIRE_DIRS = ("tx", "rx")

# dnet_device_mem_bytes{kind=}: backend memory stats summed over local
# devices, where the PJRT backend reports them (TPU/GPU; CPU returns none)
DEVICE_MEM_KINDS = ("in_use", "peak", "limit")

# dnet_tp_collective_ms{op=} / dnet_tp_collective_bytes_total{op=}: the two
# intra-shard tensor-parallel collective shapes the TP seam dispatches
# (parallel/tp_collectives.py).  The metrics lint (pass 13) cross-checks
# these against the exposed label sets both ways.
TP_OPS = ("all_reduce", "all_gather")

# dnet_events_total{name=}: the canonical wide-event vocabulary
# (obs/events.py log_event).  Every structured event a node journals uses
# one of these names — the metrics lint (pass DL030) cross-checks the
# exposed label set against this tuple both ways, so an event cannot ship
# without its counter series and a renamed one cannot strand a stale label.
#   request_complete — EXACTLY one per finished request (any outcome):
#                      status, shed/finish reason, token counts, resolved
#                      codec/kv/tp modes, and the critical-path segment
#                      ledger embedded
#   admitted         — admission granted a slot (queue wait attached)
#   shed             — admission rejected the request (reason attached)
#   preempted        — scheduler evicted a running sequence to WAITING
#   resumed          — a mid-decode failure was transparently replayed
#   recovery_round   — one auto-recovery re-solve round ended (outcome)
#   epoch_fenced     — a stale-epoch message was fenced out (kind)
#   routed           — the fleet front door chose a replica for a request
#                      (replica + routing reason attached — fleet/router.py)
#   failover         — in-flight work moved from a dead replica to a
#                      survivor mid-stream (victim/survivor attached)
EVENT_REQUEST_COMPLETE = "request_complete"
EVENT_ADMITTED = "admitted"
EVENT_SHED = "shed"
EVENT_PREEMPTED = "preempted"
EVENT_RESUMED = "resumed"
EVENT_RECOVERY_ROUND = "recovery_round"
EVENT_EPOCH_FENCED = "epoch_fenced"
EVENT_ROUTED = "routed"
EVENT_FAILOVER = "failover"
EVENT_NAMES = (
    EVENT_REQUEST_COMPLETE,
    EVENT_ADMITTED,
    EVENT_SHED,
    EVENT_PREEMPTED,
    EVENT_RESUMED,
    EVENT_RECOVERY_ROUND,
    EVENT_EPOCH_FENCED,
    EVENT_ROUTED,
    EVENT_FAILOVER,
)
