"""Declared label sets for the performance-attribution metric families.

A LEAF module (like admission/reasons.py and membership/epoch.py): imported
by `dnet_tpu.obs` for pre-touching and by the metrics lint (pass 8), which
cross-checks the exposed label sets against these tuples BOTH directions —
a new phase or instrumented jit entry point cannot ship without its series,
and a renamed one cannot strand a stale label on dashboards.
"""

from __future__ import annotations

# Sub-phases of one batched decode dispatch (core/batch.py decode_batch):
#   kv_gather  — page-table gather building the contiguous per-slot KV view
#                (paged only; the copy the ragged-attention kernel removes)
#   compute    — the jitted forward + on-device sampling program
#   kv_scatter — block write-back of the rows the step touched (paged only)
#   sample     — device->host readback of the sampled token fields
PHASE_KV_GATHER = "kv_gather"
PHASE_COMPUTE = "compute"
PHASE_KV_SCATTER = "kv_scatter"
PHASE_SAMPLE = "sample"
STEP_PHASES = (PHASE_KV_GATHER, PHASE_COMPUTE, PHASE_KV_SCATTER, PHASE_SAMPLE)

# Instrumented jitted entry points (obs/jit.py instrument_jit): the `fn`
# label of dnet_jit_compiles_total.  Every instrument_jit call site must use
# one of these names — the lint fails a stray label either direction.
JIT_FNS = (
    "local_prefill",        # LocalEngine._forward (bucketed prefill)
    "local_decode",         # LocalEngine._decode (fused decode+sample)
    "local_decode_chunk",   # LocalEngine._decode_chunk (R-step scan)
    "batched_step",         # BatchedEngine._step (vmapped decode+sample)
    "batched_chunk",        # BatchedEngine fused R-step chunk programs
    "batched_spec",         # BatchedEngine._spec_step (verify blocks)
    "kv_gather",            # BlockStore page-table gather
    "kv_scatter",           # BlockStore block write-back
    "paged_attend",         # BatchedEngine ragged decode programs (step +
                            # fused chunks) attending the pool in place
    "kv_append",            # BlockStore per-step block-append of new K/V rows
    "wire_encode",          # wire-pipeline hop encode launches (lossless
                            # cast / sparse / qsparse8 — compression/ops.py)
    "tp_window",            # TpEngine shard_map window/step programs over
                            # the ("batch", "model") mesh (parallel/tp.py)
    "tp_collective",        # standalone collective calibration probes
                            # (parallel/tp_collectives.py probe_collective_ms)
)

# dnet_wire_bytes_total{dir=}: activation/token payload bytes by wire
# direction (tx = written to outbound streams, rx = admitted at ingress).
# The metrics lint (pass 12) cross-checks these against the exposed label
# set both ways, the established leaf-enum pattern.
WIRE_DIRS = ("tx", "rx")

# dnet_device_mem_bytes{kind=}: backend memory stats summed over local
# devices, where the PJRT backend reports them (TPU/GPU; CPU returns none)
DEVICE_MEM_KINDS = ("in_use", "peak", "limit")

# dnet_tp_collective_ms{op=} / dnet_tp_collective_bytes_total{op=}: the two
# intra-shard tensor-parallel collective shapes the TP seam dispatches
# (parallel/tp_collectives.py).  The metrics lint (pass 13) cross-checks
# these against the exposed label sets both ways.
TP_OPS = ("all_reduce", "all_gather")
