"""JIT-compile tracking and device-memory gauges.

`instrument_jit(fn, name)` wraps a `jax.jit`-ed callable so every
compilation is visible: warmup vs steady-state separates cleanly in load
reports, and a recompile storm (a shape leak re-tracing per request) shows
up as a climbing `dnet_jit_compiles_total{fn=}` instead of a mystery
latency cliff.  Detection rides the jitted function's executable cache: a
call that grew `_cache_size()` traced+compiled, and its wall time — trace +
compile + first execute — is recorded in `dnet_jit_compile_ms`.  On a jax
build without `_cache_size` the wrapper degrades to a transparent
pass-through (no counts, never an error).

`update_device_mem_gauges()` publishes `dnet_device_mem_bytes{kind=}` from
the backend's PJRT memory stats where available (TPU/GPU; CPU reports
none), summed over local devices.  Refreshed lazily at /metrics scrape
(obs/http.py), the same discipline as the SLO gauges.
"""

from __future__ import annotations

import time

from dnet_tpu.obs.phases import DEVICE_MEM_KINDS, JIT_FNS


class _InstrumentedJit:
    """Transparent wrapper: __call__ counts compiles, everything else
    (lower, _cache_size, ...) forwards to the wrapped jitted callable."""

    __slots__ = ("_fn", "_name", "_compiles", "_compile_ms")

    def __init__(self, fn, name: str) -> None:
        from dnet_tpu.obs import metric

        if name not in JIT_FNS:
            # same discipline as chaos points: an entry point cannot ship
            # without its declared, lint-checked label
            raise ValueError(
                f"jit fn name {name!r} is not declared in "
                f"dnet_tpu.obs.phases.JIT_FNS"
            )
        self._fn = fn
        self._name = name
        self._compiles = metric("dnet_jit_compiles_total").labels(fn=name)
        self._compile_ms = metric("dnet_jit_compile_ms")

    def __call__(self, *args, **kwargs):
        fn = self._fn
        try:
            before = fn._cache_size()
        except Exception:
            before = None
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        if before is not None:
            try:
                compiled = fn._cache_size() > before
            except Exception:
                compiled = False
            if compiled:
                self._compiles.inc()
                self._compile_ms.observe((time.perf_counter() - t0) * 1000.0)
        return out

    def __getattr__(self, item):
        return getattr(self._fn, item)


def instrument_jit(fn, name: str):
    """Wrap a jitted callable; `name` must be declared in phases.JIT_FNS."""
    return _InstrumentedJit(fn, name)


def _backend_initialized() -> bool:
    """True only if a jax backend ALREADY exists in this process.  A
    /metrics scrape must never be the thing that creates it —
    jax.local_devices() on a cold process stalls the scrape for the whole
    XLA client bring-up and, on accelerator hosts, acquires the devices /
    preallocates memory before the serving path's own deliberate init."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:
        # private-surface drift on a future jax: fall back to refreshing
        # (the pre-0.5 behavior) rather than silently freezing the gauges
        return True


def update_device_mem_gauges() -> bool:
    """Refresh dnet_device_mem_bytes{kind=} from jax.local_devices()'
    memory_stats(), summed across devices.  Returns False (gauges left
    untouched at their pre-touched zeros) when the backend is not up yet
    or no backend reports stats — the CPU fallback — so absence is
    visible as all-zero, never stale."""
    from dnet_tpu.obs import metric

    if not _backend_initialized():
        return False
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return False
    totals = dict.fromkeys(DEVICE_MEM_KINDS, 0.0)
    seen = False
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        seen = True
        totals["in_use"] += float(stats.get("bytes_in_use", 0) or 0)
        totals["peak"] += float(stats.get("peak_bytes_in_use", 0) or 0)
        totals["limit"] += float(stats.get("bytes_limit", 0) or 0)
    if not seen:
        return False
    fam = metric("dnet_device_mem_bytes")
    for kind, v in totals.items():
        fam.labels(kind=kind).set(v)
    return True
