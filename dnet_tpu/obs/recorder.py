"""Per-request flight recorder: a bounded ring buffer of trace spans.

Every serving request (nonce == the OpenAI response id) accumulates spans —
ttft, per-decode-step, per-layer compute, transport send/recv, lane queue
wait, prefix-cache hits — as it flows through the API driver, the engine,
and the transport.  `GET /v1/debug/timeline/{rid}` (api/http.py) dumps one
request's timeline as JSON, replacing the string-grep-a-log-file workflow
the `[PROFILE]` lines forced.

Bounded both ways: at most `max_requests` request timelines (oldest evicted
first — a ring buffer over requests) and at most `max_spans` spans per
request (later spans are counted in `dropped`, never stored), so the
recorder's memory is O(1) regardless of traffic.  The defaults (64 x 2048)
cap worst-case retention at ~131k span dicts (~tens of MB); long
generations that out-span the cap keep their earliest spans and report the
tail in `dropped`.

Sampling under load (`DNET_OBS_TRACE_SAMPLE=N`, ObsSettings.trace_sample):
every Nth opened timeline is `sampled` and records its full span stream;
the rest keep ONLY forced summary spans (ttft, the closing request span)
and count everything else in `dropped` — so a load run's request flood
cannot thrash the 64-timeline ring into uselessness while still giving
RequestMetrics its per-request summary for every response.
"""

from __future__ import annotations

import contextlib
import re
import threading
import time
from collections import OrderedDict
from typing import Iterator, List, Optional

# Resume replays run under a fresh wire nonce `rid#rN`
# (resilience/checkpoint.py RequestCheckpoint.next_nonce) so shard-side
# dedup and stream identity stay per-segment — but the STORY is one
# request.  The recorder aliases every segment nonce back to the base rid
# at write time, so `/v1/debug/timeline/{rid}` (and the trace export)
# shows admission -> failure -> resume -> finish as one timeline instead
# of fragments keyed by nonces no client ever saw.
_RESUME_NONCE_RE = re.compile(r"#r\d+$")


def base_rid(rid: str) -> str:
    """Strip a resume-segment suffix (`rid#rN` -> `rid`)."""
    return _RESUME_NONCE_RE.sub("", rid)


class FlightRecorder:
    def __init__(
        self,
        max_requests: int = 64,
        max_spans: int = 2048,
        sample_every: Optional[int] = None,
    ) -> None:
        if max_requests < 1 or max_spans < 1:
            raise ValueError("recorder bounds must be >= 1")
        self.max_requests = max_requests
        self.max_spans = max_spans
        # None = read ObsSettings.trace_sample lazily per opened timeline
        # (the process-global recorder is built before settings are)
        self.sample_every = sample_every
        self._opened = 0  # timelines ever opened (sampling phase counter)
        self._lock = threading.Lock()
        # rid -> {"t_unix", "t0" (perf_counter origin), "spans", "dropped",
        #         "sampled"}
        self._requests: "OrderedDict[str, dict]" = OrderedDict()

    def _sample_n(self) -> int:
        n = self.sample_every
        if n is None:
            try:
                from dnet_tpu.config import get_settings

                n = get_settings().obs.trace_sample
            except Exception:
                n = 1
        return max(int(n), 1)

    def begin(self, rid: str) -> None:
        """Open (or re-open at the back of the ring) a request timeline."""
        with self._lock:
            self._begin_locked(base_rid(rid))

    def _begin_locked(self, rid: str) -> dict:
        entry = self._requests.get(rid)
        if entry is None:
            n = self._sample_n()
            entry = {
                "t_unix": time.time(),
                "t0": time.perf_counter(),
                "spans": [],
                "dropped": 0,
                # the 1st, N+1th, ... opened timeline records fully; the
                # rest keep only forced summary spans
                "sampled": self._opened % n == 0,
            }
            self._opened += 1
            self._requests[rid] = entry
            while len(self._requests) > self.max_requests:
                self._requests.popitem(last=False)
        else:
            self._requests.move_to_end(rid)
        return entry

    def span(
        self,
        rid: str,
        name: str,
        dur_ms: float,
        t_ms: Optional[float] = None,
        force: bool = False,
        **meta,
    ) -> None:
        """Record one completed span.  `t_ms` is the span's start offset
        from the request's first recorded activity; when omitted it is
        derived as now - dur (the common "time it, then record" shape).
        Unknown rids auto-open a timeline: shard- and transport-side spans
        arrive keyed by nonce with no driver to begin() for them.
        `force` bypasses the per-request span cap — for the few summary
        spans (ttft, the closing request span) that downstream consumers
        (RequestMetrics.from_timeline) must find even on generations long
        enough to out-span the cap.  Resume-segment nonces (`rid#rN`)
        alias to the base rid so a resumed request stays one timeline."""
        now = time.perf_counter()
        rid = base_rid(rid)
        with self._lock:
            entry = self._requests.get(rid)
            if entry is None:
                entry = self._begin_locked(rid)
                # backdate the origin: the request's first recorded
                # activity STARTED dur ago, so the first span lands at
                # t_ms=0, not -dur
                entry["t0"] = now - dur_ms / 1000.0
            else:
                # writing a span is activity: refresh the LRU position so
                # an in-flight long request outlives idle completed
                # timelines in the ring
                self._requests.move_to_end(rid)
            if not force and (
                not entry.get("sampled", True)
                or len(entry["spans"]) >= self.max_spans
            ):
                entry["dropped"] += 1
                return
            if t_ms is None:
                t_ms = max((now - entry["t0"]) * 1000.0 - dur_ms, 0.0)
            span = {"name": name, "t_ms": round(t_ms, 3),
                    "dur_ms": round(dur_ms, 3)}
            if meta:
                span["meta"] = meta
            entry["spans"].append(span)

    @contextlib.contextmanager
    def timed(self, rid: str, name: str, **meta) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.span(rid, name, (time.perf_counter() - t0) * 1000.0, **meta)

    def timeline(self, rid: str) -> Optional[dict]:
        """JSON-ready snapshot of one request's spans, or None."""
        rid = base_rid(rid)
        with self._lock:
            entry = self._requests.get(rid)
            if entry is None:
                return None
            return {
                "rid": rid,
                "t_unix": entry["t_unix"],
                "spans": [dict(s) for s in entry["spans"]],
                "dropped": entry["dropped"],
                "sampled": entry.get("sampled", True),
            }

    def request_ids(self) -> List[str]:
        with self._lock:
            return list(self._requests)

    def request_ids_since(self, t_unix: float) -> List[str]:
        """Rids whose timelines opened at or after `t_unix` (wall clock) —
        the serving-window selector behind `GET /v1/debug/trace?last_s=N`."""
        with self._lock:
            return [
                rid
                for rid, entry in self._requests.items()
                if entry["t_unix"] >= t_unix
            ]

    def clear(self) -> None:
        with self._lock:
            self._requests.clear()
            self._opened = 0  # sampling phase restarts with the ring
