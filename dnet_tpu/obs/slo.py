"""SLO tracking: rolling-window availability and latency percentiles.

The registry's histograms aggregate since process start, which is the wrong
shape for "are we good *right now*": a night of fast decode buries a slow
last five minutes.  This module keeps bounded ring buffers of recent
observations (TTFT ms, decode-step ms, request outcomes) over a sliding
window and compares windowed p95 / availability against operator targets
(`DNET_OBS_SLO_*`, config.ObsSettings).  Burn state surfaces two ways:
`/health` flips to `status: degraded` naming the burning SLO(s), and the
`dnet_slo_*` gauges export the same numbers for alerting.

Boundary semantics (tested in tests/test_obs_slo.py): an SLO with target 0
is DISABLED; an empty window never burns (no evidence is not bad
evidence); and a value exactly AT its target is meeting it — burning is
strictly `p95 > target` / `availability < target`.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, List, Optional, Tuple

SLO_TTFT = "ttft_p95_ms"
SLO_DECODE = "decode_p95_ms"
SLO_AVAILABILITY = "availability"
SLO_KINDS = (SLO_TTFT, SLO_DECODE, SLO_AVAILABILITY)


def nearest_rank(values: List[float], q: float) -> float:
    """THE nearest-rank quantile convention (0..1; empty -> 0.0), shared
    by the live SLO windows and the loadgen report so "report percentile"
    and "live gauge" are the same statistic over two vantage points."""
    if not 0.0 <= q <= 1.0:
        raise ValueError("q must be in [0, 1]")
    vals = sorted(values)
    if not vals:
        return 0.0
    rank = max(math.ceil(q * len(vals)), 1)
    return vals[rank - 1]


class RollingWindow:
    """Bounded (time, value) ring over the trailing `window_s` seconds.

    `max_events` caps memory under burst traffic; past it the oldest
    observation falls off early — the window then under-counts history, not
    the present, which is the right failure mode for an SLO."""

    def __init__(self, window_s: float = 300.0, max_events: int = 4096) -> None:
        if window_s <= 0 or max_events < 1:
            raise ValueError("window_s must be > 0 and max_events >= 1")
        self.window_s = window_s
        self._events: Deque[Tuple[float, float]] = deque(maxlen=max_events)
        self._lock = threading.Lock()

    def observe(self, value: float, now: Optional[float] = None) -> None:
        t = time.monotonic() if now is None else now
        with self._lock:
            self._events.append((t, float(value)))

    def _values(self, now: Optional[float]) -> List[float]:
        t = time.monotonic() if now is None else now
        horizon = t - self.window_s
        with self._lock:
            while self._events and self._events[0][0] < horizon:
                self._events.popleft()
            return [v for _, v in self._events]

    def count(self, now: Optional[float] = None) -> int:
        return len(self._values(now))

    def percentile(self, q: float, now: Optional[float] = None) -> float:
        """Nearest-rank q-quantile (0..1) of the live window; 0.0 when
        empty (callers treat an empty window as "no evidence")."""
        return nearest_rank(self._values(now), q)

    def mean(self, now: Optional[float] = None) -> float:
        vals = self._values(now)
        return sum(vals) / len(vals) if vals else 0.0


@dataclass(frozen=True)
class SloStatus:
    name: str
    value: float
    target: float  # 0 = disabled
    samples: int
    burning: bool

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "value": round(self.value, 3),
            "target": self.target,
            "samples": self.samples,
            "burning": self.burning,
        }


class SloTracker:
    """Windows + targets + the `dnet_slo_*` gauge exports."""

    def __init__(
        self,
        window_s: float = 300.0,
        ttft_p95_ms: float = 0.0,
        decode_p95_ms: float = 0.0,
        availability: float = 0.0,
        max_events: int = 4096,
    ) -> None:
        from dnet_tpu.obs import metric

        if window_s <= 0:
            # same "0 disables" convention as the sibling DNET_OBS_SLO_*
            # target knobs: keep a tiny live window but zero every target,
            # so a disabled-window config can never crash the serving path
            window_s, ttft_p95_ms, decode_p95_ms, availability = 1.0, 0, 0, 0
        self.window_s = window_s
        self.targets = {
            SLO_TTFT: float(ttft_p95_ms),
            SLO_DECODE: float(decode_p95_ms),
            SLO_AVAILABILITY: float(availability),
        }
        self._ttft = RollingWindow(window_s, max_events)
        self._decode = RollingWindow(window_s, max_events)
        self._outcomes = RollingWindow(window_s, max_events)  # 1 ok / 0 err
        self._g_ttft = metric("dnet_slo_ttft_p95_ms")
        self._g_decode = metric("dnet_slo_decode_p95_ms")
        self._g_avail = metric("dnet_slo_availability")
        self._g_burning = metric("dnet_slo_burning")
        # p99 twins (informational): loadgen cross-checks its client-side
        # tail percentiles against these; attainment stays p95-based
        self._g_ttft_p99 = metric("dnet_slo_ttft_p99_ms")
        self._g_decode_p99 = metric("dnet_slo_decode_p99_ms")

    # -- recording (hot path: one deque append under a lock) -------------
    def record_ttft(self, ms: float, now: Optional[float] = None) -> None:
        self._ttft.observe(ms, now)

    def record_decode(self, ms: float, now: Optional[float] = None) -> None:
        self._decode.observe(ms, now)

    def record_request(self, ok: bool, now: Optional[float] = None) -> None:
        self._outcomes.observe(1.0 if ok else 0.0, now)

    # -- evaluation -------------------------------------------------------
    def statuses(self, now: Optional[float] = None) -> List[SloStatus]:
        # ONE time snapshot for every window read below: count() and
        # mean()/percentile() each prune at their own horizon, so separate
        # clock reads could let the window's last events expire between
        # the two calls — reporting value 0.0 with samples > 0 and
        # spuriously flipping /health to degraded
        now = time.monotonic() if now is None else now
        out = []
        for name, window, higher_is_bad in (
            (SLO_TTFT, self._ttft, True),
            (SLO_DECODE, self._decode, True),
        ):
            target = self.targets[name]
            samples = window.count(now)
            value = window.percentile(0.95, now)
            burning = bool(target > 0 and samples > 0 and value > target)
            out.append(SloStatus(name, value, target, samples, burning))
        target = self.targets[SLO_AVAILABILITY]
        samples = self._outcomes.count(now)
        value = self._outcomes.mean(now) if samples else 1.0
        burning = bool(target > 0 and samples > 0 and value < target)
        out.append(SloStatus(SLO_AVAILABILITY, value, target, samples, burning))
        return out

    def snapshot(self, now: Optional[float] = None) -> dict:
        """Evaluate every SLO, refresh the gauges, and return the /health
        payload: windowed values, targets, and which SLOs are burning."""
        # one clock read shared by the p95 statuses and the p99 reads, for
        # the same expiry-race reason statuses() documents
        now = time.monotonic() if now is None else now
        statuses = self.statuses(now)
        by_name = {s.name: s for s in statuses}
        self._g_ttft.set(by_name[SLO_TTFT].value)
        self._g_decode.set(by_name[SLO_DECODE].value)
        self._g_avail.set(by_name[SLO_AVAILABILITY].value)
        ttft_p99 = self._ttft.percentile(0.99, now)
        decode_p99 = self._decode.percentile(0.99, now)
        self._g_ttft_p99.set(ttft_p99)
        self._g_decode_p99.set(decode_p99)
        for s in statuses:
            self._g_burning.labels(slo=s.name).set(1.0 if s.burning else 0.0)
        return {
            "window_s": self.window_s,
            "slos": [s.as_dict() for s in statuses],
            "burning": [s.name for s in statuses if s.burning],
            "p99": {
                "ttft_ms": round(ttft_p99, 3),
                "decode_ms": round(decode_p99, 3),
            },
        }

    def burning(self, now: Optional[float] = None) -> List[str]:
        return [s.name for s in self.statuses(now) if s.burning]
