"""Metrics federation: merge per-node Prometheus expositions into one.

Each process in a dnet cluster (the API node plus every shard) exposes its
own registry at `GET /metrics`; this module re-labels each node's samples
with `node="<id>"` and merges them into a single v0.0.4 exposition served
at `GET /v1/cluster/metrics` (api/http.py) — one scrape target for the
whole ring, so a dashboard can group `dnet_token_rpc_ms` by hop without
per-shard scrape configs.

The parser is deliberately minimal: it understands exactly what
`MetricsRegistry.expose()` emits (``# HELP`` / ``# TYPE`` comments and
``name{labels} value`` samples) and passes sample lines through verbatim
apart from the injected label, so federation cannot corrupt values it does
not understand — an unparseable line is dropped with a count rather than
re-emitted mangled.
"""

from __future__ import annotations

import re
from collections import OrderedDict
from typing import List, Sequence, Tuple

# sample line: metric name, optional {labels}, value (timestamps are not
# emitted by our registry and not preserved)
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{.*\})?"
    r"\s+(?P<value>\S+)\s*$"
)
_HELP_RE = re.compile(r"^# HELP (?P<name>\S+) (?P<help>.*)$")
_TYPE_RE = re.compile(r"^# TYPE (?P<name>\S+) (?P<kind>\S+)$")

NODE_LABEL = "node"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def add_node_label(sample_line: str, node: str) -> str:
    """Inject ``node="<id>"`` as the first label of one sample line."""
    m = _SAMPLE_RE.match(sample_line)
    if m is None:
        raise ValueError(f"unparseable sample line: {sample_line!r}")
    labels = m.group("labels")
    inner = labels[1:-1] if labels else ""
    pair = f'{NODE_LABEL}="{_escape(node)}"'
    inner = f"{pair},{inner}" if inner else pair
    return f'{m.group("name")}{{{inner}}} {m.group("value")}'


def _family_of(sample_name: str) -> str:
    """Histogram samples (`_bucket`/`_sum`/`_count`) group under the base
    family name so HELP/TYPE emit once per family, not per sample kind."""
    for suffix in ("_bucket", "_sum", "_count"):
        if sample_name.endswith(suffix):
            return sample_name[: -len(suffix)]
    return sample_name


def federate(sections: Sequence[Tuple[str, str]]) -> Tuple[str, List[str]]:
    """Merge `(node, exposition_text)` pairs into one exposition.

    Returns `(merged_text, skipped)` where `skipped` lists lines that did
    not parse (logged by the caller, never re-emitted).  Families keep
    first-seen order; HELP/TYPE come from the first node exposing them, and
    every sample gains the node label.
    """
    fams: "OrderedDict[str, dict]" = OrderedDict()
    skipped: List[str] = []
    for node, text in sections:
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            hm = _HELP_RE.match(line)
            if hm is not None:
                fam = fams.setdefault(
                    hm.group("name"), {"help": None, "type": None, "samples": []}
                )
                if fam["help"] is None:
                    fam["help"] = hm.group("help")
                continue
            tm = _TYPE_RE.match(line)
            if tm is not None:
                fam = fams.setdefault(
                    tm.group("name"), {"help": None, "type": None, "samples": []}
                )
                if fam["type"] is None:
                    fam["type"] = tm.group("kind")
                continue
            if line.startswith("#"):
                continue  # other comments carry no samples
            sm = _SAMPLE_RE.match(line)
            if sm is None:
                skipped.append(f"{node}: {line}")
                continue
            fam = fams.setdefault(
                _family_of(sm.group("name")),
                {"help": None, "type": None, "samples": []},
            )
            fam["samples"].append(add_node_label(line, node))
    lines: List[str] = []
    for name, fam in fams.items():
        if fam["help"] is not None:
            lines.append(f"# HELP {name} {fam['help']}")
        if fam["type"] is not None:
            lines.append(f"# TYPE {name} {fam['type']}")
        lines.extend(fam["samples"])
    return "\n".join(lines) + "\n", skipped
