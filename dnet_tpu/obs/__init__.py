"""Unified observability: metrics registry + per-request flight recorder.

One process-global `MetricsRegistry` (Prometheus text exposition at
`GET /metrics` on both the API and shard HTTP servers) and one
`FlightRecorder` (span timelines at `GET /v1/debug/timeline/{rid}`).
Instrumented modules fetch family handles by name via `metric()`; the
canonical family set below is registered on first access so `/metrics`
exposes every series — zero-valued — from process start, and so a typo'd
name fails loudly at import instead of silently creating a parallel series.

`obs_enabled()` is the ONE truth for profile gating: the `[PROFILE]` log
filter (utils/logger.py) and any sampling decisions both consult it, so the
legacy `DNET_PROFILE` env and `DNET_OBS_ENABLED` (config.ObsSettings) can
never disagree.  The registry and recorder themselves are always on —
counters are near-free and the recorder is bounded — gating covers only the
log-line firehose and the device-sync fences.
"""

from __future__ import annotations

import threading

from dnet_tpu.obs.metrics import (
    CONTENT_TYPE_LATEST,
    DEFAULT_MS_BUCKETS,
    METRIC_NAME_RE,
    MetricFamily,
    MetricsRegistry,
)
from dnet_tpu.obs.recorder import FlightRecorder

__all__ = [
    "CONTENT_TYPE_LATEST",
    "DEFAULT_MS_BUCKETS",
    "METRIC_NAME_RE",
    "FlightRecorder",
    "MetricFamily",
    "MetricsRegistry",
    "get_recorder",
    "get_registry",
    "get_slo_tracker",
    "metric",
    "obs_enabled",
    "reset_obs",
]

_registry = MetricsRegistry()
_recorder = FlightRecorder()
_core_once = threading.Lock()
_core_done = False

# lane-depth / small-count histograms use power-of-two buckets, not ms
COUNT_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)

_CACHE_KINDS = ("prefix", "snapshot")


def _register_core(reg: MetricsRegistry) -> None:
    """The canonical family set, pre-registered (and labeled children
    pre-touched) so exposition carries them at zero before first use."""
    reg.histogram(
        "dnet_decode_step_ms",
        "Per-token decode step wall time on the serving path (ms)",
    )
    reg.histogram(
        "dnet_prefill_ms", "Prompt prefill wall time per request (ms)"
    )
    reg.histogram(
        "dnet_ttft_ms", "Time to first token per request (ms)"
    )
    reg.histogram(
        "dnet_layer_compute_ms",
        "Per-layer compute wall time under DNET_OBS_SYNC_PER_LAYER (ms)",
    )
    reg.histogram(
        "dnet_token_rpc_ms",
        "Shard-to-API token callback RPC latency (ms)",
    )
    reg.histogram(
        "dnet_ring_hop_rtt_ms",
        "API-observed token frame send-to-resolve round trip (ms)",
    )
    reg.histogram(
        "dnet_lane_queue_wait_ms",
        "Decode-step wait in the lane coalescing queue (ms)",
    )
    reg.histogram(
        "dnet_lane_flush_depth",
        "Members per flushed multi-lane ring frame",
        buckets=COUNT_BUCKETS,
    )
    reg.counter(
        "dnet_transport_tx_bytes_total",
        "Activation/token frame payload bytes written to outbound streams",
    )
    reg.counter(
        "dnet_transport_rx_bytes_total",
        "Activation/token frame payload bytes admitted at ingress",
    )
    reg.counter(
        "dnet_transport_tx_frames_total",
        "Frames written to outbound streams",
    )
    reg.counter(
        "dnet_transport_backpressure_total",
        "Backpressure ACKs that paused an outbound stream",
    )
    for name, help_text in (
        ("dnet_kv_cache_hits_total", "KV snapshot cache hits"),
        ("dnet_kv_cache_misses_total", "KV snapshot cache misses"),
        ("dnet_kv_cache_evictions_total", "KV snapshot cache LRU evictions"),
        ("dnet_kv_cache_stores_total", "KV snapshots stored"),
    ):
        fam = reg.counter(name, help_text, labelnames=("cache",))
        for kind in _CACHE_KINDS:
            fam.labels(cache=kind)  # pre-touch: expose at 0 from the start
    reg.counter(
        "dnet_kv_sessions_evicted_total",
        "Per-nonce KV sessions dropped by the TTL sweep",
    )
    # paged KV pool (dnet_tpu/kv/paged.py): used + free == pool size at all
    # times (shared blocks count once in used; BlockPool.check_conservation)
    reg.gauge(
        "dnet_kv_blocks_used",
        "Paged KV pool blocks currently allocated (refcount >= 1)",
    )
    reg.gauge(
        "dnet_kv_blocks_free",
        "Paged KV pool blocks on the free list",
    )
    reg.gauge(
        "dnet_kv_pool_blocks",
        "Paged KV pool total capacity in blocks",
    )
    reg.counter(
        "dnet_kv_cow_copies_total",
        "Paged KV copy-on-write block copies (shared block diverged)",
    )
    reg.counter(
        "dnet_kv_prefix_shared_blocks_total",
        "Paged KV blocks shared by refcount aliasing instead of copying",
    )
    reg.counter(
        "dnet_kv_admission_rejected_total",
        "Paged KV admissions/extensions refused for lack of free blocks",
    )
    reg.counter("dnet_requests_total", "Decode requests started")
    reg.counter(
        "dnet_request_errors_total", "Decode requests failed with an error"
    )
    reg.counter(
        "dnet_tokens_generated_total", "Tokens emitted across all requests"
    )
    reg.counter(
        "dnet_prefix_refill_total",
        "Ring prefix-cache misses transparently re-sent as full prefills",
    )
    # resilience (dnet_tpu/resilience/): retries, stream re-open, resume,
    # and the chaos harness that exercises all of them
    retries = reg.counter(
        "dnet_rpc_retries_total",
        "RPC attempts retried under the resilience backoff policy",
        labelnames=("method",),
    )
    for m in ("send_activation", "send_token", "reset_cache",
              "measure_latency", "load_model"):
        retries.labels(method=m)  # pre-touch: expose at 0 from the start
    reg.counter(
        "dnet_stream_reopens_total",
        "Broken activation streams re-opened with the in-flight frame "
        "re-sent",
    )
    reg.counter(
        "dnet_request_resumed_total",
        "Requests transparently resumed after a mid-decode failure",
    )
    reg.counter(
        "dnet_resume_replay_tokens_total",
        "Tokens (prompt + generated) replayed by request-resume prefills",
    )
    # admission / overload survival (dnet_tpu/admission/): bounded queue,
    # load shedding, end-to-end deadlines, drain.  Reason/stage label sets
    # are DECLARED in admission/reasons.py and cross-checked both ways by
    # the metrics lint (pass 6).
    reg.gauge(
        "dnet_admit_queue_depth",
        "Requests currently waiting in the bounded admission queue",
    )
    reg.gauge(
        "dnet_admit_inflight",
        "Requests currently holding an admission slot (executing)",
    )
    reg.counter(
        "dnet_admit_admitted_total",
        "Requests granted an admission slot",
    )
    reg.histogram(
        "dnet_admit_wait_ms",
        "Admission-queue wait before a slot was granted (ms)",
    )
    from dnet_tpu.admission.reasons import DEADLINE_STAGES, REJECT_REASONS

    rejected = reg.counter(
        "dnet_admit_rejected_total",
        "Requests shed at admission (reason per admission/reasons.py)",
        labelnames=("reason",),
    )
    for reason in REJECT_REASONS:
        rejected.labels(reason=reason)  # pre-touch: the lint checks these
    exceeded = reg.counter(
        "dnet_deadline_exceeded_total",
        "End-to-end request deadlines found expired, by pipeline stage",
        labelnames=("stage",),
    )
    for stage in DEADLINE_STAGES:
        exceeded.labels(stage=stage)  # pre-touch: the lint checks these
    reg.counter(
        "dnet_cancel_propagated_total",
        "Client disconnects fanned out as cancel + reset_cache to the ring",
    )
    reg.gauge(
        "dnet_drain_state",
        "1 while the server is draining for shutdown (503 for new work)",
    )
    reg.counter(
        "dnet_shard_outq_dropped_total",
        "Shard output-queue frames dropped on overflow (error surfaced "
        "upstream in their place)",
    )
    # elastic ring membership (dnet_tpu/membership/): epoch fence +
    # recovery/rejoin accounting.  Kind/outcome label sets are DECLARED in
    # membership/epoch.py (a leaf module, like admission/reasons.py) and
    # cross-checked both ways by the metrics lint (pass 7).
    reg.gauge(
        "dnet_topology_epoch",
        "Ring topology epoch this process holds (API: minted; shard: "
        "pinned at load; 0 = unfenced)",
    )
    from dnet_tpu.membership.epoch import RECOVERY_OUTCOMES, STALE_EPOCH_KINDS

    stale = reg.counter(
        "dnet_stale_epoch_rejected_total",
        "Messages fenced out for carrying a dead topology epoch "
        "(kind per membership/epoch.py)",
        labelnames=("kind",),
    )
    for kind in STALE_EPOCH_KINDS:
        stale.labels(kind=kind)  # pre-touch: the lint checks these
    recovery = reg.counter(
        "dnet_recovery_total",
        "Ring recovery/rejoin rounds by outcome (membership/epoch.py)",
        labelnames=("outcome",),
    )
    for outcome in RECOVERY_OUTCOMES:
        recovery.labels(outcome=outcome)  # pre-touch: the lint checks these
    reg.histogram(
        "dnet_recovery_duration_seconds",
        "Wall time of one recovery/rejoin round (re-solve + reload)",
        buckets=(0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0),
    )
    reg.counter(
        "dnet_shard_rejoins_total",
        "Quarantined shards re-admitted to the ring without operator action",
    )
    from dnet_tpu.resilience.chaos import INJECTION_POINTS

    chaos_fam = reg.counter(
        "dnet_chaos_injected_total",
        "Faults injected by the deterministic chaos harness",
        labelnames=("point",),
    )
    for point in INJECTION_POINTS:
        chaos_fam.labels(point=point)  # pre-touch: the lint checks these
    # labeled "peer", NOT "node": federation injects node="api" into every
    # API-section sample, and a node label here would collide with it
    reg.gauge(
        "dnet_federation_scrape_ok",
        "1 if the last /v1/cluster/metrics scrape of this peer succeeded",
        labelnames=("peer",),
    )
    reg.gauge(
        "dnet_slo_ttft_p95_ms",
        "Rolling-window TTFT p95 against the SLO target (ms)",
    )
    reg.gauge(
        "dnet_slo_decode_p95_ms",
        "Rolling-window decode-step p95 against the SLO target (ms)",
    )
    # p99 twins for load-report cross-validation (attainment logic stays
    # p95-based; these exist so loadgen tail percentiles have a live peer)
    reg.gauge(
        "dnet_slo_ttft_p99_ms",
        "Rolling-window TTFT p99 (informational; attainment is p95-based)",
    )
    reg.gauge(
        "dnet_slo_decode_p99_ms",
        "Rolling-window decode-step p99 (informational; attainment is "
        "p95-based)",
    )
    reg.gauge(
        "dnet_slo_availability",
        "Rolling-window request availability (1 - errors/requests)",
    )
    burning = reg.gauge(
        "dnet_slo_burning",
        "1 when the named SLO is violating its target over the window",
        labelnames=("slo",),
    )
    from dnet_tpu.obs.slo import SLO_KINDS

    for kind in SLO_KINDS:
        burning.labels(slo=kind)  # pre-touch: expose at 0 from the start
    # performance attribution (obs/phases.py, obs/jit.py): decode-step
    # sub-phase breakdown, jit compile tracking, device memory.  Phase /
    # fn / kind label sets are DECLARED in obs/phases.py (a leaf module)
    # and cross-checked both ways by the metrics lint (pass 8).
    from dnet_tpu.obs.phases import DEVICE_MEM_KINDS, JIT_FNS, STEP_PHASES

    phase_fam = reg.histogram(
        "dnet_step_phase_ms",
        "Batched decode-step sub-phase wall time (obs/phases.py; fenced "
        "timings recorded when obs_enabled())",
        labelnames=("phase",),
    )
    for phase in STEP_PHASES:
        phase_fam.labels(phase=phase)  # pre-touch: the lint checks these
    compiles = reg.counter(
        "dnet_jit_compiles_total",
        "Traced+compiled calls per instrumented jit entry point "
        "(obs/phases.py JIT_FNS)",
        labelnames=("fn",),
    )
    for fn in JIT_FNS:
        compiles.labels(fn=fn)  # pre-touch: the lint checks these
    reg.histogram(
        "dnet_jit_compile_ms",
        "Wall time of calls that compiled (trace + compile + first run)",
        buckets=(10.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
                 10000.0, 30000.0, 60000.0),
    )
    mem = reg.gauge(
        "dnet_device_mem_bytes",
        "Backend device memory summed over local devices, where the PJRT "
        "backend reports stats (0 on CPU)",
        labelnames=("kind",),
    )
    for kind in DEVICE_MEM_KINDS:
        mem.labels(kind=kind)  # pre-touch: expose at 0 from the start
    # overlapped wire pipeline (transport/wire_pipeline.py,
    # DNET_WIRE_PIPELINE=1).  The dir label set is DECLARED in
    # obs/phases.py (leaf) and cross-checked both ways by the metrics
    # lint (pass 12).
    from dnet_tpu.obs.phases import WIRE_DIRS

    reg.histogram(
        "dnet_wire_encode_ms",
        "Hop-codec encode wall time per frame (D2H readback + byte "
        "packing; tx-stage time under the wire pipeline, compute-thread "
        "time without it)",
    )
    reg.histogram(
        "dnet_wire_decode_ms",
        "Hop-codec decode wall time per frame (H2D upload + on-device "
        "dequant dispatch; ingress time under the wire pipeline, "
        "compute-thread time without it)",
    )
    wire_bytes = reg.counter(
        "dnet_wire_bytes_total",
        "Activation/token frame payload bytes by wire direction "
        "(obs/phases.py WIRE_DIRS)",
        labelnames=("dir",),
    )
    for d in WIRE_DIRS:
        wire_bytes.labels(dir=d)  # pre-touch: the lint checks these
    reg.gauge(
        "dnet_wire_overlap_ratio",
        "Fraction of cumulative hop-codec time hidden off the compute "
        "thread (1.0 = codec fully overlapped with compute)",
    )
    # intra-shard tensor parallelism (parallel/tp.py, DNET_TP=N).  The op
    # label set is DECLARED in obs/phases.py TP_OPS (leaf) and
    # cross-checked both ways by the metrics lint (pass 13).
    from dnet_tpu.obs.phases import TP_OPS

    tp_ms = reg.histogram(
        "dnet_tp_collective_ms",
        "Intra-shard TP collective latency from the load-time calibration "
        "probe (per-op timing cannot be carved out of the fused layer "
        "programs at serving time)",
        labelnames=("op",),
    )
    tp_bytes = reg.counter(
        "dnet_tp_collective_bytes_total",
        "Analytic interconnect bytes dispatched per TP collective "
        "(ring-algorithm accounting, parallel/tp_collectives.py)",
        labelnames=("op",),
    )
    for op in TP_OPS:
        tp_ms.labels(op=op)  # pre-touch: the lint checks these
        tp_bytes.labels(op=op)  # pre-touch: the lint checks these
    reg.gauge(
        "dnet_tp_degree",
        "Resolved tensor-parallel degree of this process's serving engine "
        "(1 = single-chip, the pre-TP behavior)",
    )
    # runtime concurrency sanitizer (dnet_tpu/analysis/runtime/, DNET_SAN=1).
    # Check-code / thread label sets are DECLARED in
    # analysis/runtime/domains.py (a leaf module) and cross-checked both
    # ways by the metrics lint (pass 9).
    from dnet_tpu.analysis.runtime.domains import (
        RUNTIME_CHECK_CODES,
        ZOMBIE_THREAD_KINDS,
    )

    san_findings = reg.counter(
        "dnet_san_findings_total",
        "Runtime sanitizer (dsan) findings recorded, by DS check code",
        labelnames=("check",),
    )
    for code in RUNTIME_CHECK_CODES:
        san_findings.labels(check=code)  # pre-touch: the lint checks these
    zombies = reg.counter(
        "dnet_san_zombie_threads_total",
        "Worker threads that failed to join at stop() and were leaked as "
        "daemons (a wedged worker must be visible, not silent)",
        labelnames=("thread",),
    )
    for kind in ZOMBIE_THREAD_KINDS:
        zombies.labels(thread=kind)  # pre-touch: the lint checks these
    # iteration-level scheduler (dnet_tpu/sched/, DNET_SCHED=1).  State /
    # kind / reason label sets are DECLARED in sched/kinds.py (a leaf
    # module, like admission/reasons.py) and cross-checked both ways by
    # the metrics lint (pass 10).
    from dnet_tpu.sched.kinds import BATCH_KINDS, PREEMPT_REASONS, QUEUE_STATES

    reg.histogram(
        "dnet_sched_tick_ms",
        "One scheduler tick wall time: the mixed prefill+decode plan "
        "executed on the compute thread",
    )
    batch_fam = reg.histogram(
        "dnet_sched_batch_tokens",
        "Per-tick batch composition: prompt tokens chunk-prefilled and "
        "decode lanes stepped in the same tick (sched/kinds.py)",
        labelnames=("kind",),
        buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                 512.0, 1024.0, 2048.0),
    )
    for kind in BATCH_KINDS:
        batch_fam.labels(kind=kind)  # pre-touch: the lint checks these
    preempt = reg.counter(
        "dnet_sched_preemptions_total",
        "Sequences evicted back to WAITING by the scheduler "
        "(reason per sched/kinds.py)",
        labelnames=("reason",),
    )
    for reason in PREEMPT_REASONS:
        preempt.labels(reason=reason)  # pre-touch: the lint checks these
    depth = reg.gauge(
        "dnet_sched_queue_depth",
        "Requests resident in the scheduler queue, by live state "
        "(sched/kinds.py)",
        labelnames=("state",),
    )
    for state in QUEUE_STATES:
        depth.labels(state=state)  # pre-touch: the lint checks these
    # critical-path attribution (obs/critical_path.py): the exhaustive
    # per-request segment ledger.  The segment label set is DECLARED in
    # obs/phases.py REQUEST_SEGMENTS (leaf) and cross-checked both ways by
    # the metrics lint (pass DL028).
    from dnet_tpu.obs.phases import REQUEST_SEGMENTS

    seg_fam = reg.histogram(
        "dnet_request_segment_ms",
        "Per-request critical-path segment ledger: exhaustive, "
        "non-overlapping wall-time attribution of one request's recorded "
        "spans (obs/phases.py REQUEST_SEGMENTS; obs/critical_path.py)",
        labelnames=("segment",),
    )
    for seg in REQUEST_SEGMENTS:
        seg_fam.labels(segment=seg)  # pre-touch: the lint checks these
    # scheduler tick flight-recorder (sched/flight.py): the bounded
    # TickRecord ring behind GET /v1/debug/sched
    reg.counter(
        "dnet_sched_tick_records_total",
        "Scheduler ticks captured into the tick flight-recorder ring "
        "(sched/flight.py; bounded by DNET_OBS_TICK_RECORDS)",
    )
    # structured wide events (obs/events.py): the canonical event journal
    # behind GET /v1/debug/events.  The name vocabulary is DECLARED in
    # obs/phases.py EVENT_NAMES (leaf) and cross-checked both ways by the
    # metrics lint (pass DL030).
    from dnet_tpu.obs.phases import EVENT_NAMES

    events_fam = reg.counter(
        "dnet_events_total",
        "Structured wide events journaled by log_event "
        "(obs/phases.py EVENT_NAMES; obs/events.py)",
        labelnames=("name",),
    )
    for event_name in EVENT_NAMES:
        events_fam.labels(name=event_name)  # pre-touch: the lint checks these
    reg.histogram(
        "dnet_sched_tick_budget_used_ratio",
        "Fraction of the per-tick token budget the planned batch consumed "
        "(1.0 = saturated tick; sched/flight.py)",
        buckets=(0.1, 0.25, 0.5, 0.75, 0.9, 1.0),
    )
    # fleet routing (dnet_tpu/fleet/, DNET_FLEET=N): replica-state and
    # routing-reason label sets are DECLARED in fleet/states.py (leaf) and
    # cross-checked both ways by the metrics lint (pass DL031).  The
    # `replica` label is operator-assigned (r0, r1, ...) — dynamic, so no
    # pre-touch loop; the enum-valued families below get one.
    from dnet_tpu.fleet.states import REPLICA_STATES, ROUTE_REASONS

    reg.counter(
        "dnet_fleet_requests_total",
        "Requests the fleet front door dispatched, by serving replica "
        "(fleet/manager.py; replica ids are deployment-assigned)",
        labelnames=("replica",),
    )
    routed_fam = reg.counter(
        "dnet_fleet_routed_total",
        "Routing decisions by policy reason "
        "(fleet/states.py ROUTE_REASONS; fleet/router.py)",
        labelnames=("reason",),
    )
    for reason in ROUTE_REASONS:
        routed_fam.labels(reason=reason)  # pre-touch: the lint checks these
    reg.counter(
        "dnet_fleet_affinity_hits_total",
        "Requests routed by a sticky prefix-affinity entry to the replica "
        "holding their COW prefix blocks (fleet/router.py)",
    )
    reg.counter(
        "dnet_fleet_failovers_total",
        "In-flight requests migrated off a dead replica to a survivor "
        "via deterministic replay (fleet/manager.py)",
    )
    replicas_fam = reg.gauge(
        "dnet_fleet_replicas",
        "Fleet replicas by lifecycle state "
        "(fleet/states.py REPLICA_STATES; fleet/manager.py)",
        labelnames=("state",),
    )
    for state in REPLICA_STATES:
        replicas_fam.labels(state=state)  # pre-touch: the lint checks these


def _ensure_core() -> None:
    global _core_done
    if _core_done:
        return
    with _core_once:
        if not _core_done:
            _register_core(_registry)
            _core_done = True


def get_registry() -> MetricsRegistry:
    """The process-global registry (core families registered)."""
    _ensure_core()
    return _registry


def get_recorder() -> FlightRecorder:
    return _recorder


_slo_tracker = None
_slo_lock = threading.Lock()


def get_slo_tracker():
    """The process-global SLO tracker, built from ObsSettings targets on
    first access (lazy so tests can mutate the env, reset the settings
    cache, and reset_obs() to pick the new targets up)."""
    global _slo_tracker
    if _slo_tracker is None:
        with _slo_lock:
            if _slo_tracker is None:
                from dnet_tpu.config import get_settings
                from dnet_tpu.obs.slo import SloTracker

                obs = get_settings().obs
                _slo_tracker = SloTracker(
                    window_s=obs.slo_window_s,
                    ttft_p95_ms=obs.slo_ttft_p95_ms,
                    decode_p95_ms=obs.slo_decode_p95_ms,
                    availability=obs.slo_availability,
                )
    return _slo_tracker


def metric(name: str) -> MetricFamily:
    """Fetch a registered family by name; unknown names raise (catching
    typos at import time beats a silently separate series)."""
    _ensure_core()
    fam = _registry.get(name)
    if fam is None:
        raise KeyError(f"metric {name!r} is not registered; add it to "
                       f"dnet_tpu.obs._register_core")
    return fam


def obs_enabled() -> bool:
    """Single profile-gating truth: DNET_OBS_ENABLED (ObsSettings) or the
    legacy DNET_PROFILE env, whichever is set (read via config.env_flag,
    the sanctioned DL006 escape hatch, so post-cache flips still gate)."""
    from dnet_tpu.config import env_flag, get_settings

    if get_settings().obs.enabled:
        return True
    return env_flag("DNET_PROFILE")


def reset_obs() -> None:
    """Zero metrics in place and drop recorded timelines (for tests).
    Family/child objects survive, so handles held by instrumented modules
    stay valid.  The SLO tracker is DROPPED, not zeroed — the next
    get_slo_tracker() re-reads targets from settings, so a test that
    changed DNET_OBS_SLO_* (and reset the settings cache) sees them."""
    global _slo_tracker
    _ensure_core()
    _registry.reset()
    _recorder.clear()
    # the scheduler tick ring is obs state too (captured under
    # obs_enabled, dumped by /v1/debug/sched): a test that resets the
    # books must not inherit a previous run's ticks.  Imported here, not
    # at module top: sched.flight itself imports dnet_tpu.obs.
    from dnet_tpu.sched.flight import get_tick_recorder

    get_tick_recorder().clear()
    # the wide-event journal is obs state too: drop ring + sink so the
    # next log_event re-reads DNET_OBS_EVENTS_* from fresh settings.
    # Late import: obs.events imports dnet_tpu.obs for metric().
    from dnet_tpu.obs.events import reset_events

    reset_events()
    with _slo_lock:
        _slo_tracker = None
