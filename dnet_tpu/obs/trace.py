"""Chrome trace-event / Perfetto JSON export of flight-recorder timelines.

Everything the debug surface already records — per-request spans (local or
cluster-stitched, obs/recorder.py + obs/clock.py), scheduler tick records
(sched/flight.py), and the wire-overlap books (transport/wire_pipeline.py)
— renders as one trace-event JSON object that chrome://tracing and
ui.perfetto.dev open directly:

- one PROCESS track per node (`api`, shard instance ids), each with
  `driver` / `compute` / `tx-stage` THREAD tracks so compute work and
  wire work stack on separate lanes,
- `X` complete events for timed spans, `i` instants for zero-duration
  markers (prefix_cache_hit, deadline_drop, transport_recv),
- `s`/`f` FLOW events (cat `wire`, id `rid/seq`) stitching a request's
  frames across hops: each tx span on one node arrows to the matching
  `transport_recv` on the next,
- `C` counter tracks from the tick flight-recorder: queue depths by
  scheduler state and KV block-pool occupancy over time.

Timestamps are microseconds (the trace-event unit) relative to the
earliest timeline origin, so multi-node dumps line up on the stitched
clock.  Event count is capped (DNET_OBS_TRACE_MAX_EVENTS); a truncated
dump says so in `otherData` instead of silently looking complete.
"""

from __future__ import annotations

from typing import Iterable, List, Optional

# ---- track taxonomy ---------------------------------------------------
# thread ids within each node's process track; DL028 cross-checks these
# labels against the span routing below
TID_DRIVER = 1
TID_COMPUTE = 2
TID_TX = 3

TRACE_THREADS = {
    TID_DRIVER: "driver",
    TID_COMPUTE: "compute",
    TID_TX: "tx-stage",
}

#: span names that render on the compute thread track
COMPUTE_SPANS = frozenset({
    "prefill",
    "prefix_refill",
    "decode_sync_drain",
    "shard_compute",
    "kv_gather",
    "compute",
    "kv_scatter",
    "sample",
})

#: span names that render on the tx-stage thread track
TX_SPANS = frozenset({
    "wire_encode",
    "wire_tx_stage",
    "shard_tx",
    "transport_send",
    "transport_recv",
    "backpressure_pause",
    "token_rpc",
})

#: tx-side span names that OPEN a cross-hop flow arrow (paired with the
#: receiving node's transport_recv carrying the same seq)
FLOW_TX_SPANS = frozenset({"shard_tx", "transport_send"})
FLOW_RX_SPAN = "transport_recv"

_SPAN_CORE_KEYS = ("name", "t_ms", "dur_ms", "node")


def _tid_for(name: str) -> int:
    if name in COMPUTE_SPANS:
        return TID_COMPUTE
    if name in TX_SPANS:
        return TID_TX
    return TID_DRIVER


def _span_args(span: dict, rid: str) -> dict:
    # recorder spans nest their kwargs under "meta"; stitched spans add
    # top-level keys (node) — flatten both into the event args
    args = {
        k: v
        for k, v in span.items()
        if k not in _SPAN_CORE_KEYS and k != "meta"
    }
    args.update(span.get("meta") or {})
    args["rid"] = rid
    return args


def export_trace(
    timelines: Iterable[dict],
    tick_records: Optional[List[dict]] = None,
    max_events: Optional[int] = None,
    wide_events: Optional[List[dict]] = None,
) -> dict:
    """Render timelines (+ optional tick records) as trace-event JSON.

    `timelines` are `FlightRecorder.timeline()` dicts or cluster-stitched
    `stitch_timelines()` dicts — the only difference is that stitched
    spans carry a `node` key; bare spans land on the `api` process.
    `tick_records` are `TickRecord.as_dict()` rows and become counter
    tracks on the api process.  `wide_events` are obs/events.py journal
    rows (absolute `t_unix`, optional `node`) and render as `i` instants
    (cat `event`) on the owning node's driver track — a `preempted`
    marker lands visually inside the decode gap it caused."""
    from dnet_tpu.transport.wire_pipeline import overlap

    timelines = [tl for tl in timelines if tl]
    tick_records = list(tick_records or [])
    wide_events = list(wide_events or [])
    if max_events is None:
        try:
            from dnet_tpu.config import get_settings

            max_events = get_settings().obs.trace_max_events
        except Exception:  # config unavailable in stripped-down tests
            max_events = 50000

    # base: earliest origin across everything that carries a wall time,
    # so every ts is a small non-negative microsecond offset
    origins = [float(tl["t_unix"]) for tl in timelines]
    origins += [float(r["t_unix"]) for r in tick_records if "t_unix" in r]
    origins += [float(e["t_unix"]) for e in wide_events if "t_unix" in e]
    base = min(origins) if origins else 0.0

    # pid per node: api is always 1; shard nodes take stable sorted slots
    nodes = {"api"}
    for tl in timelines:
        for span in tl["spans"]:
            nodes.add(span.get("node") or "api")
    for e in wide_events:
        nodes.add(e.get("node") or "api")
    pids = {"api": 1}
    for i, node in enumerate(sorted(nodes - {"api"}), start=2):
        pids[node] = i

    meta_events: List[dict] = []
    for node, pid in sorted(pids.items(), key=lambda kv: kv[1]):
        meta_events.append({
            "ph": "M", "pid": pid, "name": "process_name",
            "args": {"name": node},
        })
        meta_events.append({
            "ph": "M", "pid": pid, "name": "process_sort_index",
            "args": {"sort_index": pid},
        })
        for tid, tname in TRACE_THREADS.items():
            meta_events.append({
                "ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
                "args": {"name": tname},
            })

    events: List[dict] = []
    # (rid, seq) -> [endpoints] for flow stitching.  A frame keeps its
    # seq across every hop of the ring, so one key can hold several
    # tx/rx pairs (api->shard-0, shard-0->shard-1, ...); each tx is
    # matched below to the EARLIEST unclaimed recv that happens after
    # it, which orients the arrows even when every span sits in one
    # process-wide timeline (the in-process ring has no node tags).
    flow_tx: dict = {}
    flow_rx: dict = {}
    for tl in timelines:
        rid = tl.get("rid", "")
        tl_base_us = (float(tl["t_unix"]) - base) * 1e6
        for span in tl["spans"]:
            node = span.get("node") or "api"
            pid = pids[node]
            tid = _tid_for(span["name"])
            ts = tl_base_us + float(span["t_ms"]) * 1000.0
            dur = float(span["dur_ms"]) * 1000.0
            args = _span_args(span, rid)
            if dur > 0.0:
                events.append({
                    "name": span["name"], "cat": "span", "ph": "X",
                    "ts": ts, "dur": dur, "pid": pid, "tid": tid,
                    "args": args,
                })
            else:
                events.append({
                    "name": span["name"], "cat": "span", "ph": "i",
                    "ts": ts, "s": "t", "pid": pid, "tid": tid,
                    "args": args,
                })
            seq = span.get("seq", (span.get("meta") or {}).get("seq"))
            if seq is not None:
                key = (rid, seq)
                if span["name"] in FLOW_TX_SPANS:
                    # arrow leaves with the frame: at tx-span start
                    flow_tx.setdefault(key, []).append((ts, pid, tid))
                elif span["name"] == FLOW_RX_SPAN:
                    flow_rx.setdefault(key, []).append((ts, pid, tid))

    for key in sorted(flow_tx.keys() & flow_rx.keys(), key=str):
        rid, seq = key
        txs = sorted(flow_tx[key])
        rxs = sorted(flow_rx[key])
        hop = 0
        for tx_ts, tx_pid, tx_tid in txs:
            rx = next((r for r in rxs if r[0] >= tx_ts), None)
            if rx is None:
                continue
            rxs.remove(rx)
            rx_ts, rx_pid, rx_tid = rx
            flow_id = f"{rid}/{seq}/{hop}"
            hop += 1
            events.append({
                "name": "wire", "cat": "wire", "ph": "s", "id": flow_id,
                "ts": tx_ts, "pid": tx_pid, "tid": tx_tid,
            })
            events.append({
                "name": "wire", "cat": "wire", "ph": "f", "bp": "e",
                "id": flow_id, "ts": rx_ts, "pid": rx_pid, "tid": rx_tid,
            })

    # wide events (obs/events.py): instants on the owning node's driver
    # track, correlated to the surrounding spans by wall time + rid args
    for e in wide_events:
        if "t_unix" not in e:
            continue
        node = e.get("node") or "api"
        args = {k: v for k, v in e.items() if k not in ("name", "t_unix")}
        events.append({
            "name": e["name"], "cat": "event", "ph": "i",
            "ts": (float(e["t_unix"]) - base) * 1e6, "s": "t",
            "pid": pids[node], "tid": TID_DRIVER, "args": args,
        })

    for rec in tick_records:
        if "t_unix" not in rec:
            continue
        ts = (float(rec["t_unix"]) - base) * 1e6
        depths = rec.get("queue_depths") or {}
        if depths:
            events.append({
                "name": "sched queue depth", "cat": "sched", "ph": "C",
                "ts": ts, "pid": pids["api"],
                "args": {k: int(v) for k, v in depths.items()},
            })
        events.append({
            "name": "kv blocks", "cat": "sched", "ph": "C", "ts": ts,
            "pid": pids["api"],
            "args": {
                "used": int(rec.get("kv_blocks_used", 0)),
                "free": int(rec.get("kv_blocks_free", 0)),
            },
        })

    events.sort(key=lambda e: e["ts"])
    truncated = 0
    if len(events) > max_events:
        truncated = len(events) - max_events
        events = events[:max_events]

    out = {
        "traceEvents": meta_events + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "base_unix_s": base,
            "timelines": len(timelines),
            "tick_records": len(tick_records),
            "wide_events": len(wide_events),
            "wire_overlap": overlap.snapshot(),
        },
    }
    if truncated:
        out["otherData"]["truncated_events"] = truncated
    return out
