"""Structured wide events: request-scoped context + the canonical ledger.

The third observability pillar next to metrics (obs/__init__.py) and traces
(obs/recorder.py + obs/trace.py): a dependency-free journal of structured
events riding the rid/epoch plumbing the serving path already threads
everywhere.

- **Context binding** — `bind(rid=, node=, epoch=, tick=)` establishes
  request identity for a dynamic extent via `contextvars`, so every
  `log_event()` AND every plain log line (the `ContextStampFilter`
  installed by utils/logger.py) inside the scope carries rid/node/epoch
  automatically.  The shard binds at frame dequeue (rid + epoch arrive on
  the ActivationFrame); thread hops propagate with
  `contextvars.copy_context()`.
- **Canonical events** — `log_event(name, **fields)` where `name` is one
  of `obs.phases.EVENT_NAMES` (asserted; the vocabulary is lint-checked
  against `dnet_events_total{name=}` both directions, pass DL030).  The
  wide `request_complete` event — exactly one per finished request — is
  emitted by api/inference.py with status, shed/finish reason, token
  counts, resolved modes, and the critical-path segment ledger embedded.
- **Sinks + query** — a bounded in-memory ring (DNET_OBS_EVENTS_RECORDS)
  behind `GET /v1/debug/events?rid=&name=&last_s=` on both roles, an
  optional JSONL file sink (DNET_OBS_EVENTS_PATH), and one
  `dnet_events_total{name=}` increment per event.  `?cluster=1` merges
  shard rings onto the API clock via the PR 2 offset probe
  (`merge_remote_events`).

Events store absolute wall time (`t_unix`, the cross-node join key the
clock stitcher corrects) — never monotonic time.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import threading
import time
from collections import deque
from typing import Deque, Dict, Iterable, List, Optional, Tuple

from dnet_tpu.obs.phases import EVENT_NAMES

#: identity keys a binding may carry; log records and events expose them
#: under these exact names
CONTEXT_KEYS = ("rid", "node", "epoch", "tick")

_BOUND: contextvars.ContextVar[Optional[Dict[str, object]]] = (
    contextvars.ContextVar("dnet_event_ctx", default=None)
)


def bound_fields() -> Dict[str, object]:
    """The identity fields bound in the current context (copy; {} unbound)."""
    cur = _BOUND.get()
    return dict(cur) if cur else {}


@contextlib.contextmanager
def bind(rid=None, node=None, epoch=None, tick=None):
    """Bind request identity for the dynamic extent of the `with` block.

    Nested binds MERGE (inner non-None fields shadow outer ones), so the
    API can bind `node` at startup and `rid` per request.  The binding is
    a contextvar: it follows `await` chains for free and crosses explicit
    thread hops via `contextvars.copy_context().run(...)`.
    """
    fields: Dict[str, object] = {}
    for key, value in (
        ("rid", rid), ("node", node), ("epoch", epoch), ("tick", tick)
    ):
        if value is not None:
            fields[key] = value
    merged = {**(_BOUND.get() or {}), **fields}
    token = _BOUND.set(merged)
    try:
        yield merged
    finally:
        try:
            _BOUND.reset(token)
        except ValueError:
            # exited in a different Context than entered (a generator
            # holding the scope open across yields got finalized by the
            # event loop): the entry context is unreachable, so there is
            # nothing to restore — and nothing leaked into this one
            pass


class ContextStampFilter(logging.Filter):
    """Stamp the bound identity onto every log record.

    Installed at the LOGGER level by utils/logger.py setup_logger, so the
    ~45 `get_logger()` sites upgrade without touching a single call: any
    record emitted inside a `bind()` scope exposes `record.rid` /
    `record.node` / `record.epoch` / `record.tick` (empty string when
    unbound, so structured formatters never KeyError) plus `record.ctx`,
    a pre-rendered ` [rid=... node=...]` suffix for plain-text formats.
    """

    def filter(self, record: logging.LogRecord) -> bool:
        ctx = _BOUND.get() or {}
        parts = []
        for key in CONTEXT_KEYS:
            value = ctx.get(key)
            if getattr(record, key, None) in (None, ""):
                setattr(record, key, "" if value is None else value)
            if value not in (None, ""):
                parts.append(f"{key}={value}")
        record.ctx = " [" + " ".join(parts) + "]" if parts else ""
        return True


# ---- the event ring ----------------------------------------------------

class EventRing:
    """Bounded, thread-safe journal of event dicts (newest kept).

    Shard compute threads and the API event loop both append; queries
    copy under the lock and filter outside it.  Overflow EVICTS oldest
    and counts `dropped` — the debug surface reports the loss instead of
    silently looking complete.
    """

    def __init__(self, capacity: int) -> None:
        self.capacity = max(int(capacity), 1)
        self._events: Deque[dict] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def append(self, event: dict) -> None:
        with self._lock:
            if len(self._events) == self.capacity:
                self.dropped += 1
            self._events.append(event)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def query(
        self,
        rid: str = "",
        name: str = "",
        last_s: float = 0.0,
        now: Optional[float] = None,
    ) -> List[dict]:
        """Filtered view, oldest first.  `rid` matches resume segments too
        (`rid#r1` rows join their base request); `last_s` > 0 keeps only
        events within that many seconds of `now`."""
        from dnet_tpu.obs.recorder import base_rid

        with self._lock:
            events = list(self._events)
        if rid:
            events = [
                e for e in events if base_rid(str(e.get("rid", ""))) == rid
            ]
        if name:
            events = [e for e in events if e.get("name") == name]
        if last_s and last_s > 0:
            cutoff = (time.time() if now is None else now) - float(last_s)
            events = [e for e in events if float(e.get("t_unix", 0)) >= cutoff]
        return events


_ring: Optional[EventRing] = None
_ring_lock = threading.Lock()

# JSONL sink state (lazy-opened append handle; one warning then disabled
# on I/O failure so a full disk cannot take down serving)
_sink_lock = threading.Lock()
_sink_fh = None
_sink_path: Optional[str] = None
_sink_failed = False


def _obs_settings():
    from dnet_tpu.config import get_settings

    return get_settings().obs


def get_event_ring() -> EventRing:
    """The process-wide ring, sized by DNET_OBS_EVENTS_RECORDS."""
    global _ring
    if _ring is None:
        with _ring_lock:
            if _ring is None:
                try:
                    cap = _obs_settings().events_records
                except Exception:  # config unavailable in stripped-down tests
                    cap = 1024
                _ring = EventRing(cap)
    return _ring


def reset_events() -> None:
    """Drop the ring and close the sink (tests / reset_obs): the next
    log_event re-reads capacity and path from fresh settings."""
    global _ring, _sink_fh, _sink_path, _sink_failed
    with _ring_lock:
        _ring = None
    with _sink_lock:
        if _sink_fh is not None:
            try:
                _sink_fh.close()
            except OSError:
                pass
            _sink_fh = None
        _sink_path = None
        _sink_failed = False


def _sink_write(event: dict) -> None:
    global _sink_fh, _sink_path, _sink_failed
    try:
        path = _obs_settings().events_path
    except Exception:
        return
    if not path or _sink_failed:
        return
    with _sink_lock:
        try:
            if _sink_fh is None or _sink_path != path:
                if _sink_fh is not None:
                    _sink_fh.close()
                _sink_fh = open(path, "a", encoding="utf-8")
                _sink_path = path
            _sink_fh.write(json.dumps(event, default=str) + "\n")
            _sink_fh.flush()
        except OSError as exc:
            _sink_failed = True
            from dnet_tpu.utils.logger import get_logger

            get_logger().warning(
                "events JSONL sink %s failed (%s); sink disabled for this "
                "process", path, exc,
            )


def log_event(name: str, **fields) -> dict:
    """Journal one canonical event: ring + optional JSONL sink + one
    `dnet_events_total{name=}` increment.

    `name` must be in `obs.phases.EVENT_NAMES` (the lint-checked
    vocabulary).  Identity fields (rid/node/epoch/tick) default from the
    current `bind()` scope; explicit kwargs win.  Returns the journaled
    record (tests and callers embedding it elsewhere)."""
    assert name in EVENT_NAMES, name
    event: Dict[str, object] = {"name": name, "t_unix": time.time()}
    ctx = _BOUND.get() or {}
    for key in CONTEXT_KEYS:
        value = fields.pop(key, ctx.get(key))
        if value is not None and value != "":
            event[key] = value
    event.update(fields)
    get_event_ring().append(event)
    _sink_write(event)
    from dnet_tpu.obs import metric

    metric("dnet_events_total").labels(name=name).inc()
    return event


# ---- cluster merge -----------------------------------------------------

def merge_remote_events(
    local: Iterable[dict],
    remotes: Iterable[Tuple[str, Iterable[dict], object]],
) -> List[dict]:
    """Merge shard event lists onto the local clock, oldest first.

    `remotes` rows are `(node, events, ClockEstimate)` — the estimate from
    `obs.clock.offset_from_probe` over the fetch that carried the events
    (the response's `t_wall` doubles as the probe reading, exactly like
    the stitched timeline fetch).  Each remote `t_unix` is rebased by the
    estimated offset; every event is tagged with its owning `node` (local
    events that carry no node default to "api")."""
    merged: List[dict] = []
    for event in local:
        row = dict(event)
        row.setdefault("node", "api")
        merged.append(row)
    for node, events, est in remotes:
        offset_s = float(getattr(est, "offset_s", 0.0))
        for event in events:
            row = dict(event)
            row["node"] = node
            row["t_unix"] = float(row.get("t_unix", 0.0)) - offset_s
            merged.append(row)
    merged.sort(key=lambda e: float(e.get("t_unix", 0.0)))
    return merged
