"""Cross-node clock-offset estimation and timeline stitching.

Every process records flight-recorder spans against its OWN clocks (a wall
`t_unix` origin plus perf_counter offsets), so merging shard timelines into
one cluster view needs each node's wall-clock offset from the API node.
The estimator is the classic NTP midpoint: the client notes wall time `t0`
before a round trip, the server stamps its wall time `t_remote` while
serving, the client notes `t1` on return — assuming symmetric paths the
server stamped at the midpoint, so

    offset = t_remote - (t0 + t1) / 2        (remote clock minus local)

with worst-case error bounded by half the round trip.  Samples ride the
handshakes the cluster already makes — the gRPC MeasureLatency echo stamps
`t_remote` (shard/grpc_servicer.py), and every shard timeline HTTP
response carries `t_wall` so the fetch that collects a timeline IS the
offset probe for correcting it.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class ClockEstimate:
    """One node's estimated offset from the local clock, in seconds."""

    offset_s: float  # remote wall clock minus local wall clock
    rtt_s: float  # round trip the sample rode; error bound is rtt/2

    @property
    def error_bound_s(self) -> float:
        return self.rtt_s / 2.0


def offset_from_probe(t0: float, t_remote: float, t1: float) -> ClockEstimate:
    """NTP-style midpoint estimate from one round trip (wall seconds)."""
    if t1 < t0:
        raise ValueError(f"probe ended before it started (t0={t0}, t1={t1})")
    return ClockEstimate(offset_s=t_remote - (t0 + t1) / 2.0, rtt_s=t1 - t0)


class ClockSync:
    """Per-node offset table keeping each node's tightest (min-RTT) sample.

    A shorter round trip bounds the midpoint error tighter, so a new sample
    only replaces the stored one when its RTT is smaller — a congested
    probe cannot degrade an estimate a clean probe already produced.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._estimates: Dict[str, ClockEstimate] = {}

    def update(self, node: str, t0: float, t_remote: float, t1: float) -> ClockEstimate:
        est = offset_from_probe(t0, t_remote, t1)
        with self._lock:
            cur = self._estimates.get(node)
            if cur is None or est.rtt_s < cur.rtt_s:
                self._estimates[node] = est
                return est
            return cur

    def estimate(self, node: str) -> Optional[ClockEstimate]:
        with self._lock:
            return self._estimates.get(node)

    def offset_s(self, node: str) -> float:
        est = self.estimate(node)
        return est.offset_s if est is not None else 0.0

    def clear(self) -> None:
        with self._lock:
            self._estimates.clear()


def stitch_timelines(
    local: Optional[dict],
    remotes: Sequence[Tuple[str, dict, ClockEstimate]],
    local_node: str = "api",
    rid: str = "",
) -> dict:
    """Merge per-node flight-recorder timelines into one hop-annotated view.

    `local` is this process's `FlightRecorder.timeline()` dict (or None when
    only remote nodes recorded the rid); `remotes` are `(node, timeline,
    estimate)` triples fetched from shard HTTP servers.  Every span gains a
    `node` field, remote span times are rebased onto the LOCAL clock —
    absolute wall time of a span is `t_unix + t_ms/1000` on its own node,
    minus that node's offset to land in local time — and the merged spans
    sort by corrected start time, so hop ordering reads causally up to the
    residual estimator error (bounded by each probe's rtt/2).
    """
    base: Optional[float] = local.get("t_unix") if local else None
    if base is None:
        # no local timeline: rebase on the earliest corrected remote origin
        origins = [
            tl["t_unix"] - est.offset_s for _, tl, est in remotes if tl
        ]
        base = min(origins) if origins else 0.0

    spans: List[dict] = []
    dropped = 0
    nodes: List[dict] = []
    if local:
        for s in local["spans"]:
            spans.append({**s, "node": local_node})
        dropped += int(local.get("dropped", 0))
        nodes.append(
            {"node": local_node, "offset_ms": 0.0, "rtt_ms": 0.0,
             "spans": len(local["spans"]), "dropped": int(local.get("dropped", 0))}
        )
    for node, tl, est in remotes:
        if not tl:
            continue
        shift_ms = (tl["t_unix"] - est.offset_s - base) * 1000.0
        for s in tl["spans"]:
            spans.append({**s, "t_ms": round(s["t_ms"] + shift_ms, 3),
                          "node": node})
        dropped += int(tl.get("dropped", 0))
        nodes.append(
            {"node": node, "offset_ms": round(est.offset_s * 1000.0, 3),
             "rtt_ms": round(est.rtt_s * 1000.0, 3),
             "spans": len(tl["spans"]), "dropped": int(tl.get("dropped", 0))}
        )
    spans.sort(key=lambda s: s["t_ms"])
    return {
        "rid": (local or {}).get("rid") or rid,
        "t_unix": base,
        "cluster": True,
        "nodes": nodes,
        "spans": spans,
        "dropped": dropped,
    }
