"""Load-run aggregation: outcome rows -> the BENCH_SERVE report.

Report semantics, pinned by tests/subsystems/test_loadgen.py:

- **Goodput counts 200-completed requests only.**  Shed rows (429/503/504)
  and failed rows contribute to the shed/failure breakdowns, never to
  goodput; requests scheduled inside the warmup window are excluded from
  every aggregate (they exist to absorb compiles and cache fills).
- **Percentiles are nearest-rank** over client-observed samples (TTFT,
  inter-token latency, E2E) — the same convention as obs/slo.py, so a
  report percentile and a live gauge are the same statistic over two
  vantage points.
- **Cross-validation, not duplication**: the report embeds the server's
  live `dnet_slo_*` values (and burn state) next to its own client-side
  numbers plus the relative gap, so a disagreement — a broken gauge, an
  unmeasured queue — is visible in the artifact itself.
- The decode-phase and JIT summaries are DELTAS of the server's
  `/metrics` exposition bracketing the run, so a long-lived server's
  history cannot pollute one run's attribution.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional, Sequence

from dnet_tpu.loadgen.client import RequestOutcome
from dnet_tpu.loadgen.workload import WorkloadSpec
from dnet_tpu.obs.phases import DEVICE_MEM_KINDS, REQUEST_SEGMENTS, STEP_PHASES
from dnet_tpu.obs.slo import nearest_rank

# one Prometheus v0.0.4 sample line: name{labels} value  (labels optional)
_SAMPLE_RE = re.compile(
    r"^(?P<name>[A-Za-z_:][A-Za-z0-9_:]*)"
    r"(?P<labels>\{[^}]*\})?\s+(?P<value>[^\s]+)\s*$"
)


def parse_prometheus(text: str) -> Dict[str, float]:
    """Exposition text -> {'name{labels}': value} (labels verbatim)."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        out[m.group("name") + (m.group("labels") or "")] = value
    return out


def metric_delta(
    after: Dict[str, float], before: Optional[Dict[str, float]], key: str
) -> float:
    """after[key] - before[key] (missing keys read as 0)."""
    return after.get(key, 0.0) - (before or {}).get(key, 0.0)


def percentile(values: Sequence[float], q: float) -> float:
    """Nearest-rank q-quantile (0..1); 0.0 on an empty sample.  THE same
    implementation as the live `dnet_slo_*` windows (obs/slo.py
    nearest_rank), which is what makes slo.cross_check a like-for-like
    comparison."""
    return nearest_rank(list(values), q)


def _latency_summary(values: List[float]) -> dict:
    return {
        "n": len(values),
        "mean_ms": round(sum(values) / len(values), 3) if values else 0.0,
        "p50_ms": round(percentile(values, 0.50), 3),
        "p95_ms": round(percentile(values, 0.95), 3),
        "p99_ms": round(percentile(values, 0.99), 3),
    }


def _phase_summary(
    after: Dict[str, float], before: Optional[Dict[str, float]]
) -> dict:
    """dnet_step_phase_ms + parent dnet_decode_step_ms deltas over the run:
    where a decode step's time went, and how much of the parent the four
    phases account for (`coverage`)."""
    phases = {}
    phase_sum = 0.0
    for ph in STEP_PHASES:
        s = metric_delta(
            after, before, f'dnet_step_phase_ms_sum{{phase="{ph}"}}'
        )
        n = metric_delta(
            after, before, f'dnet_step_phase_ms_count{{phase="{ph}"}}'
        )
        phase_sum += s
        phases[ph] = {
            "sum_ms": round(s, 3),
            "count": int(n),
            "mean_ms": round(s / n, 3) if n else 0.0,
        }
    parent_sum = metric_delta(after, before, "dnet_decode_step_ms_sum")
    parent_n = metric_delta(after, before, "dnet_decode_step_ms_count")
    return {
        "phases": phases,
        # count is TOKENS served (the family's per-token amortization
        # convention); the phases' counts are dispatches
        "decode_step": {
            "sum_ms": round(parent_sum, 3),
            "count": int(parent_n),
        },
        # fraction of the parent decode-step wall the phases explain; 0
        # when phases were not recorded (dense path / obs disabled)
        "coverage": round(phase_sum / parent_sum, 4) if parent_sum else 0.0,
    }


def _jit_summary(
    after: Dict[str, float], before: Optional[Dict[str, float]]
) -> dict:
    compiles: Dict[str, int] = {}
    for key, val in after.items():
        m = re.match(r'dnet_jit_compiles_total\{fn="([^"]+)"\}$', key)
        if m:
            d = val - (before or {}).get(key, 0.0)
            if d:
                compiles[m.group(1)] = int(d)
    return {
        "compiles": compiles,
        "compile_ms_sum": round(
            metric_delta(after, before, "dnet_jit_compile_ms_sum"), 1
        ),
        "compile_count": int(
            metric_delta(after, before, "dnet_jit_compile_ms_count")
        ),
    }


def _device_mem(after: Dict[str, float]) -> dict:
    return {
        kind: after.get(f'dnet_device_mem_bytes{{kind="{kind}"}}', 0.0)
        for kind in DEVICE_MEM_KINDS
    }


def _critical_path_summary(completed: List[RequestOutcome]) -> dict:
    """Aggregate the per-request segment ledgers (obs/critical_path.py)
    carried by profile=true final chunks: per-segment mean/p95 over the
    completed rows, plus which segment DOMINATED each request — the
    run-level answer to "where did the latency go"."""
    ledgers = [o.critical_path for o in completed if o.critical_path]
    segments = {}
    for seg in REQUEST_SEGMENTS:
        vals = [float((lg.get("segments_ms") or {}).get(seg, 0.0))
                for lg in ledgers]
        segments[seg] = {
            "mean_ms": round(sum(vals) / len(vals), 3) if vals else 0.0,
            "p95_ms": round(percentile(vals, 0.95), 3),
            "sum_ms": round(sum(vals), 3),
        }
    dominant: Dict[str, int] = {}
    for lg in ledgers:
        seg = lg.get("dominant") or "other"
        dominant[seg] = dominant.get(seg, 0) + 1
    coverages = [float(lg.get("coverage", 0.0)) for lg in ledgers]
    return {
        "requests": len(ledgers),
        "segments": segments,
        "dominant": dominant,
        "coverage_mean": (
            round(sum(coverages) / len(coverages), 4) if coverages else 0.0
        ),
    }


def _fleet_summary(
    measured: List[RequestOutcome],
    completed: List[RequestOutcome],
    shed: List[RequestOutcome],
    failed: List[RequestOutcome],
    window_s: float,
    after: Optional[Dict[str, float]],
    before: Optional[Dict[str, float]],
) -> Optional[dict]:
    """Per-replica breakdown of a fleet-routed run (ISSUE: fleet section).

    Rows are attributed via the `x-dnet-replica` header the front door
    stamps; the routing counters (`dnet_fleet_*`) ride next to them so a
    disagreement between header attribution and the router's own ledger
    is visible in the artifact.  Returns None when the run never touched
    a fleet (no row carries a replica and no fleet counter moved) so
    single-ring reports stay byte-identical.
    """
    replicas = sorted({o.replica for o in measured if o.replica})
    counters = {}
    if after is not None:
        for key in ("affinity_hits", "failovers"):
            d = metric_delta(after, before, f"dnet_fleet_{key}_total")
            if d:
                counters[key] = int(d)
        for reason in ("affinity", "least_loaded", "failover"):
            d = metric_delta(
                after, before,
                f'dnet_fleet_routed_total{{reason="{reason}"}}',
            )
            if d:
                counters.setdefault("routed_by_reason", {})[reason] = int(d)
    if not replicas and not counters:
        return None
    per_replica = {}
    for rid in replicas:
        mine = [o for o in completed if o.replica == rid]
        tokens = sum(o.tokens_out for o in mine)
        per_replica[rid] = {
            "completed": len(mine),
            "shed": sum(1 for o in shed if o.replica == rid),
            "failed": sum(1 for o in failed if o.replica == rid),
            "tokens_out": tokens,
            "tok_s": round(tokens / window_s, 2),
        }
    routed = sum(
        (counters.get("routed_by_reason") or {}).values()
    )
    hits = counters.get("affinity_hits", 0)
    return {
        "replicas": per_replica,
        "counters": counters,
        # fraction of routed requests served by their sticky replica —
        # the prefix-affinity effectiveness number for the bench gate
        "affinity_hit_rate": round(hits / routed, 4) if routed else 0.0,
    }


def _rel_gap(report_v: float, live_v: float) -> float:
    base = max(abs(live_v), 1e-9)
    return round((report_v - live_v) / base, 4)


def build_report(
    outcomes: Iterable[RequestOutcome],
    *,
    spec: WorkloadSpec,
    duration_s: float,
    health: Optional[dict] = None,
    metrics_before: Optional[Dict[str, float]] = None,
    metrics_after: Optional[Dict[str, float]] = None,
    include_rows: bool = True,
    meta: Optional[dict] = None,
) -> dict:
    rows = sorted(outcomes, key=lambda o: o.index)
    warmup = spec.warmup_s
    measured = [o for o in rows if o.t_sched_s >= warmup]
    completed = [o for o in measured if o.ok and o.status == 200]
    shed = [o for o in measured if o.shed]
    failed = [o for o in measured if not o.ok and not o.shed]

    shed_by_status: Dict[str, int] = {}
    shed_by_reason: Dict[str, int] = {}
    for o in shed:
        shed_by_status[str(o.status)] = shed_by_status.get(str(o.status), 0) + 1
        reason = o.shed_reason or "other"
        shed_by_reason[reason] = shed_by_reason.get(reason, 0) + 1

    window_s = max(duration_s - warmup, 1e-9)
    tokens_out = sum(o.tokens_out for o in completed)
    ttfts = [o.ttft_ms for o in completed]
    itls = [ms for o in completed for ms in o.itl_ms]
    e2es = [o.e2e_ms for o in completed]

    report = {
        "kind": "BENCH_SERVE",
        "spec": spec.as_dict(),
        "duration_s": round(duration_s, 3),
        "measured_window_s": round(window_s, 3),
        "requests": {
            "scheduled": len(rows),
            "measured": len(measured),
            "warmup_excluded": len(rows) - len(measured),
            "completed": len(completed),
            "shed": sum(shed_by_status.values()),
            "failed": len(failed),
            "shed_by_status": shed_by_status,
            "shed_by_reason": shed_by_reason,
            "shed_rate": round(len(shed) / len(measured), 4) if measured else 0.0,
            # server-assigned rids of failed rows: paste one into
            # /v1/debug/events?rid= or /v1/debug/timeline/{rid} for the
            # postmortem (shed-at-the-gate rows never got a rid)
            "failed_rids": [o.rid for o in failed if o.rid],
        },
        # goodput: tokens delivered by COMPLETED requests only, over the
        # measured window — shed and failed rows contribute nothing
        "goodput": {
            "tokens_out": tokens_out,
            "tok_s": round(tokens_out / window_s, 2),
            "requests_per_s": round(len(completed) / window_s, 3),
        },
        "latency_ms": {
            "ttft": _latency_summary(ttfts),
            "tpot": _latency_summary(itls),
            "e2e": _latency_summary(e2es),
        },
        "critical_path": _critical_path_summary(completed),
    }
    # client-observed availability over requests that were ADMITTED (shed
    # rows never enter the server's availability window either — admission
    # rejections happen before the SLO tracker sees the request)
    admitted = len(completed) + len(failed)
    report["availability"] = (
        round(len(completed) / admitted, 4) if admitted else 1.0
    )

    if health is not None and isinstance(health.get("slo"), dict) and measured:
        slo = health["slo"]
        live = {s["name"]: s for s in slo.get("slos", [])}
        cross = {}
        if "ttft_p95_ms" in live:
            lv = live["ttft_p95_ms"]["value"]
            cross["ttft_p95_ms"] = {
                "report": round(percentile(ttfts, 0.95), 3),
                "live": lv,
                "rel_gap": _rel_gap(percentile(ttfts, 0.95), lv),
            }
        if "decode_p95_ms" in live:
            lv = live["decode_p95_ms"]["value"]
            cross["decode_p95_ms"] = {
                # client-side peer of the server's decode-step window is
                # the inter-token latency
                "report": round(percentile(itls, 0.95), 3),
                "live": lv,
                "rel_gap": _rel_gap(percentile(itls, 0.95), lv),
            }
        if "availability" in live:
            lv = live["availability"]["value"]
            cross["availability"] = {
                "report": report["availability"],
                "live": lv,
                "rel_gap": _rel_gap(report["availability"], lv),
            }
        p99 = slo.get("p99") or {}
        report["slo"] = {
            "live": slo,
            "cross_check": cross,
            "live_p99": p99,
            "report_p99": {
                "ttft_ms": round(percentile(ttfts, 0.99), 3),
                "tpot_ms": round(percentile(itls, 0.99), 3),
            },
            "attained": not slo.get("burning"),
            "burning": slo.get("burning", []),
        }

    fleet = _fleet_summary(
        measured, completed, shed, failed, window_s,
        metrics_after, metrics_before,
    )
    if fleet is not None:
        report["fleet"] = fleet

    if metrics_after is not None:
        report["phase_attribution"] = _phase_summary(
            metrics_after, metrics_before
        )
        report["jit"] = _jit_summary(metrics_after, metrics_before)
        report["device_mem_bytes"] = _device_mem(metrics_after)
    if meta:
        report["meta"] = meta
    if include_rows:
        report["rows"] = [o.as_dict() for o in rows]
    return report
