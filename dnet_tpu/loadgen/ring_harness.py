"""In-process two-shard ring behind the real API serving stack.

The wire-pipeline proof harness: two ShardRuntimes (real compute threads,
real ShardCompute engines) wired into a ring by RingAdapters whose gRPC
channel layer is replaced with direct in-process calls — every frame still
crosses the full protocol surface (ActivationFrame bytes are built, codec
tags parsed, ACKs returned, epochs checked), only the sockets are gone.
On top sits the REAL RingApiAdapter + InferenceManager + ApiHTTPServer, so
an aiohttp client (loadgen, tests) exercises the identical admission/SSE/
driver path a remote deployment would.

Used by tests/subsystems/test_wire_pipeline.py (byte-identical SSE parity
legacy-vs-pipelined) and `bench_serve.py --ring-inproc` (BENCH_SERVE_r04:
legacy vs overlapped wire on the seeded r01-r03 workload).  Per-edge frame
accounting (`RingWireStats`) gives the per-hop tx bytes the report embeds:
hidden activation hops are the "inter-hop bytes" the qsparse8 codec is
supposed to shrink, token/continuation frames are counted separately so
they cannot dilute the ratio.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Dict, Optional

from dnet_tpu.transport.protocol import (
    ActivationFrame,
    Empty,
    HealthInfo,
    LatencyProbe,
    StreamAck,
)
from dnet_tpu.utils.logger import get_logger

log = get_logger()


@dataclass
class RingWireStats:
    """Per-edge frame accounting, split by payload kind."""

    hidden_bytes: Dict[str, int] = field(default_factory=dict)
    hidden_frames: Dict[str, int] = field(default_factory=dict)
    token_bytes: Dict[str, int] = field(default_factory=dict)
    token_frames: Dict[str, int] = field(default_factory=dict)
    by_codec: Dict[str, int] = field(default_factory=dict)

    def record(self, edge: str, frame: ActivationFrame) -> None:
        n = len(frame.payload or b"")
        if frame.dtype == "tokens":
            self.token_bytes[edge] = self.token_bytes.get(edge, 0) + n
            self.token_frames[edge] = self.token_frames.get(edge, 0) + 1
            return
        self.hidden_bytes[edge] = self.hidden_bytes.get(edge, 0) + n
        self.hidden_frames[edge] = self.hidden_frames.get(edge, 0) + 1
        codec = frame.codec or frame.dtype
        self.by_codec[codec] = self.by_codec.get(codec, 0) + n

    def as_dict(self) -> dict:
        return {
            "hidden_bytes": dict(self.hidden_bytes),
            "hidden_frames": dict(self.hidden_frames),
            "token_bytes": dict(self.token_bytes),
            "token_frames": dict(self.token_frames),
            "by_codec": dict(self.by_codec),
        }


class _InprocStreamCall:
    """Stands in for a grpc aio stream-stream call: write() delivers the
    frame straight into the receiving adapter's ingress and queues the
    returned ACK for the reader task."""

    def __init__(self, deliver) -> None:
        self._deliver = deliver  # async (frame) -> StreamAck
        self.acks: asyncio.Queue = asyncio.Queue()

    async def write(self, frame: ActivationFrame) -> None:
        ack = await self._deliver(frame)
        if isinstance(ack, StreamAck):
            await self.acks.put(ack)

    async def read(self):
        return await self.acks.get()

    async def done_writing(self) -> None:
        return None


class _InprocRingClient:
    """RingClient replacement: frames/resets land on the target adapter
    in-process (full protocol semantics, no sockets)."""

    def __init__(self, target_adapter, edge: str, stats: RingWireStats) -> None:
        self._adapter = target_adapter
        self._edge = edge
        self._stats = stats

    def open_stream(self) -> _InprocStreamCall:
        return _InprocStreamCall(self._deliver)

    async def _deliver(self, frame: ActivationFrame) -> StreamAck:
        self._stats.record(self._edge, frame)
        ok, msg = await self._adapter.ingress_frame(frame)
        return StreamAck(nonce=frame.nonce, seq=frame.seq, ok=ok, message=msg)

    async def send_activation(self, frame, timeout=10.0):
        return await self._deliver(frame)

    async def health_check(self, timeout=5.0):
        return HealthInfo(ok=True)

    async def reset_cache(self, nonce="", timeout=10.0, epoch=0):
        await self._adapter.reset_cache(nonce)
        return Empty()

    async def measure_latency(self, probe, timeout=30.0):
        return LatencyProbe(t_sent=probe.t_sent, payload=probe.payload)

    async def close(self):
        return None


class _InprocCallbackClient:
    """ApiCallbackClient replacement: the tail shard's SendToken resolves
    straight into the API adapter (what the gRPC servicer would do)."""

    def __init__(self, resolve) -> None:
        self._resolve = resolve

    async def send_token(self, payload, timeout=3.0):
        self._resolve(payload.to_result())
        return Empty()

    async def close(self):
        return None


class _RingManagerFacade:
    """The slice of the model-manager surface ApiHTTPServer touches for a
    pre-loaded in-process ring (health + model identity; load/unload are
    the harness's job, not the HTTP client's)."""

    def __init__(self, inference, ring: "InprocRing") -> None:
        self.inference = inference
        self._ring = ring

    @property
    def current_model_id(self) -> Optional[str]:
        return self.inference.model_id

    def is_model_available(self, model_id: str) -> bool:
        return model_id == self.inference.model_id

    async def load_model(self, model_id: str, max_seq: Optional[int] = None) -> float:
        raise RuntimeError(
            "the in-process ring harness pre-loads its model; "
            "use InprocRing.start()"
        )

    async def unload_model(self) -> None:
        return None


class InprocRing:
    """Two real shards + real ring/API adapters + the real HTTP app."""

    def __init__(
        self,
        model_dir: str,
        layers0=(0, 1),
        layers1=(2, 3),
        max_seq: int = 64,
        param_dtype: str = "float32",
        wire_codec: str = "",
        auto_steps: int = 16,
        max_concurrent: int = 8,
        request_timeout_s: float = 120.0,
        tp: int = 0,
        tp_collective: str = "",
    ) -> None:
        from dnet_tpu.shard.adapter import RingAdapter
        from dnet_tpu.shard.runtime import ShardRuntime

        self.model_dir = str(model_dir)
        self.layers0, self.layers1 = list(layers0), list(layers1)
        self.max_seq = max_seq
        self.param_dtype = param_dtype
        self.wire_codec = wire_codec
        # NamedSharding TP per shard (parallel/tp.py): each ShardCompute
        # drives `tp` forced-host devices; 1 pins today's single-chip
        # shards, 0 defers to the DNET_TP shard default.  tp_collective
        # pins the collective mode for BOTH shards ("" = the
        # DNET_TP_COLLECTIVE default resolution).
        self.tp = max(int(tp), 0)
        self.tp_collective = tp_collective
        self.auto_steps = auto_steps
        self.max_concurrent = max_concurrent
        self.request_timeout_s = request_timeout_s
        self.stats = RingWireStats()
        self.s0 = ShardRuntime("s0")
        self.s1 = ShardRuntime("s1")
        self.a0 = RingAdapter(
            self.s0,
            ring_client_factory=lambda addr: _InprocRingClient(
                self.a1, "s0->s1", self.stats
            ),
            callback_client_factory=lambda addr: _InprocCallbackClient(
                self._resolve_token
            ),
        )
        self.a1 = RingAdapter(
            self.s1,
            ring_client_factory=lambda addr: _InprocRingClient(
                self.a0, "s1->s0", self.stats
            ),
            callback_client_factory=lambda addr: _InprocCallbackClient(
                self._resolve_token
            ),
        )
        self.api = None  # RingApiAdapter, built in start()
        self.inference = None
        self.manager = None
        self.server = None

    def _resolve_token(self, result) -> None:
        if self.api is not None:
            self.api.resolve_token(result)

    async def start(self) -> None:
        from dnet_tpu.api.http import ApiHTTPServer
        from dnet_tpu.api.inference import InferenceManager
        from dnet_tpu.api.ring import RingApiAdapter
        from dnet_tpu.utils.tokenizer import load_tokenizer

        loop = asyncio.get_running_loop()
        self.s0.start(loop)
        self.s1.start(loop)
        await self.a0.start()
        await self.a1.start()
        await asyncio.gather(
            loop.run_in_executor(
                None,
                lambda: self.s0.load_model_core(
                    self.model_dir, self.layers0, max_seq=self.max_seq,
                    param_dtype=self.param_dtype, wire_codec=self.wire_codec,
                    tp_degree=self.tp, tp_collective=self.tp_collective,
                ),
            ),
            loop.run_in_executor(
                None,
                lambda: self.s1.load_model_core(
                    self.model_dir, self.layers1, max_seq=self.max_seq,
                    param_dtype=self.param_dtype, wire_codec=self.wire_codec,
                    tp_degree=self.tp, tp_collective=self.tp_collective,
                ),
            ),
        )
        # fully wired ring: tail -> head carries decode-grant continuations
        self.a0.configure_topology("s1:1")
        self.a1.configure_topology("s0:1")
        self.api = RingApiAdapter(
            head_addr="s0:1",
            callback_url="grpc://api:1",
            shard_grpc_addrs=["s0:1", "s1:1"],
            ring_client_factory=lambda addr: _InprocRingClient(
                self.a0, "api->s0", self.stats
            ),
            max_seq_len=self.max_seq,
            auto_steps=self.auto_steps,
        )
        await self.api.start()
        self.inference = InferenceManager(
            adapter=self.api,
            request_timeout_s=self.request_timeout_s,
            max_concurrent=self.max_concurrent,
        )
        self.inference.tokenizer = load_tokenizer(self.model_dir)
        self.inference.model_id = "inproc-ring"
        self.manager = _RingManagerFacade(self.inference, self)
        self.server = ApiHTTPServer(self.inference, self.manager)

    @property
    def app(self):
        return self.server.app

    async def stop(self) -> None:
        if self.api is not None:
            await self.api.shutdown()
        await self.a0.shutdown()
        await self.a1.shutdown()
        self.s0.stop()
        self.s1.stop()
        # free both engines (two per run adds up across parity runs)
        for rt in (self.s0, self.s1):
            if rt.compute is not None:
                rt.compute.engine.close()
                rt.compute = None
