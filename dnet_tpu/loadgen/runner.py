"""Open-loop run orchestration: schedule -> concurrent clients -> report.

`run_load` drives one seeded workload against a live server through any
aiohttp-compatible session, bracketing the run with `/metrics` scrapes (for
the phase/JIT attribution deltas) and closing with a `/health` fetch (for
the live SLO cross-check).  Arrivals are open-loop: every planned request
gets its own task that sleeps until its scheduled offset and fires
regardless of how many are still in flight — backpressure shows up as shed
rows, not as a silently stretched schedule.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from dnet_tpu.loadgen.client import RequestOutcome, run_request
from dnet_tpu.loadgen.report import build_report, parse_prometheus
from dnet_tpu.loadgen.workload import PlannedRequest, WorkloadSpec, schedule


@dataclass
class LoadResult:
    outcomes: List[RequestOutcome]
    report: dict
    duration_s: float


async def _scrape_metrics(session) -> Optional[Dict[str, float]]:
    try:
        resp = await session.get("/metrics")
        text = await resp.text()
        if resp.status != 200:
            return None
        return parse_prometheus(text)
    except Exception:
        return None


async def _fetch_health(session) -> Optional[dict]:
    try:
        resp = await session.get("/health")
        return await resp.json()
    except Exception:
        return None


async def run_load(
    session,
    spec: WorkloadSpec,
    model: str,
    *,
    path: str = "/v1/chat/completions",
    include_rows: bool = True,
    meta: Optional[dict] = None,
    on_outcome=None,
) -> LoadResult:
    """Execute the spec's full schedule and build the BENCH_SERVE report."""
    plan = schedule(spec)
    metrics_before = await _scrape_metrics(session)
    t0 = time.perf_counter()

    async def fire(p: PlannedRequest) -> RequestOutcome:
        delay = p.t_s - (time.perf_counter() - t0)
        if delay > 0:
            await asyncio.sleep(delay)
        out = await run_request(
            session, p, model, t0, path=path, timeout_s=spec.timeout_s
        )
        if on_outcome is not None:
            on_outcome(out)
        return out

    outcomes = list(await asyncio.gather(*(fire(p) for p in plan)))
    duration_s = time.perf_counter() - t0
    # /health FIRST: its snapshot() refresh is what also makes the metrics
    # scrape's slo gauges current for the same instant
    health = await _fetch_health(session)
    metrics_after = await _scrape_metrics(session)
    report = build_report(
        outcomes,
        spec=spec,
        duration_s=duration_s,
        health=health,
        metrics_before=metrics_before,
        metrics_after=metrics_after,
        include_rows=include_rows,
        meta=meta,
    )
    return LoadResult(outcomes=outcomes, report=report, duration_s=duration_s)
