"""BENCH_SERVE record comparison: two reports -> deltas + regression gate.

The math behind ``scripts/bench_compare.py``.  A record is either FLAT
(one ``build_report`` dict, r01–r03 shape) or MULTI-LEG (named legs each
holding a report, r04/r05 shape: ``legacy``/``pipelined``/...); legs are
matched by name across the two records and each matched pair yields a
delta block covering goodput, client latency percentiles, shed/failure
breakdowns, server-side phase attribution, and the aggregated
critical-path segment ledger (this PR's ``critical_path`` section).

Regression thresholds are DIRECTIONAL: ``--fail-on
latency_ms.e2e.p95_ms=+10%`` fails when the dotted metric ROSE more than
10% (a latency regression), ``--fail-on goodput.tok_s=-5%`` fails when
it FELL more than 5% (a throughput regression).  Absolute limits drop
the ``%`` (``+50`` = fail past a 50-unit rise).  The sign names the bad
direction, so a gate never fires on an improvement.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

#: dotted paths diffed for every matched leg pair (present-in-both only)
DELTA_PATHS = (
    "goodput.tok_s",
    "goodput.requests_per_s",
    "goodput.tokens_out",
    "availability",
    "requests.completed",
    "requests.shed",
    "requests.failed",
    "requests.shed_rate",
    "latency_ms.ttft.p50_ms",
    "latency_ms.ttft.p95_ms",
    "latency_ms.ttft.p99_ms",
    "latency_ms.tpot.p50_ms",
    "latency_ms.tpot.p95_ms",
    "latency_ms.tpot.p99_ms",
    "latency_ms.e2e.p50_ms",
    "latency_ms.e2e.p95_ms",
    "latency_ms.e2e.p99_ms",
)


def lookup(record: dict, path: str) -> Optional[float]:
    """Dotted-path numeric lookup (None when absent or non-numeric)."""
    node = record
    for part in path.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    if isinstance(node, bool) or not isinstance(node, (int, float)):
        return None
    return float(node)


def legs(record: dict) -> Dict[str, dict]:
    """Extract comparable legs: a flat report is one unnamed leg; a
    multi-leg record contributes every sub-dict that looks like a report
    (has ``latency_ms``)."""
    if "latency_ms" in record:
        return {"": record}
    return {
        k: v
        for k, v in record.items()
        if isinstance(v, dict) and "latency_ms" in v
    }


@dataclass(frozen=True)
class FailRule:
    """One ``--fail-on path=<sign><limit>[%]`` regression gate."""

    path: str
    direction: int  # +1 = fail on rise, -1 = fail on fall
    limit: float  # magnitude of the allowed change in the bad direction
    relative: bool  # True when the limit is a fraction of the old value

    def describe(self) -> str:
        arrow = "rise" if self.direction > 0 else "fall"
        lim = f"{self.limit * 100:g}%" if self.relative else f"{self.limit:g}"
        return f"{self.path} may not {arrow} more than {lim}"


_RULE_RE = re.compile(
    r"^(?P<path>[A-Za-z0-9_.]+)=(?P<sign>[+-])(?P<limit>[0-9.]+)(?P<pct>%?)$"
)


def parse_fail_rule(spec: str) -> FailRule:
    m = _RULE_RE.match(spec.strip())
    if m is None:
        raise ValueError(
            f"bad --fail-on spec {spec!r} "
            "(want path=+10% / path=-5% / path=+50)"
        )
    limit = float(m.group("limit"))
    relative = bool(m.group("pct"))
    if relative:
        limit /= 100.0
    return FailRule(
        path=m.group("path"),
        direction=1 if m.group("sign") == "+" else -1,
        limit=limit,
        relative=relative,
    )


def rule_violation(
    rule: FailRule, old: dict, new: dict
) -> Optional[str]:
    """None when the gate holds; a human-readable violation otherwise.
    A path missing from either leg is a violation too — a silently
    ungated metric is how regressions sneak past CI."""
    ov, nv = lookup(old, rule.path), lookup(new, rule.path)
    if ov is None or nv is None:
        missing = "old" if ov is None else "new"
        return f"{rule.path}: missing from {missing} record"
    change = nv - ov
    if rule.relative:
        if abs(ov) < 1e-12:
            # no baseline to scale by: only a change in the bad
            # direction at all can violate a relative rule
            bad = change * rule.direction > 0
            frac = float("inf") if bad else 0.0
        else:
            frac = change / abs(ov)
            bad = frac * rule.direction > rule.limit
        if bad:
            return (
                f"{rule.path}: {ov:g} -> {nv:g} "
                f"({frac * 100:+.1f}% vs limit "
                f"{rule.direction * rule.limit * 100:+g}%)"
            )
        return None
    if change * rule.direction > rule.limit:
        return (
            f"{rule.path}: {ov:g} -> {nv:g} "
            f"({change:+g} vs limit {rule.direction * rule.limit:+g})"
        )
    return None


def _delta_entry(ov: float, nv: float) -> dict:
    entry = {"old": ov, "new": nv, "delta": round(nv - ov, 4)}
    if abs(ov) > 1e-12:
        entry["rel"] = round((nv - ov) / abs(ov), 4)
    return entry


def diff_leg(old: dict, new: dict) -> dict:
    """Structured delta for one matched leg pair."""
    out: dict = {"metrics": {}}
    for path in DELTA_PATHS:
        ov, nv = lookup(old, path), lookup(new, path)
        if ov is None or nv is None:
            continue
        out["metrics"][path] = _delta_entry(ov, nv)

    # shed-reason breakdown: union of reasons, absent reads as 0
    o_shed = (old.get("requests") or {}).get("shed_by_reason") or {}
    n_shed = (new.get("requests") or {}).get("shed_by_reason") or {}
    reasons = sorted(set(o_shed) | set(n_shed))
    if reasons:
        out["shed_by_reason"] = {
            r: _delta_entry(float(o_shed.get(r, 0)), float(n_shed.get(r, 0)))
            for r in reasons
        }

    # phase attribution: per-phase mean_ms movement
    o_ph = ((old.get("phase_attribution") or {}).get("phases")) or {}
    n_ph = ((new.get("phase_attribution") or {}).get("phases")) or {}
    phases = sorted(set(o_ph) & set(n_ph))
    if phases:
        out["phase_mean_ms"] = {
            ph: _delta_entry(
                float(o_ph[ph].get("mean_ms", 0.0)),
                float(n_ph[ph].get("mean_ms", 0.0)),
            )
            for ph in phases
        }

    # critical-path segment ledger: per-segment mean movement + the
    # dominant-segment population shift
    o_cp = (old.get("critical_path") or {}).get("segments") or {}
    n_cp = (new.get("critical_path") or {}).get("segments") or {}
    segs = sorted(set(o_cp) & set(n_cp))
    if segs:
        out["critical_path_mean_ms"] = {
            seg: _delta_entry(
                float(o_cp[seg].get("mean_ms", 0.0)),
                float(n_cp[seg].get("mean_ms", 0.0)),
            )
            for seg in segs
        }
        o_dom = (old.get("critical_path") or {}).get("dominant") or {}
        n_dom = (new.get("critical_path") or {}).get("dominant") or {}
        out["dominant"] = {
            seg: _delta_entry(
                float(o_dom.get(seg, 0)), float(n_dom.get(seg, 0))
            )
            for seg in sorted(set(o_dom) | set(n_dom))
        }
    return out


def compare_records(
    old: dict,
    new: dict,
    rules: Tuple[FailRule, ...] = (),
    leg: Optional[str] = None,
) -> dict:
    """Full comparison: match legs, diff each pair, evaluate the gates.

    ``leg`` restricts to one named leg (must exist in both).  Gates run
    against every matched leg — a regression in ANY leg fails.  Rules
    whose path starts with ``comparison.`` are RECORD-level: they gate
    the multi-leg record's own cross-leg summary (e.g. the fleet
    record's ``comparison.goodput_ratio``, the 2-replica/1-replica
    scaling multiple) instead of being looked up inside each leg."""
    leg_rules = tuple(
        r for r in rules if not r.path.startswith("comparison.")
    )
    record_rules = tuple(r for r in rules if r.path.startswith("comparison."))
    o_legs, n_legs = legs(old), legs(new)
    if leg is not None:
        if leg not in o_legs or leg not in n_legs:
            raise ValueError(
                f"leg {leg!r} not present in both records "
                f"(old has {sorted(o_legs)}, new has {sorted(n_legs)})"
            )
        o_legs = {leg: o_legs[leg]}
        n_legs = {leg: n_legs[leg]}
    matched = sorted(set(o_legs) & set(n_legs))
    violations: List[str] = []
    legs_out = {}
    for name in matched:
        d = diff_leg(o_legs[name], n_legs[name])
        for rule in leg_rules:
            v = rule_violation(rule, o_legs[name], n_legs[name])
            if v is not None:
                violations.append(f"[{name or 'report'}] {v}")
        legs_out[name or "report"] = d
    if matched:
        for rule in record_rules:
            v = rule_violation(rule, old, new)
            if v is not None:
                violations.append(f"[record] {v}")
    return {
        "legs": legs_out,
        "unmatched_old": sorted(set(o_legs) - set(n_legs)),
        "unmatched_new": sorted(set(n_legs) - set(o_legs)),
        "violations": violations,
        "ok": not violations and bool(matched),
    }
