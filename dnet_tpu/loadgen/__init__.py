"""Serving-grade load generation (ROADMAP item 5(b)).

An open-loop, seeded load harness for the OpenAI-compatible serving
surface: `workload` builds deterministic arrival/length schedules,
`client` drives one streaming request to one outcome row, `runner`
orchestrates the fan-out and brackets it with metric scrapes, and
`report` turns the rows into the machine-readable ``BENCH_SERVE_*.json``
artifact every subsequent perf PR reports its before/after through.
`bench_serve.py` (repo root) is the operator entry point.
"""

from dnet_tpu.loadgen.client import RequestOutcome, run_request
from dnet_tpu.loadgen.report import build_report, parse_prometheus, percentile
from dnet_tpu.loadgen.runner import LoadResult, run_load
from dnet_tpu.loadgen.workload import (
    Bucket,
    PlannedRequest,
    WorkloadSpec,
    parse_buckets,
    schedule,
)

__all__ = [
    "Bucket",
    "LoadResult",
    "PlannedRequest",
    "RequestOutcome",
    "WorkloadSpec",
    "build_report",
    "parse_buckets",
    "parse_prometheus",
    "percentile",
    "run_load",
    "run_request",
    "schedule",
]
