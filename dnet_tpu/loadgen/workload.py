"""Seeded workload specs and open-loop arrival schedules.

The schedule is a PURE function of the spec: `schedule(spec)` with the same
seed yields byte-identical arrival times, prompts, and token budgets, so a
load run — and any regression it catches — replays exactly (the same
discipline as the chaos harness).  Arrivals are OPEN-LOOP: each request
fires at its scheduled offset regardless of how the server is keeping up,
which is what makes shed rate and tail latency honest under overload
(closed-loop clients self-throttle and hide both; vLLM-style serving
benchmarks use the same methodology).
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import List, Tuple

# deterministic lexicon for prompt text: lowercase words keep byte-level
# tokenizers exact (1 char = 1 token) and BPE tokenizers close
_WORD_CHARS = "abcdefghijklmnopqrstuvwxyz"


@dataclass(frozen=True)
class Bucket:
    """One length class of the mixed workload."""

    prompt_tokens: int
    max_tokens: int
    weight: float = 1.0

    def __post_init__(self) -> None:
        if self.prompt_tokens < 1 or self.max_tokens < 1:
            raise ValueError("bucket lengths must be >= 1")
        if self.weight <= 0:
            raise ValueError("bucket weight must be > 0")


def parse_buckets(spec: str, weights: str = "") -> Tuple[Bucket, ...]:
    """``"8:16,32:8"`` (+ optional ``"3,1"`` weights) -> Bucket tuple."""
    parts = [p.strip() for p in spec.split(",") if p.strip()]
    if not parts:
        raise ValueError("empty bucket spec")
    ws = [w.strip() for w in weights.split(",") if w.strip()]
    if ws and len(ws) != len(parts):
        raise ValueError(
            f"{len(ws)} weights for {len(parts)} buckets"
        )
    out = []
    for i, part in enumerate(parts):
        try:
            p, _, m = part.partition(":")
            out.append(
                Bucket(
                    prompt_tokens=int(p),
                    max_tokens=int(m),
                    weight=float(ws[i]) if ws else 1.0,
                )
            )
        except ValueError as exc:
            raise ValueError(
                f"bad bucket {part!r} (want prompt:max_tokens): {exc}"
            ) from exc
    return tuple(out)


@dataclass(frozen=True)
class PlannedRequest:
    """One scheduled request of the open-loop run."""

    index: int
    t_s: float  # arrival offset from run start
    prompt: str
    prompt_tokens: int  # the bucket's nominal prompt length
    max_tokens: int
    temperature: float = 0.0
    seed: int = 0  # per-request sampling seed (deterministic streams)


@dataclass(frozen=True)
class WorkloadSpec:
    seed: int = 0
    requests: int = 64
    rate_rps: float = 8.0
    arrival: str = "poisson"  # poisson | fixed
    buckets: Tuple[Bucket, ...] = (
        Bucket(8, 16), Bucket(32, 8), Bucket(64, 4),
    )
    temperature: float = 0.0
    warmup_s: float = 0.0
    timeout_s: float = 120.0

    def __post_init__(self) -> None:
        if self.requests < 1:
            raise ValueError("requests must be >= 1")
        if self.rate_rps <= 0:
            raise ValueError("rate_rps must be > 0")
        if self.arrival not in ("poisson", "fixed"):
            raise ValueError(
                f"arrival must be poisson|fixed, got {self.arrival!r}"
            )
        if not self.buckets:
            raise ValueError("spec needs at least one bucket")

    @classmethod
    def from_settings(cls, settings=None) -> "WorkloadSpec":
        """Resolve from the DNET_LOADGEN_* group."""
        if settings is None:
            from dnet_tpu.config import get_settings

            settings = get_settings().loadgen
        return cls(
            seed=settings.seed,
            requests=settings.requests,
            rate_rps=settings.rate_rps,
            arrival=settings.arrival,
            buckets=parse_buckets(settings.buckets, settings.weights),
            temperature=settings.temperature,
            warmup_s=settings.warmup_s,
            timeout_s=settings.timeout_s,
        )

    def as_dict(self) -> dict:
        return {
            "seed": self.seed,
            "requests": self.requests,
            "rate_rps": self.rate_rps,
            "arrival": self.arrival,
            "buckets": [
                {"prompt_tokens": b.prompt_tokens,
                 "max_tokens": b.max_tokens, "weight": b.weight}
                for b in self.buckets
            ],
            "temperature": self.temperature,
            "warmup_s": self.warmup_s,
            "timeout_s": self.timeout_s,
        }


def _prompt_text(rng: random.Random, n_tokens: int) -> str:
    """Deterministic prose of exactly `n_tokens` characters: words of 2-8
    lowercase letters separated by single spaces (every char one token
    under a byte-level tokenizer; close under BPE)."""
    chars: List[str] = []
    while len(chars) < n_tokens:
        remaining = n_tokens - len(chars)
        if remaining <= 2:
            chars.extend(rng.choice(_WORD_CHARS) for _ in range(remaining))
            break
        w = min(rng.randint(2, 8), remaining - 1 if remaining > 2 else remaining)
        chars.extend(rng.choice(_WORD_CHARS) for _ in range(w))
        if len(chars) < n_tokens:
            chars.append(" ")
    return "".join(chars[:n_tokens])


def schedule(spec: WorkloadSpec) -> List[PlannedRequest]:
    """The full run plan, deterministically derived from the spec."""
    # str seeds hash with a stable algorithm (unlike tuples, whose hash
    # varies per process under PYTHONHASHSEED randomization)
    rng = random.Random(f"dnet-loadgen:{spec.seed}")
    weights = [b.weight for b in spec.buckets]
    t = 0.0
    out: List[PlannedRequest] = []
    for i in range(spec.requests):
        if i > 0:
            if spec.arrival == "poisson":
                t += rng.expovariate(spec.rate_rps)
            else:
                t += 1.0 / spec.rate_rps
        bucket = rng.choices(spec.buckets, weights=weights, k=1)[0]
        out.append(
            PlannedRequest(
                index=i,
                t_s=t,
                prompt=_prompt_text(rng, bucket.prompt_tokens),
                prompt_tokens=bucket.prompt_tokens,
                max_tokens=bucket.max_tokens,
                temperature=spec.temperature,
                seed=rng.randrange(2**31),
            )
        )
    return out
