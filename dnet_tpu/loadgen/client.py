"""One OpenAI-API streaming client request -> one outcome row.

`run_request` drives a single planned request over any aiohttp-compatible
session (a real `aiohttp.ClientSession(base_url=...)` against a live ring,
or an `aiohttp.test_utils.TestClient` for the in-process tier-1 smoke run —
both expose `.post(path, json=...)` returning a streaming response) and
records everything the report needs: HTTP status, shed classification,
TTFT, per-token inter-arrival latencies, tokens out, end-to-end wall time.

Timing is CLIENT-side (send -> SSE chunk arrivals), the latency a caller
actually experiences; the report cross-validates these against the
server-side `dnet_slo_*` gauges.
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import dataclass, field
from typing import List, Optional

from dnet_tpu.loadgen.workload import PlannedRequest
from dnet_tpu.utils.logger import get_logger

log = get_logger()

# admission/overload shed statuses: these rows are SHED (never goodput,
# never availability failures) — everything else non-200 is a failure
SHED_STATUSES = (429, 503, 504)

# message-substring -> shed reason, mirroring the server's typed surfaces
# (admission/reasons.py reject reasons + the backpressure markers of
# api/inference.py)
_REASON_MARKERS = (
    ("queue full", "queue_full"),
    # AdmissionController's queue_timeout rejection reads "no slot within
    # {timeout}s (DNET_ADMIT_QUEUE_TIMEOUT_S)" — match that, not the
    # reason's enum name (which never appears in the message)
    ("no slot within", "queue_timeout"),
    ("draining", "draining"),
    ("deadline", "deadline"),
    ("degraded", "degraded"),
    ("paged KV pool exhausted", "backpressure"),
    ("no free lanes", "backpressure"),
    ("no free batch slots", "backpressure"),
)


def classify_shed(status: int, message: str) -> str:
    """Map a shed response to the admission-reason vocabulary."""
    for marker, reason in _REASON_MARKERS:
        if marker in message:
            return reason
    return {429: "backpressure", 503: "unavailable", 504: "deadline"}.get(
        status, "other"
    )


@dataclass
class RequestOutcome:
    """One row of the load report (ISSUE: one outcome row per request)."""

    index: int
    t_sched_s: float  # planned arrival offset
    t_start_s: float  # actual send offset from run start
    status: int = 0
    ok: bool = False  # 200 AND the stream finished cleanly
    shed: bool = False
    shed_reason: str = ""
    error: str = ""
    finish_reason: str = ""
    ttft_ms: float = 0.0
    e2e_ms: float = 0.0
    tokens_out: int = 0
    prompt_tokens: int = 0
    retry_after_s: float = 0.0
    # server-assigned request id (first SSE chunk's `id`): the join key
    # into /v1/debug/events?rid= and /v1/debug/timeline/{rid} — a failed
    # row's rid is a one-hop postmortem lookup, not a log grep
    rid: str = ""
    # serving replica (fleet front door stamps `x-dnet-replica` on every
    # routed response) — empty on single-ring runs, where no header exists
    replica: str = ""
    itl_ms: List[float] = field(default_factory=list)  # inter-token gaps
    # per-request segment ledger from the final chunk's profile metrics
    # (obs/critical_path.py decompose) — server-side attribution riding
    # next to the client-side timings above
    critical_path: Optional[dict] = None

    def as_dict(self) -> dict:
        d = {
            "index": self.index,
            "t_sched_s": round(self.t_sched_s, 4),
            "t_start_s": round(self.t_start_s, 4),
            "status": self.status,
            "ok": self.ok,
            "ttft_ms": round(self.ttft_ms, 2),
            "e2e_ms": round(self.e2e_ms, 2),
            "tokens_out": self.tokens_out,
            "prompt_tokens": self.prompt_tokens,
        }
        if self.shed:
            d["shed"] = True
            d["shed_reason"] = self.shed_reason
            if self.retry_after_s:
                d["retry_after_s"] = self.retry_after_s
        if self.rid:
            d["rid"] = self.rid
        if self.replica:
            d["replica"] = self.replica
        if self.error:
            d["error"] = self.error[:200]
        if self.finish_reason:
            d["finish_reason"] = self.finish_reason
        if self.critical_path:
            d["critical_path"] = self.critical_path
        return d


def chat_body(planned: PlannedRequest, model: str) -> dict:
    body = {
        "model": model,
        "messages": [{"role": "user", "content": planned.prompt}],
        "max_tokens": planned.max_tokens,
        "temperature": planned.temperature,
        "stream": True,
        # final chunk carries RequestMetrics (incl. the critical-path
        # segment ledger) for the report's attribution section
        "profile": True,
    }
    if planned.temperature > 0:
        body["seed"] = planned.seed
    return body


async def run_request(
    session,
    planned: PlannedRequest,
    model: str,
    t0: float,
    *,
    path: str = "/v1/chat/completions",
    timeout_s: float = 120.0,
) -> RequestOutcome:
    """Execute one planned request NOW (the runner owns the arrival sleep)
    and return its outcome row.  Never raises: transport/timeout errors
    become failed rows so one bad request cannot sink the run."""
    out = RequestOutcome(
        index=planned.index,
        t_sched_s=planned.t_s,
        t_start_s=time.perf_counter() - t0,
    )
    try:
        out_done = asyncio.wait_for(
            _drive(session, planned, model, path, out), timeout_s
        )
        await out_done
    except asyncio.TimeoutError:
        out.ok = False
        out.error = f"client timeout after {timeout_s}s"
    except Exception as exc:  # transport-level failure
        out.ok = False
        out.error = f"{type(exc).__name__}: {exc}"
    return out


async def _drive(session, planned, model, path, out: RequestOutcome) -> None:
    t_send = time.perf_counter()
    resp = await session.post(path, json=chat_body(planned, model))
    try:
        out.status = resp.status
        out.replica = resp.headers.get("x-dnet-replica", "")
        if resp.status != 200:
            out.shed = resp.status in SHED_STATUSES
            try:
                body = await resp.json()
                message = body.get("error", {}).get("message", "")
            except Exception:
                message = ""
            out.error = message or f"HTTP {resp.status}"
            if out.shed:
                out.shed_reason = classify_shed(resp.status, message)
                ra = resp.headers.get("Retry-After")
                if ra is not None:
                    try:
                        out.retry_after_s = float(ra)
                    except ValueError:
                        pass
            return
        t_last: Optional[float] = None
        finished = False
        async for raw in resp.content:
            line = raw.decode("utf-8", "replace").strip()
            if not line.startswith("data:"):
                continue
            payload = line[len("data:"):].strip()
            if payload == "[DONE]":
                finished = True
                break
            try:
                chunk = json.loads(payload)
            except json.JSONDecodeError:
                continue
            if not out.rid and chunk.get("id"):
                out.rid = str(chunk["id"])
            err = chunk.get("error")
            if err:
                # in-band mid-stream error event (post-commit shed/failure)
                out.error = err.get("message", "stream error")
                kind = err.get("type", "")
                if kind in ("deadline_exceeded", "rate_limit_exceeded"):
                    out.shed = True
                    out.status = 504 if kind == "deadline_exceeded" else 429
                    out.shed_reason = classify_shed(out.status, out.error)
                continue
            choices = chunk.get("choices") or []
            delta = (choices[0].get("delta") or {}) if choices else {}
            if delta.get("content"):
                now = time.perf_counter()
                if t_last is None:
                    out.ttft_ms = (now - t_send) * 1000.0
                else:
                    out.itl_ms.append((now - t_last) * 1000.0)
                t_last = now
            if choices and choices[0].get("finish_reason"):
                out.finish_reason = choices[0]["finish_reason"]
            usage = chunk.get("usage")
            if usage:
                out.tokens_out = int(usage.get("completion_tokens", 0))
                out.prompt_tokens = int(usage.get("prompt_tokens", 0))
            metrics = chunk.get("metrics")
            if isinstance(metrics, dict) and metrics.get("critical_path"):
                out.critical_path = metrics["critical_path"]
        out.e2e_ms = (time.perf_counter() - t_send) * 1000.0
        if out.ttft_ms == 0.0 and t_last is None and finished:
            # zero-content stream (immediate EOS): TTFT is the final-chunk
            # arrival — there was never a content token to stamp
            out.ttft_ms = out.e2e_ms
        out.ok = finished and not out.error and not out.shed
        if not finished and not out.error:
            out.error = "stream ended without [DONE]"
    finally:
        release = getattr(resp, "release", None)
        if release is not None:
            try:
                maybe = release()
                if asyncio.iscoroutine(maybe):
                    await maybe
            except Exception as exc:
                # connection-release failure cannot change the sample, but
                # leave a trace (DL007 contract)
                log.debug(
                    "response release failed for request %d: %s",
                    planned.index, exc,
                )
