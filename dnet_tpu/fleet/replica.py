"""One fleet replica: a full serving stack behind a routable handle.

A `ReplicaHandle` wraps an `InferenceManager` (and through it the whole
adapter stack — local engine or pipelined ring) the way the router needs
to see it: a lifecycle state, an epoch fence, and a live load/health
snapshot built from the same signals the single-ring server already
exposes — admission queue depth and service-rate EMA (admission/
controller.py), readiness, and drain state.  The handle owns no
lifecycle itself; `FleetManager` transitions `state` and mints epochs.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

from dnet_tpu.fleet.states import STATE_ACTIVE


class ReplicaHandle:
    """A routable replica: id + inference stack + state + epoch fence.

    `epoch` is minted by the FleetManager's EpochClock at activation and
    never changes; `fence` is the epoch this slot currently honors — the
    manager re-mints it when the replica dies, so `is_stale(fence,
    epoch)` trips on any dispatch through a zombie handle (the same
    fencing token activation frames carry, membership/epoch.py).
    """

    def __init__(self, replica_id: str, inference: Any, epoch: int = 0) -> None:
        self.replica_id = str(replica_id)
        self.inference = inference
        self.state = STATE_ACTIVE
        self.epoch = int(epoch)
        self.fence = int(epoch)

    # ---- routing signals ------------------------------------------------
    @property
    def admission(self):
        return self.inference.admission

    @property
    def serving(self) -> bool:
        """Eligible for new routes: active and not draining admission."""
        return self.state == STATE_ACTIVE and not self.admission.draining

    def load_score(self) -> Tuple[float, float]:
        """Least-loaded sort key: (occupancy, estimated queue wait).

        Occupancy is live slots+waiters over capacity — the admission
        picture right now; the estimated wait (service-time EMA x queue
        position, the Retry-After math) breaks occupancy ties toward the
        replica with more SLO headroom, i.e. the faster queue."""
        adm = self.admission
        occupancy = (adm.active + adm.queued) / max(1, adm.capacity)
        return (occupancy, adm.estimated_wait_s(adm.queued))

    # ---- introspection --------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """The per-replica health/load block /health and /v1/debug/fleet
        aggregate — the federation-style signals, one level up."""
        adm = self.admission
        occupancy, est_wait_s = self.load_score()
        return {
            "replica": self.replica_id,
            "state": self.state,
            "epoch": self.epoch,
            "ready": bool(getattr(self.inference, "ready", False)),
            "admission": {
                "active": adm.active,
                "queued": adm.queued,
                "capacity": adm.capacity,
                "draining": adm.draining,
            },
            "load": round(occupancy, 4),
            "est_wait_s": round(est_wait_s, 4),
        }
