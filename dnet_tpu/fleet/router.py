"""Fleet routing policy: prefix-affinity first, then least-loaded.

The front door's whole job is choosing which ring replica serves a
request, and the order matters:

1. **Prefix affinity** — hash the conversation's leading prefix
   (`kv.prefix.prefix_affinity_key`: turn N+1 of a conversation starts
   with turn N's first message, so the turns collide) and stick the
   session to the replica whose paged pool already holds the shared COW
   prefix blocks.  A cache hit there skips the whole shared-history
   prefill; routing elsewhere silently re-pays it.  The table is a
   bounded LRU; entries pointing at a lost replica are evicted so a
   restarted conversation re-routes by load.
2. **Least-loaded** — no sticky entry (or its replica stopped serving):
   lowest live admission occupancy wins, with the estimated queue wait
   (the service-rate EMA behind Retry-After) breaking ties toward the
   replica with more SLO headroom.

`plan()` returns the FULL candidate order, not one winner: the caller
walks it so a replica that sheds at admission falls through to the next
one, and only when every replica sheds does the request fail — with the
typed `FleetSheddingError` the HTTP layer maps to 429 + Retry-After.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple, Union

from dnet_tpu.api.inference import InferenceError
from dnet_tpu.api.schemas import ChatCompletionRequest, CompletionRequest
from dnet_tpu.fleet.replica import ReplicaHandle
from dnet_tpu.fleet.states import ROUTE_AFFINITY, ROUTE_LEAST_LOADED
from dnet_tpu.kv.prefix import prefix_affinity_key


class FleetSheddingError(InferenceError):
    """Every replica shed the request at admission.  Carries the largest
    Retry-After any replica offered — the soonest ANY slot should open —
    so the HTTP layer answers 429 with an honest backoff."""

    def __init__(self, message: str, retry_after_s: float = 1.0) -> None:
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class AffinityTable:
    """Bounded LRU of prefix-hash -> replica_id.

    Insertion refreshes recency; capacity overflow evicts the coldest
    conversation (its prefix blocks were the likeliest already evicted
    from the replica's pool too).  `evict_replica` drops every entry
    pointing at a lost replica — affinity must never outlive the cache
    it points at."""

    def __init__(self, capacity: int = 512) -> None:
        self.capacity = max(int(capacity), 1)
        self._map: "OrderedDict[str, str]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._map)

    def get(self, key: str) -> Optional[str]:
        rid = self._map.get(key)
        if rid is not None:
            self._map.move_to_end(key)
        return rid

    def put(self, key: str, replica_id: str) -> None:
        self._map[key] = replica_id
        self._map.move_to_end(key)
        while len(self._map) > self.capacity:
            self._map.popitem(last=False)

    def evict_replica(self, replica_id: str) -> int:
        stale = [k for k, v in self._map.items() if v == replica_id]
        for k in stale:
            del self._map[k]
        return len(stale)

    def snapshot(self) -> Dict[str, str]:
        return dict(self._map)


class FleetRouter:
    """The routing decision, separated from replica lifecycle (manager.py)
    so the policy is unit-testable on fake handles."""

    def __init__(self, affinity_capacity: int = 512, prefix_units: int = 256) -> None:
        self.affinity = AffinityTable(affinity_capacity)
        self.prefix_units = max(int(prefix_units), 1)

    def affinity_key(
        self, req: Union[ChatCompletionRequest, CompletionRequest]
    ) -> str:
        """The conversation identity: the FIRST message's leading text
        (chat — every later turn of the conversation still starts with
        it) or the prompt head (completions)."""
        if isinstance(req, ChatCompletionRequest):
            text = req.messages[0].text()
        else:
            p = req.prompt
            text = p if isinstance(p, str) else (p[0] if p else "")
        return prefix_affinity_key(text, self.prefix_units)

    def plan(
        self, key: str, handles: Sequence[ReplicaHandle]
    ) -> List[Tuple[ReplicaHandle, str]]:
        """Ordered (replica, reason) candidates for one request.

        Affinity target first when it is still serving (a stale entry —
        replica gone or not serving — is evicted instead); the rest
        least-loaded.  Raises `FleetSheddingError` only when NO replica
        is serving at all; per-replica admission sheds are the caller's
        walk-the-list business."""
        serving = [h for h in handles if h.serving]
        if not serving:
            raise FleetSheddingError("no serving replica in the fleet")
        by_id = {h.replica_id: h for h in serving}
        plan: List[Tuple[ReplicaHandle, str]] = []
        sticky = self.affinity.get(key)
        if sticky is not None:
            if sticky in by_id:
                plan.append((by_id[sticky], ROUTE_AFFINITY))
            else:
                self.affinity.evict_replica(sticky)
        rest = sorted(
            (h for h in serving if not plan or h is not plan[0][0]),
            key=lambda h: (h.load_score(), h.replica_id),
        )
        plan.extend((h, ROUTE_LEAST_LOADED) for h in rest)
        return plan

    def record(self, key: str, replica_id: str) -> None:
        """Stick the conversation to the replica that just served it —
        its pool now holds the prefix blocks the next turn reuses."""
        self.affinity.put(key, replica_id)
