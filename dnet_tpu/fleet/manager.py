"""FleetManager: replica lifecycle + routed dispatch + mid-stream failover.

The manager owns what the router must not: the replica table, the epoch
clock, and the dispatch loop that walks the router's candidate order.

- **Lifecycle** — `add_replica` mints a topology epoch for the new
  handle (the same `EpochClock` fencing token activation frames carry);
  `drain` flips the replica's admission into drain (in-flight work
  finishes, no new routes); `fail_replica` marks it dead AND re-mints
  its fence so any dispatch through a stale handle trips the counted
  `fleet_route` stale-epoch rejection — a zombie replica cannot serve.
- **Dispatch** — `stream()` walks the candidate plan: a replica that
  sheds at admission falls through to the next one; only when every
  replica sheds does the request fail with `FleetSheddingError`
  (HTTP 429 + the largest Retry-After any replica offered).
- **Failover** — a replica marked dead mid-stream is abandoned between
  chunks and the SAME request re-admitted to a survivor.  Decode is
  deterministic under greedy/seeded sampling (the PR 4 replay
  invariant), so the survivor regenerates the identical text and the
  wrapper suppresses the first `emitted` characters — the client's
  committed SSE stream continues seamlessly, no 5xx, one `failover`
  wide event and `dnet_fleet_failovers_total` tick per migration.
"""

from __future__ import annotations

from typing import Any, AsyncIterator, Dict, List, Optional, Set, Tuple

from dnet_tpu.admission.controller import AdmissionRejected
from dnet_tpu.api.schemas import ChatCompletionChunk
from dnet_tpu.config import get_settings
from dnet_tpu.fleet.replica import ReplicaHandle
from dnet_tpu.fleet.router import FleetRouter, FleetSheddingError
from dnet_tpu.fleet.states import (
    REPLICA_STATES,
    ROUTE_AFFINITY,
    ROUTE_FAILOVER,
    STATE_ACTIVE,
    STATE_DEAD,
    STATE_DRAINING,
    STATE_QUARANTINED,
)
from dnet_tpu.membership.epoch import EpochClock, is_stale, reject
from dnet_tpu.obs import metric
from dnet_tpu.obs.events import log_event
from dnet_tpu.resilience.chaos import inject_async as _chaos_inject
from dnet_tpu.obs.phases import EVENT_FAILOVER, EVENT_ROUTED
from dnet_tpu.utils.logger import get_logger

log = get_logger()


class _ReplicaLost(Exception):
    """Internal: the serving replica was fenced mid-stream."""


class FleetManager:
    def __init__(
        self,
        router: Optional[FleetRouter] = None,
        failover: Optional[bool] = None,
    ) -> None:
        s = get_settings().fleet
        self.router = router or FleetRouter(
            affinity_capacity=s.fleet_affinity_capacity,
            prefix_units=s.fleet_affinity_prefix,
        )
        self.failover_enabled = (
            bool(s.fleet_failover) if failover is None else bool(failover)
        )
        self.clock = EpochClock()
        self._handles: Dict[str, ReplicaHandle] = {}

    # ---- lifecycle ------------------------------------------------------
    def add_replica(self, replica_id: str, inference: Any) -> ReplicaHandle:
        if replica_id in self._handles:
            raise ValueError(f"duplicate replica id {replica_id!r}")
        handle = ReplicaHandle(replica_id, inference, epoch=self.clock.mint())
        self._handles[replica_id] = handle
        self._sync_gauges()
        log.info("fleet: replica %s added (epoch %d)", replica_id, handle.epoch)
        return handle

    def drain(self, replica_id: str) -> ReplicaHandle:
        handle = self._handles[replica_id]
        handle.state = STATE_DRAINING
        handle.inference.admission.begin_drain()
        self._sync_gauges()
        return handle

    def quarantine(self, replica_id: str) -> ReplicaHandle:
        """Membership flagged the replica's ring (recovery in progress):
        no new routes until `activate` — a recovering ring is just a
        drained replica to the router."""
        handle = self._handles[replica_id]
        handle.state = STATE_QUARANTINED
        self._sync_gauges()
        return handle

    def activate(self, replica_id: str) -> ReplicaHandle:
        """Return a quarantined/drained replica to service under a FRESH
        epoch, so frames minted before the outage stay fenced."""
        handle = self._handles[replica_id]
        handle.state = STATE_ACTIVE
        handle.epoch = handle.fence = self.clock.mint()
        self._sync_gauges()
        return handle

    def fail_replica(self, replica_id: str) -> ReplicaHandle:
        """Mark the replica dead and fence it: its affinity entries are
        evicted and its handle's fence re-minted, so in-flight streams
        migrate at their next chunk and zombie dispatches are rejected."""
        handle = self._handles[replica_id]
        handle.state = STATE_DEAD
        handle.fence = self.clock.mint()
        evicted = self.router.affinity.evict_replica(replica_id)
        self._sync_gauges()
        log.warning(
            "fleet: replica %s marked dead (fence %d, %d affinity entries evicted)",
            replica_id, handle.fence, evicted,
        )
        return handle

    def remove(self, replica_id: str) -> None:
        self._handles.pop(replica_id, None)
        self.router.affinity.evict_replica(replica_id)
        self._sync_gauges()

    def handles(self) -> List[ReplicaHandle]:
        return list(self._handles.values())

    def get(self, replica_id: str) -> Optional[ReplicaHandle]:
        return self._handles.get(replica_id)

    def __len__(self) -> int:
        return len(self._handles)

    def _sync_gauges(self) -> None:
        counts = {state: 0 for state in REPLICA_STATES}
        for handle in self._handles.values():
            counts[handle.state] += 1
        fam = metric("dnet_fleet_replicas")
        for state, n in counts.items():
            fam.labels(state=state).set(float(n))

    # ---- introspection --------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """The /v1/debug/fleet body: routing table + per-replica health."""
        return {
            "size": len(self._handles),
            "epoch": self.clock.current,
            "failover": self.failover_enabled,
            "replicas": [h.snapshot() for h in self._handles.values()],
            "affinity": {
                "entries": len(self.router.affinity),
                "capacity": self.router.affinity.capacity,
                "table": self.router.affinity.snapshot(),
            },
        }

    # ---- dispatch -------------------------------------------------------
    def check_fence(self, handle: ReplicaHandle) -> None:
        """Raise the counted stale-epoch rejection on a zombie dispatch."""
        if is_stale(handle.fence, handle.epoch):
            raise reject("fleet_route", handle.fence, handle.epoch)

    def _record_route(
        self,
        key: str,
        handle: ReplicaHandle,
        reason: str,
        route_info: Optional[Dict[str, str]],
    ) -> None:
        metric("dnet_fleet_requests_total").labels(
            replica=handle.replica_id
        ).inc()
        metric("dnet_fleet_routed_total").labels(reason=reason).inc()
        if reason == ROUTE_AFFINITY:
            metric("dnet_fleet_affinity_hits_total").inc()
        log_event(EVENT_ROUTED, replica=handle.replica_id, reason=reason, key=key)
        self.router.record(key, handle.replica_id)
        if route_info is not None:
            route_info["replica"] = handle.replica_id
            route_info["reason"] = reason

    async def _acquire(
        self, req: Any, key: str, exclude: Set[str] = frozenset()
    ) -> Tuple[ReplicaHandle, AsyncIterator[ChatCompletionChunk], Optional[ChatCompletionChunk], str]:
        """Walk the candidate plan until one replica admits the request:
        returns (handle, generator, first chunk, reason).  Admission
        happens on the generator's first __anext__, so a shed costs
        nothing downstream and falls through to the next candidate."""
        candidates = [h for h in self.handles() if h.replica_id not in exclude]
        plan = self.router.plan(key, candidates)
        retry_after_s = 1.0
        for handle, reason in plan:
            self.check_fence(handle)
            try:
                # chaos point: a fault dispatching to THIS candidate is a
                # dead/unreachable replica — fall through to the next one;
                # if every candidate faults, the shed below answers 429
                await _chaos_inject("fleet_dispatch")
            except ConnectionError:
                continue
            gen = handle.inference.generate_stream(req)
            try:
                first = await gen.__anext__()
            except AdmissionRejected as exc:
                retry_after_s = max(retry_after_s, exc.retry_after_s)
                await gen.aclose()
                continue
            except StopAsyncIteration:
                first = None
            return handle, gen, first, reason
        raise FleetSheddingError(
            f"all {len(plan)} fleet replicas shed the request", retry_after_s
        )

    async def _failover(
        self, req: Any, key: str, victim: ReplicaHandle, emitted: int
    ) -> Tuple[ReplicaHandle, AsyncIterator[ChatCompletionChunk], Optional[ChatCompletionChunk]]:
        chosen, gen, first, _reason = await self._acquire(
            req, key, exclude={victim.replica_id}
        )
        metric("dnet_fleet_failovers_total").inc()
        metric("dnet_fleet_requests_total").labels(
            replica=chosen.replica_id
        ).inc()
        metric("dnet_fleet_routed_total").labels(reason=ROUTE_FAILOVER).inc()
        log_event(
            EVENT_FAILOVER,
            victim=victim.replica_id,
            survivor=chosen.replica_id,
            emitted_chars=int(emitted),
        )
        self.router.record(key, chosen.replica_id)
        log.warning(
            "fleet: failover %s -> %s after %d emitted chars",
            victim.replica_id, chosen.replica_id, emitted,
        )
        return chosen, gen, first

    async def stream(
        self, req: Any, route_info: Optional[Dict[str, str]] = None
    ) -> AsyncIterator[ChatCompletionChunk]:
        """The routed form of `InferenceManager.generate_stream`.

        Yields the serving replica's chunks; when that replica is marked
        dead mid-stream, replays the request on a survivor and suppresses
        the already-emitted prefix of the regenerated text."""
        key = self.router.affinity_key(req)
        chosen, gen, pending, reason = await self._acquire(req, key)
        self._record_route(key, chosen, reason, route_info)
        stream_id: Optional[str] = None
        emitted = 0        # content chars the client has seen
        skip = 0           # replay chars still to suppress after failover
        sent_role = False
        replaying = False
        try:
            while True:
                if pending is None:
                    try:
                        if chosen.state == STATE_DEAD:
                            raise _ReplicaLost()
                        pending = await gen.__anext__()
                        if chosen.state == STATE_DEAD and pending.usage is None:
                            # token minted by a replica fenced this tick:
                            # drop it and migrate (final chunks pass — the
                            # stream finished before the fence mattered)
                            raise _ReplicaLost()
                    except StopAsyncIteration:
                        return
                    except _ReplicaLost:
                        pending = None
                        if not self.failover_enabled:
                            raise FleetSheddingError(
                                f"replica {chosen.replica_id} died mid-stream "
                                f"(failover disabled)"
                            ) from None
                        await gen.aclose()
                        chosen, gen, pending = await self._failover(
                            req, key, chosen, emitted
                        )
                        skip = emitted
                        replaying = True
                        continue
                chunk = pending
                pending = None
                choice = chunk.choices[0] if chunk.choices else None
                delta = choice.delta if choice is not None else None
                content = (delta.content or "") if delta is not None else ""
                final = chunk.usage is not None or (
                    choice is not None and choice.finish_reason is not None
                )
                if skip > 0 and not final:
                    if len(content) <= skip:
                        skip -= len(content)
                        continue
                    content = content[skip:]
                    skip = 0
                    if delta is not None:
                        delta.content = content
                elif skip > 0 and final:
                    # the replay produced no more text than the client
                    # already has: pass the terminal chunk through as-is
                    skip = 0
                if replaying and delta is not None and sent_role:
                    delta.role = None
                if stream_id is None:
                    stream_id = chunk.id
                elif chunk.id != stream_id:
                    chunk.id = stream_id
                if delta is not None and delta.role:
                    sent_role = True
                emitted += len(content)
                yield chunk
        finally:
            if gen is not None:
                await gen.aclose()

    async def generate(
        self,
        req: Any,
        route_info: Optional[Dict[str, str]] = None,
        method: str = "generate",
    ) -> Any:
        """The routed form of the non-streaming entry points (`generate`
        or `generate_completion`): same candidate walk; a replica dying
        mid-request retries whole on the next survivor (no partial
        output was visible)."""
        key = self.router.affinity_key(req)
        excluded: Set[str] = set()
        retry_after_s = 1.0
        failed_over = False
        while True:
            candidates = [
                h for h in self.handles() if h.replica_id not in excluded
            ]
            plan = self.router.plan(key, candidates)
            admitted_none = True
            for handle, reason in plan:
                self.check_fence(handle)
                try:
                    await _chaos_inject("fleet_dispatch")
                except ConnectionError:
                    continue
                try:
                    resp = await getattr(handle.inference, method)(req)
                except AdmissionRejected as exc:
                    retry_after_s = max(retry_after_s, exc.retry_after_s)
                    continue
                except Exception:
                    if handle.state == STATE_DEAD and self.failover_enabled:
                        excluded.add(handle.replica_id)
                        metric("dnet_fleet_failovers_total").inc()
                        log_event(
                            EVENT_FAILOVER,
                            victim=handle.replica_id,
                            survivor="",
                            emitted_chars=0,
                        )
                        failed_over = True
                        admitted_none = False
                        break
                    raise
                self._record_route(
                    key,
                    handle,
                    ROUTE_FAILOVER if failed_over else reason,
                    route_info,
                )
                return resp
            if admitted_none:
                raise FleetSheddingError(
                    f"all {len(plan)} fleet replicas shed the request",
                    retry_after_s,
                )
