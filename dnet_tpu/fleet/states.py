"""Declared label sets for the fleet-routing metric families.

A LEAF module (like admission/reasons.py and membership/epoch.py):
imported by `dnet_tpu.obs` to pre-touch one labeled series per value and
by the metrics lint (pass DL031), which cross-checks the exposed label
sets against these tuples BOTH directions — a new replica state or
routing reason cannot ship without its series, and a renamed one cannot
strand a stale label on dashboards.  Keep this module import-light so
obs can pull the enums without a cycle.
"""

from __future__ import annotations

from typing import Tuple

# dnet_fleet_replicas{state=}: one gauge per lifecycle state, counting the
# replicas currently in it (fleet/manager.py syncs on every transition).
#   active      — serving; eligible for routing
#   draining    — admission drains in-flight work; no new routes
#   quarantined — membership flagged the ring (recovery in progress); a
#                 recovering ring is just a drained replica to the router
#   dead        — failed or removed; epoch-fenced so a zombie cannot serve
STATE_ACTIVE = "active"
STATE_DRAINING = "draining"
STATE_QUARANTINED = "quarantined"
STATE_DEAD = "dead"
REPLICA_STATES: Tuple[str, ...] = (
    STATE_ACTIVE,
    STATE_DRAINING,
    STATE_QUARANTINED,
    STATE_DEAD,
)

# dnet_fleet_routed_total{reason=}: why the front door picked the replica
# it picked (fleet/router.py policy order, checked in exactly this order).
#   affinity     — the affinity table pinned this conversation's prefix to
#                  the replica holding its COW prefix blocks
#   least_loaded — no sticky entry (or its replica is gone): lowest live
#                  admission load + estimated queue wait wins
#   failover     — the original replica died mid-request; a survivor
#                  re-served it via deterministic replay
ROUTE_AFFINITY = "affinity"
ROUTE_LEAST_LOADED = "least_loaded"
ROUTE_FAILOVER = "failover"
ROUTE_REASONS: Tuple[str, ...] = (
    ROUTE_AFFINITY,
    ROUTE_LEAST_LOADED,
    ROUTE_FAILOVER,
)
