"""Fleet routing: N ring replicas behind one prefix-affine front door.

The data-parallel layer above the single pipelined ring: `ReplicaHandle`
(replica.py) wraps one full serving stack, `FleetRouter` (router.py)
orders candidates affinity-first then least-loaded, and `FleetManager`
(manager.py) owns lifecycle, epoch fencing, and mid-stream failover.
`DNET_FLEET=1` (the default) bypasses all of it — the single-ring serve
path stays byte-identical.
"""

from dnet_tpu.fleet.states import REPLICA_STATES, ROUTE_REASONS

__all__ = [
    "AffinityTable",
    "FleetManager",
    "FleetRouter",
    "FleetSheddingError",
    "ReplicaHandle",
    "REPLICA_STATES",
    "ROUTE_REASONS",
]

# Lazy re-exports (PEP 562): obs/_register_core imports fleet.states to
# pre-touch the label enums, which executes this __init__ — importing
# manager/router eagerly here would pull admission.controller back in
# while IT is still initializing (its module-scope metric() call is what
# entered obs in the first place).  states.py stays eager (leaf, no deps).
_LAZY = {
    "AffinityTable": "router",
    "FleetManager": "manager",
    "FleetRouter": "router",
    "FleetSheddingError": "router",
    "ReplicaHandle": "replica",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f"dnet_tpu.fleet.{mod}"), name)
