"""Device half of the paged KV subsystem: pool-shaped cache arrays plus
the jitted page-table gather / block-scatter programs.

The pool reuses the existing functional cache layout with the BATCH axis
repurposed as the block axis: `model.init_kv(L, pool_blocks, block_tokens)`
yields `[L, N_blocks, block_tokens, KVH, Hd]` leaves (quantized caches
bring their scale leaves along for free, since every op here is a
jax.tree.map).  Composition with the engines:

- **gather** builds the contiguous per-slot view the existing decode
  programs (`apply_window` -> `write_kv`/`cached_attend`) consume: one
  `pool[:, ids]` take per leaf — `batched_gather_cache`'s trick applied to
  the block axis — reshaped to `[L, slots, nb*bt, ...]`.  Unallocated
  table entries clamp to block 0; their rows sit at positions the causal
  mask excludes, so exp() zeroes them EXACTLY and the result is
  bit-identical to the dense path.
- **scatter** writes back only the blocks a step actually touched (the
  block-append write replacing dense `write_kv` persistence): the touched
  rows are sliced out of the dense view and `.at[:, phys].set` into the
  pool, with the pool buffers DONATED so XLA updates in place.

Scatter widths are bucketed to powers of two (padding repeats the last
triple — duplicate scatters of identical content are deterministic) so
the compiled-program set stays bounded, the same discipline as the
engines' chunk buckets.
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dnet_tpu.kv.paged import PagedKVConfig
from dnet_tpu.obs.jit import instrument_jit


def _bucket_pow2(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


class BlockStore:
    """Pool-shaped KV arrays + cached gather/scatter programs."""

    def __init__(
        self,
        model,
        n_layers: int,
        cfg: PagedKVConfig,
        kv_dtype: str,
        quant_bits: int = 0,
        session_tokens: int = 0,
    ) -> None:
        self.cfg = cfg
        self.block_tokens = cfg.block_tokens
        self.kv = model.init_kv(
            n_layers, cfg.pool_blocks, cfg.block_tokens, kv_dtype,
            quant_bits=quant_bits, rotating=False,
        )
        for leaf in jax.tree.leaves(self.kv):
            if leaf.shape[1] != cfg.pool_blocks or leaf.shape[2] != cfg.block_tokens:
                # a model with per-kind cache shapes cannot repurpose the
                # batch axis as blocks
                raise NotImplementedError(
                    "paged KV needs the flat [L, B, S, ...] cache layout; "
                    f"got leaf shape {leaf.shape}"
                )
        if session_tokens:
            # the pool probe alone cannot catch rotating-SWA models: their
            # ring buffers collapse to uniform leaves when rotating=False,
            # but the SESSION caches the engines gather into / commit from
            # (init_kv rotating=True, the default) carry W-wide ring halves
            # whose slots are position MOD W — block geometry over absolute
            # positions would silently commit the wrong rows.  Probe the
            # session layout and refuse anything non-slot-addressed.
            probe = model.init_kv(
                n_layers, 1, session_tokens, kv_dtype, quant_bits=quant_bits
            )
            if jax.tree.structure(probe) != jax.tree.structure(self.kv):
                raise NotImplementedError(
                    "paged KV needs session caches with the pool's tree "
                    "structure (per-kind cache layouts stay dense)"
                )
            for leaf in jax.tree.leaves(probe):
                if leaf.shape[1] != 1 or leaf.shape[2] != session_tokens:
                    raise NotImplementedError(
                        "paged KV needs slot-addressed max_seq session "
                        f"caches; got session leaf shape {leaf.shape} "
                        "(rotating ring buffers stay dense)"
                    )
        bt = self.block_tokens

        def gather(pool, ids):
            """ids [slots, nb] int32 -> dense [L, slots, nb*bt, ...]."""

            def one(p):
                g = p[:, ids]  # [L, slots, nb, bt, ...]
                L, s, nb = g.shape[:3]
                return g.reshape(L, s, nb * bt, *g.shape[4:])

            return jax.tree.map(one, pool)

        def scatter(pool, dense, slot_idx, block_idx, phys):
            """Write dense blocks (slot_idx[k], block_idx[k]) -> pool[phys[k]]."""

            def one(p, d):
                L, s, S = d.shape[:3]
                blk = d.reshape(L, s, S // bt, bt, *d.shape[3:])[
                    :, slot_idx, block_idx
                ]  # [L, K, bt, ...]
                return p.at[:, phys].set(blk)

            return jax.tree.map(one, pool, dense)

        def append(pool, rows, phys, off):
            """Write one new token row per slot straight into its physical
            block: rows leaves [L, slots, KVH, Hd] -> pool[:, phys[s],
            off[s]].  Inactive lanes pass phys == pool_blocks — PAST the
            block axis, so mode="drop" discards the write (a negative
            sentinel would WRAP to block N-1 and clobber a live block
            before drop semantics ever applied).  The ragged decode
            path's replacement for the whole dense round-trip: the
            step's ONLY cache write."""

            def one(p, r):
                return p.at[:, phys, off].set(r.astype(p.dtype), mode="drop")

            return jax.tree.map(one, pool, rows)

        # instrumented: a page-table geometry leak re-tracing these per
        # step shows as climbing dnet_jit_compiles_total{fn=kv_*} (gather
        # widths are pow2-bucketed by the engines, so the compiled-program
        # set stays bounded — see BatchedEngine._table_ids)
        self._gather = instrument_jit(jax.jit(gather), "kv_gather")
        self._scatter = instrument_jit(
            jax.jit(scatter, donate_argnums=(0,)), "kv_scatter"
        )
        self._append = instrument_jit(
            jax.jit(append, donate_argnums=(0,)), "kv_append"
        )

    # ---- ops ----------------------------------------------------------
    def gather(self, ids: np.ndarray) -> dict:
        """Contiguous [L, slots, nb*bt, ...] view of the tables in `ids`
        ([slots, nb], -1/unallocated entries already clamped to 0)."""
        return self._gather(self.kv, jnp.asarray(ids, dtype=jnp.int32))

    def gather_row(self, blocks: List[int], width_tokens: int) -> dict:
        """One sequence's blocks as a [L, 1, width_tokens, ...] dense row
        (padded with clamped block 0 beyond the table — rows the causal
        mask excludes)."""
        bt = self.block_tokens
        assert width_tokens % bt == 0
        ids = np.zeros((1, width_tokens // bt), dtype=np.int32)
        ids[0, : len(blocks)] = blocks
        return self.gather(ids)

    def scatter(
        self,
        dense: dict,
        triples: List[Tuple[int, int, int]],
    ) -> None:
        """Persist touched blocks: triples of (slot, logical_block, phys).
        Pads to a power-of-two width by repeating the last triple."""
        if not triples:
            return
        K = _bucket_pow2(len(triples))
        padded = list(triples) + [triples[-1]] * (K - len(triples))
        slot_idx = jnp.asarray([t[0] for t in padded], dtype=jnp.int32)
        block_idx = jnp.asarray([t[1] for t in padded], dtype=jnp.int32)
        phys = jnp.asarray([t[2] for t in padded], dtype=jnp.int32)
        self.kv = self._scatter(self.kv, dense, slot_idx, block_idx, phys)

    def append_rows(self, rows: dict, phys, off) -> None:
        """Ragged-decode block append: one new token row per slot, written
        in place (donated pool buffers).  rows leaves [L, slots, KVH, Hd]
        (the step program's stacked per-layer k/v outputs); phys/off
        [slots] int32 physical block + in-block offset; phys ==
        pool_blocks (out of range, NOT negative) = skip this lane."""
        self.kv = self._append(
            self.kv, rows,
            jnp.asarray(phys, dtype=jnp.int32),
            jnp.asarray(off, dtype=jnp.int32),
        )

    def commit_row(
        self,
        kv_row: dict,
        logical_blocks: List[int],
        phys_blocks: List[int],
    ) -> None:
        """Persist blocks of a single-sequence dense row ([L, 1, S, ...]):
        logical block index i of the row -> pool block phys_blocks[i]."""
        self.scatter(
            kv_row,
            [(0, lb, pb) for lb, pb in zip(logical_blocks, phys_blocks)],
        )

