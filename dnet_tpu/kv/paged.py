"""Paged KV block-pool allocator: free list, page tables, refcounted COW.

The dense engines pin `slots x max_seq` KV rows regardless of actual
sequence lengths (core/kvcache.py `init_cache`, core/batch.py's slot
model), and prefix reuse deep-copies whole snapshots
(core/prefix_cache.py `_copy_tree`).  Ragged Paged Attention (PAPERS.md)
shows the TPU-native alternative: block-granular KV with per-sequence
page tables — prefix sharing becomes refcounted block aliasing, and
admission becomes a function of FREE BLOCKS, not worst-case length.

This module is the host-side half: a `BlockPool` (allocation, refcounts,
exact accounting, typed backpressure) and per-sequence `PageTable`s
mapping logical block index -> physical pool block.  The device half
(`kv/store.py`) holds the pool-shaped cache arrays and the jitted
gather/scatter programs that compose with the existing functional cache
ops.  Everything here is plain Python under one lock: allocator decisions
are control flow, never traced.

Invariants (enforced by `check_conservation`, linted from tier-1 via
scripts/check_metrics_names.py):

- ``blocks_used + blocks_free == pool_blocks`` at every step; a block
  shared by N holders counts ONCE in used (that is the whole saving).
- every allocated block's refcount equals the number of holders (page
  tables + prefix-cache entries) that will eventually `free` it.
- pool exhaustion raises `KVPoolExhausted` — a clean backpressure signal
  the serving layer maps to queueing/429, never a shape error or OOM.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from dnet_tpu.analysis.runtime import ownership as dsan
from dnet_tpu.obs import metric

_USED = metric("dnet_kv_blocks_used")
_FREE = metric("dnet_kv_blocks_free")
_POOL = metric("dnet_kv_pool_blocks")
_COW = metric("dnet_kv_cow_copies_total")
_SHARED = metric("dnet_kv_prefix_shared_blocks_total")
_REJECTED = metric("dnet_kv_admission_rejected_total")


class KVPoolExhausted(RuntimeError):
    """Typed backpressure: the paged pool cannot cover an admission or an
    extension.  Callers queue / shed load; they must never see this as a
    shape/OOM crash mid-program."""

    def __init__(self, need: int, free: int, total: int) -> None:
        super().__init__(
            f"paged KV pool exhausted: need {need} block(s), "
            f"{free} free of {total}"
        )
        self.need = need
        self.free = free
        self.total = total


def ceil_div(n: int, d: int) -> int:
    return -(-n // d)


@dataclass(frozen=True)
class PagedKVConfig:
    """Pool geometry, resolved from DNET_KV_* settings by the engines."""

    block_tokens: int
    pool_blocks: int

    def __post_init__(self) -> None:
        if self.block_tokens < 1:
            raise ValueError(f"block_tokens must be >= 1, got {self.block_tokens}")
        if self.pool_blocks < 1:
            raise ValueError(f"pool_blocks must be >= 1, got {self.pool_blocks}")

    @classmethod
    def from_settings(cls, max_seq: int, slots: int = 1) -> "PagedKVConfig":
        """Resolve block/pool sizing from KVSettings; pool_blocks=0 auto-
        sizes to the dense equivalent (slots x max_seq worth of blocks), so
        flipping DNET_KV_PAGED=1 alone never ADMITS less than dense did —
        the wins come from sharing and variable lengths."""
        from dnet_tpu.config import get_settings

        kv = get_settings().kv
        bt = int(kv.block_tokens)
        if bt < 1 or max_seq % bt:
            raise ValueError(
                f"DNET_KV_BLOCK_TOKENS={bt} must be >= 1 and divide "
                f"max_seq={max_seq}"
            )
        pool = int(kv.pool_blocks) or slots * ceil_div(max_seq, bt)
        return cls(block_tokens=bt, pool_blocks=pool)

    def blocks_for(self, n_tokens: int) -> int:
        return ceil_div(n_tokens, self.block_tokens)


@dataclass
class PageTable:
    """One sequence's logical->physical block map.

    `blocks[i]` backs tokens [i*bt, (i+1)*bt); `shared_upto` marks how many
    LEADING blocks are refcount-aliased from a prefix entry (full blocks
    only — immutable for this sequence, so decode never writes them; the
    partial tail of a shared prefix is COW-copied at adoption)."""

    blocks: List[int] = field(default_factory=list)
    shared_upto: int = 0

    def __len__(self) -> int:
        return len(self.blocks)


class BlockPool:
    """Fixed-capacity block allocator with refcounts and exact accounting."""

    def __init__(self, cfg: PagedKVConfig) -> None:
        self.cfg = cfg
        self.block_tokens = cfg.block_tokens
        self.total = cfg.pool_blocks
        # every _free/_ref touch happens under _lock; the guarded-by
        # contract is declared in analysis/runtime/domains.py and enforced
        # under DNET_SAN=1 (plain containers otherwise)
        self._lock = dsan.san_lock("BlockPool._lock")
        _dom = dsan.maybe_lock_domain(self._lock)
        self._free: List[int] = dsan.guard_list(
            list(range(self.total)), _dom, "BlockPool._free"
        )
        self._ref: Dict[int, int] = dsan.guard_dict({}, _dom, "BlockPool._ref")
        # high-water mark of used blocks (tests/bench read it; the gauge
        # only shows the current value)
        self.peak_used = 0
        _POOL.set(self.total)
        self._publish()

    # ---- accounting ---------------------------------------------------
    @property
    def used(self) -> int:
        with self._lock:
            return len(self._ref)

    @property
    def free(self) -> int:
        with self._lock:
            return len(self._free)

    def _publish(self) -> None:
        # caller holds no lock: values may be momentarily torn between the
        # two gauges, but each gauge is itself consistent
        with self._lock:
            used, free = len(self._ref), len(self._free)
            if used > self.peak_used:
                self.peak_used = used
        _USED.set(used)
        _FREE.set(free)

    def can_cover(self, n_blocks: int) -> bool:
        with self._lock:
            return len(self._free) >= n_blocks

    def require(self, n_blocks: int) -> None:
        """Admission pre-check: raise KVPoolExhausted (and count the
        rejection) if the pool cannot cover n_blocks RIGHT NOW — the
        fail-before-compute gate prefill paths call before burning a
        forward pass."""
        with self._lock:
            free = len(self._free)
        if free < n_blocks:
            _REJECTED.inc()
            raise KVPoolExhausted(n_blocks, free, self.total)

    # ---- allocation ---------------------------------------------------
    def alloc(self, n_blocks: int) -> List[int]:
        """Allocate n fresh blocks (ref=1 each) or raise KVPoolExhausted
        WITHOUT a partial allocation."""
        if n_blocks == 0:
            return []
        with self._lock:
            if len(self._free) < n_blocks:
                need, free = n_blocks, len(self._free)
                _REJECTED.inc()
                raise KVPoolExhausted(need, free, self.total)
            out = [self._free.pop() for _ in range(n_blocks)]
            for b in out:
                self._ref[b] = 1
        self._publish()
        return out

    def retain(self, blocks: Sequence[int]) -> List[int]:
        """Take one extra reference per block (no sharing metric — for
        transient holds, e.g. keeping a prefix entry's blocks alive while
        their contents are gathered/copied)."""
        if not blocks:
            return []
        with self._lock:
            for b in blocks:
                if b not in self._ref:
                    raise ValueError(f"retain of unallocated block {b}")
                self._ref[b] += 1
        return list(blocks)

    def share(self, blocks: Sequence[int]) -> List[int]:
        """Alias existing blocks (ref++ each); returns them for chaining.
        Counts toward dnet_kv_prefix_shared_blocks_total — every call site
        is a copy the dense path would have made."""
        out = self.retain(blocks)
        if out:
            _SHARED.inc(len(out))
        return out

    @staticmethod
    def count_cow(n: int = 1) -> None:
        """Record COW copies performed OUTSIDE `cow()` (e.g. a partial
        shared block whose merged contents are committed from a dense
        working view instead of copied pool->pool)."""
        if n > 0:
            _COW.inc(n)

    def free_blocks(self, blocks: Sequence[int]) -> int:
        """Drop one reference per block; blocks reaching ref 0 return to
        the free list.  Returns how many became free."""
        if not blocks:
            return 0
        released = 0
        with self._lock:
            for b in blocks:
                r = self._ref.get(b)
                if r is None:
                    raise ValueError(f"free of unallocated block {b}")
                if r == 1:
                    del self._ref[b]
                    self._free.append(b)
                    released += 1
                else:
                    self._ref[b] = r - 1
        self._publish()
        return released

    def refcount(self, block: int) -> int:
        with self._lock:
            return self._ref.get(block, 0)

    def cow(self, block: int) -> int:
        """Copy-on-write: allocate a fresh block to replace a SHARED one
        this sequence is about to diverge into; the caller copies the
        device contents (kv/store.py) and drops its reference on the old
        block.  Returns the new physical block id."""
        new = self.alloc(1)[0]
        self.free_blocks([block])
        _COW.inc()
        return new

    # ---- table helpers ------------------------------------------------
    def ensure(self, table: PageTable, n_tokens: int) -> List[int]:
        """Grow `table` to cover n_tokens (appending fresh blocks); returns
        the newly appended block ids.  All-or-nothing on exhaustion."""
        need = self.cfg.blocks_for(n_tokens) - len(table.blocks)
        if need <= 0:
            return []
        fresh = self.alloc(need)
        table.blocks.extend(fresh)
        return fresh

    def release_table(self, table: Optional[PageTable]) -> int:
        if table is None or not table.blocks:
            return 0
        n = self.free_blocks(table.blocks)
        table.blocks.clear()
        table.shared_upto = 0
        return n

    # ---- invariants ---------------------------------------------------
    def check_conservation(self, holders: Optional[Sequence[Sequence[int]]] = None) -> None:
        """Assert the pool's books balance: used + free == total, the free
        list is duplicate-free and disjoint from allocated blocks, and —
        when the caller passes every live holder's block list — refcounts
        equal the number of holders per block."""
        with self._lock:
            used = len(self._ref)
            free = list(self._free)
            refs = dict(self._ref)
        if used + len(free) != self.total:
            raise AssertionError(
                f"paged pool leak: used {used} + free {len(free)} != "
                f"total {self.total}"
            )
        if len(set(free)) != len(free):
            raise AssertionError("paged pool free list has duplicates")
        if set(free) & set(refs):
            raise AssertionError("paged pool free list overlaps allocated blocks")
        if any(r < 1 for r in refs.values()):
            raise AssertionError("paged pool holds a block with refcount < 1")
        if holders is not None:
            counts: Dict[int, int] = {}
            for blocks in holders:
                for b in blocks:
                    counts[b] = counts.get(b, 0) + 1
            if counts != refs:
                raise AssertionError(
                    f"paged pool refcounts {refs} != holder counts {counts}"
                )


def paged_enabled() -> bool:
    """THE flag gate: DNET_KV_PAGED=1 (KVSettings.paged).  A raw env read
    (config.env_flag, the sanctioned DL006 escape hatch) backs the
    settings value so tests toggling os.environ after the settings cache
    warmed still see the flip."""
    from dnet_tpu.config import env_flag, get_settings

    if get_settings().kv.paged:
        return True
    return env_flag("DNET_KV_PAGED")


def ragged_enabled() -> bool:
    """DNET_KV_RAGGED=1 (KVSettings.ragged): decode attends the block pool
    in place (ops/paged_attention.py) instead of the gather->step->scatter
    sandwich.  Only meaningful under paged KV; eligibility is refined per
    engine (ops.paged_attention.ragged_refusal).  Same env_flag backing as
    paged_enabled for post-cache test flips."""
    from dnet_tpu.config import env_flag, get_settings

    if get_settings().kv.ragged:
        return True
    return env_flag("DNET_KV_RAGGED")
