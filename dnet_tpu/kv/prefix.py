"""Block-level prefix sharing over the paged pool.

The dense `PrefixCache` stores one FULL deep-copied KV snapshot per
prompt (`_copy_tree`), so a multi-turn conversation's snapshots duplicate
their shared history once per turn.  Here the same `PrefixIndex` matcher
resolves hits to refcounted BLOCK RUNS in the pool instead:

- **store dedup**: a snapshot whose prompt extends an existing entry
  aliases the parent's full blocks (ref++, `dnet_kv_prefix_shared_blocks_
  total`) and commits only its own tail blocks — turn N's snapshot costs
  O(new turn), not O(history).
- **adoption** (`lookup_blocks`): the batched engine's page tables alias
  an entry's full blocks directly — no copy at all; the partial tail
  block (a request diverging mid-block) is COW-copied by the adopter.
- **dense facade** (`lookup`/`store`): the same (n_tokens, kv_row)
  surface as `PrefixCache`, so `LocalEngine.prefill`'s hit/store flow
  runs unchanged — restores gather a private dense row out of the pool
  (its working cache is dense), while stores still dedup block-level.

Entry eviction releases the entry's references through PrefixIndex's
`on_evict` hook; the blocks themselves live until the last page table
drops them.
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence, Tuple, Union

from dnet_tpu.core.prefix_cache import PrefixIndex
from dnet_tpu.kv.paged import BlockPool, KVPoolExhausted
from dnet_tpu.kv.store import BlockStore
from dnet_tpu.utils.logger import get_logger

log = get_logger()


def prefix_affinity_key(prefix: Union[str, Sequence[int]], n_units: int = 256) -> str:
    """Stable hash of a conversation's leading prefix units.

    The fleet front door (fleet/router.py) keys its affinity table on
    this: two requests that share a prompt prefix — turn N and turn N+1
    of one conversation — hash to the same key, so the router can stick
    them to the replica whose pool already holds the shared blocks.  The
    front door has no tokenizer, so it hashes the first `n_units`
    text characters (or token ids when the caller has them — the same
    leading-run identity `PrefixIndex.lookup` matches on).
    """
    if isinstance(prefix, str):
        raw = prefix[:n_units].encode("utf-8", errors="replace")
    else:
        raw = b"\x00".join(
            str(int(t)).encode("ascii") for t in list(prefix)[:n_units]
        )
    return hashlib.sha256(raw).hexdigest()[:16]


class PagedPrefixCache:
    """PrefixIndex entries valued (n_tokens, block run) in a shared pool."""

    def __init__(
        self,
        pool: BlockPool,
        store: BlockStore,
        capacity: int,
        min_tokens: int = 16,
        row_tokens: int = 0,
    ) -> None:
        self.pool = pool
        self._dev = store
        # dense-facade restores pad the gathered row to this width (the
        # consuming engine's max_seq); 0 = facade unused (batched aliasing)
        self.row_tokens = row_tokens
        self._index = PrefixIndex(
            capacity, min_tokens, kind="prefix", on_evict=self._release
        )
        self.stats = {"hits": 0, "misses": 0, "stores": 0}

    # PrefixCache-compat knob (tests tune it for tiny prompts)
    @property
    def min_tokens(self) -> int:
        return self._index.min_tokens

    @min_tokens.setter
    def min_tokens(self, v: int) -> None:
        self._index.min_tokens = v

    def _release(self, value) -> None:
        _n, blocks = value
        self.pool.free_blocks(blocks)

    # ---- block surface (batched engine aliasing) ----------------------
    def lookup_blocks(
        self, prompt_ids: Sequence[int]
    ) -> Optional[Tuple[int, List[int], int]]:
        """Longest-prefix hit as (n_tokens, blocks, n_full).

        The first `n_full` blocks are FULL and aliased (counted shared);
        a trailing partial block (n % block_tokens != 0) is retained
        uncounted — the adopter must COW it before writing and drop the
        transient reference afterwards.  The caller owns exactly one
        reference on every returned block."""
        hit = self._index.lookup(prompt_ids)
        if hit is None:
            self.stats["misses"] += 1
            return None
        self.stats["hits"] += 1
        n, (n_entry, blocks) = hit
        assert n == n_entry
        n_full = n // self.pool.block_tokens
        out = self.pool.share(blocks[:n_full])
        out += self.pool.retain(blocks[n_full:])
        return n, out, n_full

    def store_blocks(
        self, prompt_ids: Sequence[int], n_tokens: int, blocks: Sequence[int]
    ) -> bool:
        """Snapshot a live page table by aliasing its blocks (the batched
        store path: zero copies).  Safe because rows < n_tokens of every
        aliased block are immutable — the owning slot only ever rewrites
        its partial tail block's rows >= n_tokens, and adopters COW that
        block before writing."""
        ids = list(prompt_ids)
        if len(ids) < self.min_tokens or n_tokens != len(ids):
            return False
        if self._index.get_exact(ids) is not None:
            return False
        nb = self.pool.cfg.blocks_for(n_tokens)
        entry = self.pool.share(list(blocks[:nb]))
        if not self._index.put(ids, (n_tokens, entry)):
            self.pool.free_blocks(entry)
            return False
        self.stats["stores"] += 1
        return True

    # ---- dense facade (LocalEngine's PrefixCache surface) --------------
    def lookup(self, prompt_ids: Sequence[int]) -> Optional[Tuple[int, dict]]:
        """(n_tokens, private dense kv row) — gathers the hit's blocks out
        of the pool into a fresh [L, 1, row_tokens, ...] buffer."""
        hit = self.lookup_blocks(prompt_ids)
        if hit is None:
            return None
        n, blocks, _n_full = hit
        try:
            kv_row = self._dev.gather_row(blocks, self.row_tokens)
        finally:
            # the gather copied the contents; the restore owns nothing
            self.pool.free_blocks(blocks)
        return n, kv_row

    def store(self, prompt_ids: Sequence[int], kv_row: dict) -> None:
        """Snapshot a dense session row, committing only the tail blocks a
        parent entry doesn't already hold (block-level dedup)."""
        ids = list(prompt_ids)
        n = len(ids)
        if n < self.min_tokens:
            return
        if self._index.get_exact(ids) is not None:
            return
        bt = self.pool.block_tokens
        nb = self.pool.cfg.blocks_for(n)
        parent = self._index.match_quiet(ids, allow_equal=False)
        n_parent_full = (parent[0] // bt) if parent is not None else 0
        try:
            own = self.pool.alloc(nb - n_parent_full)
        except KVPoolExhausted as exc:
            # a full pool must not fail the REQUEST over a snapshot; the
            # admission path is where exhaustion is a hard signal
            log.warning("paged prefix store skipped: %s", exc)
            return
        aliased = (
            self.pool.share(parent[1][1][:n_parent_full])
            if parent is not None
            else []
        )
        self._dev.commit_row(
            kv_row, list(range(n_parent_full, nb)), own
        )
        entry = aliased + own
        if self._index.put(ids, (n, entry)):
            self.stats["stores"] += 1
        else:
            self.pool.free_blocks(entry)

    def clear(self) -> None:
        self._index.clear()  # on_evict releases every entry's blocks
