"""Paged KV-cache subsystem: block-pool allocation, page tables,
copy-on-write prefix sharing, and free-block admission.

Host half: `paged.py` (BlockPool / PageTable / KVPoolExhausted).
Device half: `store.py` (pool-shaped arrays + gather/scatter programs).
Sharing: `prefix.py` (PagedPrefixCache over the same pool).

Enabled per-engine via DNET_KV_PAGED=1 (config.KVSettings); the dense
preallocated path stays the default.
"""

from dnet_tpu.kv.paged import (
    BlockPool,
    KVPoolExhausted,
    PagedKVConfig,
    PageTable,
    ceil_div,
    paged_enabled,
    ragged_enabled,
)
from dnet_tpu.kv.prefix import PagedPrefixCache
from dnet_tpu.kv.store import BlockStore

__all__ = [
    "BlockPool",
    "BlockStore",
    "KVPoolExhausted",
    "PagedKVConfig",
    "PagedPrefixCache",
    "PageTable",
    "ceil_div",
    "paged_enabled",
    "ragged_enabled",
]
