"""Delta reconfiguration: which shards actually need weights re-shipped.

On a re-solve, a shard whose load parameters are unchanged (same layer
range, window/residency, mesh axes, lanes/spec/prefix capacities, dtype,
...) does NOT need to re-read weights from disk — it only needs to bump
its epoch, drop per-request state (lanes/KV/snapshots), and rewire its
next pointer.  The signature is computed over the full per-shard
/load_model body minus the VOLATILE keys that legitimately change on
every reconfiguration, so any future load knob automatically participates
in the diff — a new body field can never be silently ignored by the delta
path.
"""

from __future__ import annotations

from typing import Dict, Tuple

# keys every reconfiguration rewrites; excluded from the change signature
VOLATILE_KEYS = ("next_node", "epoch")


def body_signature(body: dict) -> Tuple:
    """Order-independent, hashable signature of one shard's load body."""
    return tuple(
        sorted(
            (k, repr(v)) for k, v in body.items() if k not in VOLATILE_KEYS
        )
    )


def split_delta(
    last: Dict[str, Tuple], bodies: Dict[str, dict]
) -> Tuple[Dict[str, dict], Dict[str, dict]]:
    """Partition `bodies` (instance -> new load body) against `last`
    (instance -> signature of the body last successfully loaded) into
    (changed, unchanged).  An instance with no recorded signature is
    always `changed` — never skip a shard we have no proof about."""
    changed: Dict[str, dict] = {}
    unchanged: Dict[str, dict] = {}
    for instance, body in bodies.items():
        if last.get(instance) == body_signature(body):
            unchanged[instance] = body
        else:
            changed[instance] = body
    return changed, unchanged
