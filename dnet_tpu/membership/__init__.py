"""Elastic ring membership: topology epochs, zombie fencing, rejoin.

The paper's ring is a one-shot HALDA solve; PRs 4-5 made *transient*
failure survivable but permanent node loss still meant a full-cluster
reload and a shard pruned from monitoring forever.  This package treats
ring membership as dynamic state:

- every installed topology carries a monotonically increasing **epoch**
  (`epoch.EpochClock`, minted by the API's ClusterManager) that rides every
  cross-process hop — load fan-out, activation frames, token callbacks,
  reset_cache — and shards pin it at load;
- `epoch.StaleEpochError` + `epoch.reject()` are the authoritative fence:
  state minted under a dead epoch is rejected and counted
  (`dnet_stale_epoch_rejected_total{kind=}`), never computed — the thing
  that makes re-solve safe under partition (zombie/split-brain);
- `quarantine.QuarantineSet` keeps fenced-out shards health-probed instead
  of pruned, so a shard that comes back green for `DNET_REJOIN_STABLE_S`
  can rejoin (behind `DNET_REJOIN=1`) without operator action;
- `delta.body_signature` backs delta reconfiguration: on re-solve, only
  shards whose load parameters changed re-ship weights — unchanged shards
  bump epoch and drop per-request state via `/update_topology`.
"""

from dnet_tpu.membership.delta import body_signature, split_delta
from dnet_tpu.membership.epoch import (
    RECOVERY_OUTCOMES,
    STALE_EPOCH_KINDS,
    EpochClock,
    StaleEpochError,
    is_stale,
    reject,
    set_epoch_gauge,
)
from dnet_tpu.membership.quarantine import QuarantinedShard, QuarantineSet

__all__ = [
    "RECOVERY_OUTCOMES",
    "STALE_EPOCH_KINDS",
    "EpochClock",
    "QuarantineSet",
    "QuarantinedShard",
    "StaleEpochError",
    "body_signature",
    "is_stale",
    "reject",
    "set_epoch_gauge",
    "split_delta",
]
